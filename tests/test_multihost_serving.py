"""Multi-host serving: lockstep engine replication across 2 processes.

The worker script runs REAL cross-process collectives on the CPU
backend (same harness as tests/test_distributed.py): both ranks build a
tp=4 global mesh spanning 2 processes x 2 devices, shard the same tiny
model onto it, and drive a MultihostEngine — rank 0 submits, rank 1
sits in serve_forever(). Rank 0 asserts the multi-host outputs are
bit-identical to a local single-process unsharded engine.
"""

import jax
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.batching import BatchingEngine
from shellac_tpu.inference.multihost import MultihostEngine
from shellac_tpu.models import transformer

_WORKER = """
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
import numpy as np
from shellac_tpu import ParallelConfig, get_model_config
from shellac_tpu.inference.batching import BatchingEngine
from shellac_tpu.inference.engine import shard_params
from shellac_tpu.inference.multihost import MultihostEngine
from shellac_tpu.models import transformer
from shellac_tpu.parallel.distributed import global_mesh, initialize

assert initialize(), "initialize() did not join the cluster"
assert jax.process_count() == 2

cfg = get_model_config("tiny").replace(dtype="float32")
params = transformer.init_params(cfg, jax.random.PRNGKey(0))
mesh = global_mesh(ParallelConfig(tp=4))
sharded = shard_params(cfg, params, mesh)
eng = MultihostEngine(
    BatchingEngine(cfg, sharded, n_slots=2, max_len=64, mesh=mesh)
)

rng = np.random.default_rng(7)
prompts = [rng.integers(1, cfg.vocab_size, size=n).tolist()
           for n in (3, 7, 5, 6)]

if eng.is_primary:
    got = eng.run([(i, p, 8) for i, p in enumerate(prompts)])
    # Reference: plain single-process engine over the same local params.
    want = BatchingEngine(cfg, params, n_slots=2, max_len=64).run(
        [(i, p, 8) for i, p in enumerate(prompts)]
    )
    assert got == want, (got, want)
else:
    eng.serve_forever()
    # The follower's replica saw the same requests and produced the
    # same tokens — its counters prove it did the work, not just idled.
    assert eng.stats["requests_completed"] == len(prompts)
    assert eng.stats["tokens_generated"] == 8 * len(prompts)
print("WORKER_OK", jax.process_index(), flush=True)
"""


_HTTP_WORKER = """
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
import json, urllib.request
import numpy as np
from shellac_tpu import ParallelConfig, get_model_config
from shellac_tpu.inference.batching import BatchingEngine
from shellac_tpu.inference.engine import shard_params
from shellac_tpu.inference.multihost import MultihostEngine
from shellac_tpu.inference.server import InferenceServer, make_http_server
from shellac_tpu.models import transformer
from shellac_tpu.parallel.distributed import global_mesh, initialize

assert initialize()
cfg = get_model_config("tiny").replace(dtype="float32")
params = transformer.init_params(cfg, jax.random.PRNGKey(0))
mesh = global_mesh(ParallelConfig(tp=4))
sharded = shard_params(cfg, params, mesh)
eng = MultihostEngine(
    BatchingEngine(cfg, sharded, n_slots=2, max_len=64, mesh=mesh)
)

if eng.is_primary:
    srv = InferenceServer(cfg, sharded, engine=eng)
    httpd = make_http_server(srv)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    import threading
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    req = urllib.request.Request(
        base + "/generate",
        data=json.dumps({"tokens": [3, 5, 7], "max_new": 6}).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=120) as r:
        got = json.loads(r.read())["tokens"]
    want = BatchingEngine(cfg, params, n_slots=2, max_len=64).run(
        [(0, [3, 5, 7], 6)]
    )[0]
    assert got == want, (got, want)
    httpd.shutdown()
    srv.close()  # broadcasts shutdown -> rank 1 exits serve_forever
else:
    eng.serve_forever()
    assert eng.stats["requests_completed"] == 1
print("WORKER_OK", jax.process_index(), flush=True)
"""


_SPEC_WORKER = """import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
import numpy as np
from shellac_tpu import ParallelConfig, get_model_config
from shellac_tpu.inference.batching import BatchingEngine
from shellac_tpu.inference.engine import shard_params
from shellac_tpu.inference.multihost import MultihostEngine
from shellac_tpu.inference.spec_batching import SpeculativeBatchingEngine
from shellac_tpu.models import transformer
from shellac_tpu.parallel.distributed import global_mesh, initialize

assert initialize()
cfg = get_model_config("tiny").replace(dtype="float32")
params = transformer.init_params(cfg, jax.random.PRNGKey(0))
mesh = global_mesh(ParallelConfig(tp=4))
sharded = shard_params(cfg, params, mesh)
eng = MultihostEngine(SpeculativeBatchingEngine(
    cfg, sharded, cfg, sharded, gamma=3, n_slots=2, max_len=64, mesh=mesh,
))
rng = np.random.default_rng(29)
prompts = [rng.integers(1, cfg.vocab_size, size=n).tolist() for n in (3, 6, 4)]
if eng.is_primary:
    got = eng.run([(i, p, 8) for i, p in enumerate(prompts)])
    want = BatchingEngine(cfg, params, n_slots=2, max_len=64).run(
        [(i, p, 8) for i, p in enumerate(prompts)])
    assert got == want, (got, want)
else:
    eng.serve_forever()
    assert eng.stats["requests_completed"] == len(prompts)
print("WORKER_OK", jax.process_index(), flush=True)
"""


from conftest import needs_multiprocess_cpu as _needs_multiprocess_cpu


@_needs_multiprocess_cpu
class TestMultihostServing:
    def _run_pair(self, tmp_path, source):
        from conftest import run_two_process

        run_two_process(tmp_path, source)

    def test_two_process_http_serving(self, tmp_path):
        """Full HTTP path on rank 0, follower mirroring on rank 1."""
        self._run_pair(tmp_path, _HTTP_WORKER)

    def test_two_process_lockstep_serving(self, tmp_path):
        """Engine-level drive: rank 0 run()s, rank 1 mirrors."""
        self._run_pair(tmp_path, _WORKER)

    def test_two_process_speculative_serving(self, tmp_path):
        """Speculative batching under the lockstep wrapper: the
        draft/verify rounds are deterministic given the command stream,
        so the replicas stay bit-identical too."""
        self._run_pair(tmp_path, _SPEC_WORKER)


class TestSingleProcessDegenerate:
    """The wrapper is a clean pass-through on single-process jobs."""

    def test_run_matches_bare_engine(self):
        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        prompts = [[3, 5, 7], [11, 2]]
        want = BatchingEngine(cfg, params, n_slots=2, max_len=64).run(
            [(i, p, 6) for i, p in enumerate(prompts)]
        )
        eng = MultihostEngine(
            BatchingEngine(cfg, params, n_slots=2, max_len=64)
        )
        assert eng.is_primary
        got = eng.run([(i, p, 6) for i, p in enumerate(prompts)])
        assert got == want
        assert eng.step() is None  # shut down

    def test_follower_surface_guard(self):
        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = MultihostEngine(
            BatchingEngine(cfg, params, n_slots=2, max_len=64)
        )
        eng.is_primary = False  # simulate a follower
        with pytest.raises(RuntimeError, match="primary-only"):
            eng.submit("r", [1, 2], 4)

    def test_cancel_flows_through(self):
        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = MultihostEngine(
            BatchingEngine(cfg, params, n_slots=1, max_len=64)
        )
        eng.submit("a", [1, 2, 3], 8)
        eng.submit("b", [4, 5], 8)  # queued behind a
        assert eng.cancel("b") is True
        assert eng.cancel("nope") is False
        out = {}
        while eng.pending:
            for rid, toks in eng.step():
                out[rid] = toks
        assert set(out) == {"a"}

    def test_resync_bumps_epoch_and_drops_work(self):
        """The supervisor's recovery hook: resync() aborts local work,
        bumps the epoch, and the engine serves fresh requests after —
        the epoch command rides the next step's command stream."""
        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = MultihostEngine(
            BatchingEngine(cfg, params, n_slots=1, max_len=64)
        )
        eng.submit("in_flight", [1, 2, 3], 8)
        eng.submit("queued", [4, 5], 8)
        eng.step()  # "in_flight" takes the slot
        assert eng.resync() is eng
        assert eng.epoch == 1
        assert eng.pending == 0
        out = {}
        eng.submit("fresh", [1, 2, 3], 4)
        while eng.pending:
            for rid, toks in eng.step():
                out[rid] = toks
        assert set(out) == {"fresh"}
        want = BatchingEngine(cfg, params, n_slots=1, max_len=64).run(
            [("fresh", [1, 2, 3], 4)]
        )
        assert out == want

    def test_resync_rekeys_prng_from_seed_and_epoch(self):
        """Post-recovery sampling must stay seed-dependent: the epoch
        re-key folds the CONSTRUCTION seed, so two jobs with different
        seeds do not collapse onto one stream after a resync."""
        import numpy as np

        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = BatchingEngine(cfg, params, n_slots=1, max_len=64, seed=5)
        mh = MultihostEngine(eng)
        mh.resync()
        want = jax.random.fold_in(jax.random.PRNGKey(5), 1)
        assert (np.asarray(eng._key) == np.asarray(want)).all()
        mh.resync()
        want2 = jax.random.fold_in(jax.random.PRNGKey(5), 2)
        assert (np.asarray(eng._key) == np.asarray(want2)).all()

    def test_follower_step_faults_tolerated_within_budget(self):
        """A replicated step exception must not kill the follower loop
        outright — it drops local work and keeps participating so the
        primary's epoch bump can resynchronize it; a crash loop
        exhausts the budget and re-raises loudly."""
        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))

        aborts = []

        class _AlwaysDies(BatchingEngine):
            def step(self):
                raise OSError("transport reset by peer")

            def abort_all(self):
                aborts.append(1)
                return super().abort_all()

        mh = MultihostEngine(_AlwaysDies(cfg, params, n_slots=1,
                                         max_len=64))
        with pytest.raises(OSError, match="transport reset"):
            mh.serve_forever(fault_budget=2)
        assert len(aborts) == 2  # two tolerated faults, third re-raised
        # Default budget 0: the loud legacy contract, first fault
        # re-raises untouched.
        aborts.clear()
        mh2 = MultihostEngine(_AlwaysDies(cfg, params, n_slots=1,
                                          max_len=64))
        with pytest.raises(OSError, match="transport reset"):
            mh2.serve_forever()
        assert aborts == []

    def test_resync_after_shutdown_refused(self):
        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = MultihostEngine(
            BatchingEngine(cfg, params, n_slots=1, max_len=64)
        )
        eng.shutdown()
        with pytest.raises(RuntimeError, match="shutdown"):
            eng.resync()
