"""Unit tests for core ops against numpy/reference implementations."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu.ops.activations import softcap, swiglu
from shellac_tpu.ops.attention import attention_ref
from shellac_tpu.ops.flash_attention import flash_attention
from shellac_tpu.ops.norms import rms_norm_pallas, rms_norm_ref
from shellac_tpu.ops.rope import apply_rope, rope_angles


class TestRMSNorm:
    def test_matches_numpy(self):
        x = np.random.default_rng(0).normal(size=(4, 8, 64)).astype(np.float32)
        scale = np.random.default_rng(1).normal(size=(64,)).astype(np.float32) * 0.1
        got = rms_norm_ref(jnp.asarray(x), jnp.asarray(scale), 1e-5)
        want = x / np.sqrt((x**2).mean(-1, keepdims=True) + 1e-5) * (1 + scale)
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-5, atol=1e-5)

    def test_pallas_matches_ref(self):
        x = jnp.asarray(
            np.random.default_rng(0).normal(size=(3, 7, 128)).astype(np.float32)
        )
        scale = jnp.asarray(
            np.random.default_rng(1).normal(size=(128,)).astype(np.float32) * 0.1
        )
        got = rms_norm_pallas(x, scale, 1e-5, True)  # interpret mode
        want = rms_norm_ref(x, scale, 1e-5)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)

    def test_pallas_grad_matches_ref(self):
        x = jnp.asarray(
            np.random.default_rng(0).normal(size=(2, 4, 128)).astype(np.float32)
        )
        scale = jnp.zeros((128,), jnp.float32)

        g1 = jax.grad(lambda x_, s: rms_norm_pallas(x_, s, 1e-5, True).sum(), argnums=(0, 1))(x, scale)
        g2 = jax.grad(lambda x_, s: rms_norm_ref(x_, s, 1e-5).sum(), argnums=(0, 1))(x, scale)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)


class TestRope:
    def test_rotation_preserves_norm(self):
        x = jnp.asarray(
            np.random.default_rng(0).normal(size=(2, 8, 4, 32)).astype(np.float32)
        )
        pos = jnp.broadcast_to(jnp.arange(8, dtype=jnp.int32), (2, 8))
        cos, sin = rope_angles(pos, 32)
        y = apply_rope(x, cos, sin)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(y), axis=-1),
            np.linalg.norm(np.asarray(x), axis=-1),
            rtol=1e-4,
        )

    def test_position_zero_is_identity(self):
        x = jnp.asarray(
            np.random.default_rng(0).normal(size=(1, 1, 2, 16)).astype(np.float32)
        )
        pos = jnp.zeros((1, 1), jnp.int32)
        cos, sin = rope_angles(pos, 16)
        np.testing.assert_allclose(np.asarray(apply_rope(x, cos, sin)), np.asarray(x), rtol=1e-6)

    def test_relative_property(self):
        # <rope(q,m), rope(k,n)> depends only on m-n.
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(size=(1, 1, 1, 32)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 1, 1, 32)).astype(np.float32))

        def dot_at(m, n):
            cm, sm = rope_angles(jnp.array([[m]], jnp.int32), 32)
            cn, sn = rope_angles(jnp.array([[n]], jnp.int32), 32)
            return float(jnp.sum(apply_rope(q, cm, sm) * apply_rope(k, cn, sn)))

        assert dot_at(5, 3) == pytest.approx(dot_at(12, 10), rel=1e-4)


class TestAttention:
    def _naive(self, q, k, v, causal=True):
        b, s, h, d = q.shape
        out = np.zeros_like(q)
        for bi in range(b):
            for hi in range(h):
                logits = q[bi, :, hi] @ k[bi, :, hi].T / np.sqrt(d)
                if causal:
                    mask = np.tril(np.ones((s, s), bool))
                    logits = np.where(mask, logits, -1e30)
                p = np.exp(logits - logits.max(-1, keepdims=True))
                p /= p.sum(-1, keepdims=True)
                out[bi, :, hi] = p @ v[bi, :, hi]
        return out

    def test_ref_matches_naive(self):
        rng = np.random.default_rng(0)
        q, k, v = (rng.normal(size=(2, 16, 4, 32)).astype(np.float32) for _ in range(3))
        got = attention_ref(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True)
        np.testing.assert_allclose(np.asarray(got), self._naive(q, k, v), rtol=1e-4, atol=1e-5)

    def test_gqa_matches_repeated_kv(self):
        rng = np.random.default_rng(0)
        q = rng.normal(size=(2, 16, 8, 32)).astype(np.float32)
        k = rng.normal(size=(2, 16, 2, 32)).astype(np.float32)
        v = rng.normal(size=(2, 16, 2, 32)).astype(np.float32)
        got = attention_ref(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True)
        krep = np.repeat(k, 4, axis=2)
        vrep = np.repeat(v, 4, axis=2)
        np.testing.assert_allclose(
            np.asarray(got), self._naive(q, krep, vrep), rtol=1e-4, atol=1e-5
        )

    def test_window_masking(self):
        rng = np.random.default_rng(0)
        q, k, v = (rng.normal(size=(1, 8, 2, 16)).astype(np.float32) for _ in range(3))
        got = attention_ref(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True, window=1
        )
        # window=1: each token attends only to itself.
        want = jnp.asarray(v)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-4, atol=1e-5)

    def test_decode_positions(self):
        # Single-query decode against a cache must equal the last row of
        # full prefill attention.
        rng = np.random.default_rng(0)
        s = 12
        q = rng.normal(size=(1, s, 2, 16)).astype(np.float32)
        k = rng.normal(size=(1, s, 2, 16)).astype(np.float32)
        v = rng.normal(size=(1, s, 2, 16)).astype(np.float32)
        full = attention_ref(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True)
        last = attention_ref(
            jnp.asarray(q[:, -1:]),
            jnp.asarray(k),
            jnp.asarray(v),
            causal=True,
            q_positions=jnp.array([[s - 1]], jnp.int32),
        )
        np.testing.assert_allclose(
            np.asarray(last[:, 0]), np.asarray(full[:, -1]), rtol=1e-4, atol=1e-5
        )


class TestFlashAttention:
    @pytest.mark.parametrize("seq,heads,kv_heads", [(128, 4, 4), (256, 8, 2)])
    def test_matches_ref(self, seq, heads, kv_heads):
        rng = np.random.default_rng(0)
        d = 128
        q = jnp.asarray(rng.normal(size=(2, seq, heads, d)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(2, seq, kv_heads, d)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, seq, kv_heads, d)).astype(np.float32))
        got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64, interpret=True)
        want = attention_ref(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)

    def test_noncausal_matches_ref(self):
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(1, 128, 2, 128)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 128, 2, 128)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 128, 2, 128)).astype(np.float32))
        got = flash_attention(q, k, v, causal=False, block_q=64, block_k=64, interpret=True)
        want = attention_ref(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4)

    def test_grad_matches_ref(self):
        rng = np.random.default_rng(2)
        q = jnp.asarray(rng.normal(size=(1, 64, 2, 128)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 64, 2, 128)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 64, 2, 128)).astype(np.float32))

        def f_flash(q, k, v):
            return flash_attention(q, k, v, causal=True, block_q=32, block_k=32,
                                   interpret=True).sum()

        def f_ref(q, k, v):
            return attention_ref(q, k, v, causal=True).sum()

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4)

    def test_head_dim_64_matches_ref(self):
        """dh=64: blocks span the full head_dim, Mosaic-legal."""
        rng = np.random.default_rng(7)
        q = jnp.asarray(rng.normal(size=(2, 128, 4, 64)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(2, 128, 2, 64)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, 128, 2, 64)).astype(np.float32))

        def f_flash(q, k, v):
            return flash_attention(
                q, k, v, causal=True, block_q=32, block_k=32, interpret=True
            ).sum()

        def f_ref(q, k, v):
            return attention_ref(q, k, v, causal=True).sum()

        got = flash_attention(
            q, k, v, causal=True, block_q=32, block_k=32, interpret=True
        )
        want = attention_ref(q, k, v, causal=True)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )
        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-4
            )

    @pytest.mark.parametrize("window", [1, 20, 48, 200])
    def test_window_matches_ref(self, window):
        """Sliding windows smaller than, spanning, and exceeding blocks."""
        rng = np.random.default_rng(4)
        q = jnp.asarray(rng.normal(size=(2, 128, 4, 128)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(2, 128, 2, 128)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, 128, 2, 128)).astype(np.float32))
        got = flash_attention(
            q, k, v, causal=True, window=window, block_q=32, block_k=32,
            interpret=True,
        )
        want = attention_ref(q, k, v, causal=True, window=window)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )

    def test_segments_matches_ref(self):
        """Packed documents: block-diagonal masking, incl. a doc boundary
        inside a block and a whole block belonging to one document."""
        rng = np.random.default_rng(5)
        q = jnp.asarray(rng.normal(size=(2, 128, 4, 128)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(2, 128, 2, 128)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, 128, 2, 128)).astype(np.float32))
        seg = jnp.asarray(
            np.concatenate([
                np.repeat([0, 1, 2], [50, 14, 64])[None],
                np.repeat([0, 1], [96, 32])[None],
            ]), jnp.int32,
        )
        got = flash_attention(
            q, k, v, causal=True, segments=seg, block_q=32, block_k=32,
            interpret=True,
        )
        want = attention_ref(
            q, k, v, causal=True, q_segments=seg, kv_segments=seg
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )

    @pytest.mark.parametrize("window,seg_spec", [
        (20, None), (None, "packed"), (24, "packed"),
    ])
    def test_window_segments_grads_match_ref(self, window, seg_spec):
        rng = np.random.default_rng(6)
        q = jnp.asarray(rng.normal(size=(1, 96, 4, 128)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 96, 2, 128)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 96, 2, 128)).astype(np.float32))
        seg = None
        if seg_spec:
            seg = jnp.asarray(
                np.repeat([0, 1, 2], [40, 9, 47])[None], jnp.int32
            )

        def f_flash(q, k, v):
            out = flash_attention(
                q, k, v, causal=True, window=window, segments=seg,
                block_q=32, block_k=32, interpret=True,
            )
            return (out * jnp.arange(out.shape[1])[None, :, None, None]).sum()

        def f_ref(q, k, v):
            out = attention_ref(
                q, k, v, causal=True, window=window,
                q_segments=seg, kv_segments=seg,
            )
            return (out * jnp.arange(out.shape[1])[None, :, None, None]).sum()

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for name, a, b in zip("dq dk dv".split(), g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3,
                err_msg=name,
            )

    @pytest.mark.parametrize("causal", [True, False])
    def test_grad_matches_ref_gqa(self, causal):
        """Backward sums dk/dv over the GQA group in-kernel; check it."""
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.normal(size=(2, 64, 4, 128)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(2, 64, 2, 128)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, 64, 2, 128)).astype(np.float32))

        def f_flash(q, k, v):
            # Non-uniform cotangent so dv/dk aren't trivially symmetric.
            out = flash_attention(q, k, v, causal=causal, block_q=32,
                                  block_k=32, interpret=True)
            return (out * jnp.arange(out.shape[1])[None, :, None, None]).sum()

        def f_ref(q, k, v):
            out = attention_ref(q, k, v, causal=causal)
            return (out * jnp.arange(out.shape[1])[None, :, None, None]).sum()

        g1 = jax.grad(f_flash, argnums=(0, 1, 2))(q, k, v)
        g2 = jax.grad(f_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-3, atol=1e-3
            )


class TestActivations:
    def test_swiglu(self):
        g = jnp.array([1.0, -1.0])
        u = jnp.array([2.0, 3.0])
        got = swiglu(g, u)
        want = (1.0 / (1 + np.exp(-np.array([1.0, -1.0])))) * np.array([1.0, -1.0]) * np.array([2.0, 3.0])
        np.testing.assert_allclose(np.asarray(got), want, rtol=1e-6)

    def test_softcap_bounded(self):
        x = jnp.linspace(-1000, 1000, 101)
        y = softcap(x, 30.0)
        assert float(jnp.max(jnp.abs(y))) <= 30.0


class TestFlashDispatch:
    """flash_supported gating logic on a Pallas-capable backend
    (monkeypatched): what reaches the kernel vs falls back to ref."""

    def _sup(self, monkeypatch, **kw):
        import importlib

        # ops/__init__ re-exports a same-named function, which shadows
        # the submodule on attribute-style imports.
        fa = importlib.import_module("shellac_tpu.ops.flash_attention")
        monkeypatch.setattr(fa, "pallas_supported", lambda: True)
        q = jnp.zeros(kw.pop("q_shape", (2, 256, 8, 128)))
        k = jnp.zeros(kw.pop("kv_shape", (2, 256, 4, 128)))
        return fa.flash_supported(q, k, k, causal=kw.pop("causal", True), **kw)

    def test_plain_causal(self, monkeypatch):
        assert self._sup(monkeypatch)

    def test_window_ok(self, monkeypatch):
        assert self._sup(monkeypatch, window=128)

    def test_segments_ok(self, monkeypatch):
        seg = jnp.zeros((2, 256), jnp.int32)
        assert self._sup(monkeypatch, q_segments=seg, kv_segments=seg)

    def test_window_and_segments_ok(self, monkeypatch):
        seg = jnp.zeros((2, 256), jnp.int32)
        assert self._sup(
            monkeypatch, window=64, q_segments=seg, kv_segments=seg
        )

    def test_distinct_seg_arrays_fall_back(self, monkeypatch):
        a = jnp.zeros((2, 256), jnp.int32)
        b = jnp.zeros((2, 256), jnp.int32)
        assert not self._sup(monkeypatch, q_segments=a, kv_segments=b)

    def test_head_dim_64_ok(self, monkeypatch):
        assert self._sup(
            monkeypatch, q_shape=(2, 256, 8, 64), kv_shape=(2, 256, 4, 64)
        )

    def test_head_dim_96_falls_back(self, monkeypatch):
        assert not self._sup(
            monkeypatch, q_shape=(2, 256, 8, 96), kv_shape=(2, 256, 4, 96)
        )

    def test_positions_fall_back(self, monkeypatch):
        assert not self._sup(
            monkeypatch, q_positions=jnp.zeros((2, 256), jnp.int32)
        )


    def test_noncausal_dispatch_and_segments(self, monkeypatch):
        """Encoder (bidirectional) attention reaches the kernel; packed
        segments compose with it."""
        assert self._sup(monkeypatch, causal=False)
        seg = jnp.zeros((2, 256), jnp.int32)
        assert self._sup(
            monkeypatch, causal=False, q_segments=seg, kv_segments=seg
        )
        assert not self._sup(monkeypatch, causal=False, window=16)

    def test_noncausal_segments_matches_ref(self, monkeypatch):
        rng = np.random.default_rng(12)
        q = jnp.asarray(rng.normal(size=(2, 96, 4, 64)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(2, 96, 2, 64)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, 96, 2, 64)).astype(np.float32))
        seg = jnp.asarray(
            np.repeat([0, 1, 2], [40, 9, 47])[None].repeat(2, 0), jnp.int32
        )
        got = flash_attention(
            q, k, v, causal=False, segments=seg, block_q=32, block_k=32,
            interpret=True,
        )
        want = attention_ref(
            q, k, v, causal=False, q_segments=seg, kv_segments=seg
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-4
        )
