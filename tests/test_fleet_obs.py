"""Fleet observability conformance (ISSUE 11).

Unit level: the shared scrape-side Prometheus parser (labels intact,
labeled histograms no longer garbled, +Inf handled with cumulative
counts), histogram merge, the SLO spec grammar, and the multi-window
burn-rate engine + alert state machine against a synthetic clock.

Tier level (no engines): federation last-known-good through a dead
fake replica, staleness stamps, fresh series on revival.

Live level (tiny real engines): a two-replica tier whose /metrics
federates both replicas' series (step-phase attribution included), a
deliberately slowed replica driving an SLO page transition recorded
in the flight recorder with a violating trace-id exemplar, `top
--once` rendering per-replica rows with non-zero phase attribution,
and the error-response trace-header satellite.

CI: the fleet-obs job (tier-1's wall-clock window never reaches
late-alphabet files); the SIGKILL/readmission twin with real
subprocesses lives in tests/test_tier_chaos.py.
"""

import io
import json
import math
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import jax
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.batching import BatchingEngine
from shellac_tpu.inference.server import InferenceServer, make_http_server
from shellac_tpu.inference.tier import (
    TierRouter,
    make_tier_http_server,
    parse_prometheus,
)
from shellac_tpu.models import transformer
from shellac_tpu.obs import (
    STEP_PHASES,
    FleetCollector,
    FlightRecorder,
    Registry,
    SLOEngine,
    SLOSpec,
    cumulative_at,
    histogram_quantile,
    merge_buckets,
    parse_prometheus_text,
    parse_slo_specs,
)
from shellac_tpu.obs.top import collect, render, run_top


def wait_until(cond, timeout=60.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------
# Shared parser
# ---------------------------------------------------------------------

EXPO = """\
# HELP shellac_ttft_seconds Time to first token
# TYPE shellac_ttft_seconds histogram
shellac_ttft_seconds_bucket{le="0.1"} 6
shellac_ttft_seconds_bucket{le="1"} 9
shellac_ttft_seconds_bucket{le="+Inf"} 12
shellac_ttft_seconds_sum 9.5
shellac_ttft_seconds_count 12
# TYPE shellac_step_phase_seconds histogram
shellac_step_phase_seconds_bucket{phase="admission",le="0.01"} 5
shellac_step_phase_seconds_bucket{phase="admission",le="+Inf"} 5
shellac_step_phase_seconds_sum{phase="admission"} 0.01
shellac_step_phase_seconds_count{phase="admission"} 5
shellac_step_phase_seconds_bucket{phase="decode_sync",le="0.01"} 1
shellac_step_phase_seconds_bucket{phase="decode_sync",le="+Inf"} 5
shellac_step_phase_seconds_sum{phase="decode_sync"} 1.5
shellac_step_phase_seconds_count{phase="decode_sync"} 5
# TYPE shellac_pending_requests gauge
shellac_pending_requests 3
shellac_tier_routed_total{replica="http://r",reason="a b\\"c"} 7
not a sample line
bad{unclosed 1
"""


class TestPromTextParser:
    def test_samples_labels_and_metadata(self):
        p = parse_prometheus_text(EXPO)
        assert p.value("shellac_pending_requests") == 3
        assert p.types["shellac_ttft_seconds"] == "histogram"
        assert "first token" in p.helps["shellac_ttft_seconds"]
        # Labels survive intact, escapes decoded.
        assert p.value("shellac_tier_routed_total",
                       replica="http://r", reason='a b"c') == 7
        # Malformed lines are skipped, not fatal.
        assert p.value("bad") is None

    def test_labeled_histograms_stay_separate(self):
        p = parse_prometheus_text(EXPO)
        adm = p.buckets("shellac_step_phase_seconds", phase="admission")
        syn = p.buckets("shellac_step_phase_seconds", phase="decode_sync")
        assert adm == [(0.01, 5.0), (math.inf, 5.0)]
        assert syn == [(0.01, 1.0), (math.inf, 5.0)]
        # Unfiltered: exact edge-wise sum, not interleaved garbage.
        assert p.buckets("shellac_step_phase_seconds") == [
            (0.01, 6.0), (math.inf, 10.0)
        ]
        s, c = p.histogram_sum_count("shellac_step_phase_seconds",
                                     phase="decode_sync")
        assert (s, c) == (1.5, 5.0)

    def test_label_values(self):
        p = parse_prometheus_text(EXPO)
        assert p.label_values("shellac_step_phase_seconds_bucket",
                              "phase") == ["admission", "decode_sync"]

    def test_legacy_tier_wrapper(self):
        out = parse_prometheus(EXPO)
        assert out["shellac_pending_requests"] == 3
        # The flat view's bucket list is the label-merged histogram —
        # the old splitter produced duplicate edges here.
        assert out["shellac_step_phase_seconds!buckets"] == [
            (0.01, 6.0), (math.inf, 10.0)
        ]


class TestHistogramQuantile:
    def test_empty_and_zero(self):
        assert histogram_quantile([], 0.99) is None
        assert histogram_quantile([(0.1, 0.0), (math.inf, 0.0)],
                                  0.99) is None

    def test_interpolation(self):
        b = [(0.1, 6.0), (1.0, 9.0), (math.inf, 12.0)]
        # p50: target 6 lands exactly at the 0.1 edge.
        assert histogram_quantile(b, 0.5) == pytest.approx(0.1)
        # p0.625: target 7.5 → halfway through (0.1, 1.0].
        assert histogram_quantile(b, 0.625) == pytest.approx(0.55)

    def test_inf_edge_uses_cumulative_total(self):
        b = [(0.1, 6.0), (1.0, 9.0), (math.inf, 12.0)]
        # The TOTAL is the +Inf cum (12), not the last finite cum (9):
        # p90 (target 10.8) lands in the overflow bucket and reports
        # the last finite edge — the honest upper bound.
        assert histogram_quantile(b, 0.9) == 1.0
        # p75 (target 9.0) still resolves inside the finite buckets.
        assert histogram_quantile(b, 0.75) == pytest.approx(1.0)

    def test_cumulative_at(self):
        b = [(0.1, 6.0), (1.0, 9.0), (math.inf, 12.0)]
        assert cumulative_at(b, 0.1) == pytest.approx(6.0)
        assert cumulative_at(b, 0.55) == pytest.approx(7.5)
        # Beyond the last finite edge: the defensible lower bound.
        assert cumulative_at(b, 50.0) == pytest.approx(9.0)
        assert cumulative_at(b, 0.01) == pytest.approx(0.6)

    def test_merge_buckets(self):
        a = [(0.1, 1.0), (math.inf, 2.0)]
        b = [(0.1, 3.0), (math.inf, 4.0)]
        assert merge_buckets([a, b]) == [(0.1, 4.0), (math.inf, 6.0)]


# ---------------------------------------------------------------------
# SLO grammar + burn-rate engine
# ---------------------------------------------------------------------


class TestSLOSpecGrammar:
    def test_latency_forms(self):
        s = SLOSpec.parse("ttft_p99<500ms@99.9")
        assert (s.sli, s.threshold_s, s.percentile_tag) == (
            "ttft", 0.5, "p99")
        assert s.objective == pytest.approx(0.999)
        assert s.budget == pytest.approx(0.001)
        assert SLOSpec.parse("e2e<2s@95").threshold_s == 2.0
        assert SLOSpec.parse("tpot<=50ms@99").threshold_s == 0.05
        assert SLOSpec.parse("queue_wait<100us@90").threshold_s == (
            pytest.approx(1e-4))

    def test_availability(self):
        s = SLOSpec.parse("availability@99.9")
        assert s.sli == "availability" and s.threshold_s is None

    @pytest.mark.parametrize("bad", [
        "ttft@99",                 # latency without threshold
        "availability<1ms@99",     # availability with threshold
        "nope<1ms@99",             # unknown SLI
        "ttft<500ms@100",          # objective must be < 100
        "ttft<500ms@0",            # ... and > 0
        "ttft<500ms",              # no objective
        "",
    ])
    def test_rejects(self, bad):
        with pytest.raises(ValueError):
            SLOSpec.parse(bad)

    def test_duplicate_specs_rejected(self):
        with pytest.raises(ValueError):
            parse_slo_specs(["availability@99", "availability@99"])


EXEMPLAR = "00-" + "a" * 32 + "-" + "b" * 16 + "-01"


def _engine(spec="availability@99", **kw):
    reg = Registry()
    rec = FlightRecorder(registry=reg)
    eng = SLOEngine([SLOSpec.parse(spec)], registry=reg, recorder=rec,
                    exemplar_fn=lambda s: EXEMPLAR, **kw)
    return eng, reg, rec


class TestBurnRateEngine:
    def test_page_transition_and_recovery(self):
        eng, reg, rec = _engine()
        name = "availability@99"
        eng.tick({name: (100, 100)}, now=0.0)
        assert eng.state(name) == "ok"
        # 100 bad events of 100 new: burn = 1.0/0.01 = 100 in BOTH
        # fast windows (the 1h window anchors at the oldest snapshot).
        eng.tick({name: (100, 200)}, now=10.0)
        assert eng.state(name) == "page"
        assert reg.value("shellac_slo_state", slo=name) == 2
        assert reg.value("shellac_slo_transitions_total",
                         slo=name, to="page") == 1
        evs = [e for e in rec.tail(16) if e["event"] == "slo-transition"]
        assert evs and evs[-1]["to"] == "page"
        assert evs[-1]["from"] == "ok"
        assert evs[-1]["exemplar"] == EXEMPLAR
        # Good-only traffic later: the fast pair anchors past the
        # incident and stops burning, but the SLOW pair still sees it
        # — the workbook's de-escalation path: page -> warning.
        eng.tick({name: (1100, 1200)}, now=3700.0)
        eng.tick({name: (2100, 2200)}, now=4300.0)
        assert eng.state(name) == "warning"
        # Once the 3d window no longer covers the incident: ok.
        eng.tick({name: (3100, 3200)}, now=400000.0)
        assert eng.state(name) == "ok"
        assert reg.value("shellac_slo_transitions_total",
                         slo=name, to="ok") == 1

    def test_warning_between_thresholds(self):
        eng, reg, _ = _engine()
        name = "availability@99"
        eng.tick({name: (0, 0)}, now=0.0)
        # bad_frac 0.05 → burn 5: >= 1 on the slow pair (warning),
        # < 14.4 on the fast pair (no page).
        eng.tick({name: (9500, 10000)}, now=10.0)
        assert eng.state(name) == "warning"
        assert reg.value("shellac_slo_state", slo=name) == 1

    def test_counter_reset_reads_as_no_data(self):
        eng, _, _ = _engine()
        name = "availability@99"
        eng.tick({name: (50, 100)}, now=0.0)
        # A replica restart shrank the cumulative counts: clamp, don't
        # page on negative arithmetic.
        eng.tick({name: (10, 20)}, now=10.0)
        assert eng.state(name) == "ok"

    def test_no_traffic_no_burn(self):
        eng, _, _ = _engine()
        name = "availability@99"
        eng.tick({name: (5, 5)}, now=0.0)
        eng.tick({name: (5, 5)}, now=10.0)
        assert eng.state(name) == "ok"

    def test_status_shape(self):
        eng, _, _ = _engine()
        name = "availability@99"
        eng.tick({name: (99, 100)}, now=0.0)
        st = eng.status(now=1.0)
        assert len(st) == 1
        row = st[0]
        assert row["slo"] == name and row["state"] == "ok"
        assert set(row["windows"]) == {"5m", "1h", "6h", "3d"}
        assert row["good_fraction"] == pytest.approx(0.99)


# ---------------------------------------------------------------------
# Federation: collector unit + tier LKG with fake replicas (no jax)
# ---------------------------------------------------------------------

FAKE_METRICS = """\
# TYPE shellac_requests_total counter
shellac_requests_total{outcome="ok"} %d
# TYPE shellac_ttft_seconds histogram
shellac_ttft_seconds_bucket{le="0.1"} 4
shellac_ttft_seconds_bucket{le="+Inf"} 5
shellac_ttft_seconds_sum 1.0
shellac_ttft_seconds_count 5
# TYPE shellac_pending_requests gauge
shellac_pending_requests 2
# TYPE shellac_kv_utilization gauge
shellac_kv_utilization 0.5
"""


class TestFleetCollector:
    def test_lkg_staleness_forget(self):
        fc = FleetCollector(stale_after=60.0)
        fc.observe("http://a", FAKE_METRICS % 7)
        fc.observe("http://b", FAKE_METRICS % 3)
        text = fc.render(routable_count=2)
        assert 'shellac_requests_total{outcome="ok",replica="http://a"} 7' \
            in text
        # One family header however many replicas carry the family.
        assert text.count("# TYPE shellac_requests_total counter") == 1
        assert "shellac_fleet_replicas_routable 2" in text
        assert "shellac_fleet_pending_requests 4" in text
        assert "shellac_fleet_kv_utilization 0.5" in text
        # Merged histogram: edge-wise sums over both replicas.
        p = parse_prometheus_text(text)
        assert p.buckets("shellac_fleet_ttft_seconds") == [
            (0.1, 8.0), (math.inf, 10.0)
        ]
        assert 'shellac_fleet_scrape_stale{replica="http://a"} 0' in text

        # Unreachable: series keep serving (LKG), staleness flips.
        fc.mark_unreachable("http://a")
        text = fc.render()
        assert 'shellac_requests_total{outcome="ok",replica="http://a"} 7' \
            in text
        assert 'shellac_fleet_scrape_stale{replica="http://a"} 1' in text
        # A dead replica holds no pending work.
        assert "shellac_fleet_pending_requests 2" in text

        # Fresh scrape (restarted process, reset counters): overwrites.
        fc.observe("http://a", FAKE_METRICS % 1)
        text = fc.render()
        assert 'shellac_requests_total{outcome="ok",replica="http://a"} 1' \
            in text
        assert 'shellac_fleet_scrape_stale{replica="http://a"} 0' in text

        fc.forget("http://a")
        assert 'replica="http://a"' not in fc.render()

    def test_skip_families_suppresses_header_not_samples(self):
        fc = FleetCollector()
        fc.observe("http://a", FAKE_METRICS % 2)
        text = fc.render(
            skip_families=frozenset({"shellac_requests_total"}))
        assert "# TYPE shellac_requests_total counter" not in text
        assert 'shellac_requests_total{outcome="ok",replica="http://a"}' \
            in text


class _FakeReplica:
    """A metrics/health-only fake replica (no engine): lets the tier
    LKG/staleness path run without jax, and can die and revive on the
    SAME port (allow_reuse_address) like a restarted process."""

    def __init__(self, port=0, ok_count=5):
        fake = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/health":
                    body = json.dumps(
                        {"status": "ok", "pending": 0}).encode()
                elif self.path == "/metrics":
                    body = (FAKE_METRICS % fake.ok_count).encode()
                else:
                    self.send_response(404)
                    self.end_headers()
                    return
                self.send_response(200)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.ok_count = ok_count
        self.httpd = ThreadingHTTPServer(("127.0.0.1", port), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.port = self.httpd.server_address[1]
        self.url = f"http://127.0.0.1:{self.port}"

    def close(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class TestTierFederationLKG:
    def test_dead_replica_serves_lkg_until_revival(self):
        rep = _FakeReplica(ok_count=9)
        other = _FakeReplica(ok_count=1)
        router = TierRouter(
            [rep.url, other.url], registry=Registry(),
            health_interval=0.05, health_timeout=1.0,
            breaker_cooldown=0.2, stale_after=0.5,
        )
        try:
            wait_until(lambda: all(r.state == "healthy"
                                   for r in router.replicas),
                       msg="fakes healthy")
            wait_until(lambda: 'replica="' + rep.url + '"'
                       in router.metrics_text(), msg="federated")
            p = parse_prometheus_text(router.metrics_text())
            assert p.value("shellac_requests_total",
                           replica=rep.url, outcome="ok") == 9

            rep.close()  # process death: scrapes start failing
            wait_until(
                lambda: [r for r in router.replicas
                         if r.url == rep.url][0].state == "ejected",
                msg="dead fake ejected")
            wait_until(
                lambda: parse_prometheus_text(router.metrics_text())
                .value("shellac_fleet_scrape_stale",
                       replica=rep.url) == 1,
                msg="staleness stamped")
            p = parse_prometheus_text(router.metrics_text())
            # Last-known-good: the dead replica's final numbers stay
            # visible, stamped stale with a rising age.
            assert p.value("shellac_requests_total",
                           replica=rep.url, outcome="ok") == 9
            assert p.value("shellac_fleet_scrape_age_seconds",
                           replica=rep.url) > 0

            # Revival on the SAME port with reset counters: the
            # half-open probe readmits it and fresh series replace LKG.
            revived = _FakeReplica(port=rep.port, ok_count=2)
            try:
                wait_until(
                    lambda: [r for r in router.replicas
                             if r.url == rep.url][0].state == "healthy",
                    msg="revived fake readmitted")
                wait_until(
                    lambda: parse_prometheus_text(router.metrics_text())
                    .value("shellac_requests_total",
                           replica=rep.url, outcome="ok") == 2,
                    msg="fresh series after revival")
                p = parse_prometheus_text(router.metrics_text())
                assert p.value("shellac_fleet_scrape_stale",
                               replica=rep.url) == 0
            finally:
                revived.close()
        finally:
            router.close()
            other.close()


# ---------------------------------------------------------------------
# Step-phase attribution (tiny real engine)
# ---------------------------------------------------------------------


def _tiny():
    return get_model_config("tiny").replace(dtype="float32")


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny()
    return cfg, transformer.init_params(cfg, jax.random.PRNGKey(0))


@pytest.mark.slow
class TestStepPhaseAttribution:
    """Marked slow (like the other engine-backed conformance suites):
    tier-1's 870s wall-clock window is dot-count-bound, and these
    build real engines; the fleet-obs CI job runs them unfiltered."""

    @pytest.mark.parametrize("overlap", [False, True])
    def test_phases_observed_and_partition_step(self, tiny_model,
                                                overlap):
        cfg, params = tiny_model
        reg = Registry()
        eng = BatchingEngine(
            cfg, params, n_slots=2, max_len=64, temperature=0.0,
            registry=reg, overlap_decode=overlap,
        )
        for i in range(3):
            eng.submit(i, [1 + i, 2, 3], max_new=4)
        while eng.pending:
            eng.step()
        for phase in STEP_PHASES:
            h = reg.get("shellac_step_phase_seconds", phase=phase)
            assert h is not None and h.count > 0, phase
        # The phases that must have real mass in any serving run.
        for phase in ("prefill_dispatch", "decode_sync"):
            assert reg.get("shellac_step_phase_seconds",
                           phase=phase).sum > 0, phase
        # Flush any window still in flight at drain time (overlap
        # leaves one; settling it is real work and is observed).
        for _ in range(2):
            eng.step()
        # Idle steps are not observed: counts stay put while the
        # engine polls an empty queue.
        before = reg.get("shellac_step_phase_seconds",
                         phase="admission").count
        for _ in range(5):
            eng.step()
        assert reg.get("shellac_step_phase_seconds",
                       phase="admission").count == before


# ---------------------------------------------------------------------
# Live two-replica fleet
# ---------------------------------------------------------------------


class _LocalReplica:
    """In-process replica: a real tiny engine behind a real HTTP
    server, with its own registry so per-replica /metrics stay
    distinct inside one test process."""

    def __init__(self, cfg, params, **srv_kw):
        self.registry = Registry()
        self.srv = InferenceServer(
            cfg, params, registry=self.registry, n_slots=2, max_len=64,
            temperature=0.0, **srv_kw,
        )
        self.httpd = make_http_server(self.srv)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()
        self.srv.close()


def _post(url, payload, timeout=120):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read()), dict(r.headers)


@pytest.fixture(scope="module")
def fleet(tiny_model):
    cfg, params = tiny_model
    reps = [_LocalReplica(cfg, params) for _ in range(2)]
    for rep in reps:
        _post(rep.url + "/generate",
              {"tokens": [1, 2, 3], "max_new": 2, "timeout": 300})
    yield reps
    for rep in reps:
        rep.close()


@pytest.fixture(scope="module")
def tier(fleet):
    router = TierRouter(
        [r.url for r in fleet], registry=Registry(),
        health_interval=0.1, backoff_base=0.02, stale_after=5.0,
        # Pin affinity hard (the chaos-test pattern): a cold-compile
        # TTFT outlier would otherwise make load-aware spill unroute
        # the session keys these tests pin per replica.
        affinity_tolerance=4000.0,
    )
    httpd = make_tier_http_server(router)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    wait_until(lambda: all(r.state == "healthy"
                           for r in router.replicas),
               msg="fleet healthy")
    yield router, base, fleet
    httpd.shutdown()
    router.close()


def _session_for(url, urls):
    """A session key whose rendezvous hash pins traffic onto `url`."""
    return next(
        f"k{i}" for i in range(1000)
        if max(urls, key=lambda u: TierRouter._rendezvous(
            f"s:k{i}", u.rstrip("/"))) == url
    )


@pytest.mark.slow
class TestLiveFleet:
    """Marked slow for the same reason as TestStepPhaseAttribution:
    two live engines + a tier; the fleet-obs CI job runs it."""

    def test_federated_metrics_with_step_phases(self, tier):
        router, base, fleet = tier
        urls = [r.url for r in fleet]
        # Traffic pinned to EACH replica so both expose live series.
        for u in urls:
            sess = _session_for(u, urls)
            for i in range(2):
                out, _ = _post(base + "/generate",
                               {"tokens": [1 + i, 2, 3], "max_new": 3,
                                "session": sess, "timeout": 120})
                assert out["tokens"]

        def federated():
            p = parse_prometheus_text(router.metrics_text())
            return all(
                (p.value("shellac_requests_total",
                         replica=u, outcome="ok") or 0) >= 2
                for u in urls
            )

        wait_until(federated, msg="both replicas federated")
        with urllib.request.urlopen(base + "/metrics", timeout=10) as r:
            text = r.read().decode()
        p = parse_prometheus_text(text)
        for u in urls:
            # Replica-labeled series on the TIER's exposition.
            assert p.value("shellac_fleet_scrape_stale",
                           replica=u) == 0
            assert p.buckets("shellac_ttft_seconds", replica=u)
            # Step-phase attribution flows through federation.
            assert (p.value("shellac_step_phase_seconds_count",
                            replica=u, phase="decode_sync") or 0) > 0
        # Fleet aggregates: merged TTFT histogram counts both replicas.
        fleet_b = p.buckets("shellac_fleet_ttft_seconds")
        assert fleet_b and fleet_b[-1][1] >= 4
        # The exposition stays format-sane: one TYPE header per family.
        assert text.count("# TYPE shellac_ttft_seconds histogram") == 1

    def test_top_once_renders_fleet(self, tier):
        router, base, fleet = tier
        buf = io.StringIO()
        assert run_top(base, once=True, out=buf) == 0
        text = buf.getvalue()
        assert "shellac top" in text
        assert "2/2 routable" in text
        for rep in fleet:
            assert rep.url.replace("http://", "")[-20:] in text
        # Per-replica rows render a non-zero step-phase attribution.
        assert "step-time attribution" in text
        assert any(
            f"{tag} " in text for tag in ("sync", "pf")
        )
        snap = collect(base)
        rendered = render(snap)
        assert "p99" in rendered or "fleet p99" in rendered

    def test_top_trace_drilldown(self, tier):
        router, base, _ = tier
        out, headers = _post(base + "/generate",
                             {"tokens": [9, 9], "max_new": 2,
                              "timeout": 120})
        tid = headers.get("x-request-id")
        assert tid
        buf = io.StringIO()
        assert run_top(base, trace=tid, out=buf) == 0
        text = buf.getvalue()
        assert tid in text and "tier-attempt" in text
        # Unknown trace: graceful non-zero exit.
        buf = io.StringIO()
        assert run_top(base, trace="00-" + "0" * 32 + "-" + "0" * 16
                       + "-01", out=buf) == 1

    def test_error_responses_carry_request_id(self, tier):
        router, base, fleet = tier
        # Tier: malformed JSON 400, unknown route 404.
        for url, data in ((base + "/generate", b"{nope"),
                          (base + "/nowhere", b"{}")):
            req = urllib.request.Request(
                url, data=data,
                headers={"Content-Type": "application/json"})
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=10)
            assert e.value.headers.get("x-request-id"), url
        # Replica server: unknown POST route and GET debug miss.
        rep = fleet[0]
        req = urllib.request.Request(rep.url + "/nowhere", data=b"{}")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=10)
        assert e.value.code == 404
        assert e.value.headers.get("x-request-id")
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(
                rep.url + "/debug/request/unknown-id", timeout=10)
        assert e.value.code == 404
        assert e.value.headers.get("x-request-id")

    def test_slowed_replica_drives_slo_page_with_exemplar(self, fleet):
        from shellac_tpu.inference.autotune import SimulatedHostLatency

        a, b = fleet
        urls = [a.url, b.url]
        # Deliberately slow replica B's decode windows (the simulated-
        # RPC shim PR 7 built): its requests blow the e2e objective.
        shim = SimulatedHostLatency(b.srv.engine, device_s=0.4)
        router = TierRouter(
            urls, registry=Registry(), health_interval=0.1,
            slos=["e2e<250ms@99", "availability@90"],
            # Affinity pinned hard so traffic deterministically lands
            # on the deliberately slowed replica (chaos-test pattern).
            affinity_tolerance=4000.0,
        )
        try:
            wait_until(lambda: all(r.state == "healthy"
                                   for r in router.replicas),
                       msg="fleet healthy")
            sess = _session_for(b.url, urls)
            for i in range(4):
                status, body, _ = router.forward_json(
                    "/generate",
                    {"tokens": [2 + i, 3], "max_new": 2,
                     "session": sess, "timeout": 120},
                )
                assert status == 200, body
            wait_until(
                lambda: router._slo.state("e2e<250ms@99") == "page",
                timeout=30, msg="burn-rate page on the slowed replica")
            # Availability stayed clean: every request succeeded.
            assert router._slo.state("availability@90") == "ok"
            # The transition landed in the flight recorder with a
            # violating request's trace id as exemplar...
            evs = [e for e in router.recorder.tail(512)
                   if e["event"] == "slo-transition"
                   and e.get("to") == "page"]
            assert evs, "no slo-transition event recorded"
            exemplar = evs[-1].get("exemplar")
            assert exemplar, evs[-1]
            # ... and the exemplar resolves to a real tier timeline.
            timeline = router.debug_request(exemplar)
            assert timeline is not None
            assert any(e["event"] == "tier-attempt"
                       for e in timeline["events"])
            # Gauges + /slo agree.
            assert router._registry.value(
                "shellac_slo_state", slo="e2e<250ms@99") == 2
            status = router.slo_status()
            row = next(s for s in status["slos"]
                       if s["slo"] == "e2e<250ms@99")
            assert row["state"] == "page"
            assert row["windows"]["5m"]["burn_rate"] > 14.4
        finally:
            shim.uninstall()
            router.close()
