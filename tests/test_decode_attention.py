"""Parity tests for the Pallas decode-attention kernels (interpret mode).

The kernels are graded against the masked reference path that the
engines used before: identical semantics (causal vs per-row positions
derived from cache lengths, optional sliding window, garbage beyond the
valid length ignored) across GQA, ragged lengths, s=1 and small-s
decode. Paged variants walk a shuffled block table. Caches are
head-major: dense (B, Hkv, L, D), pools (nb, Hkv, bs, D) — see
kvcache.py. Compiled-mode parity runs on the chip via
scripts/tpu_parity_decode.py (driven by tests/test_tpu_parity.py).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu.ops.decode_attention import (
    _decode_ref,
    decode_attention,
    paged_decode_attention,
)

B, L, H, HKV, D = 3, 128, 8, 4, 128


def _rand(key, shape):
    return jax.random.normal(key, shape, jnp.float32)


@pytest.mark.parametrize("s", [1, 4])
@pytest.mark.parametrize("window", [None, 20])
def test_dense_decode_matches_ref(s, window):
    ks = jax.random.split(jax.random.PRNGKey(s * 7 + (window or 0)), 3)
    q = _rand(ks[0], (B, s, H, D))
    ck = _rand(ks[1], (B, HKV, L, D))
    cv = _rand(ks[2], (B, HKV, L, D))
    index = jnp.array([0, 37, L - s], jnp.int32)  # empty, mid, full

    ref = _decode_ref(q, ck, cv, index, window, D ** -0.5)
    out = decode_attention(
        q, ck, cv, index, window=window, impl="flash", block_k=64,
        interpret=True,
    )
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_dense_decode_mha_no_gqa():
    ks = jax.random.split(jax.random.PRNGKey(9), 3)
    q = _rand(ks[0], (2, 1, 4, D))
    ck = _rand(ks[1], (2, 4, L, D))
    cv = _rand(ks[2], (2, 4, L, D))
    index = jnp.array([5, 99], jnp.int32)
    ref = _decode_ref(q, ck, cv, index, None, D ** -0.5)
    out = decode_attention(
        q, ck, cv, index, impl="flash", block_k=64, interpret=True
    )
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_dense_decode_ignores_garbage_tail():
    """Slots beyond index+s must not leak into the output."""
    ks = jax.random.split(jax.random.PRNGKey(3), 3)
    q = _rand(ks[0], (1, 1, H, D))
    ck = _rand(ks[1], (1, HKV, L, D))
    cv = _rand(ks[2], (1, HKV, L, D))
    index = jnp.array([10], jnp.int32)
    out1 = decode_attention(
        q, ck, cv, index, impl="flash", block_k=64, interpret=True
    )
    poison = jnp.full_like(ck[:, :, 11:], 1e4)
    ck2 = ck.at[:, :, 11:].set(poison)
    cv2 = cv.at[:, :, 11:].set(poison)
    out2 = decode_attention(
        q, ck2, cv2, index, impl="flash", block_k=64, interpret=True
    )
    np.testing.assert_allclose(out1, out2, atol=1e-6)


@pytest.mark.parametrize("s", [1, 3])
@pytest.mark.parametrize("window", [None, 20])
def test_paged_decode_matches_dense(s, window):
    """Paged kernel through a shuffled table == dense ref on the same kv."""
    bs = 16
    n_blocks = (L // bs) * B + 1  # + scratch block 0
    max_blocks = L // bs
    ks = jax.random.split(jax.random.PRNGKey(s * 5 + (window or 0)), 3)
    q = _rand(ks[0], (B, s, H, D))
    dense_k = _rand(ks[1], (B, L, HKV, D))
    dense_v = _rand(ks[2], (B, L, HKV, D))
    index = jnp.array([0, 37, L - s], jnp.int32)

    # Scatter the dense cache into a shuffled pool.
    rng = np.random.default_rng(0)
    ids = rng.permutation(np.arange(1, n_blocks))
    tables = ids.reshape(B, max_blocks)
    pool_k = np.zeros((n_blocks, HKV, bs, D), np.float32)
    pool_v = np.zeros((n_blocks, HKV, bs, D), np.float32)
    dkn = np.asarray(dense_k).transpose(0, 2, 1, 3)  # (B, HKV, L, D)
    dvn = np.asarray(dense_v).transpose(0, 2, 1, 3)
    for b in range(B):
        for j in range(max_blocks):
            pool_k[tables[b, j]] = dkn[b, :, j * bs:(j + 1) * bs]
            pool_v[tables[b, j]] = dvn[b, :, j * bs:(j + 1) * bs]

    ref = _decode_ref(
        q, dense_k.transpose(0, 2, 1, 3), dense_v.transpose(0, 2, 1, 3),
        index, window, D ** -0.5,
    )
    out = paged_decode_attention(
        q, jnp.asarray(pool_k), jnp.asarray(pool_v), jnp.asarray(tables),
        index, window=window, impl="flash", interpret=True,
    )
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def _scatter_pool(dense_k, dense_v, bs, shuffle_seed=0):
    """Scatter head-major dense caches (B, HKV, L, D) into a shuffled
    pool + tables (block 0 reserved scratch)."""
    b, hkv, l, d = dense_k.shape
    max_blocks = l // bs
    n_blocks = b * max_blocks + 1
    rng = np.random.default_rng(shuffle_seed)
    ids = rng.permutation(np.arange(1, n_blocks))
    tables = ids.reshape(b, max_blocks)
    pool_k = np.zeros((n_blocks, hkv, bs, d), np.float32)
    pool_v = np.zeros((n_blocks, hkv, bs, d), np.float32)
    dkn, dvn = np.asarray(dense_k), np.asarray(dense_v)
    for bi in range(b):
        for j in range(max_blocks):
            pool_k[tables[bi, j]] = dkn[bi, :, j * bs:(j + 1) * bs]
            pool_v[tables[bi, j]] = dvn[bi, :, j * bs:(j + 1) * bs]
    return (jnp.asarray(pool_k), jnp.asarray(pool_v),
            jnp.asarray(tables, jnp.int32))


@pytest.mark.parametrize("window", [None, 100])
def test_paged_grouped_multi_group(window):
    """Grouped gather across num_groups > 1: the cross-group online-
    softmax carry, per-page liveness (zeroed dead pages), and windowed
    first-page skipping must all match the dense ref. The small-table
    tests only ever hit num_groups == 1."""
    from shellac_tpu.ops.decode_attention import _paged_group

    big_l, bs = 2048, 16
    ks = jax.random.split(jax.random.PRNGKey(31), 3)
    q = _rand(ks[0], (2, 1, H, D))
    dense_k = _rand(ks[1], (2, HKV, big_l, D))
    dense_v = _rand(ks[2], (2, HKV, big_l, D))
    # One short slot (first group boundary) and one near the end.
    index = jnp.array([7, big_l - 1], jnp.int32)
    pool_k, pool_v, tables = _scatter_pool(dense_k, dense_v, bs)
    assert tables.shape[1] // _paged_group(tables, pool_k) > 1

    ref = _decode_ref(q, dense_k, dense_v, index, window, D ** -0.5)
    out = paged_decode_attention(
        q, pool_k, pool_v, tables, index, window=window, impl="flash",
        interpret=True,
    )
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_paged_one_page_kernel_pinned():
    """The one-page kernel stays correct for 128-aligned head dims
    (it is the fallback when grouping cannot divide the table)."""
    from shellac_tpu.ops.decode_attention import _paged_flash

    bs = 16
    ks = jax.random.split(jax.random.PRNGKey(33), 3)
    q = _rand(ks[0], (2, 1, H, D))
    dense_k = _rand(ks[1], (2, HKV, L, D))
    dense_v = _rand(ks[2], (2, HKV, L, D))
    index = jnp.array([5, L - 1], jnp.int32)
    pool_k, pool_v, tables = _scatter_pool(dense_k, dense_v, bs)
    ref = _decode_ref(q, dense_k, dense_v, index, None, D ** -0.5)
    out = _paged_flash(
        q, pool_k, pool_v, tables, index, D ** -0.5, None, True
    )
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


def test_paged_group_respects_sublane_tiling():
    """bs=8 bf16 pools must take the one-page kernel (a grouped gather
    would land pages at sublane offset 8 of a 16-tiled bf16 VMEM tile,
    which Mosaic rejects compiled)."""
    from shellac_tpu.ops.decode_attention import _paged_group

    tables = jnp.zeros((2, 64), jnp.int32)
    assert _paged_group(tables, jnp.zeros((9, 4, 8, 128), jnp.bfloat16)) == 1
    assert _paged_group(tables, jnp.zeros((9, 4, 16, 128), jnp.bfloat16)) > 1
    assert _paged_group(tables, jnp.zeros((9, 4, 8, 128), jnp.float32)) > 1
    assert _paged_group(tables, jnp.zeros((9, 4, 16, 128), jnp.int8)) == 1


def test_auto_falls_back_to_ref_off_tpu():
    """impl='auto' off-TPU must take the ref path bit-for-bit."""
    ks = jax.random.split(jax.random.PRNGKey(1), 3)
    q = _rand(ks[0], (B, 1, H, D))
    ck = _rand(ks[1], (B, HKV, L, D))
    cv = _rand(ks[2], (B, HKV, L, D))
    index = jnp.array([4, 9, 50], jnp.int32)
    auto = decode_attention(q, ck, cv, index, impl="auto")
    ref = _decode_ref(q, ck, cv, index, None, D ** -0.5)
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(ref))


def test_flash_rejects_bad_head_dim():
    # dh must be a multiple of 64 (dh=64 itself IS supported).
    q = jnp.zeros((1, 1, 4, 96))
    ck = jnp.zeros((1, 4, 64, 96))
    with pytest.raises(ValueError, match="unsupported"):
        decode_attention(q, ck, ck, jnp.zeros((1,), jnp.int32), impl="flash")


@pytest.mark.parametrize("paged", [False, True])
def test_head_dim_64_matches_ref(paged):
    """dh=64 models (Qwen2-0.5B class) run the kernels natively."""
    d64 = 64
    ks = jax.random.split(jax.random.PRNGKey(21), 3)
    q = _rand(ks[0], (2, 1, H, d64))
    if not paged:
        ck = _rand(ks[1], (2, HKV, L, d64))
        cv = _rand(ks[2], (2, HKV, L, d64))
        index = jnp.array([9, 77], jnp.int32)
        ref = _decode_ref(q, ck, cv, index, None, d64 ** -0.5)
        out = decode_attention(
            q, ck, cv, index, impl="flash", block_k=64, interpret=True
        )
    else:
        bs = 16
        max_blocks = L // bs
        n_blocks = 2 * max_blocks + 1
        dense_k = _rand(ks[1], (2, HKV, L, d64))
        dense_v = _rand(ks[2], (2, HKV, L, d64))
        index = jnp.array([9, 77], jnp.int32)
        tables = np.arange(1, n_blocks).reshape(2, max_blocks)
        pool_k = np.zeros((n_blocks, HKV, bs, d64), np.float32)
        pool_v = np.zeros((n_blocks, HKV, bs, d64), np.float32)
        for b in range(2):
            for j in range(max_blocks):
                pool_k[tables[b, j]] = np.asarray(dense_k)[b, :, j*bs:(j+1)*bs]
                pool_v[tables[b, j]] = np.asarray(dense_v)[b, :, j*bs:(j+1)*bs]
        ref = _decode_ref(q, dense_k, dense_v, index, None, d64 ** -0.5)
        out = paged_decode_attention(
            q, jnp.asarray(pool_k), jnp.asarray(pool_v),
            jnp.asarray(tables, jnp.int32), index, impl="flash",
            interpret=True,
        )
    np.testing.assert_allclose(out, ref, atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize(
    "bs,d", [(12, 128), (32, 96)],
    ids=["bad_page_size", "bad_head_dim"],
)
def test_paged_quant_fallback_warns_on_tpu_like_backend(monkeypatch, bs, d):
    """Int8 pools still WANT the kernel under auto (the ref fallback
    dequantizes gathered pages every tick), so silently losing it to a
    disqualifying shape must surface a PagedFallbackWarning."""
    import shellac_tpu.ops.decode_attention as da

    monkeypatch.setattr(da, "pallas_supported", lambda: True)
    n_blocks, max_blocks = 5, 4
    q = jnp.zeros((1, 1, 4, d))
    pool = jnp.zeros((n_blocks, 4, bs, d), jnp.int8)
    scale = jnp.ones((n_blocks, 4, bs), jnp.float32)
    tables = jnp.arange(1, 1 + max_blocks, dtype=jnp.int32)[None, :]
    index = jnp.zeros((1,), jnp.int32)
    with pytest.warns(da.PagedFallbackWarning, match="falling"):
        da.paged_decode_attention(
            q, pool, pool, tables, index, interpret=True,
            k_scale=scale, v_scale=scale,
        )


def test_paged_bf16_auto_prefers_reference(monkeypatch):
    """bf16 pools default to the XLA reference under auto even on a
    Pallas-capable backend (the grouped-gather kernel has never beaten
    it on hardware — BENCH_DECODE), and that is a decision, not a
    fallback: no warning."""
    import warnings as _w

    import shellac_tpu.ops.decode_attention as da

    monkeypatch.setattr(da, "pallas_supported", lambda: True)
    q = jnp.zeros((1, 1, 4, 128))
    pool = jnp.zeros((5, 4, 16, 128))
    tables = jnp.arange(1, 5, dtype=jnp.int32)[None, :]
    index = jnp.zeros((1,), jnp.int32)
    with _w.catch_warnings():
        _w.simplefilter("error", da.PagedFallbackWarning)
        da.paged_decode_attention(q, pool, pool, tables, index,
                                  interpret=True)


def test_paged_supported_shapes_do_not_warn():
    import warnings as _w

    import shellac_tpu.ops.decode_attention as da

    q = jnp.zeros((1, 1, 4, 128))
    pool = jnp.zeros((5, 4, 16, 128))
    tables = jnp.arange(1, 5, dtype=jnp.int32)[None, :]
    index = jnp.zeros((1,), jnp.int32)
    with _w.catch_warnings():
        _w.simplefilter("error", da.PagedFallbackWarning)
        # Off-TPU: pallas_supported() is False, so no warning and the
        # ref path runs.
        da.paged_decode_attention(q, pool, pool, tables, index)
