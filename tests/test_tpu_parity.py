"""Drive the TPU-compiled Pallas parity gate when a chip is reachable.

The suite itself pins the CPU platform (conftest.py), so the compiled
kernels are exercised in a subprocess that initializes the TPU backend
fresh. Off-TPU (or with a wedged relay) the test skips rather than
fails: the gate's job is to stop compiled-only regressions from
landing silently when hardware IS available — interpret-mode tests
cover the math everywhere else.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "tpu_parity_decode.py")


def _tpu_usable(timeout_s: float = 45.0) -> bool:
    code = "import jax; assert jax.default_backend() == 'tpu'"
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    try:
        r = subprocess.run(
            [sys.executable, "-c", code], timeout=timeout_s,
            capture_output=True, env=env,
        )
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


@pytest.mark.skipif(
    os.environ.get("SHELLAC_SKIP_TPU_PARITY") == "1",
    reason="explicitly disabled",
)
def test_compiled_kernels_match_ref_on_tpu():
    if not _tpu_usable():
        pytest.skip("no TPU backend reachable from a fresh subprocess")
    env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
    r = subprocess.run(
        [sys.executable, SCRIPT], timeout=560, capture_output=True,
        text=True, env=env,
    )
    assert r.returncode == 0, f"parity gate failed:\n{r.stdout}\n{r.stderr}"
    line = r.stdout.strip().splitlines()[-1]
    result = json.loads(line)
    assert result["ok"], result
    # Every case family must have run.
    joined = " ".join(result["checks"])
    for family in ("dense", "paged", "flash fwd", "flash bwd"):
        assert family in joined, f"missing {family}: {result['checks']}"
