"""Encoder (bidirectional) family and shared-expert MoE."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.config import TrainConfig
from shellac_tpu.models import transformer
from shellac_tpu.training import init_train_state, make_train_step
from shellac_tpu.training.losses import cross_entropy, mlm_mask_tokens


def _enc(**kw):
    return get_model_config("tiny-encoder").replace(dtype="float32", **kw)


class TestEncoder:
    def test_bidirectional_information_flow(self):
        """Changing a FUTURE token must change PAST logits (no causality)."""
        cfg = _enc()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        toks = jax.random.randint(jax.random.PRNGKey(1), (1, 16), 0,
                                  cfg.vocab_size)
        l1 = transformer.forward(cfg, params, toks)
        toks2 = toks.at[0, 12].set((toks[0, 12] + 1) % cfg.vocab_size)
        l2 = transformer.forward(cfg, params, toks2)
        assert not np.allclose(np.asarray(l1[0, :12]), np.asarray(l2[0, :12]))

    def test_cache_generation_rejected(self):
        cfg = _enc()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        from shellac_tpu.inference.kvcache import init_cache

        cache = init_cache(cfg, 1, 32)
        with pytest.raises(ValueError, match="causal"):
            transformer.forward_with_cache(
                cfg, params, jnp.ones((1, 4), jnp.int32), cache
            )

    def test_mlm_training_loss_decreases(self):
        cfg = _enc()
        tcfg = TrainConfig(warmup_steps=1, total_steps=100, learning_rate=3e-3)
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        from shellac_tpu.training.optimizer import make_optimizer
        import optax

        opt = make_optimizer(tcfg)
        opt_state = opt.init(params)
        toks = jnp.asarray(
            np.tile(np.arange(64, dtype=np.int32) % 97, (4, 1))
        )
        mask_id = cfg.vocab_size - 1

        @jax.jit
        def step(params, opt_state, key):
            corrupted, lmask = mlm_mask_tokens(
                key, toks, mask_id=mask_id, vocab_size=cfg.vocab_size
            )

            def loss_fn(p):
                logits = transformer.forward(cfg, p, corrupted)
                loss, _ = cross_entropy(logits, toks, lmask)
                return loss

            loss, grads = jax.value_and_grad(loss_fn)(params)
            updates, opt_state = opt.update(grads, opt_state, params)
            return optax.apply_updates(params, updates), opt_state, loss

        key = jax.random.PRNGKey(0)
        losses = []
        for i in range(30):
            key, sub = jax.random.split(key)
            params, opt_state, loss = step(params, opt_state, sub)
            losses.append(float(loss))
        assert np.mean(losses[-5:]) < np.mean(losses[:5])

    def test_mlm_mask_fractions(self):
        toks = jnp.zeros((64, 64), jnp.int32) + 7
        corrupted, mask = mlm_mask_tokens(
            jax.random.PRNGKey(0), toks, mask_id=255, vocab_size=256
        )
        frac = float(mask.mean())
        assert 0.10 < frac < 0.20
        # Of selected positions, ~80% should be the mask id.
        sel = np.asarray(mask) > 0
        masked_frac = (np.asarray(corrupted)[sel] == 255).mean()
        assert 0.7 < masked_frac < 0.9


class TestSharedExperts:
    def test_params_and_forward(self):
        cfg = get_model_config("tiny-moe-shared").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        assert "w_gate_shared" in params["layers"]
        assert params["layers"]["w_gate_shared"].shape == (
            cfg.n_layers, cfg.d_model, cfg.ff_dim
        )
        toks = jnp.ones((2, 16), jnp.int32)
        logits = transformer.forward(cfg, params, toks)
        assert np.isfinite(np.asarray(logits)).all()

    def test_shared_path_contributes(self):
        cfg = get_model_config("tiny-moe-shared").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        toks = jnp.ones((1, 8), jnp.int32)
        l1 = transformer.forward(cfg, params, toks)
        zeroed = dict(params)
        zeroed["layers"] = dict(params["layers"])
        zeroed["layers"]["w_down_shared"] = jnp.zeros_like(
            params["layers"]["w_down_shared"]
        )
        l2 = transformer.forward(cfg, zeroed, toks)
        assert not np.allclose(np.asarray(l1), np.asarray(l2))

    def test_axes_match(self):
        cfg = get_model_config("tiny-moe-shared")
        params = jax.eval_shape(
            lambda: transformer.init_params(cfg, jax.random.PRNGKey(0))
        )
        axes = transformer.logical_axes(cfg)
        flat_p = jax.tree_util.tree_flatten_with_path(params)[0]
        flat_a = jax.tree_util.tree_flatten_with_path(
            axes, is_leaf=lambda x: isinstance(x, tuple)
        )[0]
        paths_p = {tuple(str(k) for k in p): leaf.ndim for p, leaf in flat_p}
        paths_a = {tuple(str(k) for k in p): len(leaf) for p, leaf in flat_a}
        assert paths_p == paths_a

    def test_train_step(self, mesh8):
        # fsdp=1 in this mesh: the experts axis (4) must divide the mesh
        # axis it shards over.
        cfg = get_model_config("tiny-moe-shared").replace(dtype="float32")
        tcfg = TrainConfig(warmup_steps=1, total_steps=10)
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0),
                                 mesh=mesh8)
        step = make_train_step(cfg, tcfg, mesh=mesh8)
        toks = np.ones((8, 32), np.int32)
        state, metrics = step(state, {"inputs": toks, "targets": toks})
        assert np.isfinite(float(metrics["loss"]))
