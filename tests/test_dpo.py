"""DPO preference fine-tuning.

Synthetic task: prompts of the form [P, a, b] with chosen completion
[a, a] and rejected [b, b]. After a few DPO steps the policy must rank
chosen above rejected (accuracy -> 1, positive reward margin) and — the
end-to-end check — greedy generation from the prompt must emit the
chosen continuation.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu.config import ModelConfig, TrainConfig
from shellac_tpu.models.transformer import init_params
from shellac_tpu.training.dpo import (
    DPOConfig,
    dpo_loss,
    make_dpo_step,
    sequence_logprobs,
)
from shellac_tpu.training.trainer import init_train_state


def _cfg():
    return ModelConfig(
        vocab_size=64, d_model=64, n_layers=2, n_heads=4,
        max_seq_len=32, dtype="float32", remat=False,
    ).validate()


def _pref_batch(b=8, seed=0):
    """[P, x, y | x, x] chosen vs [P, x, y | y, y] rejected."""
    rng = np.random.RandomState(seed)
    prompts = rng.randint(0, 64, (b, 3))
    chosen = np.concatenate(
        [prompts, prompts[:, 1:2], prompts[:, 1:2]], axis=1
    )
    rejected = np.concatenate(
        [prompts, prompts[:, 2:3], prompts[:, 2:3]], axis=1
    )
    mask = np.zeros((b, 5), np.float32)
    mask[:, 3:] = 1.0  # completion targets only
    return {
        "chosen": jnp.asarray(chosen, jnp.int32),
        "rejected": jnp.asarray(rejected, jnp.int32),
        "chosen_mask": jnp.asarray(mask),
        "rejected_mask": jnp.asarray(mask),
    }


def test_dpo_config_validation():
    with pytest.raises(ValueError, match="loss_type"):
        DPOConfig(loss_type="banana").validate()
    with pytest.raises(ValueError, match="label_smoothing"):
        DPOConfig(label_smoothing=0.7).validate()
    with pytest.raises(ValueError, match="sigmoid"):
        DPOConfig(loss_type="ipo", label_smoothing=0.1).validate()
    with pytest.raises(ValueError, match="beta"):
        DPOConfig(beta=0.0).validate()


def test_dpo_loss_values():
    """Hand-computed sigmoid loss on scalars."""
    pc = jnp.array([1.0])
    pr = jnp.array([0.0])
    rc = jnp.array([0.5])
    rr = jnp.array([0.2])
    cfg = DPOConfig(beta=2.0)
    loss, metrics = dpo_loss(pc, pr, rc, rr, cfg)
    h = (1.0 - 0.5) - (0.0 - 0.2)  # 0.7
    expect = -np.log(1.0 / (1.0 + np.exp(-2.0 * h)))
    np.testing.assert_allclose(float(loss), expect, rtol=1e-6)
    np.testing.assert_allclose(float(metrics["reward_margin"]), 2.0 * h,
                               rtol=1e-6)
    assert float(metrics["accuracy"]) == 1.0
    # ipo: squared distance from the 1/(2 beta) margin
    loss_ipo, _ = dpo_loss(pc, pr, rc, rr, DPOConfig(beta=2.0,
                                                     loss_type="ipo"))
    np.testing.assert_allclose(float(loss_ipo), (h - 0.25) ** 2, rtol=1e-6)


def test_sequence_logprobs_mask():
    """Masked positions contribute exactly their token log-prob."""
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    toks = jnp.asarray([[5, 9, 2, 31, 7]], jnp.int32)
    full_mask = jnp.ones((1, 5), jnp.float32)
    tail_mask = jnp.asarray([[0, 0, 0, 1, 1]], jnp.float32)
    lp_full = sequence_logprobs(cfg, params, toks, full_mask)
    lp_tail = sequence_logprobs(cfg, params, toks, tail_mask)
    head_mask = jnp.asarray([[0, 1, 1, 0, 0]], jnp.float32)
    lp_head = sequence_logprobs(cfg, params, toks, head_mask)
    np.testing.assert_allclose(
        np.asarray(lp_full), np.asarray(lp_tail) + np.asarray(lp_head),
        rtol=1e-5,
    )
    assert float(lp_full[0]) < 0.0


@pytest.mark.parametrize("loss_type", ["sigmoid", "ipo", "hinge"])
def test_dpo_training_learns_preference(loss_type):
    cfg = _cfg()
    tcfg = TrainConfig(learning_rate=5e-4, warmup_steps=0, total_steps=60)
    state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
    ref_params = jax.tree.map(jnp.copy, state.params)
    step = make_dpo_step(cfg, tcfg, DPOConfig(beta=0.5,
                                              loss_type=loss_type))
    batch = _pref_batch()
    state, m0 = step(state, ref_params, batch)
    for _ in range(40):
        state, m = step(state, ref_params, batch)
    assert float(m["loss"]) < float(m0["loss"])
    assert float(m["accuracy"]) == 1.0
    assert float(m["reward_margin"]) > 0.0
    assert float(m["reward_chosen"]) > float(m["reward_rejected"])


def test_dpo_reference_free():
    cfg = _cfg()
    tcfg = TrainConfig(learning_rate=5e-4, warmup_steps=0, total_steps=60)
    state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
    step = make_dpo_step(
        cfg, tcfg, DPOConfig(beta=0.5, reference_free=True)
    )
    batch = _pref_batch()
    for _ in range(30):
        state, m = step(state, None, batch)
    assert float(m["accuracy"]) == 1.0


def test_dpo_generation_prefers_chosen():
    """End-to-end: after DPO the greedy decode emits the chosen
    continuation for every training prompt."""
    from shellac_tpu.inference.engine import Engine

    cfg = _cfg()
    tcfg = TrainConfig(learning_rate=1e-3, warmup_steps=0, total_steps=80)
    state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
    ref_params = jax.tree.map(jnp.copy, state.params)
    step = make_dpo_step(cfg, tcfg, DPOConfig(beta=0.5))
    batch = _pref_batch(b=4, seed=3)
    for _ in range(70):
        state, m = step(state, ref_params, batch)
    eng = Engine(cfg, state.params, temperature=0.0, max_len=16)
    out = eng.generate(batch["chosen"][:, :3], max_new_tokens=2)
    np.testing.assert_array_equal(
        np.asarray(out.tokens), np.asarray(batch["chosen"][:, 3:])
    )


def test_dpo_sharded_matches_unsharded():
    from shellac_tpu.config import ParallelConfig
    from shellac_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-device CPU mesh")
    cfg = _cfg()
    tcfg = TrainConfig(learning_rate=5e-4, warmup_steps=0, total_steps=60)
    key = jax.random.PRNGKey(0)
    batch = _pref_batch()
    dcfg = DPOConfig(beta=0.5)

    state_u = init_train_state(cfg, tcfg, key)
    ref_u = jax.tree.map(jnp.copy, state_u.params)
    step_u = make_dpo_step(cfg, tcfg, dcfg)
    for _ in range(3):
        state_u, mu = step_u(state_u, ref_u, batch)

    mesh = make_mesh(ParallelConfig(fsdp=2, tp=2),
                     devices=jax.devices()[:4])
    state_s = init_train_state(cfg, tcfg, key, mesh=mesh)
    ref_s = jax.tree.map(jnp.copy, state_s.params)
    step_s = make_dpo_step(cfg, tcfg, dcfg, mesh=mesh)
    for _ in range(3):
        state_s, ms = step_s(state_s, ref_s, batch)

    np.testing.assert_allclose(
        float(ms["loss"]), float(mu["loss"]), rtol=2e-4, atol=2e-5
    )
    np.testing.assert_allclose(
        float(ms["reward_margin"]), float(mu["reward_margin"]),
        rtol=2e-3, atol=2e-4,
    )


def test_preference_batches(tmp_path):
    import json

    from shellac_tpu.training.dpo import preference_batches

    path = tmp_path / "pairs.jsonl"
    rows = [
        {"prompt": [1, 2, 3], "chosen": [4, 4], "rejected": [5, 5]},
        {"prompt": [9] * 20, "chosen": [7, 7, 7], "rejected": [8]},
    ]
    path.write_text("\n".join(json.dumps(r) for r in rows))
    it = preference_batches(str(path), batch_size=2, max_len=8, loop=False)
    b = next(it)
    assert b["chosen"].shape == (2, 8)
    # each row's mask marks exactly its completion tokens
    for i in range(2):
        row = np.asarray(b["chosen"][i])
        mask = np.asarray(b["chosen_mask"][i])
        n_comp = int(mask.sum())
        assert n_comp in (2, 3)
        comp = row[mask == 1.0]
        assert set(comp.tolist()) <= {4, 7}
    # over-long prompt was LEFT-truncated: the [9]*20 prompt row keeps
    # its full completion
    lens = [int(np.asarray(b["rejected_mask"][i]).sum()) for i in range(2)]
    assert sorted(lens) == [1, 2]


def test_dpo_cli_roundtrip(tmp_path, capsys):
    import json

    from shellac_tpu.cli import main

    pairs = tmp_path / "pairs.jsonl"
    rows = [
        {"prompt": [1, 2], "chosen": [3, 3], "rejected": [4, 4]},
        {"prompt": [5, 6], "chosen": [7, 7], "rejected": [8, 8]},
    ]
    pairs.write_text("\n".join(json.dumps(r) for r in rows))
    ckpt = tmp_path / "ckpt"
    rc = main([
        "dpo", "--model", "tiny", "--data", str(pairs), "--steps", "5",
        "--batch", "2", "--max-len", "8", "--learning-rate", "1e-4",
        "--ckpt-dir", str(ckpt), "--log-every", "1",
    ])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert json.loads(out[-1])["final_step"] == 5
    # the checkpoint restores for generation
    rc = main([
        "generate", "--model", "tiny", "--ckpt-dir", str(ckpt),
        "--prompt", "1,2", "--max-new", "4", "--temperature", "0",
    ])
    assert rc == 0


def test_fit_dpo_resume_keeps_reference_anchor(tmp_path):
    """On resume the frozen reference must be the ORIGINAL base policy,
    not the restored half-trained one: the chosen reward (beta * policy
    vs reference log-ratio) must continue from where the first run left
    off, not reset toward 0. Also: ema_params must actually track the
    policy when ema_decay is set."""
    import json

    from shellac_tpu.training.dpo import fit_dpo

    cfg = _cfg()
    dcfg = DPOConfig(beta=0.5)
    batch = _pref_batch(b=4, seed=1)
    data = lambda: iter([batch] * 100)  # noqa: E731
    log1 = tmp_path / "m1.jsonl"
    tcfg1 = TrainConfig(learning_rate=1e-3, warmup_steps=0, total_steps=6,
                        ema_decay=0.5)
    state1 = fit_dpo(
        cfg, tcfg1, dcfg, data(), checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_every=3, log_path=str(log1), log_every=1,
    )
    # EMA tracked the policy (not stuck at init)
    d = jax.tree.map(
        lambda e, p: float(jnp.abs(e - p).max()),
        state1.ema_params, state1.params,
    )
    moved = max(jax.tree.leaves(d))
    ref_step1 = [json.loads(l) for l in log1.read_text().splitlines()]
    m6 = next(r for r in ref_step1 if r["step"] == 6)
    assert moved < 1.0  # ema followed along

    log2 = tmp_path / "m2.jsonl"
    tcfg2 = tcfg1.replace(total_steps=8)
    fit_dpo(
        cfg, tcfg2, dcfg, data(), checkpoint_dir=str(tmp_path / "ck"),
        checkpoint_every=100, log_path=str(log2), log_every=1,
    )
    rows2 = [json.loads(l) for l in log2.read_text().splitlines()]
    m7 = next(r for r in rows2 if r["step"] == 7)
    # With the anchor preserved, step 7's margin continues from step
    # 6's; a re-anchored reference would snap the margin back to ~0.
    assert m7["reward_margin"] > 0.5 * m6["reward_margin"] > 0.0
