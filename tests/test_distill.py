"""Knowledge distillation.

The pinned invariants: a student distilled from a trained teacher must
converge to the teacher's greedy behavior (higher agreement than it
started with, and reproducing the teacher's learned pattern); alpha=0
must reduce exactly to the ordinary cross-entropy step's loss; a
DIFFERENT-architecture teacher works; sharded matches unsharded.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu.config import ModelConfig, TrainConfig
from shellac_tpu.training.distill import (
    DistillConfig,
    distill_loss,
    make_distill_step,
)
from shellac_tpu.training.trainer import init_train_state, make_train_step


def _cfg(**kw):
    base = dict(
        vocab_size=64, d_model=64, n_layers=2, n_heads=4,
        max_seq_len=64, dtype="float32", remat=False,
    )
    base.update(kw)
    return ModelConfig(**base).validate()


def _pattern_batch(b=4, s=32, seed=0):
    pat = np.tile([7, 21, 63, 3], 32)
    rows = np.stack([pat[i:i + s + 1] for i in range(b)]).astype(np.int32)
    toks = jnp.asarray(rows)
    return {"inputs": toks[:, :-1], "targets": toks[:, 1:]}


def _trained_teacher(cfg, batch, steps=80):
    tcfg = TrainConfig(learning_rate=3e-3, warmup_steps=5, total_steps=100)
    state = init_train_state(cfg, tcfg, jax.random.PRNGKey(1))
    step = make_train_step(cfg, tcfg)
    for _ in range(steps):
        state, _ = step(state, batch)
    return state.params


def test_distill_config_validation():
    with pytest.raises(ValueError, match="temperature"):
        DistillConfig(temperature=0.0).validate()
    with pytest.raises(ValueError, match="alpha"):
        DistillConfig(alpha=1.5).validate()
    with pytest.raises(ValueError, match="kind"):
        DistillConfig(kind="sideways").validate()


def test_distill_loss_zero_at_match():
    """KL of identical logits is 0 in both directions."""
    logits = jax.random.normal(jax.random.PRNGKey(0), (2, 8, 32))
    for kind in ("forward", "reverse"):
        loss, m = distill_loss(
            logits, logits, DistillConfig(kind=kind).validate()
        )
        assert abs(float(loss)) < 1e-5
        assert float(m["teacher_agreement"]) == 1.0


def test_student_learns_teacher_pattern():
    """Pure distillation (alpha=1, no hard targets): the student ends
    up reproducing the teacher's learned period-4 pattern greedily."""
    from shellac_tpu.inference.engine import Engine

    cfg = _cfg()
    batch = _pattern_batch()
    teacher = _trained_teacher(cfg, batch)
    tcfg = TrainConfig(learning_rate=3e-3, warmup_steps=0, total_steps=200)
    state = init_train_state(cfg, tcfg, jax.random.PRNGKey(7))
    step = make_distill_step(cfg, tcfg, DistillConfig(alpha=1.0))
    state, m0 = step(state, teacher, batch)
    for _ in range(150):
        state, m = step(state, teacher, batch)
    assert float(m["teacher_agreement"]) > float(m0["teacher_agreement"])
    assert float(m["kd_loss"]) < float(m0["kd_loss"])
    pat = np.tile([7, 21, 63, 3], 4)
    out = Engine(cfg, state.params, temperature=0.0, max_len=32).generate(
        jnp.asarray(pat[None, :8], jnp.int32), max_new_tokens=8
    )
    np.testing.assert_array_equal(np.asarray(out.tokens)[0], pat[8:16])


def test_alpha_zero_is_plain_ce():
    """alpha=0 must produce exactly the regular train step's loss (the
    KD term contributes nothing; same CE + z-loss math)."""
    cfg = _cfg()
    batch = _pattern_batch()
    tcfg = TrainConfig(learning_rate=1e-3, warmup_steps=0, total_steps=10)
    key = jax.random.PRNGKey(0)
    state_a = init_train_state(cfg, tcfg, key)
    state_b = init_train_state(cfg, tcfg, key)
    teacher = jax.tree.map(jnp.copy, state_a.params)
    kd_step = make_distill_step(cfg, tcfg, DistillConfig(alpha=0.0))
    ce_step = make_train_step(cfg, tcfg)
    _, m_kd = kd_step(state_a, teacher, batch)
    _, m_ce = ce_step(state_b, batch)
    np.testing.assert_allclose(
        float(m_kd["ce_loss"]), float(m_ce["loss"]), rtol=1e-6
    )


def test_cross_architecture_teacher():
    """A wider, deeper teacher distills into a smaller student (only
    the vocab must match); mismatched vocabs are rejected loudly."""
    student = _cfg()
    teacher_cfg = _cfg(d_model=128, n_layers=3, n_heads=8)
    batch = _pattern_batch()
    teacher = _trained_teacher(teacher_cfg, batch, steps=60)
    tcfg = TrainConfig(learning_rate=2e-3, warmup_steps=0, total_steps=60)
    state = init_train_state(student, tcfg, jax.random.PRNGKey(3))
    step = make_distill_step(
        student, tcfg, DistillConfig(alpha=0.7), teacher_cfg=teacher_cfg
    )
    for _ in range(50):
        state, m = step(state, teacher, batch)
    assert float(m["teacher_agreement"]) > 0.9
    with pytest.raises(ValueError, match="vocab"):
        make_distill_step(
            student, tcfg, DistillConfig(),
            teacher_cfg=_cfg(vocab_size=128),
        )


def test_distill_sharded_matches_unsharded():
    from shellac_tpu.config import ParallelConfig
    from shellac_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs the 8-device CPU mesh")
    cfg = _cfg()
    batch = _pattern_batch()
    tcfg = TrainConfig(learning_rate=1e-3, warmup_steps=0, total_steps=10)
    key = jax.random.PRNGKey(0)
    dcfg = DistillConfig(alpha=0.5)

    state_u = init_train_state(cfg, tcfg, key)
    teacher_u = jax.tree.map(jnp.copy, state_u.params)
    step_u = make_distill_step(cfg, tcfg, dcfg)
    for _ in range(3):
        state_u, mu = step_u(state_u, teacher_u, batch)

    mesh = make_mesh(ParallelConfig(fsdp=2, tp=2),
                     devices=jax.devices()[:4])
    state_s = init_train_state(cfg, tcfg, key, mesh=mesh)
    teacher_s = jax.tree.map(jnp.copy, state_s.params)
    step_s = make_distill_step(cfg, tcfg, dcfg, mesh=mesh)
    for _ in range(3):
        state_s, ms = step_s(state_s, teacher_s, batch)
    np.testing.assert_allclose(
        float(ms["loss"]), float(mu["loss"]), rtol=2e-4, atol=2e-5
    )


def test_distill_cli_roundtrip(tmp_path, capsys):
    import json

    from shellac_tpu.cli import main

    teacher_dir = tmp_path / "teacher"
    rc = main([
        "train", "--model", "tiny", "--steps", "5", "--batch", "2",
        "--seq", "32", "--ckpt-dir", str(teacher_dir),
    ])
    assert rc == 0
    student_dir = tmp_path / "student"
    rc = main([
        "distill", "--model", "tiny", "--teacher-ckpt", str(teacher_dir),
        "--steps", "4", "--batch", "2", "--seq", "32", "--alpha", "1.0",
        "--ckpt-dir", str(student_dir), "--log-every", "1",
    ])
    assert rc == 0
    out = capsys.readouterr().out.strip().splitlines()
    assert json.loads(out[-1])["final_step"] == 4
    rc = main([
        "generate", "--model", "tiny", "--ckpt-dir", str(student_dir),
        "--prompt", "1,2", "--max-new", "4", "--temperature", "0",
    ])
    assert rc == 0
