"""Tests for the concurrency + contract lint passes (SH010-SH016).

Mirrors tests/test_analysis.py: each rule triggers on a fixture, stays
quiet on the fixed form, respects `# shellac: ignore[...]` and the new
`# shellac: guarded-by(<lock>)` annotation — and the live tree (the
same path set CI lints) reports zero findings.

SH015/SH016 fixtures are written to tmp trees with their own miniature
`docs/observability.md` and `obs/` package: both rules locate their
contract source by walking up from scanned paths that exist on disk,
so in-memory snippets with fake paths are hermetic by design (tested
below too).
"""

import json
from pathlib import Path

import pytest

from shellac_tpu.analysis import lint_files, lint_paths
from shellac_tpu.analysis.cli import main as lint_main

REPO = Path(__file__).resolve().parents[1]


def codes(findings):
    return sorted({f.rule for f in findings})


def lint_snippet(source, filename="mod.py", **kw):
    return lint_files({filename: source}, **kw)


# ---- SH010 unguarded shared state ----------------------------------


SH010_RACE = """
import threading


class Poller:
    def __init__(self):
        self._lock = threading.Lock()
        self.failures = 0

    def start(self):
        self._t = threading.Thread(target=self._loop, daemon=True)
        self._t.start()

    def _loop(self):
        self.failures = self.failures + 1

    def health(self):
        return self.failures
"""


def test_sh010_spawned_thread_write_without_common_lock():
    found = lint_snippet(SH010_RACE, select=["SH010"])
    assert codes(found) == ["SH010"]
    assert "failures" in found[0].message


def test_sh010_both_sides_locked_is_clean():
    src = SH010_RACE.replace(
        "        self.failures = self.failures + 1",
        "        with self._lock:\n"
        "            self.failures = self.failures + 1",
    ).replace(
        "        return self.failures",
        "        with self._lock:\n"
        "            return self.failures",
    )
    assert lint_snippet(src, select=["SH010"]) == []


def test_sh010_guarded_by_on_both_sides_satisfies():
    src = SH010_RACE.replace(
        "self.failures = self.failures + 1",
        "self.failures = self.failures + 1"
        "  # shellac: guarded-by(_lock)",
    ).replace(
        "return self.failures",
        "return self.failures  # shellac: guarded-by(_lock)",
    )
    assert lint_snippet(src, select=["SH010"]) == []


def test_sh010_suppression():
    src = SH010_RACE.replace(
        "self.failures = self.failures + 1",
        "self.failures = self.failures + 1  # shellac: ignore[SH010]",
    )
    assert lint_snippet(src, select=["SH010"]) == []


SH010_RMW = """
import threading


class Manager:
    def __init__(self):
        self._lock = threading.Lock()
        self.write_errors = 0

    def fail(self):
        self.write_errors += 1
"""


def test_sh010_bare_rmw_in_lock_owning_class():
    found = lint_snippet(SH010_RMW, select=["SH010"])
    assert codes(found) == ["SH010"]
    assert "read-modify-write" in found[0].message


def test_sh010_rmw_under_lock_is_clean():
    src = SH010_RMW.replace(
        "        self.write_errors += 1",
        "        with self._lock:\n"
        "            self.write_errors += 1",
    )
    assert lint_snippet(src, select=["SH010"]) == []


def test_sh010_rmw_in_lockless_class_not_flagged():
    # No locks, no spawned threads: the class never declared itself
    # cross-thread, so a bare increment is fine.
    src = """
class Tally:
    def __init__(self):
        self.n = 0

    def bump(self):
        self.n += 1
"""
    assert lint_snippet(src, select=["SH010"]) == []


def test_sh010_locked_helper_gets_callers_held_set():
    # The *_locked convention: a helper only ever called under the
    # caller's lock is scanned with that lock held, not a spurious
    # empty set.
    src = """
import threading


class Spool:
    def __init__(self):
        self._lock = threading.Lock()
        self.bytes = 0

    def start(self):
        threading.Thread(target=self._drain, daemon=True).start()

    def _drain(self):
        with self._lock:
            self._rotate_locked()

    def read(self):
        with self._lock:
            return self.bytes

    def _rotate_locked(self):
        self.bytes = 0
"""
    assert lint_snippet(src, select=["SH010"]) == []


# ---- SH011 callback under lock -------------------------------------


SH011_HOOK = """
import threading


class SLOEngine:
    def __init__(self, on_transition=None):
        self._lock = threading.Lock()
        self._on_transition = on_transition

    def tick(self):
        with self._lock:
            if self._on_transition is not None:
                self._on_transition("page")
"""


def test_sh011_ctor_callback_invoked_under_lock():
    found = lint_snippet(SH011_HOOK, select=["SH011"])
    assert codes(found) == ["SH011"]
    assert "_on_transition" in found[0].message


def test_sh011_collect_then_fire_after_lock_is_clean():
    src = """
import threading


class SLOEngine:
    def __init__(self, on_transition=None):
        self._lock = threading.Lock()
        self._on_transition = on_transition

    def tick(self):
        fired = []
        with self._lock:
            if self._on_transition is not None:
                fired.append("page")
        for f in fired:
            self._on_transition(f)
"""
    assert lint_snippet(src, select=["SH011"]) == []


def test_sh011_on_prefix_attr_without_ctor_wiring():
    src = """
import threading


class Worker:
    on_done = None

    def __init__(self):
        self._lock = threading.Lock()

    def finish(self):
        with self._lock:
            if self.on_done:
                self.on_done()
"""
    assert codes(lint_snippet(src, select=["SH011"])) == ["SH011"]


def test_sh011_on_prefix_method_is_not_a_hook():
    # A same-class method named on_* is internal dispatch, not a
    # user-supplied seam.
    src = """
import threading


class Worker:
    def __init__(self):
        self._lock = threading.Lock()

    def on_step(self):
        pass

    def finish(self):
        with self._lock:
            self.on_step()
"""
    assert lint_snippet(src, select=["SH011"]) == []


def test_sh011_suppression():
    src = SH011_HOOK.replace(
        'self._on_transition("page")',
        'self._on_transition("page")  # shellac: ignore[SH011]',
    )
    assert lint_snippet(src, select=["SH011"]) == []


# ---- SH012 lock-order inversion ------------------------------------


SH012_SAME_CLASS = """
import threading


class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:
                pass

    def backward(self):
        with self._b:
            with self._a:
                pass
"""


def test_sh012_nested_with_inversion():
    found = lint_snippet(SH012_SAME_CLASS, select=["SH012"])
    assert codes(found) == ["SH012"]
    assert "Pair._a" in found[0].message
    assert "Pair._b" in found[0].message


def test_sh012_consistent_order_is_clean():
    src = SH012_SAME_CLASS.replace(
        "        with self._b:\n            with self._a:",
        "        with self._a:\n            with self._b:",
    )
    assert lint_snippet(src, select=["SH012"]) == []


SH012_CROSS_CLASS = """
import threading


class Store:
    def __init__(self):
        self._lock = threading.Lock()
        self._index = Index()

    def put(self):
        with self._lock:
            pass

    def flush(self):
        with self._lock:
            self._index.rebuild()


class Index:
    def __init__(self):
        self._lock = threading.Lock()
        self._store = Store()

    def rebuild(self):
        with self._lock:
            pass

    def add(self):
        with self._lock:
            self._store.put()
"""


def test_sh012_cross_class_cycle():
    found = lint_snippet(SH012_CROSS_CLASS, select=["SH012"])
    assert codes(found) == ["SH012"]
    msg = found[0].message
    assert "Store._lock" in msg and "Index._lock" in msg


def test_sh012_one_direction_cross_class_is_clean():
    src = SH012_CROSS_CLASS.replace(
        "    def add(self):\n"
        "        with self._lock:\n"
        "            self._store.put()",
        "    def add(self):\n"
        "        self._store.put()",
    )
    assert lint_snippet(src, select=["SH012"]) == []


def test_sh012_file_level_suppression():
    src = "# shellac: ignore[SH012]\n" + SH012_SAME_CLASS
    assert lint_snippet(src, select=["SH012"]) == []


# ---- SH013 blocking call under lock --------------------------------


SH013_SLEEP = """
import threading
import time


class Cache:
    def __init__(self):
        self._lock = threading.Lock()

    def refresh(self):
        with self._lock:
            time.sleep(1.0)
"""


def test_sh013_sleep_under_lock():
    found = lint_snippet(SH013_SLEEP, select=["SH013"])
    assert codes(found) == ["SH013"]
    assert "time.sleep" in found[0].message


def test_sh013_sleep_outside_lock_is_clean():
    src = """
import threading
import time


class Cache:
    def __init__(self):
        self._lock = threading.Lock()

    def refresh(self):
        with self._lock:
            pass
        time.sleep(1.0)
"""
    assert lint_snippet(src, select=["SH013"]) == []


def test_sh013_untimed_queue_get_and_join_under_lock():
    src = """
import queue
import threading


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self._t = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        pass

    def drain(self):
        with self._lock:
            item = self._q.get()
        return item

    def stop(self):
        with self._lock:
            self._t.join()
"""
    found = lint_snippet(src, select=["SH013"])
    assert len(found) == 2
    assert any(".get()" in f.message for f in found)
    assert any(".join()" in f.message for f in found)


def test_sh013_timeouts_are_exempt():
    src = """
import queue
import threading


class Pump:
    def __init__(self):
        self._lock = threading.Lock()
        self._q = queue.Queue()
        self._t = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        pass

    def drain(self):
        with self._lock:
            return self._q.get(timeout=0.5)

    def stop(self):
        with self._lock:
            self._t.join(timeout=5)
"""
    assert lint_snippet(src, select=["SH013"]) == []


def test_sh013_condition_wait_on_own_lock_is_protocol():
    src = """
import threading


class Mailbox:
    def __init__(self):
        self._cv = threading.Condition()

    def take(self):
        with self._cv:
            self._cv.wait()
"""
    assert lint_snippet(src, select=["SH013"]) == []


def test_sh013_condition_wait_holding_another_lock():
    src = """
import threading


class Mailbox:
    def __init__(self):
        self._lock = threading.Lock()
        self._cv = threading.Condition()

    def take(self):
        with self._lock:
            with self._cv:
                self._cv.wait()
"""
    found = lint_snippet(src, select=["SH013"])
    assert codes(found) == ["SH013"]
    assert "also holding" in found[0].message


def test_sh013_guarded_by_surfaces_blocking_call():
    # guarded-by FEEDS the held-set model, so it can surface findings:
    # a blocking call inside a declared *_locked helper is visible.
    src = """
import threading
import time


class Spool:
    def __init__(self):
        self._lock = threading.Lock()

    def _rotate_locked(self):  # shellac: guarded-by(_lock)
        time.sleep(0.2)
"""
    found = lint_snippet(src, select=["SH013"])
    assert codes(found) == ["SH013"]


def test_sh013_suppression():
    src = SH013_SLEEP.replace(
        "time.sleep(1.0)",
        "time.sleep(1.0)  # shellac: ignore[SH013]",
    )
    assert lint_snippet(src, select=["SH013"]) == []


# ---- SH014 non-daemon thread without join --------------------------


SH014_ANON = """
import threading


class Runner:
    def start(self):
        threading.Thread(target=self._run).start()

    def _run(self):
        pass
"""


def test_sh014_anonymous_non_daemon_thread():
    found = lint_snippet(SH014_ANON, select=["SH014"])
    assert codes(found) == ["SH014"]


def test_sh014_daemon_true_is_clean():
    src = SH014_ANON.replace(
        "threading.Thread(target=self._run)",
        "threading.Thread(target=self._run, daemon=True)",
    )
    assert lint_snippet(src, select=["SH014"]) == []


def test_sh014_bound_and_joined_is_clean():
    src = """
import threading


class Runner:
    def start(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def close(self):
        self._t.join(timeout=5)

    def _run(self):
        pass
"""
    assert lint_snippet(src, select=["SH014"]) == []


def test_sh014_bound_never_joined():
    src = """
import threading


class Runner:
    def start(self):
        self._t = threading.Thread(target=self._run)
        self._t.start()

    def _run(self):
        pass
"""
    found = lint_snippet(src, select=["SH014"])
    assert codes(found) == ["SH014"]
    assert "self._t" in found[0].message


def test_sh014_tests_are_exempt():
    assert lint_snippet(SH014_ANON,
                        filename="tests/test_worker.py") == []


def test_sh014_suppression():
    src = SH014_ANON.replace(
        "threading.Thread(target=self._run).start()",
        "threading.Thread(target=self._run).start()"
        "  # shellac: ignore[SH014]",
    )
    assert lint_snippet(src, select=["SH014"]) == []


# ---- SH015 metric-catalog drift ------------------------------------


def _contract_tree(tmp_path, *, doc, obs, extra):
    """A miniature repo: docs/observability.md + obs/ + serving code."""
    (tmp_path / "docs").mkdir()
    (tmp_path / "docs" / "observability.md").write_text(doc)
    (tmp_path / "obs").mkdir()
    (tmp_path / "obs" / "bundle.py").write_text(obs)
    for name, src in extra.items():
        (tmp_path / name).write_text(src)
    return tmp_path


OBS_BUNDLE = """
def build(reg):
    return reg.counter("shellac_requests_total", "requests")
"""


def test_sh015_undeclared_and_uncataloged_metric(tmp_path):
    root = _contract_tree(
        tmp_path,
        doc="# catalog\n\n- `shellac_requests_total`\n",
        obs=OBS_BUNDLE,
        extra={"srv.py": """
def wire(reg):
    reg.gauge("shellac_mystery_depth", "queue depth")
"""},
    )
    found = lint_paths([str(root)], select=["SH015"])
    # Both prongs: not declared in obs/, not in the docs catalog.
    assert len(found) == 2
    assert all(f.rule == "SH015" for f in found)
    assert all("shellac_mystery_depth" in f.message for f in found)


def test_sh015_declared_and_cataloged_is_clean(tmp_path):
    root = _contract_tree(
        tmp_path,
        doc="# catalog\n\n- `shellac_requests_total`\n"
            "- `shellac_queue_depth`\n",
        obs=OBS_BUNDLE + """
QUEUE_GAUGE = "shellac_queue_depth"
""",
        extra={"srv.py": """
def wire(reg):
    reg.gauge("shellac_queue_depth", "queue depth")
"""},
    )
    assert lint_paths([str(root)], select=["SH015"]) == []


def test_sh015_obs_registration_needs_only_docs(tmp_path):
    # A metric registered IN obs/ satisfies the namespace prong by
    # construction; the docs prong still applies.
    root = _contract_tree(
        tmp_path,
        doc="# catalog\n",
        obs=OBS_BUNDLE,
        extra={},
    )
    found = lint_paths([str(root)], select=["SH015"])
    assert len(found) == 1
    assert "not cataloged" in found[0].message


def test_sh015_in_memory_snippet_is_hermetic():
    # A fake-path snippet never binds to the live repo's docs or obs
    # tree, so unit fixtures cannot trip the project contract.
    src = """
def wire(reg):
    reg.counter("shellac_not_a_real_metric_total", "nope")
"""
    assert lint_snippet(src, select=["SH015"]) == []


def test_sh015_tests_are_exempt(tmp_path):
    root = _contract_tree(
        tmp_path,
        doc="# catalog\n",
        obs=OBS_BUNDLE + '\nDOC_ONLY = "shellac_requests_total"\n',
        extra={"test_srv.py": """
def test_wire(reg):
    reg.gauge("shellac_test_only_metric", "fixture")
"""},
    )
    found = lint_paths([str(root)], select=["SH015"])
    assert all("shellac_test_only_metric" not in f.message
               for f in found)


def test_sh015_file_level_suppression(tmp_path):
    root = _contract_tree(
        tmp_path,
        doc="# catalog\n\n- `shellac_requests_total`\n",
        obs=OBS_BUNDLE,
        extra={"bench.py": """
# shellac: ignore[SH015] — bench-local series, deliberately uncataloged

def wire(reg):
    reg.gauge("shellac_bench_tokens_per_sec", "headline")
"""},
    )
    assert lint_paths([str(root)], select=["SH015"]) == []


# ---- SH016 event-catalog drift -------------------------------------


def test_sh016_unknown_event_kind(tmp_path):
    root = _contract_tree(
        tmp_path,
        doc="# events\n\n| `admit` | server |\n",
        obs=OBS_BUNDLE,
        extra={"srv.py": """
def settle(recorder, tid):
    recorder.record(tid, "mystery-event", src="server")
"""},
    )
    found = lint_paths([str(root)], select=["SH016"])
    assert codes(found) == ["SH016"]
    assert "mystery-event" in found[0].message


def test_sh016_cataloged_kind_is_clean(tmp_path):
    root = _contract_tree(
        tmp_path,
        doc="# events\n\n| `admit` | server |\n",
        obs=OBS_BUNDLE,
        extra={"srv.py": """
def settle(recorder, tid):
    recorder.record(tid, "admit", src="server")
"""},
    )
    assert lint_paths([str(root)], select=["SH016"]) == []


def test_sh016_non_kind_second_arg_ignored(tmp_path):
    # .record() calls whose second argument is not a kebab-case kind
    # (some other API) are not the recorder contract.
    root = _contract_tree(
        tmp_path,
        doc="# events\n",
        obs=OBS_BUNDLE,
        extra={"srv.py": """
def save(db, row):
    db.record(row, "UPPER_CASE")
    db.record(row, 42)
"""},
    )
    assert lint_paths([str(root)], select=["SH016"]) == []


def test_sh016_in_memory_snippet_is_hermetic():
    src = """
def settle(recorder, tid):
    recorder.record(tid, "never-cataloged-kind", src="server")
"""
    assert lint_snippet(src, select=["SH016"]) == []


def test_sh016_suppression(tmp_path):
    root = _contract_tree(
        tmp_path,
        doc="# events\n",
        obs=OBS_BUNDLE,
        extra={"srv.py": """
def settle(recorder, tid):
    recorder.record(tid, "private-kind")  # shellac: ignore[SH016]
"""},
    )
    assert lint_paths([str(root)], select=["SH016"]) == []


# ---- guarded-by annotation mechanics -------------------------------


def test_guarded_by_inside_string_literal_is_inert():
    # Tokenize-based parsing: an annotation inside an embedded source
    # string cannot alter the enclosing file's held-set model.
    src = '''
import threading


class Manager:
    def __init__(self):
        self._lock = threading.Lock()
        self.write_errors = 0

    def fail(self):
        self.write_errors += 1
        worker = "x = 1  # shellac: guarded-by(_lock)"
        return worker
'''
    assert codes(lint_snippet(src, select=["SH010"])) == ["SH010"]


def test_guarded_by_multiple_locks():
    src = """
import threading


class Manager:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()
        self.n = 0

    def bump(self):
        self.n += 1  # shellac: guarded-by(_a, _b)
"""
    assert lint_snippet(src, select=["SH010"]) == []


# ---- CLI wiring -----------------------------------------------------


NEW_RULES = ["SH010", "SH011", "SH012", "SH013", "SH014", "SH015",
             "SH016"]


def test_cli_list_rules_includes_concurrency_pass(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for code in NEW_RULES:
        assert code in out, f"{code} missing from --list-rules"


@pytest.fixture(scope="module")
def concurrency_fixture_tree(tmp_path_factory):
    root = tmp_path_factory.mktemp("concurrency_fixtures")
    (root / "docs").mkdir()
    (root / "docs" / "observability.md").write_text("# catalog\n")
    (root / "obs").mkdir()
    (root / "obs" / "bundle.py").write_text(OBS_BUNDLE)
    fixtures = {
        "sh010.py": SH010_RACE,
        "sh011.py": SH011_HOOK,
        "sh012.py": SH012_SAME_CLASS,
        "sh013.py": SH013_SLEEP,
        "sh014.py": SH014_ANON,
        "sh015.py": """
def wire(reg):
    reg.gauge("shellac_mystery_depth", "queue depth")
""",
        "sh016.py": """
def settle(recorder, tid):
    recorder.record(tid, "mystery-event", src="server")
""",
    }
    for name, src in fixtures.items():
        (root / name).write_text(src)
    return root


def test_cli_exits_nonzero_on_each_new_rule(concurrency_fixture_tree,
                                            capsys):
    rc = lint_main([str(concurrency_fixture_tree)])
    out = capsys.readouterr().out
    assert rc == 1
    for code in NEW_RULES:
        assert code in out, f"{code} missing from CLI output"


def test_cli_json_report_carries_new_rules(concurrency_fixture_tree,
                                           capsys):
    rc = lint_main([str(concurrency_fixture_tree), "--format", "json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert set(report["summary"]["by_rule"]) >= set(NEW_RULES)


def test_seeded_callback_under_lock_fails_the_gate(tmp_path, capsys):
    # The CI regression: an injected callback-under-lock MUST fail the
    # lint gate (exit 1 with SH011 in the output) — proof the gate is
    # live, not vacuously green.
    (tmp_path / "seeded.py").write_text(SH011_HOOK)
    rc = lint_main([str(tmp_path)])
    out = capsys.readouterr().out
    assert rc == 1
    assert "SH011" in out


# ---- lint_report.py: exit 2 + schema check -------------------------


def _report_tool():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "lint_report", REPO / "scripts" / "lint_report.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_lint_report_missing_baseline_exits_two(tmp_path, capsys):
    tool = _report_tool()
    (tmp_path / "cur.json").write_text(
        '{"version": 1, "paths": [], "findings": [], '
        '"summary": {"findings": 0, "by_rule": {}}}')
    with pytest.raises(SystemExit) as exc:
        tool.main([str(tmp_path / "gone.json"),
                   str(tmp_path / "cur.json")])
    assert exc.value.code == 2
    assert "cannot read" in capsys.readouterr().err


def test_lint_report_corrupt_baseline_exits_two(tmp_path):
    tool = _report_tool()
    (tmp_path / "bad.json").write_text("{not json")
    (tmp_path / "cur.json").write_text(
        '{"version": 1, "paths": [], "findings": [], '
        '"summary": {"findings": 0, "by_rule": {}}}')
    with pytest.raises(SystemExit) as exc:
        tool.main([str(tmp_path / "bad.json"),
                   str(tmp_path / "cur.json")])
    assert exc.value.code == 2


def test_lint_report_schema_check_accepts_real_output(tmp_path, capsys):
    tool = _report_tool()
    (tmp_path / "x.py").write_text("import jax\n\nfn = jax.jit(lambda s: s)\n")
    rc = lint_main([str(tmp_path), "--format", "json"])
    del rc
    (tmp_path / "report.json").write_text(capsys.readouterr().out)
    assert tool.main([str(tmp_path / "report.json"),
                      "--check-schema"]) == 0


def test_lint_report_schema_check_rejects_drift(tmp_path, capsys):
    tool = _report_tool()
    bad = {
        "version": 1, "paths": ["x"],
        "findings": [{"path": "x.py", "line": "3", "col": 1,
                      "rule": "SH001", "message": "m"}],
        "summary": {"findings": 1, "by_rule": {"SH001": 1}},
    }
    (tmp_path / "report.json").write_text(json.dumps(bad))
    assert tool.main([str(tmp_path / "report.json"),
                      "--check-schema"]) == 2
    assert "line" in capsys.readouterr().err


def test_lint_report_schema_check_rejects_summary_mismatch(tmp_path):
    tool = _report_tool()
    bad = {
        "version": 1, "paths": [],
        "findings": [],
        "summary": {"findings": 3, "by_rule": {}},
    }
    (tmp_path / "report.json").write_text(json.dumps(bad))
    assert tool.main([str(tmp_path / "report.json"),
                      "--check-schema"]) == 2


# ---- the meta-test: the live tree stays clean ----------------------


def test_live_tree_reports_no_concurrency_findings():
    # The exact path set the CI lint gate scans. Genuine findings were
    # fixed (slo.py exemplar fetch, incident.py write_errors) or
    # annotated with rationale (server.py's lock-free snapshots); this
    # keeps it that way.
    findings = lint_paths(
        [str(REPO / "shellac_tpu"), str(REPO / "scripts"),
         str(REPO / "bench.py")],
        select=NEW_RULES,
    )
    assert findings == [], "\n".join(f.render() for f in findings)
