"""Overlapped decode dispatch: the two-deep window pipeline must be
invisible to every request's math.

Core contracts under test:
  - overlap on/off produce TOKEN-IDENTICAL outputs for greedy and
    per-request-seeded sampling, dense and paged (the acceptance
    criterion of the overlap PR);
  - device-side stop decisions (EOS, max_new budget) cut windows
    exactly where the host's historical scan did;
  - cancellation / abort with a window in flight never leaks stale
    tokens into a successor request;
  - decode_ticks auto-tuning picks by measurement (fake-timer unit
    tests), restores engine state, and "auto" construction is inert
    until tuned;
  - the simulated host-latency harness shows the overlap win the
    perf gate asserts in CI.

NOTE tier-1 timing: this file sorts late enough that the 870s window
never reaches it locally; CI runs it explicitly in the perf-gate job.
"""

import time

import jax
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.autotune import (
    SimulatedHostLatency,
    autotune_decode_ticks,
    maybe_autotune,
)
from shellac_tpu.inference.batching import (
    BatchingEngine,
    PagedBatchingEngine,
)


def _tiny(**kw):
    return get_model_config("tiny").replace(dtype="float32", **kw)


@pytest.fixture(scope="module")
def setup():
    cfg = _tiny()
    params = transformer_params(cfg)
    return cfg, params


def transformer_params(cfg):
    from shellac_tpu.models import transformer

    return transformer.init_params(cfg, jax.random.PRNGKey(0))


def _drain(eng):
    out = {}
    while eng.pending:
        for rid, toks in eng.step():
            out[rid] = list(toks)
    return out


def _build(cfg, params, *, paged=False, overlap=False, **kw):
    if paged:
        kw.setdefault("block_size", 16)
        kw.setdefault("pool_tokens", 1024)
        return PagedBatchingEngine(cfg, params, overlap_decode=overlap,
                                   **kw)
    return BatchingEngine(cfg, params, overlap_decode=overlap, **kw)


class TestOverlapParity:
    @pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
    @pytest.mark.parametrize("ticks", [1, 3])
    def test_greedy_token_identical(self, setup, paged, ticks):
        cfg, params = setup
        rng = np.random.default_rng(0)
        reqs = [(i, rng.integers(0, cfg.vocab_size, 4 + i % 6), 3 + i % 8)
                for i in range(7)]
        outs = []
        for overlap in (False, True):
            eng = _build(cfg, params, paged=paged, overlap=overlap,
                         n_slots=3, max_len=64, decode_ticks=ticks)
            for r in reqs:
                eng.submit(*r)
            outs.append(_drain(eng))
        assert outs[0] == outs[1]
        assert len(outs[0]) == len(reqs)

    @pytest.mark.parametrize("paged", [False, True], ids=["dense", "paged"])
    def test_seeded_sampling_token_identical(self, setup, paged):
        cfg, params = setup
        rng = np.random.default_rng(1)
        outs = []
        for overlap in (False, True):
            eng = _build(cfg, params, paged=paged, overlap=overlap,
                         n_slots=2, max_len=64, decode_ticks=4,
                         temperature=1.0)
            for i in range(5):
                eng.submit(i, rng.integers(0, cfg.vocab_size, 5 + i), 6,
                           temperature=1.3, top_k=None, seed=1000 + i)
            rng = np.random.default_rng(1)  # same prompts both runs
            outs.append(_drain(eng))
        assert outs[0] == outs[1]

    def test_eos_cut_matches_strict_ordering(self, setup):
        """Device-side EOS freeze must cut exactly where the host's
        scan did, including EOS landing mid-window."""
        cfg, params = setup
        rng = np.random.default_rng(2)
        prompt = rng.integers(0, cfg.vocab_size, 6)
        probe = _build(cfg, params, n_slots=1, max_len=64, decode_ticks=1)
        full = probe.run([("p", prompt, 12)])["p"]
        eos = full[len(full) // 2]
        outs = []
        for overlap in (False, True):
            eng = _build(cfg, params, overlap=overlap, n_slots=1,
                         max_len=64, eos_id=eos, decode_ticks=5)
            outs.append({k: list(v)
                         for k, v in eng.run([("x", prompt, 12)]).items()})
        assert outs[0] == outs[1]
        assert outs[0]["x"][-1] == eos or len(outs[0]["x"]) == 12

    def test_stop_sequence_mid_window(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(3)
        prompt = rng.integers(0, cfg.vocab_size, 6)
        full = _build(cfg, params, n_slots=1, max_len=64,
                      decode_ticks=1).run([("p", prompt, 10)])["p"]
        stop = [full[3], full[4]] if len(full) > 4 else [full[-1]]
        outs = []
        for overlap in (False, True):
            eng = _build(cfg, params, overlap=overlap, n_slots=1,
                         max_len=64, decode_ticks=4)
            eng.submit("s", prompt, 10, stop=[stop])
            outs.append(_drain(eng))
        assert outs[0] == outs[1]

    def test_chunked_prefill_composes(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(4)
        long_p = rng.integers(0, cfg.vocab_size, 20)
        short_p = rng.integers(0, cfg.vocab_size, 5)
        outs = []
        for overlap in (False, True):
            eng = _build(cfg, params, overlap=overlap, n_slots=2,
                         max_len=64, decode_ticks=2, prefill_chunk=8,
                         max_prefills_per_step=1)
            for r in [("lp", long_p, 6), ("sp", short_p, 4)]:
                eng.submit(*r)
            outs.append(_drain(eng))
        assert outs[0] == outs[1]

    def test_logprobs_and_top_logprobs_identical(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(5)
        prompt = rng.integers(0, cfg.vocab_size, 6)
        got = []
        for overlap in (False, True):
            eng = _build(cfg, params, overlap=overlap, n_slots=2,
                         max_len=64, decode_ticks=3, logprobs=True,
                         top_logprobs=2)
            eng.submit("l", prompt, 6)
            out = _drain(eng)
            got.append((out, eng.finished_logprobs.pop("l"),
                        eng.finished_top_logprobs.pop("l")))
        assert got[0] == got[1]

    def test_min_tokens_and_bias_identical(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(6)
        prompt = rng.integers(0, cfg.vocab_size, 5)
        full = _build(cfg, params, n_slots=1, max_len=64,
                      decode_ticks=1).run([("p", prompt, 12)])["p"]
        eos = full[2]
        outs = []
        for overlap in (False, True):
            eng = _build(cfg, params, overlap=overlap, n_slots=1,
                         max_len=64, eos_id=eos, decode_ticks=4)
            eng.submit("m", prompt, 12, min_tokens=7,
                       logit_bias={int(full[1]): -2.5})
            outs.append(_drain(eng))
        assert outs[0] == outs[1]
        assert len(outs[0]["m"]) >= 7 or outs[0]["m"][-1] != eos


class TestOverlapLifecycle:
    def test_cancel_with_window_in_flight(self, setup):
        """A slot cancelled while its window is in flight must not leak
        that window's tokens into the slot's next tenant."""
        cfg, params = setup
        rng = np.random.default_rng(7)
        prompt = rng.integers(0, cfg.vocab_size, 6)
        eng = _build(cfg, params, overlap=True, n_slots=1, max_len=64,
                     decode_ticks=2)
        eng.submit("c1", prompt, 10)
        eng.step()
        eng.step()  # a window is now in flight
        assert eng._windows
        assert eng.cancel("c1")
        eng.submit("c2", prompt[:4], 5)
        got = _drain(eng)
        want = _build(cfg, params, n_slots=1, max_len=64,
                      decode_ticks=2).run([("c2", prompt[:4], 5)])
        assert got == {k: list(v) for k, v in want.items()}

    def test_abort_all_drains_inflight_windows(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(8)
        prompt = rng.integers(0, cfg.vocab_size, 6)
        eng = _build(cfg, params, overlap=True, n_slots=2, max_len=64,
                     decode_ticks=3)
        eng.submit("a", prompt, 10)
        eng.submit("b", prompt[:3], 8)
        eng.step()
        eng.step()
        assert eng._windows
        dropped = eng.abort_all()
        assert sorted(dropped) == ["a", "b"]
        assert not eng._windows  # drained, not leaked
        eng.submit("fresh", prompt[:4], 6)
        got = _drain(eng)
        want = _build(cfg, params, n_slots=2, max_len=64,
                      decode_ticks=3).run([("fresh", prompt[:4], 6)])
        assert got == {k: list(v) for k, v in want.items()}

    def test_paged_abort_restores_pool(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(9)
        prompt = rng.integers(0, cfg.vocab_size, 8)
        eng = _build(cfg, params, paged=True, overlap=True, n_slots=2,
                     max_len=64, decode_ticks=2)
        free0 = len(eng._free)
        eng.submit("a", prompt, 8)
        eng.step()
        eng.step()
        eng.abort_all()
        assert len(eng._free) == free0
        got = _drain_after_submit(eng, ("z", prompt[:5], 4))
        want = _build(cfg, params, paged=True, n_slots=2, max_len=64,
                      decode_ticks=2).run([("z", prompt[:5], 4)])
        assert got == {k: list(v) for k, v in want.items()}

    def test_trailing_window_is_discarded_on_next_submit(self, setup):
        """After the last request finishes, overlap leaves one garbage
        window in flight; the next activity must discard it cleanly."""
        cfg, params = setup
        rng = np.random.default_rng(10)
        prompt = rng.integers(0, cfg.vocab_size, 5)
        eng = _build(cfg, params, overlap=True, n_slots=1, max_len=64,
                     decode_ticks=2)
        first = _drain_after_submit(eng, ("one", prompt, 4))
        got = _drain_after_submit(eng, ("two", prompt[:3], 5))
        ref = _build(cfg, params, n_slots=1, max_len=64, decode_ticks=2)
        assert first == {"one": list(ref.run([("one", prompt, 4)])["one"])}
        ref2 = _build(cfg, params, n_slots=1, max_len=64, decode_ticks=2)
        assert got == {"two": list(ref2.run([("two", prompt[:3], 5)])["two"])}


def _drain_after_submit(eng, req):
    eng.submit(*req)
    out = {}
    while eng.pending:
        for rid, toks in eng.step():
            out[rid] = list(toks)
    return out


class TestAutotune:
    def test_auto_is_inert_until_tuned(self, setup):
        cfg, params = setup
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             decode_ticks="auto")
        assert eng.decode_ticks == 1
        assert eng.decode_ticks_requested == "auto"
        assert eng.decode_ticks_source == "auto"
        assert eng.stats["decode_ticks"] == 1

    def test_bad_decode_ticks_string_rejected(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="auto"):
            BatchingEngine(cfg, params, decode_ticks="fast")

    def test_fake_timer_selects_scripted_winner(self, setup):
        """Selection is measurement-driven: a scripted clock that makes
        K=4 fastest must elect K=4 regardless of real wall time."""
        cfg, params = setup
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=128,
                             decode_ticks="auto")
        elapsed = {1: 5.0, 2: 3.0, 4: 0.5, 8: 4.0}
        clock = {"t": 0.0, "pending": None}

        def timer():
            # Two calls per candidate: t0, then t0 + scripted elapsed.
            if clock["pending"] is None:
                k = eng.decode_ticks
                clock["pending"] = clock["t"] + elapsed[k]
                return clock["t"]
            t = clock["pending"]
            clock["t"] = t
            clock["pending"] = None
            return t

        res = autotune_decode_ticks(
            eng, candidates=(1, 2, 4, 8), probe_windows=1, timer=timer,
        )
        assert res.best == 4
        assert eng.decode_ticks == 4
        assert eng.decode_ticks_source == "auto-tuned"
        assert eng.stats["decode_ticks"] == 4
        assert set(res.measurements) == {1, 2, 4, 8}

    def test_tune_restores_key_and_leaves_engine_idle(self, setup):
        cfg, params = setup
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=128,
                             decode_ticks="auto", seed=7)
        key0 = np.asarray(eng._key).copy()
        autotune_decode_ticks(eng, candidates=(1, 2), probe_windows=1)
        assert eng.pending == 0
        assert (np.asarray(eng._key) == key0).all()

    def test_tuned_engine_still_matches_reference(self, setup):
        """Post-tune traffic is bit-identical to a fresh engine pinned
        at the tuned K with the same seed."""
        cfg, params = setup
        rng = np.random.default_rng(11)
        prompt = rng.integers(0, cfg.vocab_size, 6)
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=128,
                             decode_ticks="auto", seed=3)
        autotune_decode_ticks(eng, candidates=(1, 2, 4), probe_windows=1)
        got = _drain_after_submit(eng, ("r", prompt, 8))
        ref = BatchingEngine(cfg, params, n_slots=2, max_len=128,
                             decode_ticks=eng.decode_ticks, seed=3)
        assert got == {"r": list(ref.run([("r", prompt, 8)])["r"])}

    def test_maybe_autotune_skips_fixed_and_spec(self, setup):
        cfg, params = setup
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=128,
                             decode_ticks=2)
        assert maybe_autotune(eng) is None
        assert eng.decode_ticks == 2

    def test_tight_cache_degrades_instead_of_failing(self, setup):
        cfg, params = setup
        eng = BatchingEngine(cfg, params, n_slots=1, max_len=24,
                             decode_ticks="auto")
        res = autotune_decode_ticks(eng, candidates=(1, 64),
                                    probe_windows=3)
        # 64 cannot fit a 24-token cache; the tuner shrinks its range
        # (or returns untouched) rather than failing serving startup.
        assert eng.decode_ticks in (1,)
        assert res.best == 1


class TestSimulatedLatencyHarness:
    def test_overlap_hides_injected_latency(self, setup):
        """The CI gate's core claim at smoke scale: with an injected
        device/RPC latency and per-step host work, overlapped dispatch
        beats strict ordering. Thresholds are lenient (the gate's
        calibrated run asserts the real 1.5x floor)."""
        cfg, params = setup
        rng = np.random.default_rng(12)
        prompt = rng.integers(0, cfg.vocab_size, 8)

        def run(overlap):
            eng = _build(cfg, params, overlap=overlap, n_slots=2,
                         max_len=96, decode_ticks=4)
            eng.run([("w", prompt, 2)])  # warm compiles
            shim = SimulatedHostLatency(eng, device_s=0.05)
            for i in range(4):
                eng.submit(i, prompt, 16)
            t0 = time.perf_counter()
            done = {}
            while eng.pending:
                for rid, out in eng.step():
                    done[rid] = out
                time.sleep(0.04)  # simulated serving-layer work
            dt = time.perf_counter() - t0
            shim.uninstall()
            assert len(done) == 4
            return dt

        serial, overlapped = run(False), run(True)
        assert serial / overlapped > 1.15, (serial, overlapped)

    def test_shim_outputs_identical(self, setup):
        """The shim shapes the clock only — tokens are untouched."""
        cfg, params = setup
        rng = np.random.default_rng(13)
        prompt = rng.integers(0, cfg.vocab_size, 6)
        eng = _build(cfg, params, overlap=True, n_slots=1, max_len=64,
                     decode_ticks=2)
        shim = SimulatedHostLatency(eng, device_s=0.02, dispatch_s=0.005)
        got = _drain_after_submit(eng, ("x", prompt, 6))
        shim.uninstall()
        ref = _build(cfg, params, n_slots=1, max_len=64, decode_ticks=2)
        assert got == {"x": list(ref.run([("x", prompt, 6)])["x"])}


class TestStatsSurface:
    def test_engine_stats_expose_window_config(self, setup):
        cfg, params = setup
        eng = _build(cfg, params, overlap=True, n_slots=1, max_len=64,
                     decode_ticks=2)
        assert eng.stats["decode_ticks"] == 2
        assert eng.stats["overlap_depth"] == 2
        eng2 = _build(cfg, params, n_slots=1, max_len=64, decode_ticks=3)
        assert eng2.stats["overlap_depth"] == 1

    def test_host_overhead_histogram_observes(self, setup):
        from shellac_tpu.obs import Registry

        cfg, params = setup
        reg = Registry()
        rng = np.random.default_rng(14)
        prompt = rng.integers(0, cfg.vocab_size, 5)
        for overlap in (False, True):
            eng = _build(cfg, params, overlap=overlap, n_slots=1,
                         max_len=64, decode_ticks=2, registry=reg)
            _drain_after_submit(eng, ("h", prompt, 6))
        snap = reg.snapshot()
        assert any("shellac_decode_host_overhead_seconds" in k
                   for k in snap), sorted(snap)[:5]

    def test_spec_engine_rejects_overlap(self, setup):
        cfg, params = setup
        from shellac_tpu.inference.spec_batching import (
            SpeculativeBatchingEngine,
        )

        dcfg = _tiny()
        with pytest.raises(ValueError, match="overlap_decode"):
            SpeculativeBatchingEngine(
                cfg, params, dcfg, transformer_params(dcfg),
                overlap_decode=True, n_slots=2, max_len=64,
            )
