"""Rolling (ring-buffer) KV cache for sliding-window models.

The serving contract: a ring of window + chunk-slack rows must produce
BIT-IDENTICAL greedy output to the dense max_len cache — the ring is a
storage optimization, never a numerics change. Tests run well past the
ring-wrap point so eviction actually happens.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu.config import ModelConfig
from shellac_tpu.inference.batching import BatchingEngine
from shellac_tpu.inference.engine import Engine
from shellac_tpu.inference.kvcache import (
    init_cache,
    init_rolling_cache,
    roll_update_layer,
    rolled_kv_positions,
)
from shellac_tpu.models.transformer import forward_with_cache, init_params


def _cfg(**kw):
    base = dict(
        vocab_size=128, d_model=64, n_layers=2, n_heads=4, n_kv_heads=2,
        max_seq_len=256, attn_window=8, dtype="float32", remat=False,
    )
    base.update(kw)
    return ModelConfig(**base).validate()


def test_ring_math():
    """rolled_kv_positions reconstructs the newest occupant of every
    slot; unwritten slots are masked."""
    pos, mask = rolled_kv_positions(jnp.asarray([3, 20]), ring=8)
    pos, mask = np.asarray(pos), np.asarray(mask)
    # lengths=3: positions 0,1,2 live at slots 0,1,2; rest unwritten.
    assert pos[0, :3].tolist() == [0, 1, 2]
    assert mask[0].tolist() == [True] * 3 + [False] * 5
    # lengths=20 (newest 19): slot j holds the largest p<=19, p%8==j.
    assert pos[1].tolist() == [16, 17, 18, 19, 12, 13, 14, 15]
    assert mask[1].all()


def test_roll_update_last_wins():
    """A chunk longer than the ring leaves exactly the newest occupant
    in every slot (scatter order must not matter)."""
    b, hkv, ring, d, s = 1, 2, 8, 4, 20
    ck = jnp.zeros((b, hkv, ring, d))
    cv = jnp.zeros((b, hkv, ring, d))
    k_new = jnp.broadcast_to(
        jnp.arange(s, dtype=jnp.float32)[None, :, None, None],
        (b, s, hkv, d),
    )
    ck2, _ = roll_update_layer(ck, cv, k_new, k_new, jnp.asarray([0]))
    got = np.asarray(ck2[0, 0, :, 0])
    # position p lands at p % 8; newest occupant of slot j is the
    # largest p < 20 with p % 8 == j.
    expect = [16, 17, 18, 19, 12, 13, 14, 15]
    assert got.tolist() == expect


def test_forward_with_cache_parity_through_wrap():
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    toks = jax.random.randint(jax.random.PRNGKey(1), (2, 48), 0, 128)
    dense = init_cache(cfg, 2, 128)
    roll = init_rolling_cache(cfg, 2, 128)
    assert roll.ring < 128
    ld, dense = forward_with_cache(
        cfg, params, toks[:, :16], dense, fresh_cache=True, attn_impl="ref"
    )
    lr, roll = forward_with_cache(
        cfg, params, toks[:, :16], roll, fresh_cache=True, attn_impl="ref"
    )
    np.testing.assert_array_equal(np.asarray(ld), np.asarray(lr))
    for t in range(16, 48):
        ld, dense = forward_with_cache(
            cfg, params, toks[:, t:t + 1], dense, attn_impl="ref"
        )
        lr, roll = forward_with_cache(
            cfg, params, toks[:, t:t + 1], roll, attn_impl="ref"
        )
        np.testing.assert_allclose(
            np.asarray(ld), np.asarray(lr), atol=1e-5
        )


def test_engine_greedy_bit_parity():
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = jnp.asarray(
        np.random.RandomState(0).randint(0, 128, (2, 12)), jnp.int32
    )
    dense = Engine(cfg, params, temperature=0.0, max_len=128).generate(
        prompt, max_new_tokens=40
    )
    roll = Engine(
        cfg, params, temperature=0.0, max_len=128, rolling_window=True
    ).generate(prompt, max_new_tokens=40)
    np.testing.assert_array_equal(
        np.asarray(dense.tokens), np.asarray(roll.tokens)
    )


def test_batching_bit_parity_with_churn():
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))

    def run(**kw):
        eng = BatchingEngine(
            cfg, params, n_slots=2, max_len=128, temperature=0.0, **kw
        )
        # Sizes 17-19 bucket to 32 > ring(16): the padded prefill
        # write WRAPS, the regime where unmasked pad rows would clobber
        # in-window positions.
        for i, size in enumerate([17, 19, 7, 18, 4]):
            rng = np.random.RandomState(i)
            eng.submit(i, rng.randint(0, 128, size), 40)
        done = {}
        while len(done) < 5:
            done.update(eng.step())
        return done

    assert run() == run(rolling_window=True)


def test_chunked_prefill_parity():
    """Continuation chunks READ the ring; the prefill_chunk slack must
    keep the earliest chunk row's window intact."""
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))

    def run(**kw):
        # prefill_chunk=12 buckets to 16 > the chunk itself: padded
        # continuation writes must mask their pad tail too.
        eng = BatchingEngine(
            cfg, params, n_slots=2, max_len=160, temperature=0.0,
            prefill_chunk=12, **kw
        )
        rng = np.random.RandomState(3)
        for i in range(3):
            eng.submit(i, rng.randint(0, 128, 50), 20)
        done = {}
        while len(done) < 3:
            done.update(eng.step())
        return done

    assert run() == run(rolling_window=True)


def test_gptoss_sinks_on_rolling():
    """Sinks + softmax_topk MoE + uniform window on the ring: the
    rolled read path must apply sink logits identically."""
    from shellac_tpu.models.registry import get_model_config

    cfg = get_model_config("tiny-gptoss").replace(
        dtype="float32", attn_pattern=None,  # uniform window
    )
    params = init_params(cfg, jax.random.PRNGKey(0))
    params["layers"]["sinks"] = jax.random.normal(
        jax.random.PRNGKey(7), params["layers"]["sinks"].shape
    ) * 2.0
    prompt = jnp.asarray([[5, 9, 2, 31]], jnp.int32)
    dense = Engine(cfg, params, temperature=0.0, max_len=96).generate(
        prompt, max_new_tokens=30
    )
    roll = Engine(
        cfg, params, temperature=0.0, max_len=96, rolling_window=True
    ).generate(prompt, max_new_tokens=30)
    np.testing.assert_array_equal(
        np.asarray(dense.tokens), np.asarray(roll.tokens)
    )


def test_guards():
    cfg = _cfg(attn_window=None)
    params = init_params(cfg, jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="attn_window"):
        Engine(cfg, params, rolling_window=True)
    with pytest.raises(NotImplementedError, match="patterned"):
        init_rolling_cache(
            _cfg(attn_pattern=("window", "full"), n_layers=2), 1, 64
        )


def test_patterned_mixed_cache_parity():
    """Gemma-2-style pattern: window layers roll in rings, full layers
    keep the dense stack — bit-parity with the all-dense cache through
    ring wrap, via the Engine."""
    from shellac_tpu.models.registry import get_model_config

    cfg = get_model_config("tiny-gemma2").replace(dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = jnp.asarray(
        np.random.RandomState(1).randint(0, 256, (2, 19)), jnp.int32
    )
    dense = Engine(cfg, params, temperature=0.0, max_len=128).generate(
        prompt, max_new_tokens=40
    )
    roll = Engine(
        cfg, params, temperature=0.0, max_len=128, rolling_window=True
    ).generate(prompt, max_new_tokens=40)
    np.testing.assert_array_equal(
        np.asarray(dense.tokens), np.asarray(roll.tokens)
    )


def test_patterned_gptoss_batching_parity():
    """GPT-OSS default (patterned, sinks, softmax_topk MoE) through the
    batching engine with slot churn and pad buckets wider than the
    ring."""
    from shellac_tpu.models.registry import get_model_config

    cfg = get_model_config("tiny-gptoss").replace(dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    params["layers"]["sinks"] = jax.random.normal(
        jax.random.PRNGKey(7), params["layers"]["sinks"].shape
    ) * 2.0

    def run(**kw):
        eng = BatchingEngine(
            cfg, params, n_slots=2, max_len=128, temperature=0.0, **kw
        )
        for i, size in enumerate([18, 7, 19, 4]):
            rng = np.random.RandomState(i)
            eng.submit(i, rng.randint(0, 256, size), 35)
        done = {}
        while len(done) < 4:
            done.update(eng.step())
        return done

    assert run() == run(rolling_window=True)


def test_patterned_chunked_prefill_parity():
    from shellac_tpu.models.registry import get_model_config

    cfg = get_model_config("tiny-gemma2").replace(dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))

    def run(**kw):
        eng = BatchingEngine(
            cfg, params, n_slots=2, max_len=160, temperature=0.0,
            prefill_chunk=12, **kw
        )
        rng = np.random.RandomState(5)
        for i in range(3):
            eng.submit(i, rng.randint(0, 256, 50), 20)
        done = {}
        while len(done) < 3:
            done.update(eng.step())
        return done

    assert run() == run(rolling_window=True)


def test_patterned_gemma3_dual_rope_parity():
    """Gemma-3: 5:1 pattern + DUAL rope — the ring layers rope with the
    local theta, the dense layer with the scaled global theta."""
    from shellac_tpu.models.registry import get_model_config

    cfg = get_model_config("tiny-gemma3").replace(dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = jnp.asarray(
        np.random.RandomState(2).randint(0, 256, (1, 17)), jnp.int32
    )
    dense = Engine(cfg, params, temperature=0.0, max_len=128).generate(
        prompt, max_new_tokens=40
    )
    roll = Engine(
        cfg, params, temperature=0.0, max_len=128, rolling_window=True
    ).generate(prompt, max_new_tokens=40)
    np.testing.assert_array_equal(
        np.asarray(dense.tokens), np.asarray(roll.tokens)
    )


def test_rolling_sharded_parity():
    """tp-sharded engine with the ring cache == unsharded greedy."""
    from shellac_tpu.config import ParallelConfig
    from shellac_tpu.inference.engine import shard_params
    from shellac_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs the CPU mesh")
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    prompt = jnp.asarray(
        np.random.RandomState(4).randint(0, 128, (2, 17)), jnp.int32
    )
    base = Engine(
        cfg, params, temperature=0.0, max_len=128, rolling_window=True
    ).generate(prompt, max_new_tokens=30)
    mesh = make_mesh(ParallelConfig(tp=2), devices=jax.devices()[:2])
    sp = shard_params(cfg, params, mesh)
    sharded = Engine(
        cfg, sp, temperature=0.0, max_len=128, rolling_window=True,
        mesh=mesh,
    ).generate(prompt, max_new_tokens=30)
    np.testing.assert_array_equal(
        np.asarray(base.tokens), np.asarray(sharded.tokens)
    )


def test_int8_rolling_matches_int8_dense():
    """kv_quant="int8" x rolling_window: the int8 ring must reproduce
    the int8 DENSE cache (both quantize at the same write points, so
    the stored values are identical; the ring read dequantizes in fp32
    with no extra rounding)."""
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))

    def run(**kw):
        eng = BatchingEngine(
            cfg, params, n_slots=2, max_len=128, temperature=0.0,
            kv_quant="int8", **kw
        )
        for i, size in enumerate([17, 7, 19, 4]):
            rng = np.random.RandomState(i)
            eng.submit(i, rng.randint(0, 128, size), 40)
        done = {}
        while len(done) < 4:
            done.update(eng.step())
        return done

    assert run() == run(rolling_window=True)


def test_int8_rolling_sharded():
    """The sharded engine must pin QuantRollingKVCache axes (the
    cache-kind dispatch is shared with init_cache_for)."""
    from shellac_tpu.config import ParallelConfig
    from shellac_tpu.inference.engine import shard_params
    from shellac_tpu.parallel.mesh import make_mesh

    if len(jax.devices()) < 2:
        pytest.skip("needs the CPU mesh")
    cfg = _cfg()
    params = init_params(cfg, jax.random.PRNGKey(0))
    mesh = make_mesh(ParallelConfig(tp=2), devices=jax.devices()[:2])
    sp = shard_params(cfg, params, mesh)
    eng = BatchingEngine(
        cfg, sp, n_slots=2, max_len=128, temperature=0.0,
        kv_quant="int8", rolling_window=True, mesh=mesh,
    )
    eng.submit("r", [5, 9, 2, 31], 20)
    done = {}
    while len(done) < 1:
        done.update(eng.step())
    base = BatchingEngine(
        cfg, params, n_slots=2, max_len=128, temperature=0.0,
        kv_quant="int8", rolling_window=True,
    )
    base.submit("r", [5, 9, 2, 31], 20)
    ref = {}
    while len(ref) < 1:
        ref.update(base.step())
    assert done == ref


def _run_patterned_int8(cfg, params, sizes, budget, **kw):
    eng = BatchingEngine(
        cfg, params, n_slots=2, max_len=128, temperature=0.0,
        kv_quant="int8", **kw
    )
    for i, size in enumerate(sizes):
        rng = np.random.RandomState(i)
        eng.submit(i, rng.randint(0, cfg.vocab_size, size), budget)
    done = {}
    while len(done) < len(sizes):
        done.update(eng.step())
    return done


def test_int8_patterned_matches_int8_dense():
    """kv_quant x patterned rolling (the quant MIXED cache): window
    layers ring int8, full layers dense int8 — outputs must reproduce
    the all-dense int8 cache bit-for-bit (same write-point
    quantization, ring reads dequantize in fp32 like the uniform ring),
    well past the ring wrap."""
    from shellac_tpu.models.registry import get_model_config

    cfg = get_model_config("tiny-gemma2").replace(dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    sizes = [17, 7, 19, 4]  # window=16: wraps during decode
    dense = _run_patterned_int8(cfg, params, sizes, 40)
    mixed = _run_patterned_int8(cfg, params, sizes, 40,
                                rolling_window=True)
    assert dense == mixed


def test_int8_patterned_gptoss_sinks():
    """GPT-OSS shape: sinks + biased MoE + pattern + int8 mixed cache."""
    from shellac_tpu.models.registry import get_model_config

    cfg = get_model_config("tiny-gptoss").replace(dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    sizes = [21, 9]
    dense = _run_patterned_int8(cfg, params, sizes, 30)
    mixed = _run_patterned_int8(cfg, params, sizes, 30,
                                rolling_window=True)
    assert dense == mixed


def test_int8_patterned_chunked_prefill():
    from shellac_tpu.models.registry import get_model_config

    cfg = get_model_config("tiny-gemma2").replace(dtype="float32")
    params = init_params(cfg, jax.random.PRNGKey(0))
    sizes = [40, 23]
    whole = _run_patterned_int8(cfg, params, sizes, 12,
                                rolling_window=True)
    chunked = _run_patterned_int8(cfg, params, sizes, 12,
                                  rolling_window=True, prefill_chunk=16)
    assert whole == chunked


def test_int8_patterned_memory_is_smaller():
    from shellac_tpu.inference.kvcache import (
        init_quant_cache,
        init_quant_patterned_cache,
    )
    from shellac_tpu.models.registry import get_model_config

    cfg = get_model_config("tiny-gemma2")
    dense = init_quant_cache(cfg, 2, 4096)
    mixed = init_quant_patterned_cache(cfg, 2, 4096)
    size = lambda c: sum(  # noqa: E731
        x.size * x.dtype.itemsize for x in jax.tree.leaves(c)
    )
    # Half the layers ring at window+slack (~24 rows) instead of 4096.
    assert size(mixed) < 0.6 * size(dense)
