"""Paged KV cache: block-pool correctness and memory behavior.

Core invariant (same as dense continuous batching): paging must be
invisible to the math — greedy output equals the single-request Engine
for every request, through block allocation, slot churn, and reuse.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.batching import PagedBatchingEngine
from shellac_tpu.inference.engine import Engine
from shellac_tpu.inference.kvcache import (
    init_cache,
    init_paged_cache,
    paged_gather_layer,
    paged_update_layer,
)
from shellac_tpu.models import transformer


def _tiny(**kw):
    return get_model_config("tiny").replace(dtype="float32", **kw)


@pytest.fixture(scope="module")
def setup():
    cfg = _tiny()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _ref(cfg, params, tokens, max_new):
    eng = Engine(cfg, params, temperature=0.0)
    out = eng.generate(
        jnp.asarray(np.asarray(tokens, np.int32)[None]), max_new_tokens=max_new
    )
    return np.asarray(out.tokens)[0].tolist()


class TestPagedOps:
    def test_update_then_gather_roundtrip(self, rng):
        pool_k = jnp.zeros((5, 2, 4, 8))  # (nb, H=2, bs=4, D=8)
        pool_v = jnp.zeros((5, 2, 4, 8))
        tables = jnp.asarray([[1, 3], [2, 4]], jnp.int32)  # 2 slots
        k_new = jnp.asarray(rng.normal(size=(2, 3, 2, 8)), jnp.float32)
        v_new = jnp.asarray(rng.normal(size=(2, 3, 2, 8)), jnp.float32)
        index = jnp.asarray([2, 0], jnp.int32)  # slot0 writes pos 2..4
        pk, pv = paged_update_layer(pool_k, pool_v, k_new, v_new, index, tables)
        k_all, _ = paged_gather_layer(pk, pv, tables)  # (B, H, mb*bs, D)
        k_all = jnp.transpose(k_all, (0, 2, 1, 3))  # token-major for asserts
        # Slot 0 positions 2,3 -> block 1 offsets 2,3; pos 4 -> block 3 off 0.
        np.testing.assert_allclose(np.asarray(k_all[0, 2:5]), np.asarray(k_new[0]))
        # Slot 1 positions 0..2 -> block 2.
        np.testing.assert_allclose(np.asarray(k_all[1, 0:3]), np.asarray(k_new[1]))

    def test_paged_forward_matches_dense(self, setup):
        """Same tokens through dense and paged caches -> same logits."""
        cfg, params = setup
        toks = jax.random.randint(jax.random.PRNGKey(3), (2, 7), 0,
                                  cfg.vocab_size)
        dense = init_cache(cfg, 2, 32)
        paged = init_paged_cache(cfg, 2, n_blocks=17, block_size=4,
                                 max_blocks_per_slot=8)
        # Allocate disjoint nonzero blocks for both slots up front.
        tables = jnp.asarray(
            [[1, 2, 3, 4, 0, 0, 0, 0], [5, 6, 7, 8, 0, 0, 0, 0]], jnp.int32
        )
        paged = paged.replace(tables=tables)

        ld, dense = transformer.forward_with_cache(cfg, params, toks, dense)
        lp, paged = transformer.forward_with_cache(cfg, params, toks, paged)
        np.testing.assert_allclose(np.asarray(lp), np.asarray(ld), atol=1e-5)
        # And one decode step each.
        nxt = jnp.argmax(ld[:, -1], -1).astype(jnp.int32)[:, None]
        ld2, _ = transformer.forward_with_cache(cfg, params, nxt, dense)
        lp2, _ = transformer.forward_with_cache(cfg, params, nxt, paged)
        np.testing.assert_allclose(np.asarray(lp2), np.asarray(ld2), atol=1e-5)


class TestPagedEngine:
    def test_matches_engine(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(0)
        reqs = [
            ("a", rng.integers(0, cfg.vocab_size, 5), 7),
            ("b", rng.integers(0, cfg.vocab_size, 19), 4),
            ("c", rng.integers(0, cfg.vocab_size, 2), 9),
        ]
        srv = PagedBatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  block_size=8)
        results = srv.run(reqs)
        for rid, toks, max_new in reqs:
            assert results[rid] == _ref(cfg, params, toks, max_new), rid

    def test_blocks_recycled(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(1)
        srv = PagedBatchingEngine(
            cfg, params, n_slots=2, max_len=64, block_size=8,
            pool_tokens=96,  # 12 usable blocks < 2 slots * 8 blocks dense
        )
        free0 = len(srv._free)
        reqs = [(i, rng.integers(0, cfg.vocab_size, 20), 6)
                for i in range(6)]
        results = srv.run(reqs)
        assert len(results) == 6
        for rid, toks, max_new in reqs:
            assert results[rid] == _ref(cfg, params, toks, max_new), rid
        assert len(srv._free) == free0  # everything returned to the pool

    def test_admission_blocks_until_blocks_free(self, setup):
        """Pool smaller than two concurrent requests: they serialize."""
        cfg, params = setup
        srv = PagedBatchingEngine(
            cfg, params, n_slots=2, max_len=64, block_size=8,
            pool_tokens=40,  # 5+1 blocks: one 33-token request at a time
        )
        rng = np.random.default_rng(2)
        reqs = [(i, rng.integers(0, cfg.vocab_size, 33), 4) for i in range(3)]
        results = srv.run(reqs)
        assert len(results) == 3
        for rid, toks, max_new in reqs:
            assert results[rid] == _ref(cfg, params, toks, max_new), rid

    def test_non_power_of_two_max_len(self, setup):
        """Prompt whose pad bucket exceeds max_len must not corrupt KV.

        Regression: pad=64 > max_len=48 used to clamp pad positions onto
        the slot's last real block, overwriting prompt K/V.
        """
        cfg, params = setup
        rng = np.random.default_rng(3)
        toks = rng.integers(0, cfg.vocab_size, 44)
        srv = PagedBatchingEngine(cfg, params, n_slots=1, max_len=48,
                                  block_size=8)
        results = srv.run([("x", toks, 3)])
        assert results["x"] == _ref(cfg, params, toks, 3)

    def test_full_footprint_reserved_at_admission(self, setup):
        """Concurrent requests that would exhaust the pool mid-decode
        must serialize at admission instead of crashing the engine."""
        cfg, params = setup
        rng = np.random.default_rng(4)
        srv = PagedBatchingEngine(
            cfg, params, n_slots=2, max_len=64, block_size=8,
            pool_tokens=64,  # 8 usable blocks; each request needs 6
        )
        reqs = [(i, rng.integers(0, cfg.vocab_size, 20), 20)
                for i in range(2)]
        results = srv.run(reqs)
        for rid, toks, max_new in reqs:
            assert results[rid] == _ref(cfg, params, toks, max_new), rid

    def test_memory_is_actually_smaller(self, setup):
        cfg, params = setup
        dense_tokens = 8 * 512
        srv = PagedBatchingEngine(cfg, params, n_slots=8, max_len=512,
                                  block_size=16)
        pool_positions = srv._cache.k.shape[1] * srv._cache.k.shape[3]
        assert pool_positions < dense_tokens * 0.6
