"""Speculative decoding in the continuous-batching engine.

Core invariant (inherited from both parents): speculation AND
scheduling are invisible to the math — greedy output per request is
bit-identical to the single-request Engine, through slot churn, stop
sequences, and mixed batches.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.engine import Engine
from shellac_tpu.inference.spec_batching import SpeculativeBatchingEngine
from shellac_tpu.models import transformer


def _tiny(**kw):
    return get_model_config("tiny").replace(dtype="float32", **kw)


@pytest.fixture(scope="module")
def setup():
    cfg = _tiny()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    # Draft: same tiny family, different weights (realistic mismatch).
    dcfg = _tiny()
    dparams = transformer.init_params(dcfg, jax.random.PRNGKey(7))
    return cfg, params, dcfg, dparams


def _ref(cfg, params, tokens, max_new):
    eng = Engine(cfg, params, temperature=0.0)
    out = eng.generate(
        jnp.asarray(np.asarray(tokens, np.int32)[None]), max_new_tokens=max_new
    )
    return np.asarray(out.tokens)[0].tolist()


def _engine(setup, **kw):
    cfg, params, dcfg, dparams = setup
    kw.setdefault("n_slots", 2)
    kw.setdefault("max_len", 96)
    kw.setdefault("gamma", 3)
    return SpeculativeBatchingEngine(cfg, params, dcfg, dparams, **kw)


class TestGreedyParity:
    def test_matches_engine_ragged(self, setup):
        cfg, params = setup[:2]
        rng = np.random.default_rng(0)
        reqs = [
            ("a", rng.integers(0, cfg.vocab_size, 5), 9),
            ("b", rng.integers(0, cfg.vocab_size, 12), 4),
            ("c", rng.integers(0, cfg.vocab_size, 3), 12),
        ]
        srv = _engine(setup)
        results = srv.run(reqs)
        for rid, toks, max_new in reqs:
            assert results[rid] == _ref(cfg, params, toks, max_new), rid
        assert srv.stats["spec_rounds"] > 0
        assert srv.stats["spec_accepted"] <= srv.stats["spec_proposed"]

    def test_more_requests_than_slots(self, setup):
        cfg, params = setup[:2]
        rng = np.random.default_rng(1)
        reqs = [(i, rng.integers(0, cfg.vocab_size, 4 + i % 3), 6)
                for i in range(6)]
        srv = _engine(setup)
        results = srv.run(reqs)
        assert len(results) == 6
        for rid, toks, max_new in reqs:
            assert results[rid] == _ref(cfg, params, toks, max_new), rid

    def test_self_draft_accepts_everything(self, setup):
        """Draft == target: every greedy proposal must be accepted."""
        cfg, params = setup[:2]
        srv = SpeculativeBatchingEngine(
            cfg, params, cfg, params, gamma=3, n_slots=1, max_len=96
        )
        prompt = np.array([1, 2, 3], np.int32)
        assert srv.run([("x", prompt, 12)])["x"] == _ref(
            cfg, params, prompt, 12
        )
        assert srv.stats["spec_accepted"] == srv.stats["spec_proposed"]

    def test_moe_verify_window_exact(self):
        """MoE targets: the g+1-token verification forward must not
        capacity-drop (a dropped token zeroes its FFN output and broke
        bit-parity with the plain engine). Self-draft greedy must
        accept every proposal on tiny-moe."""
        from shellac_tpu.inference.batching import BatchingEngine

        cfg = get_model_config("tiny-moe").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(5)
        prompt = rng.integers(0, cfg.vocab_size, 6)
        ref = BatchingEngine(cfg, params, n_slots=1, max_len=96).run(
            [("x", prompt, 12)]
        )["x"]
        srv = SpeculativeBatchingEngine(
            cfg, params, cfg, params, gamma=3, n_slots=1, max_len=96
        )
        assert srv.run([("x", prompt, 12)])["x"] == ref
        assert srv.stats["spec_accepted"] == srv.stats["spec_proposed"]

    def test_stop_sequences(self, setup):
        cfg, params = setup[:2]
        prompt = np.array([4, 8], np.int32)
        full = _ref(cfg, params, prompt, 12)
        stop = [full[4:6]]
        srv = _engine(setup)
        assert srv.run([("x", prompt, 12, stop)])["x"] == full[:4]

    def test_eos_frees_slot_early(self, setup):
        cfg, params = setup[:2]
        prompt = np.array([1, 2, 3], np.int32)
        full = _ref(cfg, params, prompt, 12)
        eos = full[3]
        srv = _engine(setup, eos_id=eos, n_slots=1)
        assert srv.run([("x", prompt, 12)])["x"] == full[:4]


class TestSampledAndMixed:
    def test_mixed_greedy_and_sampled(self, setup):
        """A greedy request mixed with a sampled one stays exact."""
        cfg, params = setup[:2]
        rng = np.random.default_rng(2)
        gp = rng.integers(0, cfg.vocab_size, 6)
        want = _ref(cfg, params, gp, 8)
        srv = _engine(setup)
        srv.submit("hot", rng.integers(0, cfg.vocab_size, 4), 8,
                   temperature=1.3)
        srv.submit("greedy", gp, 8, temperature=0.0)
        results = {}
        while srv.pending:
            results.update(srv.step())
        assert results["greedy"] == want
        assert len(results["hot"]) == 8

    def test_sampled_lengths_and_finiteness(self, setup):
        srv = _engine(setup, temperature=1.0)
        rng = np.random.default_rng(3)
        cfg = setup[0]
        reqs = [(i, rng.integers(0, cfg.vocab_size, 5), 10)
                for i in range(4)]
        results = srv.run(reqs)
        for i, _, max_new in reqs:
            assert len(results[i]) <= max_new
            assert all(0 <= t < cfg.vocab_size for t in results[i])


class TestChunkedPrefill:
    def test_chunked_greedy_bit_exact(self, setup):
        """Chunked prompts through the speculative engine: the draft
        cache chunks alongside the target's, so by the final chunk
        both hold the full prompt — outputs identical to the
        whole-prompt spec engine AND the plain Engine."""
        cfg, params = setup[:2]
        rng = np.random.default_rng(5)
        reqs = [
            ("long", rng.integers(0, cfg.vocab_size, 37), 8),
            ("short", rng.integers(0, cfg.vocab_size, 4), 6),
            ("mid", rng.integers(0, cfg.vocab_size, 19), 7),
        ]
        srv = _engine(setup, prefill_chunk=8)
        results = srv.run(reqs)
        for rid, toks, max_new in reqs:
            assert results[rid] == _ref(cfg, params, toks, max_new), rid
        assert srv.stats["prefill_chunks"] > 0

    def test_chunked_matches_whole_prompt_spec(self, setup):
        cfg, params = setup[:2]
        rng = np.random.default_rng(6)
        reqs = [(i, rng.integers(0, cfg.vocab_size, 21), 6)
                for i in range(3)]
        whole = _engine(setup).run(reqs)
        chunked = _engine(setup, prefill_chunk=6).run(reqs)
        assert chunked == whole


class TestTopLogprobs:
    def test_top_logprobs_over_verify_window(self, setup):
        """Alternatives ride the verify pass: greedy invariant top-1 ==
        the chosen token at its exact logprob, for EVERY emitted
        position of every accepted window."""
        cfg, params = setup[:2]
        rng = np.random.default_rng(8)
        reqs = [("x", rng.integers(0, cfg.vocab_size, 6), 7)]
        srv = _engine(setup, logprobs=True, top_logprobs=3)
        results = srv.run(reqs)
        toks = results["x"]
        lps = srv.finished_logprobs["x"]
        tlp = srv.finished_top_logprobs["x"]
        assert len(tlp) == len(toks) == len(lps)
        for tok, lp, (ids, vals) in zip(toks, lps, tlp):
            assert len(ids) == 3
            assert ids[0] == tok  # greedy: best alternative IS chosen
            np.testing.assert_allclose(vals[0], lp, atol=1e-5)
            assert vals == sorted(vals, reverse=True)

    def test_top_logprobs_matches_plain_engine(self, setup):
        """The recorded alternatives equal the plain BatchingEngine's
        for the same greedy request (same model, same positions)."""
        from shellac_tpu.inference.batching import BatchingEngine

        cfg, params = setup[:2]
        rng = np.random.default_rng(9)
        prompt = rng.integers(0, cfg.vocab_size, 5)
        plain = BatchingEngine(cfg, params, n_slots=2, max_len=96,
                               temperature=0.0, logprobs=True,
                               top_logprobs=2)
        plain.run([("r", prompt, 6)])
        spec = _engine(setup, logprobs=True, top_logprobs=2)
        spec.run([("r", prompt, 6)])
        want = plain.finished_top_logprobs["r"]
        got = spec.finished_top_logprobs["r"]
        assert [ids for ids, _ in got] == [ids for ids, _ in want]
        for (_, gv), (_, wv) in zip(got, want):
            np.testing.assert_allclose(gv, wv, atol=1e-4)


class TestValidation:
    def test_int8_composes(self, setup):
        """PR 9 burned down the int8 exclusion: the verify forward
        WRITES each position's K/V (quantizing at write) before its
        in-window attention READS them back through the cache, so
        draft scoring sees the same int8-rounded bits sequential
        decode re-reads. Greedy parity vs the int8 sequential engine
        is pinned in tests/test_cache_backends.py; this pins the
        construction + self-draft acceptance identity."""
        cfg, params = setup[:2]
        srv = SpeculativeBatchingEngine(
            cfg, params, cfg, params, gamma=3, n_slots=1, max_len=96,
            kv_quant="int8",
        )
        assert srv.cache_backend.name == "dense-int8"
        prompt = np.array([1, 2, 3], np.int32)
        out = srv.run([("x", prompt, 10)])["x"]
        assert len(out) == 10
        # Self-draft greedy on one shared int8 cache path: every
        # proposal must be accepted, or the write-then-read identity
        # is broken somewhere.
        assert srv.stats["spec_accepted"] == srv.stats["spec_proposed"]

    def test_filter_params_compose(self, setup):
        """top-k/top-p/min-p requests are admitted (burned down in
        PR 9): the identical truncation is applied to draft and
        target distributions before the acceptance test. Distribution
        equivalence is pinned in tests/test_cache_backends.py."""
        srv = _engine(setup, temperature=1.0)
        srv.submit("x", np.array([1, 2], np.int32), 6,
                   temperature=0.9, top_k=8, top_p=0.9, min_p=0.05)
        results = {}
        while srv.pending:
            results.update(srv.step())
        assert len(results["x"]) == 6

    def test_slack_budget_enforced(self, setup):
        srv = _engine(setup, max_len=32, gamma=4)
        with pytest.raises(ValueError, match="slack"):
            srv.submit("x", np.ones(10, np.int32), 20)

    def test_decode_ticks_rejected(self, setup):
        with pytest.raises(ValueError, match="decode_ticks"):
            _engine(setup, decode_ticks=2)

    def test_vocab_mismatch(self, setup):
        cfg, params = setup[:2]
        dcfg = _tiny(vocab_size=128)
        dparams = transformer.init_params(dcfg, jax.random.PRNGKey(1))
        with pytest.raises(ValueError, match="vocab"):
            SpeculativeBatchingEngine(cfg, params, dcfg, dparams)


class TestServerIntegration:
    def test_streaming_over_spec_engine(self, setup):
        """The server's streaming path composes with multi-token
        speculative chunks (holdback logic is length-based)."""
        from shellac_tpu.inference.server import InferenceServer

        cfg, params = setup[:2]
        eng = _engine(setup)
        srv = InferenceServer(cfg, params, engine=eng)
        try:
            prompt = [3, 7, 11]
            want = _ref(cfg, params, prompt, 10)
            got, final = [], None
            for kind, val in srv.generate_stream(prompt, max_new=10,
                                                 timeout=120):
                if kind == "delta":
                    got.extend(val)
                else:
                    final = val
            assert final == want
            assert got == final[:len(got)]
        finally:
            srv.close()
