"""Byte tokenizer, tokenize CLI, and fp8 weight quantization tests."""

import json

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.cli import main
from shellac_tpu.models import transformer
from shellac_tpu.ops.quant import dequantize, quantize, quantize_params
from shellac_tpu.training.data import read_token_shard
from shellac_tpu.training.tokenizer import ByteTokenizer, get_tokenizer


class TestByteTokenizer:
    def test_roundtrip(self):
        tok = ByteTokenizer()
        text = "héllo, wörld! \U0001f680"
        ids = tok.encode(text)
        assert ids.dtype == np.int32
        assert tok.decode(ids) == text

    def test_specials(self):
        tok = ByteTokenizer()
        ids = tok.encode("ab", bos=True, eos=True)
        assert ids[0] == ByteTokenizer.BOS and ids[-1] == ByteTokenizer.EOS
        assert tok.decode(ids) == "ab"  # specials dropped on decode
        assert tok.vocab_size == 259

    def test_documents_eos_separated(self):
        tok = ByteTokenizer()
        stream = tok.encode_documents(["a", "b"])
        assert list(stream) == [ord("a"), tok.EOS, ord("b"), tok.EOS]

    def test_get_tokenizer(self):
        assert isinstance(get_tokenizer("byte"), ByteTokenizer)


class TestTokenizeCLI:
    def test_tokenize_then_train(self, tmp_path, capsys):
        text = tmp_path / "corpus.txt"
        text.write_text("the quick brown fox jumps over the lazy dog. " * 200)
        shard = tmp_path / "corpus.bin"
        rc = main(["tokenize", "--input", str(text), "--output", str(shard)])
        assert rc == 0
        meta = json.loads(capsys.readouterr().out)
        assert meta["tokens"] > 1000
        tokens = read_token_shard(str(shard))
        assert tokens.size == meta["tokens"]

        rc = main([
            "train", "--model", "tiny", "--steps", "3", "--batch", "2",
            "--seq", "32", "--data", str(shard),
        ])
        assert rc == 0

    def test_generate_text(self, capsys):
        rc = main([
            "generate", "--model", "tiny", "--text", "ab",
            "--max-new", "4", "--temperature", "0",
        ])
        assert rc == 0
        out = json.loads(capsys.readouterr().out)
        assert len(out["tokens"]) == 4
        assert isinstance(out["text"], str)


class TestFP8:
    def test_fp8_roundtrip_better_than_int8_for_smalls(self, rng):
        # Log-normal weights span decades; fp8's relative precision
        # should beat int8's absolute grid on the small entries.
        w = jnp.asarray(
            np.exp(rng.normal(size=(2, 32, 64)) * 2.0).astype(np.float32)
        )
        q8 = dequantize(quantize(w, dtype=jnp.int8))
        f8 = dequantize(quantize(w, dtype=jnp.float8_e4m3fn))
        small = np.asarray(w) < np.median(np.asarray(w))
        rel8 = np.abs(np.asarray(q8 - w))[small] / np.asarray(w)[small]
        relf = np.abs(np.asarray(f8 - w))[small] / np.asarray(w)[small]
        assert relf.mean() < rel8.mean()

    def test_fp8_forward(self):
        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        qparams = quantize_params(cfg, params, dtype=jnp.float8_e4m3fn)
        assert qparams["layers"]["wq"].q.dtype == jnp.float8_e4m3fn
        tokens = jnp.zeros((1, 8), jnp.int32)
        l_fp = transformer.forward(cfg, params, tokens)
        l_q = transformer.forward(cfg, qparams, tokens)
        scale = float(jnp.std(l_fp)) + 1e-6
        assert float(jnp.max(jnp.abs(l_q - l_fp))) / scale < 0.2

    def test_bad_dtype_raises(self):
        with pytest.raises(ValueError, match="unsupported quantization"):
            quantize(jnp.ones((2, 4, 4)), dtype=jnp.float16)


class TestBPETokenizer:
    def test_train_roundtrip(self, tmp_path):
        from shellac_tpu.training.tokenizer import BPETokenizer, get_tokenizer

        corpus = tmp_path / "corpus.txt"
        corpus.write_text(
            "the quick brown fox jumps over the lazy dog\n" * 50
            + "pack my box with five dozen liquor jugs\n" * 50
        )
        path = str(tmp_path / "tok.json")
        tok = BPETokenizer.train([str(corpus)], vocab_size=512,
                                 out_path=path)
        text = "the quick liquor fox"
        ids = tok.encode(text)
        assert tok.decode(ids) == text
        # trained merges actually compress vs raw bytes
        assert ids.size < len(text.encode())
        # bos/eos specials resolve and strip on decode
        ids2 = tok.encode(text, bos=True, eos=True)
        assert ids2[0] == tok.bos_id and ids2[-1] == tok.eos_id
        assert tok.decode(ids2) == text
        # reload from file via the spec dispatcher
        tok2 = get_tokenizer(path)
        np.testing.assert_array_equal(tok2.encode(text), ids)

    def test_cli_train_and_shard(self, tmp_path, capsys):
        import json as _json

        from shellac_tpu.cli import main

        corpus = tmp_path / "c.txt"
        corpus.write_text("hello world, hello tokenizer\n" * 40)
        tokp = str(tmp_path / "tok.json")
        shard = str(tmp_path / "s.bin")
        rc = main([
            "tokenize", "--input", str(corpus), "--output", shard,
            "--tokenizer", tokp, "--train-bpe", "400",
        ])
        assert rc == 0
        out = _json.loads(capsys.readouterr().out.strip().splitlines()[-1])
        assert out["vocab_size"] <= 400 and out["tokens"] > 0
        # the trained tokenizer file reloads for a second encode run
        rc = main([
            "tokenize", "--input", str(corpus), "--output", shard,
            "--tokenizer", tokp,
        ])
        assert rc == 0
