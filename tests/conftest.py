"""Test configuration: force an 8-device virtual CPU mesh.

The sandbox's sitecustomize registers the axon TPU plugin and imports jax
at interpreter startup, so env vars (JAX_PLATFORMS / XLA_FLAGS) are too
late — the platform must be overridden through jax.config before any
backend is initialized. conftest runs before test modules import
anything, which is early enough.
"""

import jax

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    # Older jax has no jax_num_cpu_devices; the CPU client reads
    # XLA_FLAGS at (lazy) backend init, which has not happened yet at
    # conftest time even though jax is imported.
    import os

    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=8"
    )

import threading  # noqa: E402

import numpy as np  # noqa: E402
import pytest  # noqa: E402

#: Shared by the multi-host test modules: the jax<0.5 CPU backend
#: cannot run multiprocess computations at all, so those suites skip
#: wholesale rather than fail at rendezvous.
needs_multiprocess_cpu = pytest.mark.skipif(
    tuple(int(v) for v in jax.__version__.split(".")[:2]) < (0, 5),
    reason="the jax<0.5 CPU backend has no multiprocess computations",
)

_last_module = [None]


def pytest_runtest_setup(item):
    """Clear XLA's compiled-executable caches at module boundaries.

    A full serial run accumulates ~600 modules' worth of CPU
    executables in one process and eventually crashes inside an XLA
    compile (round-4 root cause analysis; every crash site passes in
    isolation). Dropping the caches when the suite moves to a new test
    module bounds the accumulation; within-module compile reuse — the
    kind that matters for runtime — is preserved.
    """
    mod = getattr(item, "module", None)
    name = getattr(mod, "__name__", None)
    if _last_module[0] is not None and name != _last_module[0]:
        jax.clear_caches()
    _last_module[0] = name


@pytest.fixture(autouse=True)
def no_thread_leaks():
    """Fail any test that leaks a non-daemon thread (SH014's runtime
    twin): a scheduler/poller/push-worker thread that outlives its test
    would hang the interpreter at exit and, in a full serial run, bleed
    state into every later test. Daemon threads are exempt — they are
    the explicitly fire-and-forget class — as are threads that predate
    the test (pytest plugins, jax's internals)."""
    before = set(threading.enumerate())
    yield
    leaked = [
        t for t in threading.enumerate()
        if t not in before and not t.daemon and t.is_alive()
    ]
    if not leaked:
        return
    # Close paths signal first and join second; give a shutting-down
    # thread one grace period before calling it a leak.
    deadline = 5.0 / max(1, len(leaked))
    for t in leaked:
        t.join(timeout=deadline)
    leaked = [t for t in leaked if t.is_alive()]
    assert not leaked, (
        "test leaked non-daemon thread(s): "
        + ", ".join(sorted(t.name for t in leaked))
        + " — join them on the owning object's close() path"
    )


@pytest.fixture(scope="session")
def mesh8():
    from shellac_tpu import ParallelConfig, make_mesh

    return make_mesh(ParallelConfig(dp=2, fsdp=1, sp=2, tp=2))


@pytest.fixture(scope="session")
def mesh_fsdp8():
    from shellac_tpu import ParallelConfig, make_mesh

    return make_mesh(ParallelConfig(fsdp=8))


@pytest.fixture
def rng():
    return np.random.default_rng(0)


def run_two_process(tmp_path, source, timeout=300, ok_ranks=(0, 1)):
    """Launch `source` as 2 rendezvousing jax.distributed processes.

    Shared by the multi-host serving/training tests. Asserts ranks in
    `ok_ranks` exit 0 and printed "WORKER_OK <rank>"; returns their
    outputs. Fault-injection tests pass ok_ranks=(0,) when rank 1 is
    MEANT to die mid-run.
    """
    import os
    import pathlib
    import socket
    import subprocess
    import sys

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    script = tmp_path / "worker.py"
    script.write_text(source)
    env_base = {
        **os.environ,
        "PYTHONPATH": str(pathlib.Path(__file__).parents[1]),
        "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
        "JAX_NUM_PROCESSES": "2",
    }
    procs = [
        subprocess.Popen(
            [sys.executable, str(script)],
            env={**env_base, "JAX_PROCESS_ID": str(r)},
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for r in range(2)
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    for r, (p, out) in enumerate(zip(procs, outs)):
        if r not in ok_ranks:
            continue
        assert p.returncode == 0, f"rank {r} failed:\n{out}"
        assert f"WORKER_OK {r}" in out, out
    return outs
