"""Test configuration: force an 8-device virtual CPU mesh.

The sandbox's sitecustomize registers the axon TPU plugin and imports jax
at interpreter startup, so env vars (JAX_PLATFORMS / XLA_FLAGS) are too
late — the platform must be overridden through jax.config before any
backend is initialized. conftest runs before test modules import
anything, which is early enough.
"""

import jax

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture(scope="session")
def mesh8():
    from shellac_tpu import ParallelConfig, make_mesh

    return make_mesh(ParallelConfig(dp=2, fsdp=1, sp=2, tp=2))


@pytest.fixture(scope="session")
def mesh_fsdp8():
    from shellac_tpu import ParallelConfig, make_mesh

    return make_mesh(ParallelConfig(fsdp=8))


@pytest.fixture
def rng():
    return np.random.default_rng(0)
