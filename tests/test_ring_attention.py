"""Ring attention (sequence parallelism) vs the dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import ParallelConfig, get_model_config, make_mesh
from shellac_tpu.models import transformer
from shellac_tpu.ops.attention import attention_ref
from shellac_tpu.parallel.ring_attention import ring_attention


@pytest.fixture(scope="module")
def mesh_sp4():
    return make_mesh(ParallelConfig(sp=4, tp=2))


class TestRingAttention:
    def test_causal_matches_ref(self, mesh_sp4):
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(size=(2, 64, 4, 32)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(2, 64, 2, 32)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, 64, 2, 32)).astype(np.float32))
        got = jax.jit(lambda q, k, v: ring_attention(q, k, v, mesh_sp4))(q, k, v)
        want = attention_ref(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)

    def test_noncausal_matches_ref(self, mesh_sp4):
        rng = np.random.default_rng(1)
        q, k, v = (
            jnp.asarray(rng.normal(size=(1, 32, 2, 16)).astype(np.float32))
            for _ in range(3)
        )
        got = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh_sp4, causal=False)
        )(q, k, v)
        want = attention_ref(q, k, v, causal=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)

    def test_grads_match_ref(self, mesh_sp4):
        rng = np.random.default_rng(2)
        q = jnp.asarray(rng.normal(size=(1, 32, 4, 16)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 32, 4, 16)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 32, 4, 16)).astype(np.float32))
        g1 = jax.grad(
            lambda q, k, v: ring_attention(q, k, v, mesh_sp4).sum(), argnums=(0, 1, 2)
        )(q, k, v)
        g2 = jax.grad(
            lambda q, k, v: attention_ref(q, k, v, causal=True).sum(), argnums=(0, 1, 2)
        )(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)

    def test_model_forward_with_sp_matches_dense(self, mesh_sp4):
        """Full model forward with ring attention == meshless forward."""
        cfg = get_model_config("tiny").replace(
            d_model=64, n_heads=4, vocab_size=512, dtype="float32"
        )
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)
        dense = transformer.forward(cfg, params, tokens)
        ringed = jax.jit(
            lambda p, t: transformer.forward(cfg, p, t, mesh=mesh_sp4)
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(dense), np.asarray(ringed), rtol=1e-4, atol=1e-4
        )

    @pytest.mark.parametrize("window", [1, 5, 16, 40])
    def test_window_matches_ref(self, mesh_sp4, window):
        """Banded (sliding-window) masking across rotating chunks."""
        rng = np.random.default_rng(7)
        q = jnp.asarray(rng.normal(size=(2, 64, 4, 32)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(2, 64, 2, 32)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, 64, 2, 32)).astype(np.float32))
        got = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh_sp4, window=window)
        )(q, k, v)
        want = attention_ref(q, k, v, causal=True, window=window)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

    def test_window_with_sp_uses_ring(self, mesh_sp4):
        """auto + sliding window on an sp mesh: ulysses can't split 4
        heads over sp=4 after tp=2, so ring (banded) carries it."""
        cfg = get_model_config("tiny").replace(attn_window=8, dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)
        dense = transformer.forward(cfg, params, tokens)
        sharded = jax.jit(
            lambda p, t: transformer.forward(cfg, p, t, mesh=mesh_sp4)
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(dense), np.asarray(sharded), rtol=1e-4, atol=1e-4
        )
        # Explicit ring with a window now also works.
        ringed = jax.jit(
            lambda p, t: transformer.forward(
                cfg, p, t, mesh=mesh_sp4, attn_impl="ring"
            )
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(dense), np.asarray(ringed), rtol=1e-4, atol=1e-4
        )

    def test_ring_without_sp_raises(self):
        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.zeros((1, 16), jnp.int32)
        with pytest.raises(ValueError, match="requires a mesh with sp"):
            transformer.forward(cfg, params, tokens, attn_impl="ring")


class TestRingSegments:
    def test_packed_segments_match_ref(self, mesh_sp4):
        rng = np.random.default_rng(5)
        q = jnp.asarray(rng.normal(size=(2, 64, 4, 32)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(2, 64, 2, 32)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, 64, 2, 32)).astype(np.float32))
        # Segment boundaries NOT aligned to the sp chunking (64/4=16):
        # doc lengths 10, 30, 24 straddle chunk edges.
        seg_row = np.concatenate(
            [np.full(10, 1), np.full(30, 2), np.full(24, 3)]
        )
        segs = jnp.asarray(np.stack([seg_row, seg_row[::-1]]), jnp.int32)
        got = jax.jit(
            lambda q, k, v, s: ring_attention(q, k, v, mesh_sp4, segments=s)
        )(q, k, v, segs)
        want = attention_ref(
            q, k, v, causal=True, q_segments=segs, kv_segments=segs
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

    def test_segments_noncausal(self, mesh_sp4):
        rng = np.random.default_rng(6)
        q, k, v = (
            jnp.asarray(rng.normal(size=(1, 32, 2, 16)).astype(np.float32))
            for _ in range(3)
        )
        segs = jnp.asarray(
            np.repeat(np.array([[1, 2, 2, 3]]), 8, axis=1), jnp.int32
        )
        got = jax.jit(
            lambda q, k, v, s: ring_attention(
                q, k, v, mesh_sp4, causal=False, segments=s
            )
        )(q, k, v, segs)
        want = attention_ref(
            q, k, v, causal=False, q_segments=segs, kv_segments=segs
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )
