"""Fused (vocab-chunked) cross-entropy vs the materialized reference.

The fused path must be a pure memory optimization: same loss, same
gradients (both dhidden and dW), same metrics — to fp32 tolerance —
for plain, masked, and z-loss cases, and through a full train step.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.config import TrainConfig
from shellac_tpu.training.losses import cross_entropy, fused_cross_entropy


def _setup(n=24, d=32, v=64, seed=0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    hidden = jax.random.normal(ks[0], (2, n // 2, d), jnp.float32)
    w = jax.random.normal(ks[1], (d, v), jnp.float32) * 0.1
    targets = jax.random.randint(ks[2], (2, n // 2), 0, v)
    return hidden, w, targets


class TestFusedVsRef:
    @pytest.mark.parametrize("chunk", [16, 32, 64])
    @pytest.mark.parametrize("zw", [0.0, 1e-3])
    def test_loss_and_grads_match(self, chunk, zw):
        hidden, w, targets = _setup()
        mask = jnp.asarray(
            np.random.default_rng(1).random((2, 12)) > 0.3, jnp.float32
        )

        def ref(h, w):
            logits = jnp.einsum(
                "bsd,dv->bsv", h, w, preferred_element_type=jnp.float32
            )
            return cross_entropy(logits, targets, mask, zw)[0]

        def fused(h, w):
            return fused_cross_entropy(
                h, w, targets, mask, zw, vocab_chunk=chunk
            )[0]

        np.testing.assert_allclose(
            float(fused(hidden, w)), float(ref(hidden, w)), rtol=1e-5
        )
        gf = jax.grad(fused, argnums=(0, 1))(hidden, w)
        gr = jax.grad(ref, argnums=(0, 1))(hidden, w)
        for name, a, b in zip(("dhidden", "dw"), gf, gr):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-6,
                err_msg=name,
            )

    def test_no_mask(self):
        hidden, w, targets = _setup(seed=3)
        logits = jnp.einsum("bsd,dv->bsv", hidden, w,
                            preferred_element_type=jnp.float32)
        ref_loss, ref_m = cross_entropy(logits, targets)
        f_loss, f_m = fused_cross_entropy(
            hidden, w, targets, vocab_chunk=32
        )
        np.testing.assert_allclose(float(f_loss), float(ref_loss), rtol=1e-5)
        np.testing.assert_allclose(
            float(f_m["tokens"]), float(ref_m["tokens"])
        )

    def test_bad_chunk_raises(self):
        hidden, w, targets = _setup()
        with pytest.raises(ValueError, match="not divisible"):
            fused_cross_entropy(hidden, w, targets, vocab_chunk=48)

    def test_bf16_inputs(self):
        """Compute-dtype inputs (the real train-step case)."""
        hidden, w, targets = _setup(seed=4)
        h16, w16 = hidden.astype(jnp.bfloat16), w.astype(jnp.bfloat16)
        logits = jnp.einsum("bsd,dv->bsv", h16, w16,
                            preferred_element_type=jnp.float32)
        ref_loss, _ = cross_entropy(logits, targets)
        f_loss, _ = fused_cross_entropy(h16, w16, targets, vocab_chunk=16)
        np.testing.assert_allclose(float(f_loss), float(ref_loss), rtol=1e-4)


class TestFusedTrainStep:
    def test_step_matches_unfused(self):
        from shellac_tpu.training import init_train_state, make_train_step

        cfg = get_model_config("tiny")
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size
        )
        batch = {"inputs": tokens, "targets": tokens}
        losses = {}
        for chunk in (None, 64):
            tcfg = TrainConfig(
                learning_rate=1e-3, warmup_steps=1, total_steps=10,
                fused_loss_chunk=chunk,
            )
            state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
            step = make_train_step(cfg, tcfg)
            for _ in range(5):
                state, m = step(state, batch)
            losses[chunk] = float(m["loss"])
        np.testing.assert_allclose(losses[64], losses[None], rtol=1e-4)

    def test_softcap_falls_back(self):
        """Models with logit softcap silently use the unfused path."""
        from shellac_tpu.training import init_train_state, make_train_step

        cfg = get_model_config("tiny").replace(logit_softcap=30.0)
        tcfg = TrainConfig(
            warmup_steps=1, total_steps=5, fused_loss_chunk=64
        )
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
        step = make_train_step(cfg, tcfg)
        state, m = step(state, {"inputs": jnp.zeros((2, 16), jnp.int32),
                                "targets": jnp.zeros((2, 16), jnp.int32)})
        assert np.isfinite(float(m["loss"]))

    def test_fused_on_mesh(self, mesh_fsdp8):
        """Fused loss composes with GSPMD sharding (fsdp mesh)."""
        from shellac_tpu.training import (
            batch_shardings,
            init_train_state,
            make_train_step,
        )

        cfg = get_model_config("tiny")
        tcfg = TrainConfig(
            warmup_steps=1, total_steps=5, fused_loss_chunk=64
        )
        state = init_train_state(
            cfg, tcfg, jax.random.PRNGKey(0), mesh=mesh_fsdp8
        )
        step = make_train_step(cfg, tcfg, mesh=mesh_fsdp8)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size
        )
        bs = batch_shardings(mesh_fsdp8)
        batch = {
            "inputs": jax.device_put(tokens, bs),
            "targets": jax.device_put(tokens, bs),
        }
        state, m = step(state, batch)
        assert np.isfinite(float(m["loss"]))
