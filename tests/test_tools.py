"""Tool calling & structured output over the byte-DFA engine.

OpenAI-client-shaped conformance: `tools`/`tool_choice` on
/v1/chat/completions produce `message.tool_calls` whose `arguments`
parse as JSON and validate against the declared parameter schema —
enforced by the token DFA (asserted via a logit-mask probe over the
compiled transition table, not just output inspection). Streamed
tool-call delta chunks must reassemble to byte-identical JSON with the
non-streamed result, and the serving tier must relay tool-call streams
unmodified.

Schemas in the HTTP tests are fully BOUNDED (enums, not free strings):
an untrained model under a grammar with an unbounded value (a free
string, an integer) greedily never terminates it, which is the
length-truncation case — tested separately, not a flake source.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.constraints import (
    CharDFA,
    compile_token_dfa,
)
from shellac_tpu.inference.tools import (
    SENTINEL,
    ToolCallStreamParser,
    events_to_stream,
    parse_payload_tools,
    parse_tool_calls,
    render_tool_calls,
    safe_stream_text,
    tool_grammar,
    tools_prompt_block,
)
from shellac_tpu.models import transformer
from shellac_tpu.training.tokenizer import ByteTokenizer

EOS = ByteTokenizer.EOS  # 257


def _cfg():
    return get_model_config("tiny").replace(
        dtype="float32", vocab_size=ByteTokenizer.vocab_size
    )


def _matcher(pattern):
    d = CharDFA(pattern)

    def m(s):
        st = d.start
        for ch in s:
            st = d.step(st, ch)
            if st is None:
                return False
        return d.accepting(st)

    return m


def _fn(name, params, description=""):
    return {"type": "function", "function": {
        "name": name, "description": description, "parameters": params,
    }}


WEATHER = _fn("get_weather", {
    "type": "object",
    "properties": {"city": {"enum": ["oslo", "rio"]},
                   "days": {"enum": [1, 2, 3]}},
    "required": ["city", "days"],
}, description="weather lookup")

CALC = _fn("calc", {
    "type": "object",
    "properties": {"op": {"enum": ["add", "mul"]}},
    "required": ["op"],
})

CALL = '{"name":"get_weather","arguments":{"city":"oslo","days":2}}'


class TestGrammar:
    def test_required_forces_call(self):
        m = _matcher(tool_grammar([dict(WEATHER["function"])],
                                  "required"))
        assert m(SENTINEL + "[" + CALL + "]")
        assert m(SENTINEL + "[" + CALL + "," + CALL + "]")
        assert not m("sure, it is sunny")          # free text forbidden
        assert not m(SENTINEL + "[]")              # empty calls array
        assert not m(SENTINEL + "[" + CALL)        # unterminated
        assert not m(
            SENTINEL + '[{"name":"get_weather","arguments":'
            '{"city":"paris","days":2}}]'          # off-enum argument
        )

    def test_auto_allows_free_text_not_starting_sentinel(self):
        m = _matcher(tool_grammar(
            [dict(WEATHER["function"])], "auto"))
        assert m("it is sunny in oslo")
        assert m("")                               # empty output legal
        assert m(SENTINEL + "[" + CALL + "]")
        # Starting '<' commits to the sentinel: a '<'-prefixed non-call
        # is out of grammar (later '<' is fine).
        assert not m("<html>hello")
        assert m("a <b> c")

    def test_named_restricts_to_forced_tool(self):
        fns = [dict(WEATHER["function"]), dict(CALC["function"])]
        m = _matcher(tool_grammar(fns, "named", forced_name="calc"))
        assert m(SENTINEL + '[{"name":"calc","arguments":{"op":"add"}}]')
        assert not m(SENTINEL + "[" + CALL + "]")

    def test_parallel_false_forbids_second_call(self):
        m = _matcher(tool_grammar([dict(WEATHER["function"])],
                                  "required", parallel=False))
        assert m(SENTINEL + "[" + CALL + "]")
        assert not m(SENTINEL + "[" + CALL + "," + CALL + "]")

    def test_ref_in_parameters_resolves_against_parameters(self):
        """A tool schema's local `$ref` must resolve against the
        PARAMETERS document, not the synthesized {"name","arguments"}
        wrapper the grammar embeds it in."""
        fns = [{"name": "pick", "description": "", "parameters": {
            "$defs": {"c": {"enum": ["oslo", "rio"]}},
            "type": "object",
            "properties": {"city": {"$ref": "#/$defs/c"}},
            "required": ["city"],
        }}]
        m = _matcher(tool_grammar(fns, "required", parallel=False))
        assert m(SENTINEL
                 + '[{"name":"pick","arguments":{"city":"rio"}}]')
        assert not m(SENTINEL
                     + '[{"name":"pick","arguments":{"city":"ugh"}}]')

    def test_cyclic_ref_in_parameters_fails_loudly(self):
        fns = [{"name": "loopy", "description": "", "parameters": {
            "$defs": {"a": {"$ref": "#/$defs/a"}},
            "type": "object",
            "properties": {"x": {"$ref": "#/$defs/a"}},
            "required": ["x"],
        }}]
        with pytest.raises(ValueError, match="cyclic"):
            tool_grammar(fns, "required", parallel=False)

    def test_undeclared_parameters_accept_any_object(self):
        fns = [{"name": "log", "description": "", "parameters": None}]
        m = _matcher(tool_grammar(fns, "required", parallel=False))
        assert m(SENTINEL + '[{"name":"log","arguments":{}}]')
        assert m(SENTINEL
                 + '[{"name":"log","arguments":{"x":[1,"a"],"y":null}}]')
        assert not m(SENTINEL + '[{"name":"log","arguments":7}]')


class TestPayloadValidation:
    def test_no_tools_is_none(self):
        assert parse_payload_tools({}) is None
        assert parse_payload_tools({"tool_choice": "none"}) is None

    def test_tool_choice_without_tools_rejected(self):
        with pytest.raises(ValueError, match="tools"):
            parse_payload_tools({"tool_choice": "required"})

    def test_modes(self):
        base = {"tools": [WEATHER, CALC]}
        assert parse_payload_tools(base).mode == "auto"
        assert parse_payload_tools(
            base | {"tool_choice": "auto"}).mode == "auto"
        none = parse_payload_tools(base | {"tool_choice": "none"})
        assert none.mode == "none" and none.pattern is None
        req = parse_payload_tools(base | {"tool_choice": "required"})
        assert req.mode == "required" and req.pattern is not None
        named = parse_payload_tools(base | {"tool_choice": {
            "type": "function", "function": {"name": "calc"}}})
        assert named.mode == "named" and named.forced_name == "calc"

    @pytest.mark.parametrize("payload,msg", [
        ({"tools": []}, "non-empty"),
        ({"tools": [{"type": "retrieval"}]}, "not supported"),
        ({"tools": [{"type": "function", "function": {}}]}, "name"),
        ({"tools": [_fn("bad name!", None)]}, "bad tool name"),
        ({"tools": [_fn("a", None), _fn("a", None)]}, "duplicate"),
        ({"tools": [_fn("a", "not-a-schema")]}, "schema object"),
        ({"tools": [WEATHER], "tool_choice": {
            "type": "function", "function": {"name": "ghost"}}},
         "unknown tool"),
        ({"tools": [WEATHER], "tool_choice": "sometimes"},
         "bad tool_choice"),
        ({"tools": [WEATHER], "parallel_tool_calls": "yes"}, "boolean"),
    ])
    def test_malformed_shapes_rejected(self, payload, msg):
        with pytest.raises(ValueError, match=msg):
            parse_payload_tools(payload)


class TestStreamParser:
    SURFACE = SENTINEL + "[" + CALL + "," + \
        '{"name":"calc","arguments":{"op":"mul"}}' + "]"

    def _feed_in_pieces(self, text, size):
        p = ToolCallStreamParser("required")
        events = []
        for i in range(size, len(text) + size, size):
            events.extend(p.feed(text[:i]))
        return p, events

    @pytest.mark.parametrize("size", [1, 3, 7, 1000])
    def test_incremental_reassembly(self, size):
        p, events = self._feed_in_pieces(self.SURFACE, size)
        calls = p.result()
        assert [c["function"]["name"] for c in calls] == \
            ["get_weather", "calc"]
        # Fragments concatenate to byte-identical arguments JSON.
        frags = ["", ""]
        heads = 0
        for kind, val in events:
            assert kind == "tool_delta"
            if "id" in val:
                heads += 1
                assert val["type"] == "function"
                assert val["function"]["arguments"] == ""
            else:
                frags[val["index"]] += val["function"]["arguments"]
        assert heads == 2
        assert frags[0] == '{"city":"oslo","days":2}'
        assert frags[1] == '{"op":"mul"}'
        assert [c["function"]["arguments"] for c in calls] == frags
        assert all(c["id"].startswith("call_") for c in calls)

    def test_auto_free_text_streams_as_content(self):
        p = ToolCallStreamParser("auto")
        ev = p.feed("well")
        ev += p.feed("well, hello")
        assert [k for k, _ in ev] == ["content", "content"]
        assert "".join(v for _, v in ev) == "well, hello"
        assert p.result() is None

    def test_sentinel_prefix_is_withheld_until_decided(self):
        p = ToolCallStreamParser("auto")
        assert p.feed("<too") == []        # could still become a call
        ev = p.feed("<tool_call>[" + CALL + "]")
        assert ev and ev[0][0] == "tool_delta"
        assert p.result() is not None

    def test_truncated_call_falls_back_to_content(self):
        text = SENTINEL + "[" + CALL[:20]
        content, calls = parse_tool_calls(text, "required")
        assert calls is None
        assert content == text            # raw text, never a fabrication
        p = ToolCallStreamParser("required")
        p.feed(text)
        assert p.result() is None

    def test_out_of_grammar_input_breaks_cleanly(self):
        p = ToolCallStreamParser("required")
        p.feed(SENTINEL + "[oops]")
        assert p.broken and p.result() is None

    def test_events_to_stream_shapes(self):
        assert events_to_stream([]) is None
        out = events_to_stream([("content", "hi"), ("content", "!"),
                                ("tool_delta", {"index": 0})])
        assert out == {"content": "hi!", "tool_calls": [{"index": 0}]}

    def test_safe_stream_text_trims_partial_utf8(self):
        assert safe_stream_text("ab�") == "ab"
        assert safe_stream_text("ab") == "ab"

    def test_render_round_trips_through_parser(self):
        calls = [{"id": "call_1", "type": "function", "function": {
            "name": "get_weather",
            "arguments": '{"city":"oslo","days":2}'}}]
        surface = render_tool_calls(calls)
        _, parsed = parse_tool_calls(surface, "required")
        assert parsed is not None
        assert parsed[0]["function"]["name"] == "get_weather"
        assert (json.loads(parsed[0]["function"]["arguments"])
                == {"city": "oslo", "days": 2})

    def test_prompt_block_is_deterministic(self):
        fns = parse_payload_tools({"tools": [WEATHER, CALC]}).functions
        assert tools_prompt_block(fns) == tools_prompt_block(fns)
        assert "get_weather" in tools_prompt_block(fns)
        assert SENTINEL in tools_prompt_block(fns)


@pytest.fixture(scope="module")
def http_srv():
    from shellac_tpu.inference.server import (
        InferenceServer,
        make_http_server,
    )

    cfg = _cfg()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    srv = InferenceServer(
        cfg, params, tokenizer=ByteTokenizer(), model_name="tiny",
        n_slots=2, max_len=1024, temperature=0.0, eos_id=EOS,
    )
    httpd = make_http_server(srv)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield base
    httpd.shutdown()
    srv.close()


def _post(base, path, payload):
    req = urllib.request.Request(
        base + path, json.dumps(payload).encode(),
        {"Content-Type": "application/json"},
    )
    return json.loads(urllib.request.urlopen(req, timeout=300).read())


def _sse(base, path, payload):
    req = urllib.request.Request(
        base + path, json.dumps(payload).encode(),
        {"Content-Type": "application/json"},
    )
    chunks, done = [], False
    with urllib.request.urlopen(req, timeout=300) as r:
        for raw in r:
            line = raw.decode().strip()
            if not line.startswith("data: "):
                continue
            data = line[len("data: "):]
            if data == "[DONE]":
                done = True
                break
            chunks.append(json.loads(data))
    return chunks, done


def _chat(messages, **kw):
    return {"messages": messages, "max_tokens": 120,
            "tools": [WEATHER, CALC], "parallel_tool_calls": False, **kw}


def _user(text):
    return [{"role": "user", "content": text}]


def _reassemble(chunks):
    """OpenAI-client-shaped SSE reassembly: index-keyed calls, id/name
    from the head delta, arguments concatenated across fragments."""
    calls, content = {}, ""
    finish = None
    for c in chunks:
        choice = c["choices"][0]
        finish = choice["finish_reason"] or finish
        d = choice["delta"]
        content += d.get("content") or ""
        for item in d.get("tool_calls", []):
            slot = calls.setdefault(item["index"],
                                    {"id": None, "name": None, "args": ""})
            if "id" in item:
                slot["id"] = item["id"]
                slot["name"] = item["function"]["name"]
            slot["args"] += item["function"].get("arguments", "")
    return calls, content, finish


def _assert_weather_args(args_json):
    args = json.loads(args_json)
    assert set(args) == {"city", "days"}
    assert args["city"] in ("oslo", "rio")
    assert args["days"] in (1, 2, 3)
    return args


class TestToolCallingHTTP:
    def test_required_returns_schema_valid_call(self, http_srv):
        r = _post(http_srv, "/v1/chat/completions",
                  _chat(_user("weather in oslo?"),
                        tool_choice="required"))
        ch = r["choices"][0]
        assert ch["finish_reason"] == "tool_calls"
        msg = ch["message"]
        assert msg["content"] is None
        (tc,) = msg["tool_calls"]
        assert tc["type"] == "function"
        assert tc["id"].startswith("call_")
        assert tc["function"]["name"] in ("get_weather", "calc")
        if tc["function"]["name"] == "get_weather":
            _assert_weather_args(tc["function"]["arguments"])

    def test_named_tool_forcing(self, http_srv):
        for name in ("get_weather", "calc"):
            r = _post(http_srv, "/v1/chat/completions",
                      _chat(_user("do something"),
                            tool_choice={"type": "function",
                                         "function": {"name": name}}))
            (tc,) = r["choices"][0]["message"]["tool_calls"]
            assert tc["function"]["name"] == name

    def test_dfa_logit_mask_enforces_grammar(self, http_srv):
        """The probe: walk the emitted token ids through the compiled
        transition table. Every emitted token must be a legal move of
        the advancing DFA state, the mask must be NON-trivial at every
        step (some token forbidden — a trivial mask proves nothing),
        and the same prompt unconstrained must not produce the
        sentinel — i.e. the grammar came from the mask, not the
        model."""
        payload = {"text": "weather? ", "max_new": 120,
                   "tools": [WEATHER], "tool_choice": "required",
                   "parallel_tool_calls": False}
        r = _post(http_srv, "/generate", payload)
        assert r.get("tool_calls"), r
        ctx = parse_payload_tools(payload)
        dfa = compile_token_dfa(ctx.pattern, ByteTokenizer(),
                                ByteTokenizer.vocab_size, EOS)
        st = 0
        for t in r["tokens"]:
            row = dfa.trans[st]
            col = row.shape[0] - 1 if t == EOS else t
            assert row[col] >= 0, (st, t)
            assert (row[:-1] < 0).any(), "mask trivial at state %d" % st
            st = int(row[col])
        bare = _post(http_srv, "/generate",
                     {"text": payload["text"], "max_new": 120})
        assert not bare["text"].startswith(SENTINEL)

    def test_streamed_deltas_reassemble_to_valid_json(self, http_srv):
        body = _chat(_user("weather in oslo?"), tool_choice="required")
        plain = _post(http_srv, "/v1/chat/completions", body)
        chunks, done = _sse(http_srv, "/v1/chat/completions",
                            body | {"stream": True})
        assert done
        calls, content, finish = _reassemble(chunks)
        assert finish == "tool_calls"
        assert content == ""
        (ptc,) = plain["choices"][0]["message"]["tool_calls"]
        assert calls[0]["name"] == ptc["function"]["name"]
        # Greedy + DFA-masked: the streamed arguments are byte-identical
        # to the non-streamed request's.
        assert calls[0]["args"] == ptc["function"]["arguments"]
        json.loads(calls[0]["args"])

    def test_multi_turn_with_tool_role(self, http_srv):
        messages = [
            {"role": "user", "content": "weather in oslo?"},
            {"role": "assistant", "content": None, "tool_calls": [
                {"id": "call_h1", "type": "function", "function": {
                    "name": "get_weather",
                    "arguments": '{"city":"oslo","days":1}'}}]},
            {"role": "tool", "tool_call_id": "call_h1",
             "content": "sunny, 21C"},
        ]
        r = _post(http_srv, "/v1/chat/completions",
                  _chat(messages, tool_choice="auto"))
        ch = r["choices"][0]
        msg = ch["message"]
        # auto: either a follow-up call or free text — both must be
        # well-formed, never both at once.
        if ch["finish_reason"] == "tool_calls":
            assert msg["content"] is None and msg["tool_calls"]
        else:
            assert isinstance(msg["content"], str)
            assert "tool_calls" not in msg

    def test_tool_choice_none_renders_but_never_parses(self, http_srv):
        r = _post(http_srv, "/v1/chat/completions",
                  _chat(_user("hi"), tool_choice="none"))
        msg = r["choices"][0]["message"]
        assert isinstance(msg["content"], str)
        assert "tool_calls" not in msg

    @pytest.mark.parametrize("path,payload,msg", [
        ("/v1/completions",
         {"prompt": "x", "tools": [WEATHER]}, "chat-completions"),
        ("/v1/chat/completions",
         {"messages": [{"role": "user", "content": "x"}],
          "tools": [WEATHER], "num_beams": 2}, "num_beams"),
        ("/generate",
         {"text": "x", "tools": [WEATHER],
          "constraint": {"regex": "a+"}}, "constraint"),
        ("/v1/chat/completions",
         {"messages": [{"role": "user", "content": "x"}],
          "tool_choice": "required"}, "tools"),
    ])
    def test_bad_compositions_are_http_400(self, http_srv, path,
                                           payload, msg):
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(http_srv, path, payload)
        assert e.value.code == 400
        assert msg in e.value.read().decode()

    def test_tool_metrics_exported(self, http_srv):
        body = urllib.request.urlopen(http_srv + "/metrics",
                                      timeout=30).read().decode()
        assert "shellac_tool_requests_total" in body
        assert "shellac_constraint_cache_total" in body
        assert "shellac_constraint_compile_seconds" in body


class TestBeamOverHTTP:
    def test_native_beams_compose_with_constraint(self, http_srv):
        r = _post(http_srv, "/generate", {
            "text": "choose: ", "max_new": 16, "num_beams": 4,
            "constraint": {"regex": "(yes|no|maybe)"},
        })
        assert 1 <= len(r["choices"]) <= 4
        m = _matcher("(yes|no|maybe)")
        texts = [c["text"] for c in r["choices"]]
        assert len(set(texts)) == len(texts)  # beams are distinct
        for c in r["choices"]:
            assert m(c["text"]), c
            assert c["beam_score"] <= 0.0
        scores = [c["beam_score"] for c in r["choices"]]
        assert scores == sorted(scores, reverse=True)

    def test_openai_num_beams_with_json_schema(self, http_srv):
        r = _post(http_srv, "/v1/chat/completions", {
            "messages": _user("pick"), "max_tokens": 24, "num_beams": 3,
            "response_format": {"type": "json_schema", "json_schema": {
                "name": "o", "schema": {
                    "type": "object",
                    "properties": {"ok": {"type": "boolean"}},
                    "required": ["ok"]}}},
        })
        assert 1 <= len(r["choices"]) <= 3
        for c in r["choices"]:
            v = json.loads(c["message"]["content"])
            assert isinstance(v["ok"], bool)
            assert "beam_score" in c

    def test_beam_rejects_non_neutral_sampling(self, http_srv):
        for extra in ({"stream": True}, {"temperature": 0.7}, {"n": 2},
                      {"logprobs": True}):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(http_srv, "/generate",
                      {"text": "x", "max_new": 8, "num_beams": 2,
                       **extra})
            assert e.value.code == 400

    def test_beam_cap_is_loud(self, http_srv):
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(http_srv, "/generate",
                  {"text": "x", "max_new": 8, "num_beams": 4096})
        assert e.value.code == 400
        assert "cap" in e.value.read().decode()


class TestTierPassThrough:
    def test_router_relays_tool_call_stream_unmodified(self, http_srv):
        """The serving tier forwards tool-call SSE streams verbatim:
        same chunk structure, same reassembled call as a direct
        replica request (ids are per-request random, so compare
        everything but the ids)."""
        from shellac_tpu.inference.tier import (
            TierRouter,
            make_tier_http_server,
        )

        router = TierRouter([http_srv], health_interval=0.1,
                            metrics=False)
        tier_httpd = make_tier_http_server(router)
        t = threading.Thread(target=tier_httpd.serve_forever,
                             daemon=True)
        t.start()
        tier_base = f"http://127.0.0.1:{tier_httpd.server_address[1]}"
        try:
            deadline = time.monotonic() + 10
            while time.monotonic() < deadline:
                if any(x.state == "healthy" for x in router.replicas):
                    break
                time.sleep(0.05)
            else:
                pytest.fail("replica never became routable")
            body = _chat(_user("weather in oslo?"),
                         tool_choice="required", stream=True)
            direct, ddone = _sse(http_srv, "/v1/chat/completions", body)
            relayed, rdone = _sse(tier_base, "/v1/chat/completions",
                                  body)
            assert ddone and rdone
            dc, dcontent, dfinish = _reassemble(direct)
            rc, rcontent, rfinish = _reassemble(relayed)
            assert rfinish == dfinish == "tool_calls"
            assert rcontent == dcontent == ""
            assert rc[0]["name"] == dc[0]["name"]
            assert rc[0]["args"] == dc[0]["args"]
            assert rc[0]["name"] in ("get_weather", "calc")
            if rc[0]["name"] == "get_weather":
                _assert_weather_args(rc[0]["args"])
            else:
                assert json.loads(rc[0]["args"])["op"] in ("add", "mul")
            # Chunk-for-chunk relay: same count, same delta payloads
            # (ids/created/trace ids differ per request — strip them).
            def strip(chunks):
                out = []
                for c in chunks:
                    c = json.loads(json.dumps(c))
                    c.pop("id", None)
                    c.pop("created", None)
                    c.pop("trace_id", None)
                    for ch in c["choices"]:
                        for item in ch["delta"].get("tool_calls", []):
                            item.pop("id", None)
                    out.append(c)
                return out
            assert strip(relayed) == strip(direct)
        finally:
            tier_httpd.shutdown()
            router.close()
