"""Native (C++) data loader tests. Builds the .so on first run."""

import numpy as np
import pytest

from shellac_tpu.training.data import shard_batches, write_token_shard

pytest.importorskip("ctypes")


def _make_shards(tmp_path, n=2, tokens_each=5000):
    paths = []
    for i in range(n):
        p = str(tmp_path / f"s{i}.bin")
        write_token_shard(
            p, (np.arange(tokens_each, dtype=np.int32) + i * tokens_each) % 32768
        )
        paths.append(p)
    return paths


@pytest.fixture(scope="module")
def native_available():
    from shellac_tpu.runtime.loader import ensure_built

    try:
        ensure_built()
        return True
    except OSError:
        pytest.skip("no C++ toolchain available")


class TestNativeLoader:
    def test_batches_and_window_consistency(self, tmp_path, native_available):
        from shellac_tpu.runtime.loader import NativeShardReader

        paths = _make_shards(tmp_path)
        r = NativeShardReader(paths, seed=1)
        assert r.total_tokens == 10000
        batches = list(r.batches(batch_size=4, seq_len=64, num_batches=3))
        assert len(batches) == 3
        for b in batches:
            assert b["inputs"].shape == (4, 64)
            assert b["inputs"].dtype == np.int32
            # targets are inputs shifted by one within the same window
            np.testing.assert_array_equal(b["inputs"][:, 1:], b["targets"][:, :-1])

    def test_single_thread_deterministic(self, tmp_path, native_available):
        from shellac_tpu.runtime.loader import NativeShardReader

        paths = _make_shards(tmp_path)

        def first_batch():
            r = NativeShardReader(paths, seed=7)
            return next(
                r.batches(batch_size=4, seq_len=32, num_batches=1, num_threads=1)
            )

        b1, b2 = first_batch(), first_batch()
        np.testing.assert_array_equal(b1["inputs"], b2["inputs"])

    def test_bad_shard_raises(self, tmp_path, native_available):
        from shellac_tpu.runtime.loader import NativeShardReader

        bad = str(tmp_path / "bad.bin")
        with open(bad, "wb") as f:
            f.write(b"x" * 64)
        with pytest.raises(ValueError, match="bad magic"):
            NativeShardReader([bad])

    def test_shard_smaller_than_seq_raises(self, tmp_path, native_available):
        from shellac_tpu.runtime.loader import NativeShardReader

        p = str(tmp_path / "tiny.bin")
        write_token_shard(p, np.arange(10, dtype=np.int32))
        r = NativeShardReader([p])
        with pytest.raises(ValueError, match="seq_len"):
            next(r.batches(batch_size=1, seq_len=64, num_batches=1))

    def test_shard_batches_uses_native(self, tmp_path, native_available):
        paths = _make_shards(tmp_path)
        got = list(
            shard_batches(paths, batch_size=2, seq_len=16, num_batches=2)
        )
        assert len(got) == 2
        assert got[0]["inputs"].shape == (2, 16)

    def test_values_come_from_shards(self, tmp_path, native_available):
        from shellac_tpu.runtime.loader import NativeShardReader

        # One shard of constant value: every batch must be that constant.
        p = str(tmp_path / "const.bin")
        write_token_shard(p, np.full(1000, 77, np.int32))
        r = NativeShardReader([p])
        b = next(r.batches(batch_size=2, seq_len=32, num_batches=1))
        assert (b["inputs"] == 77).all() and (b["targets"] == 77).all()
