"""Fleet-wide KV fabric (inference/fabric.py + docs/serving_tier.md
§KV fabric).

  - TestPrefixHelper — the ONE shared prompt-hashing helper: chain
    hashes are deterministic, canonicalized across input types, and
    prefix-monotone; affinity head/hash match the tier's routing key
    semantics; the tier-computed chain tip is exactly the hash the
    paged backend registers (the identity the directory depends on).
  - TestPrefixDirectory — pure directory units: manifest folding,
    unchanged/unsupported replies, overlap measured in tokens,
    per-chain hit deltas, fleet aggregation, forget-on-respawn.
  - TestKVParkStore — spool durability: atomic writes, torn-file
    quarantine at crc read-back, LRU trim, id validation.
  - TestChainSeedEngine — export_chain -> wire -> seed_chain onto a
    fresh engine gives bit-identical greedy continuations with the
    prefix served from seeded blocks; the refusal matrix leaves the
    registry untouched; seeding never evicts live slots (PoolExhausted
    at the headroom check); a torn chain refuses to export.
  - TestFabricHTTP — GET /kv/prefixes (manifest + delta), POST
    /kv/push -> /kv/seed between two live replicas, corrupt-seed
    refusal at the door.
  - TestParkResumeHTTP — park receipt, resume on a DIFFERENT replica
    sharing the spool, unknown-id 400, torn-spool 500 + quarantine,
    park/resume input validation.
  - TestTierFabric — the routing acceptance: the tier's directory
    learns replica cache contents, routes by measured overlap, and
    the replication planner pushes a hot chain to the peer, which then
    serves the hot prefix without re-prefilling (seeded blocks + hit
    tokens asserted via /metrics); a stale directory entry after a
    replica death costs a miss, never an error.
  - TestParkResumeChaos — THE park acceptance: freeze + park on one
    real replica process, SIGKILL it, resume on a survivor — the
    continuation is token-identical to an uninterrupted run.

Everything that builds an engine is marked `slow` (test_fabric.py is
an early-alphabet file; the dedicated `kv-fabric` CI job runs the
module unfiltered — the disagg precedent).
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference import disagg, fabric
from shellac_tpu.inference import prefix as prefix_mod
from shellac_tpu.inference.cache import PoolExhausted, engine_class
from shellac_tpu.inference.server import InferenceServer, make_http_server
from shellac_tpu.models import transformer
from shellac_tpu.obs import Registry
from shellac_tpu.training.tokenizer import ByteTokenizer

BLOCK = 16
#: 64 tokens = 4 full blocks AND the whole PR 6 affinity head, so every
#: request sharing it routes to the same replica by affinity.
PREFIX = [(i * 7 + 3) % 200 + 1 for i in range(64)]


def _tail(seed, n=4):
    return [(seed * 13 + j * 5) % 200 + 1 for j in range(n)]


def _tiny():
    return get_model_config("tiny").replace(dtype="float32")


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny()
    return cfg, transformer.init_params(cfg, jax.random.PRNGKey(0))


def _paged_engine(cfg, params, **kw):
    kw.setdefault("n_slots", 2)
    kw.setdefault("max_len", 96)
    kw.setdefault("block_size", BLOCK)
    kw.setdefault("pool_tokens", 4 * 96)
    kw.setdefault("temperature", 0.0)
    kw.setdefault("prefix_cache", True)
    return engine_class("paged")(cfg, params, cache_backend="paged", **kw)


def _drain(eng):
    out = {}
    while eng.pending:
        out.update(eng.step())
    return out


def _post(base, path, payload, headers=None, timeout=120):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, r.read()


def _get_json(base, path, timeout=30):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


def _metric(base, prefix, timeout=30):
    """First sample whose exposition line starts with `prefix` (pass
    the full `name{label="v"}` form for labeled series), or None."""
    with urllib.request.urlopen(base + "/metrics", timeout=timeout) as r:
        text = r.read().decode()
    for ln in text.splitlines():
        if ln.startswith(prefix + " "):
            return float(ln.rsplit(" ", 1)[1])
    return None


# ---------------------------------------------------------------------
# The shared prefix-hashing helper (fast: no engines)
# ---------------------------------------------------------------------


class TestPrefixHelper:
    def test_chain_hashes_deterministic_and_canonical(self):
        toks = list(range(64))
        h = prefix_mod.chain_hashes(toks, 16)
        assert len(h) == 4
        assert all(isinstance(x, bytes) and len(x) == 16 for x in h)
        # Canonicalization: list, int64 array, int32 array all agree.
        assert prefix_mod.chain_hashes(np.asarray(toks, np.int64), 16) == h
        assert prefix_mod.chain_hashes(np.asarray(toks, np.int32), 16) == h

    def test_chain_is_prefix_monotone(self):
        toks = list(range(64))
        h = prefix_mod.chain_hashes(toks, 16)
        # A shorter prompt's chain is a prefix of the longer one's.
        assert prefix_mod.chain_hashes(toks[:32], 16) == h[:2]
        # A trailing partial block contributes nothing.
        assert prefix_mod.chain_hashes(toks + [7, 7], 16) == h
        # Chaining: same last block after a different first block gives
        # a different tip (position-bound, not content-addressed alone).
        other = [99] + toks[1:]
        assert prefix_mod.chain_hashes(other, 16)[-1] != h[-1]

    def test_affinity_head_token_and_text(self):
        head, est = prefix_mod.affinity_head(list(range(100)))
        assert est == 100
        # Only the first 64 tokens key the route.
        head2, _ = prefix_mod.affinity_head(list(range(64)) + [999])
        assert head2 == head
        key = prefix_mod.affinity_hash(head)
        assert key.startswith("p:") and len(key) == 18
        shead, sest = prefix_mod.affinity_head("x" * 600)
        assert len(shead) == 256 and sest == 150
        assert prefix_mod.affinity_hash(shead) != key

    @pytest.mark.slow
    def test_helper_matches_backend_registry(self, tiny_model):
        """The identity the directory depends on: the tier-computed
        chain tip for a prompt is byte-for-byte the hash the paged
        backend registered when it served that prompt."""
        cfg, params = tiny_model
        eng = _paged_engine(cfg, params)
        eng.run([("r", PREFIX + _tail(1), 2)])
        chain = prefix_mod.chain_hashes(PREFIX, BLOCK)
        backend = eng.cache_backend
        for h in chain:
            assert h in backend._hash_to_block
        assert backend._hash_depth[chain[-1]] == 4


# ---------------------------------------------------------------------
# Prefix directory (fast: pure, fed synthetic manifests)
# ---------------------------------------------------------------------


def _doc(hashes, hot=(), version=1, bs=BLOCK):
    return {"supported": True, "version": version, "block_size": bs,
            "blocks": [h.hex() for h in hashes],
            "blocks_total": len(hashes), "hot": list(hot)}


class TestPrefixDirectory:
    def test_overlap_measured_in_tokens(self):
        d = fabric.PrefixDirectory()
        chain = prefix_mod.chain_hashes(PREFIX, BLOCK)
        d.observe("u", _doc(chain))
        assert d.overlap("u", PREFIX + _tail(1)) == 64
        # Partial hold: only the first half of the chain walks.
        d.observe("u", _doc(chain[:2], version=2))
        assert d.overlap("u", PREFIX + _tail(1)) == 32
        # Foreign prompt shares nothing.
        assert d.overlap("u", list(range(64))) == 0
        # Unknown replica / no answer yet.
        assert d.overlap("nope", PREFIX) == 0

    def test_unsupported_and_unchanged(self):
        d = fabric.PrefixDirectory()
        chain = prefix_mod.chain_hashes(PREFIX, BLOCK)
        d.observe("u", {"supported": False})
        assert d.supported("u") is False
        assert d.overlap("u", PREFIX) == 0
        d.observe("u", _doc(chain, version=5))
        assert d.supported("u") and d.since("u") == 5
        # An unchanged delta reply keeps the held contents.
        d.observe("u", {"supported": True, "version": 5,
                        "unchanged": True})
        assert d.overlap("u", PREFIX) == 64

    def test_hit_deltas_and_fleet_aggregation(self):
        d = fabric.PrefixDirectory()
        chain = prefix_mod.chain_hashes(PREFIX, BLOCK)
        tip = chain[-1].hex()
        hot = [{"h": tip, "hits": 5, "depth": 4, "age_s": 0.1}]
        d.observe("a", _doc(chain, hot=hot, version=1))
        agg = d.hot_chains()
        assert agg[tip]["hits"] == 5 and agg[tip]["delta"] == 5
        assert agg[tip]["holders"] == ["a"]
        # Next poll: 3 more hits since.
        hot2 = [{"h": tip, "hits": 8, "depth": 4, "age_s": 0.1}]
        d.observe("a", _doc(chain, hot=hot2, version=2))
        agg = d.hot_chains()
        assert agg[tip]["hits"] == 8 and agg[tip]["delta"] == 3
        # A second holder aggregates.
        d.observe("b", _doc(chain, hot=hot, version=1))
        agg = d.hot_chains()
        assert sorted(agg[tip]["holders"]) == ["a", "b"]
        assert d.holds("a", tip) and d.holds("b", tip)
        assert d.distinct_blocks() == len(chain)

    def test_forget_on_respawn(self):
        d = fabric.PrefixDirectory()
        chain = prefix_mod.chain_hashes(PREFIX, BLOCK)
        d.observe("u", _doc(chain))
        assert d.overlap("u", PREFIX) == 64
        d.forget("u")
        assert d.overlap("u", PREFIX) == 0
        assert d.supported("u") is False
        assert d.since("u") == -1
        assert d.stats() == {}


# ---------------------------------------------------------------------
# Park spool durability (fast: no engines)
# ---------------------------------------------------------------------


def _blob(n=64):
    return disagg.MigrationBlob(
        {"backend": "paged", "length": 8, "complete": False,
         "request": {"out": [1]}},
        {"k": np.arange(n, dtype=np.float32)},
    )


class TestKVParkStore:
    def test_round_trip_and_listing(self, tmp_path):
        store = fabric.KVParkStore(str(tmp_path))
        data = _blob().serialize()
        path = store.put("park-1", data)
        assert os.path.exists(path)
        back = store.get("park-1")
        np.testing.assert_array_equal(back.arrays["k"],
                                      _blob().arrays["k"])
        assert [e["park_id"] for e in store.list()] == ["park-1"]
        # Atomic write discipline: no tmp litter under any outcome.
        assert not [f for f in os.listdir(tmp_path)
                    if f.endswith(".tmp")]
        store.delete("park-1")
        assert store.list() == []
        store.delete("park-1")  # idempotent

    def test_bad_park_id_refused(self, tmp_path):
        store = fabric.KVParkStore(str(tmp_path))
        for bad in ("", "a/b", "../x", "a b"):
            with pytest.raises(ValueError, match="park id"):
                store.put(bad, b"x")

    def test_unknown_id_is_keyerror(self, tmp_path):
        with pytest.raises(KeyError):
            fabric.KVParkStore(str(tmp_path)).get("ghost")

    def test_torn_file_quarantined(self, tmp_path):
        store = fabric.KVParkStore(str(tmp_path))
        store.put("p", _blob().serialize())
        path = store._path("p")
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 5)
        with pytest.raises(ValueError):
            store.get("p")
        assert store.torn_reads == 1
        # Quarantined out of the spool: the retry sees a MISSING park,
        # not the same bad sectors again.
        assert os.path.exists(path + ".torn")
        with pytest.raises(KeyError):
            store.get("p")
        assert store.list() == []

    def test_lru_trim_never_evicts_the_new_park(self, tmp_path):
        data = _blob().serialize()
        store = fabric.KVParkStore(str(tmp_path),
                                   max_bytes=2 * len(data))
        store.put("old", data)
        os.utime(store._path("old"), (1.0, 1.0))
        store.put("mid", data)
        os.utime(store._path("mid"), (2.0, 2.0))
        store.put("new", data)
        ids = {e["park_id"] for e in store.list()}
        assert "new" in ids and "old" not in ids
        # A cap smaller than one blob still admits the newest park.
        tight = fabric.KVParkStore(str(tmp_path / "tight"),
                                   max_bytes=1)
        tight.put("only", data)
        assert [e["park_id"] for e in tight.list()] == ["only"]


# ---------------------------------------------------------------------
# Engine-level chain export / seed
# ---------------------------------------------------------------------


@pytest.mark.slow
class TestChainSeedEngine:
    def _seed_blob(self, cfg, params, wire=True):
        warm = _paged_engine(cfg, params)
        warm.run([("w", PREFIX + _tail(1), 2)])
        tip = prefix_mod.chain_hashes(PREFIX, BLOCK)[-1]
        blob = fabric.export_chain(warm, tip, trace_id="t-1")
        if wire:
            blob = disagg.MigrationBlob.deserialize(blob.serialize())
        return blob

    def test_seed_round_trip_token_identity(self, tiny_model):
        cfg, params = tiny_model
        probe = (PREFIX + _tail(9), 6)
        ctrl = _paged_engine(cfg, params)
        ctrl.run([("warmup", PREFIX + _tail(8), 2)])
        expected = ctrl.run([("c", probe[0], probe[1])])["c"]

        blob = self._seed_blob(cfg, params)
        assert blob.header["kind"] == fabric.SEED_KIND
        assert len(blob.header["chain"]) == 4
        cold = _paged_engine(cfg, params)
        assert fabric.seed_chain(cold, blob) == 4
        assert cold.stats["prefix_seeded_blocks"] == 4
        # Re-seeding the same chain is a no-op, not an error.
        assert fabric.seed_chain(cold, blob) == 0
        got = cold.run([("r", probe[0], probe[1])])["r"]
        assert got == expected
        # The prefix was SERVED from seeded blocks, not re-prefilled.
        assert cold.stats["prefix_hit_tokens"] >= 64

    def test_refusal_matrix_leaves_registry_untouched(self, tiny_model):
        cfg, params = tiny_model
        blob = self._seed_blob(cfg, params)
        cold = _paged_engine(cfg, params)
        backend = cold.cache_backend

        def refused(mutate, match):
            b = disagg.MigrationBlob.deserialize(blob.serialize())
            mutate(b)
            before = (dict(backend._hash_to_block),
                      backend._prefix_version)
            with pytest.raises(ValueError, match=match):
                fabric.seed_chain(cold, b)
            assert (dict(backend._hash_to_block),
                    backend._prefix_version) == before

        refused(lambda b: b.header.update(kind="migration"),
                "not a prefix seed")
        refused(lambda b: b.header.update(backend="dense"),
                "backend")
        refused(lambda b: b.header["model"].update(n_layers=99),
                "geometry")
        refused(lambda b: b.header.update(block_size=32), "pages are")
        refused(lambda b: b.header.update(chain=["zz"]), "malformed")
        refused(lambda b: b.header.update(chain=[]), "empty chain")
        refused(lambda b: b.arrays.update(
            k=b.arrays["k"][:, :2]), "does not cover")
        # Corruption refuses at the wire, before seed_chain ever runs.
        data = bytearray(blob.serialize())
        data[-2] ^= 0xFF
        with pytest.raises(ValueError, match="crc32"):
            disagg.MigrationBlob.deserialize(bytes(data))

    def test_seed_never_evicts_live_slots(self, tiny_model):
        """Headroom rule: with live slots holding the pool, seeding
        raises PoolExhausted instead of evicting — and the live
        requests finish unharmed."""
        cfg, params = tiny_model
        blob = self._seed_blob(cfg, params)
        # 10-block pool: two live 68-token prompts pin 6 blocks (the
        # shared prefix is refcounted), leaving less than one slot's
        # worth of headroom.
        cold = _paged_engine(cfg, params, pool_tokens=160)
        other = [(i * 11 + 2) % 200 + 1 for i in range(64)]
        cold.submit("a", other + _tail(1), 4)
        cold.submit("b", other + _tail(2), 4)
        cold.step()
        before = len(cold.cache_backend._hash_to_block)
        with pytest.raises(PoolExhausted):
            fabric.seed_chain(cold, blob)
        assert len(cold.cache_backend._hash_to_block) == before
        done = _drain(cold)
        assert len(done["a"]) == 4 and len(done["b"]) == 4

    def test_torn_chain_refuses_export(self, tiny_model):
        cfg, params = tiny_model
        warm = _paged_engine(cfg, params)
        warm.run([("w", PREFIX + _tail(1), 2)])
        chain = prefix_mod.chain_hashes(PREFIX, BLOCK)
        # Evict a middle link the way LRU pressure would.
        warm.cache_backend._hash_to_block.pop(chain[1])
        with pytest.raises(ValueError, match="link evicted"):
            fabric.export_chain(warm, chain[-1])


# ---------------------------------------------------------------------
# Replica HTTP surfaces: /kv/prefixes, /kv/push -> /kv/seed
# ---------------------------------------------------------------------


def _mk_server(cfg, params, *, paged=True, **srv_kw):
    reg = Registry()
    if paged:
        eng = _paged_engine(cfg, params, registry=reg)
    else:
        eng = engine_class("dense")(cfg, params, n_slots=2, max_len=96,
                                    cache_backend="dense",
                                    temperature=0.0, registry=reg)
    srv = InferenceServer(cfg, params, tokenizer=ByteTokenizer(),
                          registry=reg, engine=eng, **srv_kw)
    httpd = make_http_server(srv)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return srv, httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


@pytest.mark.slow
class TestFabricHTTP:
    @pytest.fixture(scope="class")
    def pair(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        servers = [_mk_server(cfg, params) for _ in range(2)]
        yield servers
        for srv, httpd, _ in servers:
            httpd.shutdown()
            srv.close()

    def test_manifest_and_delta(self, pair):
        warm_u = pair[0][2]
        payload = {"tokens": PREFIX + _tail(1), "max_new": 2,
                   "temperature": 0.0, "timeout": 120}
        st, _ = _post(warm_u, "/generate", payload)
        assert st == 200
        doc = _get_json(warm_u, "/kv/prefixes")
        assert doc["supported"] and doc["block_size"] == BLOCK
        chain = prefix_mod.chain_hashes(PREFIX, BLOCK)
        for h in chain:
            assert h.hex() in doc["blocks"]
        # Delta poll: same version collapses to unchanged.
        again = _get_json(warm_u,
                          f"/kv/prefixes?since={doc['version']}")
        assert again.get("unchanged") is True
        with pytest.raises(urllib.error.HTTPError) as e:
            _get_json(warm_u, "/kv/prefixes?since=banana")
        assert e.value.code == 400

    def test_dense_replica_reports_unsupported(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        srv, httpd, url = _mk_server(cfg, params, paged=False)
        try:
            assert _get_json(url, "/kv/prefixes") == {"supported": False}
        finally:
            httpd.shutdown()
            srv.close()

    def test_push_seeds_peer_which_serves_without_prefill(self, pair):
        warm_u, cold_u = pair[0][2], pair[1][2]
        tip = prefix_mod.chain_hashes(PREFIX, BLOCK)[-1]
        st, body = _post(warm_u, "/kv/push",
                         {"chain": tip.hex(), "target": cold_u})
        assert st == 200
        rep = json.loads(body)
        assert rep["pushed"] and rep["seeded"] == 4 and rep["bytes"] > 0
        assert _metric(cold_u, "shellac_fabric_seeded_blocks_total") == 4
        assert _metric(cold_u, "shellac_engine_prefix_seeded_blocks") == 4
        # The seeded replica serves the hot prefix WITHOUT
        # re-prefilling it, token-identically to the holder.
        payload = {"tokens": PREFIX + _tail(2), "max_new": 4,
                   "temperature": 0.0, "timeout": 120}
        _, warm_body = _post(warm_u, "/generate", payload)
        _, cold_body = _post(cold_u, "/generate", payload)
        assert (json.loads(cold_body)["tokens"]
                == json.loads(warm_body)["tokens"])
        assert _metric(cold_u, "shellac_engine_prefix_hit_tokens") >= 64
        # Re-pushing the held chain is a cheap no-op.
        st, body = _post(warm_u, "/kv/push",
                         {"chain": tip.hex(), "target": cold_u})
        assert json.loads(body)["seeded"] == 0

    def test_corrupt_seed_refused_at_the_door(self, pair):
        cold_u = pair[1][2]
        before = _get_json(cold_u, "/kv/prefixes")
        req = urllib.request.Request(
            cold_u + "/kv/seed", data=b"garbage-not-a-blob",
            headers={"Content-Type": "application/octet-stream"},
        )
        with pytest.raises(urllib.error.HTTPError) as e:
            urllib.request.urlopen(req, timeout=30)
        assert e.value.code == 400
        assert _metric(
            cold_u,
            'shellac_fabric_seed_rejects_total{reason="corrupt"}') >= 1
        # Registry untouched: same version, same contents.
        after = _get_json(cold_u, "/kv/prefixes")
        assert after["version"] == before["version"]

    def test_push_input_validation(self, pair):
        warm_u, cold_u = pair[0][2], pair[1][2]
        for bad in ({"target": cold_u},
                    {"chain": "zz", "target": cold_u},
                    {"chain": "ab" * 16, "target": "no-scheme"}):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(warm_u, "/kv/push", bad)
            assert e.value.code == 400
        # A chain this replica does not hold is a 400, not a crash.
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(warm_u, "/kv/push",
                  {"chain": "ab" * 16, "target": cold_u})
        assert e.value.code == 400


# ---------------------------------------------------------------------
# Park / resume over HTTP (shared spool, two replicas)
# ---------------------------------------------------------------------


@pytest.mark.slow
class TestParkResumeHTTP:
    @pytest.fixture(scope="class")
    def duo(self, tmp_path_factory):
        spool = str(tmp_path_factory.mktemp("park"))
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        servers = [_mk_server(cfg, params, park_dir=spool)
                   for _ in range(2)]
        yield servers, spool
        for srv, httpd, _ in servers:
            httpd.shutdown()
            srv.close()

    PAYLOAD = {"tokens": PREFIX[:12], "max_new": 6,
               "temperature": 0.0, "timeout": 120}

    def _park(self, url, payload=None):
        st, body = _post(url, "/generate",
                         {**(payload or self.PAYLOAD),
                          "prefill_only": True, "park": True})
        assert st == 200
        receipt = json.loads(body)
        assert receipt["parked"] is True and receipt["bytes"] > 0
        return receipt

    def test_park_resume_on_other_replica_identity(self, duo):
        (a, b), _ = duo
        a_u, b_u = a[2], b[2]
        _, ctrl = _post(b_u, "/generate", self.PAYLOAD)
        ctrl_tokens = json.loads(ctrl)["tokens"]
        receipt = self._park(a_u)
        assert _metric(a_u, "shellac_fabric_parked_total") >= 1
        assert _metric(a_u, "shellac_fabric_park_bytes") > 0
        st, body = _post(b_u, "/generate",
                         {**self.PAYLOAD, "resume": receipt["park_id"]})
        assert st == 200
        assert json.loads(body)["tokens"] == ctrl_tokens
        assert _metric(
            b_u, 'shellac_fabric_resumed_total{outcome="ok"}') >= 1

    def test_unknown_park_id_400(self, duo):
        (_, b), _ = duo
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(b[2], "/generate",
                  {**self.PAYLOAD, "resume": "never-parked"})
        assert e.value.code == 400
        assert _metric(
            b[2],
            'shellac_fabric_resumed_total{outcome="missing"}') >= 1

    def test_torn_spool_file_is_loud_and_quarantined(self, duo):
        (a, b), spool = duo
        receipt = self._park(a[2])
        path = os.path.join(spool, receipt["park_id"] + ".shlkv")
        with open(path, "r+b") as f:
            f.truncate(os.path.getsize(path) - 5)
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(b[2], "/generate",
                  {**self.PAYLOAD, "resume": receipt["park_id"]})
        assert e.value.code == 500
        assert _metric(
            b[2], 'shellac_fabric_resumed_total{outcome="torn"}') >= 1
        assert os.path.exists(path + ".torn")
        # The quarantine means the retry sees a missing park (400),
        # not the same torn bytes wedging every attempt.
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(b[2], "/generate",
                  {**self.PAYLOAD, "resume": receipt["park_id"]})
        assert e.value.code == 400

    def test_park_validation(self, duo, tiny_model):
        (a, _), _ = duo
        # park + migrate_to are mutually exclusive.
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(a[2], "/generate",
                  {**self.PAYLOAD, "prefill_only": True, "park": True,
                   "migrate_to": "http://127.0.0.1:1"})
        assert e.value.code == 400
        # A replica without --park-dir refuses park AND resume.
        cfg, params = tiny_model
        srv, httpd, url = _mk_server(cfg, params)
        try:
            for payload in (
                    {**self.PAYLOAD, "prefill_only": True, "park": True},
                    {**self.PAYLOAD, "resume": "x"}):
                with pytest.raises(urllib.error.HTTPError) as e:
                    _post(url, "/generate", payload)
                assert e.value.code == 400
        finally:
            httpd.shutdown()
            srv.close()


# ---------------------------------------------------------------------
# Tier: directory routing + hot-prefix replication (the acceptance)
# ---------------------------------------------------------------------


@pytest.mark.slow
class TestTierFabric:
    @pytest.fixture(scope="class")
    def tier(self):
        from shellac_tpu.inference.tier import (
            TierRouter,
            make_tier_http_server,
        )

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        servers = [_mk_server(cfg, params) for _ in range(2)]
        reg = Registry()
        router = TierRouter(
            [u for _, _, u in servers], registry=reg,
            health_interval=0.2, default_timeout=120.0,
            fabric_hot_hits=1,
        )
        httpd = make_tier_http_server(router)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            router.poll_once()
            if all(r.routable for r in router.replicas):
                break
            time.sleep(0.1)
        yield router, reg, base, servers
        httpd.shutdown()
        router.close()
        for srv, h, _ in servers:
            h.shutdown()
            srv.close()

    def _gen(self, base, tail_seed, max_new=4):
        st, body = _post(base, "/generate",
                         {"tokens": PREFIX + _tail(tail_seed),
                          "max_new": max_new, "temperature": 0.0,
                          "timeout": 120})
        assert st == 200
        return json.loads(body)["tokens"]

    def test_directory_learns_and_routes_by_overlap(self, tier):
        router, reg, base, _ = tier
        # Two same-prefix sessions warm the fleet; the health sweeps
        # in between feed the directory their registered chains.
        self._gen(base, 1)
        self._gen(base, 2)
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            router.poll_once()
            if (router.stats()["fabric"] or {}).get("directory_chains"):
                break
            time.sleep(0.1)
        fab = router.stats()["fabric"]
        assert fab["directory_chains"] >= 4
        # With the directory populated, the next same-prefix request
        # routes on MEASURED overlap, not the affinity guess.
        before = reg.value("shellac_fabric_directory_hits_total") or 0
        self._gen(base, 3)
        assert (reg.value("shellac_fabric_directory_hits_total")
                or 0) > before

    def test_hot_chain_replicates_to_cold_peer(self, tier):
        """The fleet acceptance: a replica that never saw the hot
        prefix gets its chain pushed by the planner and then serves it
        without re-prefilling — seeded blocks + hit tokens asserted
        via /metrics, outputs identical to the original holder.

        The hot prefix is warmed DIRECTLY on one replica (tier routing
        may legitimately warm both replicas of a 2-wide fleet, leaving
        the planner nothing to do), so exactly one holder advertises
        it and the peer genuinely lacks it."""
        router, reg, base, servers = tier
        warm_u, cold_u = servers[0][2], servers[1][2]
        hot = [(i * 17 + 5) % 200 + 1 for i in range(64)]
        for seed in (21, 22):  # second request HITS -> chain goes hot
            st, _ = _post(warm_u, "/generate",
                          {"tokens": hot + _tail(seed), "max_new": 4,
                           "temperature": 0.0, "timeout": 120})
            assert st == 200
        seeded0 = _metric(cold_u, "shellac_fabric_seeded_blocks_total") or 0
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            router.poll_once()
            if (reg.value("shellac_fabric_pushes_total",
                          outcome="ok") or 0) >= 1:
                break
            time.sleep(0.2)
        assert (reg.value("shellac_fabric_pushes_total",
                          outcome="ok") or 0) >= 1, \
            "replication planner never pushed the hot chain"
        assert (_metric(cold_u, "shellac_fabric_seeded_blocks_total")
                or 0) >= seeded0 + 4
        payload = {"tokens": hot + _tail(23), "max_new": 4,
                   "temperature": 0.0, "timeout": 120}
        hits0 = _metric(cold_u, "shellac_engine_prefix_hit_tokens") or 0
        _, warm_body = _post(warm_u, "/generate", payload)
        _, cold_body = _post(cold_u, "/generate", payload)
        assert (json.loads(cold_body)["tokens"]
                == json.loads(warm_body)["tokens"])
        assert (_metric(cold_u, "shellac_engine_prefix_hit_tokens")
                >= hits0 + 64)

    def test_stale_directory_entry_is_a_miss_not_an_error(self, tier):
        """Kill a replica the directory still advertises: requests
        keep succeeding on the survivor — the stale entry costs at
        most one prefix miss, never a client error."""
        router, reg, base, servers = tier
        srv, httpd, dead_u = servers[0]
        httpd.shutdown()
        srv.close()
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            router.poll_once()
            rep = next(r for r in router.replicas if r.url == dead_u)
            if not rep.routable:
                break
            time.sleep(0.1)
        toks = self._gen(base, 11)
        assert len(toks) == 4


# ---------------------------------------------------------------------
# Chaos acceptance: park, SIGKILL the parker, resume on a survivor
# ---------------------------------------------------------------------


@pytest.mark.slow
class TestParkResumeChaos:
    def test_sigkill_parker_resume_identity(self, tmp_path):
        """THE park acceptance scenario: freeze + park a session on
        replica A, SIGKILL A (a true process death), resume on B —
        the continuation is token-identical to an uninterrupted run.
        Real `serve` subprocesses via the chaos harness, sharing one
        spool directory."""
        from shellac_tpu.inference.chaos import ReplicaProc

        spool = str(tmp_path / "park")
        procs = []
        try:
            procs = [
                ReplicaProc(extra_args=["--park-dir", spool],
                            max_len=96)
                for _ in range(2)
            ]
            for p in procs:
                p.wait_ready()
            a, b = procs
            payload = {"tokens": PREFIX[:12], "max_new": 6,
                       "temperature": 0.0, "timeout": 60}
            _, ctrl = _post(b.url, "/generate", payload)
            ctrl_tokens = json.loads(ctrl)["tokens"]
            st, body = _post(a.url, "/generate",
                             {**payload, "prefill_only": True,
                              "park": True})
            assert st == 200
            receipt = json.loads(body)
            assert receipt["parked"] is True
            a.kill()  # SIGKILL: the replica that parked is GONE.
            st, body = _post(b.url, "/generate",
                             {**payload, "resume": receipt["park_id"]})
            assert st == 200
            assert json.loads(body)["tokens"] == ctrl_tokens
        finally:
            for p in procs:
                p.terminate()
