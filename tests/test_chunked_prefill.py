"""Chunked prefill: long prompts prefill incrementally across engine
steps so they cannot stall active decodes.

Invariant: chunking is invisible to the math — greedy output per
request is bit-identical to the single-request Engine, dense and paged,
with and without prefix caching.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.batching import BatchingEngine, PagedBatchingEngine
from shellac_tpu.inference.engine import Engine
from shellac_tpu.models import transformer


def _tiny(**kw):
    return get_model_config("tiny").replace(dtype="float32", **kw)


@pytest.fixture(scope="module")
def setup():
    cfg = _tiny()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _ref(cfg, params, tokens, max_new):
    eng = Engine(cfg, params, temperature=0.0)
    out = eng.generate(
        jnp.asarray(np.asarray(tokens, np.int32)[None]), max_new_tokens=max_new
    )
    return np.asarray(out.tokens)[0].tolist()


class TestChunkedDense:
    def test_long_prompt_bit_match(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(0)
        prompt = rng.integers(0, cfg.vocab_size, 50).astype(np.int32)
        srv = BatchingEngine(cfg, params, n_slots=2, max_len=96,
                             prefill_chunk=16)
        got = srv.run([("x", prompt, 8)])["x"]
        assert got == _ref(cfg, params, prompt, 8)
        # 50 tokens at chunk 16 -> 4 chunk programs, one prefill.
        assert srv.stats["prefill_chunks"] == 4
        assert srv.stats["prefills"] == 1

    def test_short_prompt_single_program(self, setup):
        cfg, params = setup
        prompt = np.array([1, 2, 3], np.int32)
        srv = BatchingEngine(cfg, params, n_slots=1, max_len=64,
                             prefill_chunk=16)
        assert srv.run([("x", prompt, 6)])["x"] == _ref(cfg, params, prompt, 6)
        assert srv.stats["prefill_chunks"] == 0

    def test_decode_continues_during_chunked_prefill(self, setup):
        """An active request keeps emitting while a long prompt
        prefills chunk by chunk under a per-step budget."""
        cfg, params = setup
        rng = np.random.default_rng(1)
        short = rng.integers(0, cfg.vocab_size, 4).astype(np.int32)
        long = rng.integers(0, cfg.vocab_size, 48).astype(np.int32)
        srv = BatchingEngine(cfg, params, n_slots=2, max_len=96,
                             prefill_chunk=16, max_prefills_per_step=1)
        srv.submit("short", short, 10)
        srv.step()  # admits+prefills short (1 program), emits token 1
        srv.submit("long", long, 6)
        before = len(srv._slots[0].out) if srv._slots[0] else 0
        results = {}
        steps = 0
        while srv.pending:
            results.update(srv.step())
            steps += 1
            # While the long prompt is mid-prefill, the short request
            # must still have advanced every step.
            if srv._prefilling:
                cur = next(r for r in srv._slots
                           if r is not None and r.rid == "short")
                assert len(cur.out) > before
                before = len(cur.out)
        assert results["short"] == _ref(cfg, params, short, 10)
        assert results["long"] == _ref(cfg, params, long, 6)

    def test_many_long_prompts_churn(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(2)
        reqs = [(i, rng.integers(0, cfg.vocab_size, 30 + i).astype(np.int32),
                 5) for i in range(5)]
        srv = BatchingEngine(cfg, params, n_slots=2, max_len=96,
                             prefill_chunk=8, max_prefills_per_step=2)
        results = srv.run(reqs)
        for rid, toks, max_new in reqs:
            assert results[rid] == _ref(cfg, params, toks, max_new), rid


class TestChunkedPaged:
    def test_paged_long_prompt_bit_match(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(3)
        prompt = rng.integers(0, cfg.vocab_size, 50).astype(np.int32)
        srv = PagedBatchingEngine(cfg, params, n_slots=2, max_len=96,
                                  block_size=8, prefill_chunk=16)
        assert srv.run([("x", prompt, 8)])["x"] == _ref(
            cfg, params, prompt, 8
        )
        assert srv.stats["prefill_chunks"] == 4

    def test_paged_chunked_with_prefix_cache(self, setup):
        """Chunking composes with prefix caching: the second request
        chunks only the unmatched suffix."""
        cfg, params = setup
        rng = np.random.default_rng(4)
        shared = rng.integers(0, cfg.vocab_size, 40)
        p1 = np.asarray(shared, np.int32)
        p2 = np.concatenate(
            [shared, rng.integers(0, cfg.vocab_size, 20)]
        ).astype(np.int32)
        srv = PagedBatchingEngine(cfg, params, n_slots=2, max_len=96,
                                  block_size=8, prefill_chunk=16,
                                  prefix_cache=True)
        assert srv.run([("a", p1, 6)])["a"] == _ref(cfg, params, p1, 6)
        chunks_before = srv.stats["prefill_chunks"]
        assert srv.run([("b", p2, 6)])["b"] == _ref(cfg, params, p2, 6)
        assert srv.stats["prefix_hit_tokens"] == 40
        # Suffix = 60 - 40 = 20 tokens -> 2 chunks of 16 (vs 4 cold).
        assert srv.stats["prefill_chunks"] - chunks_before == 2


class TestConcurrentPrefix:
    def test_same_prefix_admitted_mid_chunked_prefill(self, setup):
        """A request matching a prompt whose blocks are still being
        written must NOT attend over unwritten KV: hashes register
        only at prefill completion, so the second request misses (or
        matches completed blocks) and stays bit-exact."""
        cfg, params = setup
        rng = np.random.default_rng(5)
        prompt = rng.integers(0, cfg.vocab_size, 48).astype(np.int32)
        srv = PagedBatchingEngine(cfg, params, n_slots=2, max_len=96,
                                  block_size=8, prefill_chunk=16,
                                  prefix_cache=True,
                                  max_prefills_per_step=1)
        # Both in flight at once: B is admitted while A is mid-prefill.
        srv.submit("a", prompt, 6)
        srv.submit("b", prompt, 6)
        results = {}
        while srv.pending:
            results.update(srv.step())
        want = _ref(cfg, params, prompt, 6)
        assert results["a"] == want
        assert results["b"] == want
        # And a third, after both completed, hits the full chain.
        hits = srv.stats["prefix_hit_tokens"]
        assert srv.run([("c", prompt, 6)])["c"] == want
        assert srv.stats["prefix_hit_tokens"] - hits == 40

    def test_chunks_advance_under_short_prompt_stream(self, setup):
        """A stream of short prompts must not starve an in-flight
        chunked prefill: in-flight chunks get the budget first."""
        cfg, params = setup
        rng = np.random.default_rng(6)
        long = rng.integers(0, cfg.vocab_size, 48).astype(np.int32)
        srv = BatchingEngine(cfg, params, n_slots=2, max_len=96,
                             prefill_chunk=16, max_prefills_per_step=1)
        srv.submit("long", long, 4)
        srv.step()  # admits long into _prefilling, runs chunk 1
        for i in range(8):
            srv.submit(f"s{i}", rng.integers(
                0, cfg.vocab_size, 3).astype(np.int32), 2)
        results = {}
        steps = 0
        while srv.pending and steps < 60:
            results.update(srv.step())
            steps += 1
        assert results["long"] == _ref(cfg, params, long, 4)
        assert len(results) == 9


class TestValidation:
    def test_bad_chunk_size(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="prefill_chunk"):
            BatchingEngine(cfg, params, prefill_chunk=0)

    def test_spec_engine_accepts_chunking(self, setup):
        # Round 5 lifted the exclusion: the draft cache chunks
        # alongside the target's. Full parity coverage lives in
        # tests/test_spec_batching.py::TestChunkedPrefill; this pins
        # the constructor accepting the flag.
        from shellac_tpu.inference.spec_batching import (
            SpeculativeBatchingEngine,
        )

        cfg, params = setup
        eng = SpeculativeBatchingEngine(cfg, params, cfg, params,
                                        prefill_chunk=16)
        assert eng.prefill_chunk == 16
