"""Per-token logprobs in the serving engines and HTTP API.

Convention (shared with the single-request Engine): logprob of each
emitted token under the raw — unfiltered, untempered — model
distribution.
"""

import json
import threading
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.batching import BatchingEngine, PagedBatchingEngine
from shellac_tpu.inference.engine import Engine
from shellac_tpu.inference.server import InferenceServer, make_http_server
from shellac_tpu.models import transformer


def _tiny(**kw):
    return get_model_config("tiny").replace(dtype="float32", **kw)


@pytest.fixture(scope="module")
def setup():
    cfg = _tiny()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _ref(cfg, params, tokens, max_new):
    eng = Engine(cfg, params, temperature=0.0)
    out = eng.generate(
        jnp.asarray(np.asarray(tokens, np.int32)[None]), max_new_tokens=max_new
    )
    return (np.asarray(out.tokens)[0].tolist(),
            np.asarray(out.logprobs)[0].tolist())


class TestEngineLogprobs:
    def test_matches_single_request_engine(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(0)
        prompt = rng.integers(0, cfg.vocab_size, 6).astype(np.int32)
        want_toks, want_lps = _ref(cfg, params, prompt, 8)
        srv = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             logprobs=True)
        got = srv.run([("x", prompt, 8)])["x"]
        assert got == want_toks
        lps = srv.finished_logprobs.pop("x")
        assert len(lps) == len(got)
        np.testing.assert_allclose(lps, want_lps, rtol=1e-4, atol=1e-5)
        assert not srv.finished_logprobs

    def test_paged_and_chunked(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(1)
        prompt = rng.integers(0, cfg.vocab_size, 40).astype(np.int32)
        want_toks, want_lps = _ref(cfg, params, prompt, 6)
        srv = PagedBatchingEngine(cfg, params, n_slots=2, max_len=96,
                                  block_size=8, prefix_cache=True,
                                  prefill_chunk=16, logprobs=True)
        for rid in ("cold", "warm"):  # second run hits the prefix cache
            assert srv.run([(rid, prompt, 6)])[rid] == want_toks
            lps = srv.finished_logprobs.pop(rid)
            np.testing.assert_allclose(lps, want_lps, rtol=1e-4,
                                       atol=1e-5, err_msg=rid)

    def test_stop_truncation_keeps_lockstep(self, setup):
        cfg, params = setup
        prompt = np.array([5, 6], np.int32)
        full, _ = _ref(cfg, params, prompt, 12)
        stop = [full[3:5]]
        srv = BatchingEngine(cfg, params, n_slots=1, max_len=64,
                             logprobs=True)
        got = srv.run([("x", prompt, 12, stop)])["x"]
        assert got == full[:3]
        assert len(srv.finished_logprobs.pop("x")) == 3

    def test_disabled_by_default(self, setup):
        cfg, params = setup
        srv = BatchingEngine(cfg, params, n_slots=1, max_len=64)
        srv.run([("x", np.array([1, 2], np.int32), 4)])
        assert srv.finished_logprobs == {}

    def test_speculative_engine(self, setup):
        from shellac_tpu.inference.spec_batching import (
            SpeculativeBatchingEngine,
        )

        cfg, params = setup
        rng = np.random.default_rng(2)
        prompt = rng.integers(0, cfg.vocab_size, 5).astype(np.int32)
        want_toks, want_lps = _ref(cfg, params, prompt, 10)
        srv = SpeculativeBatchingEngine(cfg, params, cfg, params, gamma=3,
                                        n_slots=1, max_len=96,
                                        logprobs=True)
        assert srv.run([("x", prompt, 10)])["x"] == want_toks
        lps = srv.finished_logprobs.pop("x")
        np.testing.assert_allclose(lps, want_lps, rtol=1e-4, atol=1e-5)


class TestHTTPLogprobs:
    @pytest.fixture(scope="class")
    def http(self, setup):
        cfg, params = setup
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, logprobs=True)
        srv = InferenceServer(cfg, params, engine=eng)
        httpd = make_http_server(srv)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        yield f"http://127.0.0.1:{httpd.server_address[1]}"
        httpd.shutdown()
        srv.close()

    def _post(self, base, payload):
        req = urllib.request.Request(
            f"{base}/generate", data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            return json.loads(r.read())

    def test_blocking(self, http, setup):
        cfg, params = setup
        want_toks, want_lps = _ref(cfg, params, [3, 7, 11], 6)
        out = self._post(http, {"tokens": [3, 7, 11], "max_new": 6,
                                "logprobs": True})
        assert out["tokens"] == want_toks
        np.testing.assert_allclose(out["logprobs"], want_lps, rtol=1e-4,
                                   atol=1e-5)

    def test_not_requested_not_returned(self, http):
        out = self._post(http, {"tokens": [1, 2], "max_new": 4})
        assert "logprobs" not in out

    def test_streaming_final_record(self, http):
        blocking = self._post(http, {"tokens": [2, 4], "max_new": 6,
                                     "logprobs": True})
        req = urllib.request.Request(
            f"{http}/generate",
            data=json.dumps({"tokens": [2, 4], "max_new": 6,
                             "stream": True, "logprobs": True}).encode(),
            headers={"Content-Type": "application/json"},
        )
        lines = []
        with urllib.request.urlopen(req, timeout=120) as r:
            for raw in r:
                lines.append(json.loads(raw))
        assert lines[-1]["done"] is True
        assert lines[-1]["logprobs"] == blocking["logprobs"]

    def test_parallel_sampling_choices(self, http):
        out = self._post(http, {"tokens": [1, 2, 3], "max_new": 6, "n": 2,
                                "best_of": 2, "temperature": 1.2})
        assert len(out["choices"]) == 2
        for c in out["choices"]:
            assert len(c["tokens"]) == 6

    def test_best_of_ranks_by_mean_logprob(self, http):
        out = self._post(http, {"tokens": [4, 5], "max_new": 6, "n": 2,
                                "best_of": 4, "temperature": 1.3,
                                "logprobs": True})
        assert len(out["choices"]) == 2
        means = [sum(c["logprobs"]) / len(c["logprobs"])
                 for c in out["choices"]]
        assert means[0] >= means[1]

    def test_greedy_n_rejected(self, http):
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post(http, {"tokens": [1], "max_new": 2, "n": 2,
                              "best_of": 2})
        assert ei.value.code == 400

    def test_stream_n_rejected(self, http):
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post(http, {"tokens": [1], "max_new": 2, "n": 2,
                              "best_of": 2, "temperature": 1.0,
                              "stream": True})
        assert ei.value.code == 400

    def test_best_of_cap_is_400(self, http):
        with pytest.raises(urllib.error.HTTPError) as ei:
            self._post(http, {"tokens": [1], "max_new": 2, "n": 1,
                              "best_of": 1000, "temperature": 1.0})
        assert ei.value.code == 400

    def test_bad_n_types_are_400(self, http):
        for bad in (None, [2], "two"):
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._post(http, {"tokens": [1], "max_new": 2, "n": bad})
            assert ei.value.code == 400, bad

    def test_best_of_without_flag_is_400(self, setup):
        cfg, params = setup
        srv = InferenceServer(cfg, params, n_slots=2, max_len=64,
                              temperature=1.0)
        httpd = make_http_server(srv)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            base = f"http://127.0.0.1:{httpd.server_address[1]}"
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._post(base, {"tokens": [1], "max_new": 2, "n": 1,
                                  "best_of": 3})
            assert ei.value.code == 400
        finally:
            httpd.shutdown()
            srv.close()

    def test_engine_without_flag_is_400(self, setup):
        cfg, params = setup
        srv = InferenceServer(cfg, params, n_slots=1, max_len=64)
        httpd = make_http_server(srv)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        try:
            base = f"http://127.0.0.1:{httpd.server_address[1]}"
            with pytest.raises(urllib.error.HTTPError) as ei:
                self._post(base, {"tokens": [1], "max_new": 2,
                                  "logprobs": True})
            assert ei.value.code == 400
        finally:
            httpd.shutdown()
            srv.close()


class TestPromptLogprobs:
    def test_prompt_logprobs_match_forward(self):
        """Engine prompt logprobs == log_softmax of the training
        forward at each prompt position."""
        from shellac_tpu.inference.batching import BatchingEngine

        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        prompt = [5, 9, 2, 31, 7, 12]
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0)
        eng.submit("r", prompt, 4, prompt_logprobs=True)
        done = {}
        while len(done) < 1:
            done.update(eng.step())
        plp = eng.finished_prompt_logprobs.pop("r")
        assert len(plp) == len(prompt) and plp[0] == 0.0

        logits = transformer.forward(
            cfg, params, jnp.asarray([prompt], jnp.int32)
        )
        lps = jax.nn.log_softmax(logits[0].astype(jnp.float32))
        expect = [
            float(lps[t - 1, prompt[t]]) for t in range(1, len(prompt))
        ]
        np.testing.assert_allclose(plp[1:], expect, atol=1e-5)

    def test_chunked_prefill_matches_whole_prompt(self):
        """Prompt logprobs stitched across prefill chunks (in-chunk rows
        + boundary values) must equal the whole-prompt scoring
        exactly."""
        from shellac_tpu.inference.batching import BatchingEngine

        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        prompt = list(np.random.RandomState(0).randint(0, 256, 27))

        def run(**kw):
            eng = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                                 temperature=0.0, **kw)
            eng.submit("r", prompt, 4, prompt_logprobs=True)
            done = {}
            while len(done) < 1:
                done.update(eng.step())
            return eng.finished_prompt_logprobs.pop("r")

        whole = run()
        chunked = run(prefill_chunk=10)  # 3 ragged chunks
        assert len(chunked) == len(prompt)
        np.testing.assert_allclose(chunked, whole, atol=1e-5)

    def test_paged_matches_dense(self):
        """Prompt scoring over the paged pool — whole-prompt AND
        chunked — equals the dense engine's exactly."""
        from shellac_tpu.inference.batching import (
            BatchingEngine,
            PagedBatchingEngine,
        )

        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        prompt = list(np.random.RandomState(1).randint(0, 256, 27))

        def run(kind, **kw):
            eng = kind(cfg, params, n_slots=2, max_len=64,
                       temperature=0.0, **kw)
            eng.submit("r", prompt, 4, prompt_logprobs=True)
            done = {}
            while len(done) < 1:
                done.update(eng.step())
            return eng.finished_prompt_logprobs.pop("r")

        dense = run(BatchingEngine)
        paged = run(PagedBatchingEngine, block_size=16, pool_tokens=256)
        np.testing.assert_allclose(paged, dense, atol=1e-5)
        chunked = run(PagedBatchingEngine, block_size=16,
                      pool_tokens=256, prefill_chunk=10)
        np.testing.assert_allclose(chunked, dense, atol=1e-5)

    def test_guards(self):
        from shellac_tpu.inference.batching import PagedBatchingEngine

        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = PagedBatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  block_size=16, pool_tokens=256,
                                  prefix_cache=True)
        with pytest.raises(ValueError, match="prefix cache"):
            eng.submit("r", [1, 2, 3], 4, prompt_logprobs=True)


class TestTopLogprobs:
    def _engine(self, **kw):
        from shellac_tpu.inference.batching import BatchingEngine

        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        return cfg, params, BatchingEngine(
            cfg, params, n_slots=2, max_len=64, temperature=0.0,
            logprobs=True, top_logprobs=3, **kw,
        )

    def test_topk_covers_every_token(self):
        """One (ids, lps) entry per emitted token — including the
        prefill-sampled first one and through a multi-tick window —
        with greedy's choice as the top-1 alternative at its exact
        logprob."""
        cfg, params, eng = self._engine(decode_ticks=2)
        eng.submit("r", [5, 9, 2], 5)
        done = {}
        while eng.pending:
            done.update(eng.step())
        tl = eng.finished_top_logprobs.pop("r")
        lps = eng.finished_logprobs.pop("r")
        assert len(tl) == len(done["r"]) == 5
        for (ids, vals), tok, lp in zip(tl, done["r"], lps):
            assert len(ids) == 3 and vals == sorted(vals, reverse=True)
            assert ids[0] == tok and abs(vals[0] - lp) < 1e-5

    def test_chunked_prefill_first_token(self):
        cfg, params, eng = self._engine(prefill_chunk=8)
        prompt = list(np.random.RandomState(2).randint(0, 256, 20))
        eng.submit("r", prompt, 3)
        done = {}
        while eng.pending:
            done.update(eng.step())
        tl = eng.finished_top_logprobs.pop("r")
        assert len(tl) == len(done["r"])
        assert tl[0][0][0] == done["r"][0]  # top-1 == greedy first token

    def test_stop_truncation_lockstep(self):
        """Stop-sequence truncation must shorten the alternatives list
        in lockstep with the token stream.  The stream is pinned to a
        constant token via logit_bias so the test does not depend on
        what the random init happens to emit: a stop of two pinned
        tokens suffix-matches at the earliest opportunity and consumes
        the whole output, so both lists must come back empty."""
        cfg, params, eng = self._engine()
        pin = {7: 100.0}
        eng.submit("probe", [4, 4, 4], 8, logit_bias=pin)
        ref = {}
        while eng.pending:
            ref.update(eng.step())
        assert ref["probe"] == [7] * 8
        eng.finished_top_logprobs.clear()
        eng.submit("r", [4, 4, 4], 8, stop=[[7, 7]], logit_bias=pin)
        done = {}
        while eng.pending:
            done.update(eng.step())
        assert done["r"] == []
        assert len(eng.finished_top_logprobs.pop("r")) == 0

    def test_guards(self):
        from shellac_tpu.inference.batching import BatchingEngine
        from shellac_tpu.inference.spec_batching import (
            SpeculativeBatchingEngine,
        )

        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="logprobs=True"):
            BatchingEngine(cfg, params, top_logprobs=3)
        with pytest.raises(ValueError, match="top_logprobs"):
            BatchingEngine(cfg, params, logprobs=True, top_logprobs=64)
        # Round 5 lifted the speculative exclusion: alternatives ride
        # the verify pass (parity coverage in test_spec_batching).
        eng = SpeculativeBatchingEngine(cfg, params, cfg, params,
                                        logprobs=True, top_logprobs=2)
        assert eng.top_logprobs == 2

    def test_http_and_openai(self):
        import json as _json
        import threading
        import urllib.error
        import urllib.request

        from shellac_tpu.inference.server import (
            InferenceServer,
            make_http_server,
        )
        from shellac_tpu.training.tokenizer import ByteTokenizer

        cfg, params, eng = self._engine()
        srv = InferenceServer(cfg, params, tokenizer=ByteTokenizer(),
                              engine=eng)
        httpd = make_http_server(srv)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"

        def post(path, payload, code=None):
            req = urllib.request.Request(
                base + path, _json.dumps(payload).encode(),
                {"Content-Type": "application/json"},
            )
            if code is None:
                return _json.loads(
                    urllib.request.urlopen(req, timeout=300).read()
                )
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=300)
            assert e.value.code == code
            return _json.loads(e.value.read())

        # Native: k slices the engine's recorded 3 down to 2.
        r = post("/generate", {"tokens": [3, 7], "max_new": 4,
                               "logprobs": True, "top_logprobs": 2})
        assert len(r["top_logprobs"]) == len(r["tokens"])
        for per_tok, tok in zip(r["top_logprobs"], r["tokens"]):
            assert len(per_tok) == 2
            assert per_tok[0]["id"] == tok  # greedy = top-1
        # k beyond the engine cap is a 400, not silent truncation.
        post("/generate", {"tokens": [3], "max_new": 2,
                           "logprobs": True, "top_logprobs": 9}, code=400)
        post("/generate", {"tokens": [3], "max_new": 2,
                           "top_logprobs": 2}, code=400)  # needs logprobs
        # OpenAI completions: int logprobs=3 -> per-position dicts.
        r = post("/v1/completions", {"prompt": [3, 7], "max_tokens": 3,
                                     "temperature": 0, "logprobs": 3})
        lp = r["choices"][0]["logprobs"]
        assert len(lp["top_logprobs"]) == 3
        assert all(len(d) >= 1 for d in lp["top_logprobs"])
        # OpenAI chat: logprobs + top_logprobs -> content alternatives.
        r = post("/v1/chat/completions", {
            "messages": [{"role": "user", "content": "hi"}],
            "max_tokens": 3, "temperature": 0,
            "logprobs": True, "top_logprobs": 2,
        })
        content = r["choices"][0]["logprobs"]["content"]
        assert all(len(c["top_logprobs"]) == 2 for c in content)
        # Native ndjson streaming: alternatives ride the final record.
        req = urllib.request.Request(
            base + "/generate",
            _json.dumps({"tokens": [3, 7], "max_new": 3, "stream": True,
                         "logprobs": True, "top_logprobs": 2}).encode(),
            {"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as resp:
            records = [_json.loads(x) for x in resp.read().splitlines()]
        final = records[-1]
        assert final.get("done") and len(final["top_logprobs"]) == 3
        # OpenAI SSE chat streaming: the finish chunk carries them too
        # (the silent-drop regression).
        req = urllib.request.Request(
            base + "/v1/chat/completions",
            _json.dumps({
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 3, "temperature": 0, "stream": True,
                "logprobs": True, "top_logprobs": 2,
            }).encode(),
            {"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as resp:
            datas = [
                _json.loads(line[len(b"data: "):])
                for line in resp.read().splitlines()
                if line.startswith(b"data: ") and line != b"data: [DONE]"
            ]
        with_lp = [d for d in datas
                   if d["choices"][0].get("logprobs") is not None]
        assert with_lp, datas
        content = with_lp[-1]["choices"][0]["logprobs"]["content"]
        assert all(len(c["top_logprobs"]) == 2 for c in content)
        httpd.shutdown()
        srv.close()

    def test_openai_echo_logprobs(self):
        """completions echo=true + logprobs: text = prompt + completion,
        logprobs cover prompt tokens (first null) then completion."""
        import json as _json
        import threading
        import urllib.request

        from shellac_tpu.inference.server import (
            InferenceServer,
            make_http_server,
        )
        from shellac_tpu.training.tokenizer import ByteTokenizer

        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        srv = InferenceServer(
            cfg, params, tokenizer=ByteTokenizer(), model_name="tiny",
            n_slots=2, max_len=64, temperature=0.0, logprobs=True,
        )
        httpd = make_http_server(srv)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            req = urllib.request.Request(
                f"{base}/v1/completions",
                data=_json.dumps({
                    "prompt": "hello", "max_tokens": 4, "temperature": 0,
                    "echo": True, "logprobs": 1,
                }).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=120) as r:
                out = _json.loads(r.read())
            choice = out["choices"][0]
            assert choice["text"].startswith("hello")
            lp = choice["logprobs"]
            # 5 prompt tokens (first null) + 4 completion tokens
            assert len(lp["token_logprobs"]) == 9
            assert lp["token_logprobs"][0] is None
            assert all(v <= 0.0 for v in lp["token_logprobs"][1:])
        finally:
            httpd.shutdown()
            srv.close()
