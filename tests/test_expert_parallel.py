"""Expert-parallel (ep) mesh axis on the virtual 8-device CPU mesh.

The ep design is pure GSPMD sharding (docs/parallelism.md): expert
weights and the dispatched capacity buckets shard E over (ep, fsdp);
XLA inserts the token all-to-all at the dispatch/combine resharding
boundaries, and the expert FFN einsums stay local to each ep group.
Parity with the unsharded path is therefore the whole correctness
story — these tests pin it for the plain MoE, the interleaved
dense/MoE stack, and the DeepSeek shape (shared experts + MLA +
first-k-dense).
"""

import jax
import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from shellac_tpu import ParallelConfig, get_model_config, make_mesh
from shellac_tpu.config import TrainConfig
from shellac_tpu.parallel.sharding import logical_to_spec
from shellac_tpu.training import (
    batch_shardings,
    init_train_state,
    make_train_step,
)


@pytest.fixture(scope="module")
def mesh_ep8():
    # dp=2 x ep=2 x tp=2: tokens shard over dp, experts over ep, expert
    # FFN width over tp — the three-way composition a real MoE run uses.
    return make_mesh(ParallelConfig(dp=2, ep=2, tp=2))


class TestEpRules:
    def test_expert_param_spec(self):
        assert logical_to_spec(("experts", "embed", "mlp")) == P(
            ("ep", "fsdp"), None, "tp"
        )

    def test_stacked_expert_param_spec(self):
        # Layer-stacked expert weights: layers->pp, experts->(ep,fsdp).
        assert logical_to_spec(("layers", "experts", "embed", "mlp")) == P(
            "pp", ("ep", "fsdp"), None, "tp"
        )


def _losses(cfg, tcfg, batch, mesh, steps=3):
    key = jax.random.PRNGKey(0)
    state = init_train_state(cfg, tcfg, key, mesh=mesh)
    step = make_train_step(cfg, tcfg, mesh=mesh)
    if mesh is not None:
        bs = batch_shardings(mesh)
        batch = jax.tree.map(lambda x: jax.device_put(x, bs), batch)
    out = []
    for _ in range(steps):
        state, m = step(state, batch)
        out.append(float(m["loss"]))
    return out


class TestEpTraining:
    def _batch(self, cfg, b=4, s=32):
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (b, s), 0, cfg.vocab_size
        )
        return {"inputs": tokens, "targets": tokens}

    def test_ep_step_matches_unsharded(self, mesh_ep8):
        cfg = get_model_config("tiny-moe").replace(dtype="float32")
        tcfg = TrainConfig(warmup_steps=0, total_steps=100,
                           learning_rate=1e-3)
        batch = self._batch(cfg)
        ref = _losses(cfg, tcfg, batch, None)
        ep = _losses(cfg, tcfg, batch, mesh_ep8)
        np.testing.assert_allclose(ref, ep, rtol=1e-4)

    def test_ep_deepseek_shared_experts(self, mesh_ep8):
        # MLA + first-k-dense + shared expert + narrow routed experts:
        # the DeepSeek composition the VERDICT asked ep to cover.
        cfg = get_model_config("tiny-deepseek").replace(dtype="float32")
        tcfg = TrainConfig(warmup_steps=0, total_steps=100,
                           learning_rate=1e-3)
        batch = self._batch(cfg)
        ref = _losses(cfg, tcfg, batch, None)
        ep = _losses(cfg, tcfg, batch, mesh_ep8)
        np.testing.assert_allclose(ref, ep, rtol=1e-4)

    def test_ep_interleaved_stack(self, mesh_ep8):
        cfg = get_model_config("tiny-moe-interleaved").replace(
            dtype="float32"
        )
        tcfg = TrainConfig(warmup_steps=0, total_steps=100,
                           learning_rate=1e-3)
        batch = self._batch(cfg)
        ref = _losses(cfg, tcfg, batch, None)
        ep = _losses(cfg, tcfg, batch, mesh_ep8)
        np.testing.assert_allclose(ref, ep, rtol=1e-4)

    def test_ep_fsdp_composition(self):
        # ep=2 x fsdp=2: E shards over both (ZeRO over the ep groups).
        mesh = make_mesh(ParallelConfig(fsdp=2, ep=2, tp=2))
        cfg = get_model_config("tiny-moe").replace(dtype="float32")
        tcfg = TrainConfig(warmup_steps=0, total_steps=100,
                           learning_rate=1e-3)
        batch = self._batch(cfg)
        ref = _losses(cfg, tcfg, batch, None)
        ep = _losses(cfg, tcfg, batch, mesh)
        np.testing.assert_allclose(ref, ep, rtol=1e-4)

    def test_ep_serving_bit_parity(self, mesh_ep8):
        """MoE decode on an ep mesh: greedy bit-identical to the
        unsharded engine (decode runs dropless, so expert sharding must
        not change which experts compute or what they return)."""
        from shellac_tpu.inference.batching import BatchingEngine
        from shellac_tpu.inference.engine import shard_params
        from shellac_tpu.models import transformer

        cfg = get_model_config("tiny-moe").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        reqs = [(i, [3 + i, 9, 2, 31], 6) for i in range(3)]
        want = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                              temperature=0.0).run(reqs)
        sharded = shard_params(cfg, params, mesh_ep8)
        got = BatchingEngine(cfg, sharded, n_slots=2, max_len=64,
                             temperature=0.0, mesh=mesh_ep8).run(reqs)
        assert got == want

    def test_indivisible_experts_raise(self):
        mesh = make_mesh(ParallelConfig(ep=8))
        cfg = get_model_config("tiny-moe")  # 4 experts, 8 ep shards
        tcfg = TrainConfig()
        # Either guard may fire first: jax refuses the param sharding at
        # init ("divisible by 8"), or moe_ffn's explicit check ("divide
        # evenly") on paths that build no sharded params.
        with pytest.raises(ValueError, match="divis|divide"):
            state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0),
                                     mesh=mesh)
            step = make_train_step(cfg, tcfg, mesh=mesh)
            bs = batch_shardings(mesh)
            batch = jax.tree.map(
                lambda x: jax.device_put(x, bs), self._batch(cfg, b=8)
            )
            step(state, batch)
