"""shellac_tpu.obs: metrics core, Prometheus exposition, request-trace
spans, engine instrumentation, and a live-server /metrics scrape."""

import json
import re
import threading
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.batching import BatchingEngine
from shellac_tpu.inference.server import InferenceServer, make_http_server
from shellac_tpu.models import transformer
from shellac_tpu.obs import (
    Registry,
    ServeMetrics,
    linear_buckets,
    log_buckets,
)
from shellac_tpu.training.tokenizer import ByteTokenizer
from shellac_tpu.utils.metrics import MetricsLogger


def _tiny():
    return get_model_config("tiny").replace(dtype="float32")


# ---------------------------------------------------------------------
# bucket math + histogram core


class TestBuckets:
    def test_log_buckets_monotonic_and_covering(self):
        b = log_buckets(0.001, 60.0, per_decade=4)
        assert all(x < y for x, y in zip(b, b[1:]))
        assert b[0] <= 0.001 and b[-1] >= 60.0
        # 4 per decade over ~5 decades: enough resolution, bounded size.
        assert 15 <= len(b) <= 30

    def test_log_buckets_validation(self):
        with pytest.raises(ValueError):
            log_buckets(0.0, 1.0)
        with pytest.raises(ValueError):
            log_buckets(1.0, 1.0)
        with pytest.raises(ValueError):
            log_buckets(0.1, 1.0, per_decade=0)

    def test_linear_buckets(self):
        assert linear_buckets(0.25, 0.25, 4) == (0.25, 0.5, 0.75, 1.0)


class TestHistogram:
    def _h(self, buckets=(1.0, 2.0, 4.0)):
        return Registry().histogram("h", "test", buckets=buckets)

    def test_observe_lands_in_correct_bucket(self):
        h = self._h()
        h.observe(0.5)   # le=1
        h.observe(1.0)   # le=1 (upper bounds are inclusive)
        h.observe(1.5)   # le=2
        h.observe(4.0)   # le=4
        h.observe(99.0)  # +Inf overflow
        assert h.counts == [2, 1, 1, 1]
        assert h.count == 5
        assert h.sum == pytest.approx(0.5 + 1.0 + 1.5 + 4.0 + 99.0)

    def test_percentile_interpolates(self):
        h = self._h(buckets=tuple(float(i) for i in range(1, 11)))
        for v in range(1, 11):  # one observation per bucket
            h.observe(v - 0.5)
        # p50 sits at the 5th of 10 observations: inside the (4, 5]
        # bucket's span.
        p50 = h.percentile(0.5)
        assert 4.0 <= p50 <= 5.0
        assert h.percentile(1.0) >= h.percentile(0.5)

    def test_percentile_empty_and_overflow(self):
        h = self._h()
        assert h.percentile(0.5) is None
        h.observe(123.0)  # overflow bucket
        assert h.percentile(0.99) == pytest.approx(123.0)

    def test_summary_digest(self):
        h = self._h()
        for v in (0.5, 1.5, 3.0):
            h.observe(v)
        s = h.summary()
        assert s["count"] == 3
        assert s["mean"] == pytest.approx(5.0 / 3)
        assert s["p50"] is not None and s["p99"] is not None

    def test_bad_buckets_rejected(self):
        r = Registry()
        with pytest.raises(ValueError):
            r.histogram("h1", buckets=())
        with pytest.raises(ValueError):
            r.histogram("h2", buckets=(1.0, 1.0))
        with pytest.raises(ValueError):
            r.histogram("h3", buckets=(1.0, float("inf")))


# ---------------------------------------------------------------------
# registry + label handling


class TestRegistry:
    def test_counter_and_gauge(self):
        r = Registry()
        c = r.counter("c", "help")
        c.inc()
        c.inc(2.5)
        assert r.value("c") == pytest.approx(3.5)
        with pytest.raises(ValueError):
            c.inc(-1)
        g = r.gauge("g")
        g.set(4.0)
        g.dec()
        assert r.value("g") == pytest.approx(3.0)

    def test_registration_idempotent(self):
        r = Registry()
        assert r.counter("c") is r.counter("c")
        h = r.histogram("h", buckets=(1.0, 2.0))
        assert r.histogram("h", buckets=(1.0, 2.0)) is h

    def test_kind_and_label_conflicts_raise(self):
        r = Registry()
        r.counter("m")
        with pytest.raises(ValueError):
            r.gauge("m")
        r.counter("lab", labels=("a",))
        with pytest.raises(ValueError):
            r.counter("lab", labels=("b",))
        r.histogram("hb", buckets=(1.0,))
        with pytest.raises(ValueError):
            r.histogram("hb", buckets=(2.0,))

    def test_labeled_series(self):
        r = Registry()
        fam = r.counter("req", labels=("outcome",))
        fam.labels(outcome="ok").inc()
        fam.labels(outcome="ok").inc()
        fam.labels(outcome="shed").inc()
        assert fam.labels(outcome="ok") is fam.labels(outcome="ok")
        assert r.value("req", outcome="ok") == 2
        assert r.value("req", outcome="shed") == 1
        assert r.value("req", outcome="never") is None
        with pytest.raises(ValueError):
            fam.labels(wrong="x")

    def test_disabled_registry_noops(self):
        r = Registry(enabled=False)
        c = r.counter("c")
        h = r.histogram("h")
        c.inc()
        h.observe(1.0)
        assert c.value == 0 and h.count == 0
        r.enable()
        c.inc()
        assert c.value == 1


# ---------------------------------------------------------------------
# Prometheus exposition format

# One sample line: metric name, optional {labels}, a number.
_SAMPLE_RE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^{}]*\})? "
    r"(-?[0-9]+(\.[0-9]+)?([eE][+-]?[0-9]+)?|NaN|[+-]Inf)$"
)


def assert_valid_exposition(text):
    """Every line is a comment or a well-formed sample; histograms have
    cumulative buckets ending at +Inf == _count."""
    assert text.endswith("\n")
    for line in text.splitlines():
        if line.startswith("# HELP ") or line.startswith("# TYPE "):
            continue
        assert _SAMPLE_RE.match(line), f"bad exposition line: {line!r}"


class TestExposition:
    def test_render_counter_gauge(self):
        r = Registry()
        r.counter("shellac_c", "a counter").inc(2)
        r.gauge("shellac_g").set(1.5)
        text = r.render()
        assert "# HELP shellac_c a counter" in text
        assert "# TYPE shellac_c counter" in text
        assert "shellac_c 2" in text
        assert "shellac_g 1.5" in text
        assert_valid_exposition(text)

    def test_render_labels_escaped(self):
        r = Registry()
        r.counter("c", labels=("x",)).labels(x='we"ird\\').inc()
        text = r.render()
        assert 'c{x="we\\"ird\\\\"} 1' in text
        assert_valid_exposition(text)

    def test_render_histogram_cumulative(self):
        r = Registry()
        h = r.histogram("lat", "latency", buckets=(1.0, 2.0, 4.0))
        for v in (0.5, 1.5, 1.7, 3.0, 9.0):
            h.observe(v)
        text = r.render()
        assert_valid_exposition(text)
        assert '# TYPE lat histogram' in text
        assert 'lat_bucket{le="1"} 1' in text
        assert 'lat_bucket{le="2"} 3' in text
        assert 'lat_bucket{le="4"} 4' in text
        assert 'lat_bucket{le="+Inf"} 5' in text
        assert "lat_count 5" in text
        assert f"lat_sum {0.5 + 1.5 + 1.7 + 3.0 + 9.0}" in text

    def test_snapshot_roundtrips_to_json(self):
        r = Registry()
        r.counter("c", labels=("o",)).labels(o="ok").inc()
        r.histogram("h", buckets=(1.0, 2.0)).observe(0.5)
        snap = r.snapshot()
        json.dumps(snap)  # must be JSON-able
        assert snap["c"]["type"] == "counter"
        row = snap["h"]["series"][0]
        assert row["count"] == 1 and row["p50"] is not None
        assert row["buckets"]["1"] == 1


# ---------------------------------------------------------------------
# request-trace span lifecycle


class TestRequestTrace:
    def _sm(self):
        return ServeMetrics(Registry())

    def test_full_lifecycle_deposits_histograms(self):
        sm = self._sm()
        t = sm.trace()
        t.prefill_start()
        t.first_token()
        t.finish(8)
        r = sm.registry
        assert r.value("shellac_queue_wait_seconds") == 1  # count
        assert r.value("shellac_ttft_seconds") == 1
        assert r.value("shellac_e2e_seconds") == 1
        assert r.value("shellac_tpot_seconds") == 1
        assert r.value("shellac_requests_total", outcome="ok") == 1

    def test_single_token_has_no_tpot(self):
        sm = self._sm()
        t = sm.trace()
        t.prefill_start()
        t.first_token()
        t.finish(1)
        assert sm.registry.value("shellac_tpot_seconds") == 0

    def test_events_idempotent(self):
        sm = self._sm()
        t = sm.trace()
        t.prefill_start()
        t.prefill_start()
        t.first_token()
        t.first_token()
        t.finish(4)
        assert sm.registry.value("shellac_queue_wait_seconds") == 1
        assert sm.registry.value("shellac_ttft_seconds") == 1

    def test_shed_settles_once(self):
        sm = self._sm()
        t = sm.trace()
        t.shed()
        t.finish(4)  # late duplicate settlement is ignored
        r = sm.registry
        assert r.value("shellac_requests_total", outcome="shed") == 1
        assert r.value("shellac_requests_shed_total") == 1
        assert r.value("shellac_requests_total", outcome="ok") is None
        assert r.value("shellac_e2e_seconds") == 0

    def test_abort_outcomes(self):
        sm = self._sm()
        for outcome in ("cancelled", "error", "fault"):
            t = sm.trace()
            t.abort(outcome)
            assert sm.registry.value(
                "shellac_requests_total", outcome=outcome
            ) == 1


# ---------------------------------------------------------------------
# engine instrumentation (no HTTP in the way)


class TestEngineInstrumentation:
    def test_engine_records_spans_and_gauges(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        reg = Registry()
        sm = ServeMetrics(reg)
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, registry=reg)
        traces = {}
        for i in range(3):
            traces[i] = sm.trace()
            eng.submit(i, [1 + i, 2, 3], 4, trace=traces[i])
        results = {}
        while eng.pending:
            for rid, out in eng.step():
                traces[rid].finish(len(out))
                results[rid] = out
        assert len(results) == 3
        # Spans: every request got a queue-wait, TTFT, e2e, and (4
        # tokens each) a TPOT observation.
        assert reg.value("shellac_queue_wait_seconds") == 3
        assert reg.value("shellac_ttft_seconds") == 3
        assert reg.value("shellac_e2e_seconds") == 3
        assert reg.value("shellac_tpot_seconds") == 3
        # Engine-side sections + occupancy + utilization gauges.
        assert reg.value("shellac_prefill_seconds") >= 1
        assert reg.value("shellac_decode_window_seconds") >= 1
        assert reg.value("shellac_batch_occupancy") >= 1
        occ = reg.get("shellac_batch_occupancy")
        assert occ.percentile(1.0) <= 1.0
        assert reg.value("shellac_slots_busy") == 0  # all drained
        assert 0.0 <= reg.value("shellac_kv_utilization") <= 1.0

    def test_cancel_settles_trace(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        reg = Registry()
        sm = ServeMetrics(reg)
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, registry=reg)
        t = sm.trace()
        eng.submit("a", [1, 2], 4, trace=t)
        assert eng.cancel("a")
        assert reg.value(
            "shellac_requests_total", outcome="cancelled"
        ) == 1

    def test_paged_pool_gauges(self):
        from shellac_tpu.inference.batching import PagedBatchingEngine

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        reg = Registry()
        eng = PagedBatchingEngine(
            cfg, params, n_slots=2, max_len=64, block_size=16,
            temperature=0.0, prefix_cache=True, registry=reg,
        )
        eng.submit(0, list(range(1, 20)), 4)
        while eng.pending:
            eng.step()
        assert 0.0 <= reg.value("shellac_kv_utilization") <= 1.0
        # Released prompt blocks stay registered in the prefix cache.
        assert reg.value("shellac_prefix_cache_blocks") >= 1


# ---------------------------------------------------------------------
# MetricsLogger: context manager + registry routing


class TestMetricsLogger:
    def test_context_manager_closes_on_exception(self, tmp_path):
        path = tmp_path / "m.jsonl"
        with pytest.raises(RuntimeError):
            with MetricsLogger(str(path), stdout=False) as logger:
                logger.log(1, {"loss": 2.0})
                raise RuntimeError("boom")
        assert logger._file is None  # closed despite the raise
        rows = [json.loads(x) for x in path.read_text().splitlines()]
        assert rows[0]["loss"] == 2.0

    def test_old_call_pattern_still_works(self, tmp_path):
        path = tmp_path / "m.jsonl"
        logger = MetricsLogger(str(path), stdout=False, every=2)
        logger.log(1, {"loss": 1.0})  # skipped (every=2)
        logger.log(2, {"loss": 0.5})
        logger.close()
        logger.close()  # idempotent
        rows = [json.loads(x) for x in path.read_text().splitlines()]
        assert len(rows) == 1 and rows[0]["step"] == 2

    def test_scalars_routed_to_registry(self, tmp_path):
        reg = Registry()
        logger = MetricsLogger(None, stdout=False, registry=reg)
        logger.log(10, {"loss": 1.25, "grad/norm": 3.0, "note": "str"})
        logger.close()
        assert reg.value("shellac_train_loss") == pytest.approx(1.25)
        assert reg.value("shellac_train_grad_norm") == pytest.approx(3.0)
        assert reg.value("shellac_train_step") == 10
        assert reg.value("shellac_train_log_steps_total") == 1
        assert reg.value("shellac_train_note") is None


# ---------------------------------------------------------------------
# live server scrape


@pytest.fixture(scope="module")
def obs_srv():
    cfg = _tiny()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    reg = Registry()
    srv = InferenceServer(
        cfg, params, tokenizer=ByteTokenizer(),
        n_slots=2, max_len=64, temperature=0.0, registry=reg,
    )
    httpd = make_http_server(srv)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield base, srv, reg
    httpd.shutdown()
    srv.close()


def _post(base, payload, timeout=120):
    req = urllib.request.Request(
        f"{base}/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _get(base, path, timeout=60):
    with urllib.request.urlopen(f"{base}{path}", timeout=timeout) as r:
        return r.status, r.headers.get("Content-Type", ""), r.read().decode()


class TestLiveServerScrape:
    def test_metrics_exposes_spans_under_load(self, obs_srv):
        base, srv, reg = obs_srv
        for i in range(3):
            out = _post(base, {"tokens": [1 + i, 2, 3], "max_new": 4})
            assert len(out["tokens"]) == 4
        status, ctype, text = _get(base, "/metrics")
        assert status == 200
        assert ctype.startswith("text/plain")
        assert_valid_exposition(text)
        # The acceptance-criteria series, present with real counts.
        assert 'shellac_ttft_seconds_bucket{le="' in text
        assert "shellac_tpot_seconds_count" in text
        assert "shellac_queue_wait_seconds_count" in text
        assert reg.value("shellac_ttft_seconds") >= 3
        assert reg.value("shellac_queue_wait_seconds") >= 3
        assert reg.value("shellac_tpot_seconds") >= 3
        assert reg.value("shellac_requests_total", outcome="ok") >= 3
        # Supervisor counters are exposed even while zero.
        assert "shellac_supervisor_restarts_total 0" in text
        assert "shellac_requests_shed_total 0" in text
        assert "shellac_engine_generation 0" in text
        # Engine stats mirror in as gauges at scrape time.
        assert re.search(
            r"shellac_engine_requests_completed [1-9]", text
        )
        assert "shellac_uptime_seconds" in text

    def test_stats_carries_uptime_and_percentiles(self, obs_srv):
        base, srv, reg = obs_srv
        _post(base, {"tokens": [5, 6, 7], "max_new": 4})
        status, _, body = _get(base, "/stats")
        assert status == 200
        stats = json.loads(body)
        assert stats["uptime_s"] >= 0
        for key in ("ttft_s", "e2e_s", "queue_wait_s"):
            digest = stats[key]
            assert digest["count"] >= 1
            assert digest["p50"] is not None
            assert digest["p50"] <= digest["p99"]

    def test_trace_rides_streaming(self, obs_srv):
        base, srv, reg = obs_srv
        before = reg.value("shellac_requests_total", outcome="ok") or 0
        req = urllib.request.Request(
            f"{base}/generate",
            data=json.dumps({"tokens": [9, 8], "max_new": 3,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            lines = [json.loads(x) for x in r.read().splitlines()]
        assert lines[-1]["done"] is True
        assert reg.value("shellac_requests_total", outcome="ok") \
            == before + 1

    def test_metrics_404_when_disabled(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        srv = InferenceServer(cfg, params, n_slots=2, max_len=64,
                              temperature=0.0, metrics=False)
        httpd = make_http_server(srv)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            out = np.asarray(srv.generate([1, 2], max_new=2, timeout=120))
            assert out.size == 2  # serving works, metrics just no-op
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(base + "/metrics", timeout=30)
            assert e.value.code == 404
            # /stats still answers; digests are empty, not broken.
            status, _, body = _get(base, "/stats")
            assert status == 200
            assert json.loads(body)["ttft_s"]["count"] == 0
        finally:
            httpd.shutdown()
            srv.close()
