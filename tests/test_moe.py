"""MoE routing and model tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import MoEConfig, ParallelConfig, get_model_config, make_mesh
from shellac_tpu.config import TrainConfig
from shellac_tpu.models import transformer
from shellac_tpu.ops.moe import expert_capacity, moe_ffn, route
from shellac_tpu.training import batch_shardings, init_train_state, make_train_step


class TestRouting:
    def test_slots_unique_and_capped(self):
        cfg = MoEConfig(num_experts=4, num_experts_per_token=2, capacity_factor=1.0)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(32, 16)), jnp.float32)
        w = jnp.asarray(np.random.default_rng(1).normal(size=(16, 4)), jnp.float32)
        slot, weight, aux, metrics = route(x, w, cfg)
        c = expert_capacity(cfg, 32)
        s = np.asarray(slot).reshape(-1)
        valid = s[s < 4 * c]
        # No two assignments share a capacity slot.
        assert len(valid) == len(set(valid.tolist()))
        # Combine weights are normalized over kept experts.
        np.testing.assert_allclose(np.asarray(weight).sum(-1), 1.0, rtol=1e-5)

    def test_capacity_drops_overflow(self):
        # Router forced to send everything to expert 0 -> all but C dropped.
        cfg = MoEConfig(num_experts=4, num_experts_per_token=1, capacity_factor=1.0)
        x = jnp.ones((16, 8), jnp.float32)
        w = jnp.zeros((8, 4), jnp.float32).at[:, 0].set(10.0)
        slot, _, _, metrics = route(x, w, cfg)
        c = expert_capacity(cfg, 16)  # = 4
        kept = int((np.asarray(slot) < 4 * c).sum())
        assert kept == c
        assert float(metrics["moe_dropped_frac"]) == pytest.approx(1 - c / 16)

    def test_uniform_router_balance_loss_is_one(self):
        # With a uniform router, balance loss == num_experts * E[f*p] == 1.
        cfg = MoEConfig(num_experts=8, num_experts_per_token=2)
        x = jnp.asarray(np.random.default_rng(0).normal(size=(64, 16)), jnp.float32)
        w = jnp.zeros((16, 8), jnp.float32)
        _, _, _, metrics = route(x, w, cfg)
        assert float(metrics["moe_balance_loss"]) == pytest.approx(1.0, rel=1e-3)


class TestGroupedDropless:
    def _weights(self, rng, d=16, f=32, e=4):
        r = np.random.default_rng(rng)
        mk = lambda *s: jnp.asarray(  # noqa: E731
            r.normal(size=s, scale=0.3), jnp.float32
        )
        return (mk(d, e), mk(e, d, f), mk(e, d, f), mk(e, f, d))

    def test_matches_bucket_path_when_nothing_drops(self):
        """Parity at capacity_factor -> inf: with capacity covering
        every assignment, the bucket path drops nothing and the
        grouped path must produce the same outputs and the same aux
        (the gate scoring is one shared definition)."""
        from shellac_tpu.ops.moe import moe_ffn_grouped

        cfg = MoEConfig(num_experts=4, num_experts_per_token=2,
                        capacity_factor=64.0)
        wr, wg, wu, wd = self._weights(3)
        x = jnp.asarray(
            np.random.default_rng(0).normal(size=(2, 24, 16)),
            jnp.float32,
        )
        want, aux_w, m_w = moe_ffn(x, wr, wg, wu, wd, cfg)
        got, aux_g, m_g = moe_ffn_grouped(x, wr, wg, wu, wd, cfg)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   rtol=2e-5, atol=2e-5)
        assert float(aux_g) == pytest.approx(float(aux_w), rel=1e-6)
        assert float(m_w["moe_dropped_frac"]) == 0.0
        assert float(m_g["moe_dropped_frac"]) == 0.0

    def test_nothing_drops_under_pathological_routing(self):
        """Every token routed to ONE expert — the bucket path at
        capacity_factor=1 drops most assignments; the grouped path
        drops none, by construction."""
        from shellac_tpu.ops.moe import moe_ffn_grouped

        cfg = MoEConfig(num_experts=4, num_experts_per_token=1,
                        capacity_factor=1.0)
        _, wg, wu, wd = self._weights(5)
        wr = jnp.zeros((16, 4), jnp.float32).at[:, 0].set(10.0)
        x = jnp.asarray(
            np.random.default_rng(1).normal(size=(1, 16, 16)),
            jnp.float32,
        )
        _, _, m_bucket = moe_ffn(x, wr, wg, wu, wd, cfg)
        got, _, m_g = moe_ffn_grouped(x, wr, wg, wu, wd, cfg)
        assert float(m_bucket["moe_dropped_frac"]) >= 0.5
        assert float(m_g["moe_dropped_frac"]) == 0.0
        # And the grouped output equals an exact per-token reference.
        ref, _, _ = moe_ffn(x, wr, wg, wu, wd, cfg, drop_tokens=False)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)

    def test_training_step_dropped_frac_zero(self, mesh8):
        """A sharded train step on the ep mesh with grouped_dropless:
        moe_dropped_frac == 0 BY CONSTRUCTION, loss finite, gradients
        flow (loss changes over steps)."""
        import dataclasses

        from shellac_tpu.parallel.mesh import factor_devices

        base = get_model_config("tiny-moe")
        cfg = base.replace(
            d_model=128, n_heads=4, vocab_size=512, remat=True,
            moe=dataclasses.replace(base.moe, grouped_dropless=True,
                                    capacity_factor=1.0),
        )
        mesh = make_mesh(factor_devices(8, moe=True))
        tcfg = TrainConfig(learning_rate=1e-3, warmup_steps=1,
                           total_steps=10)
        key = jax.random.PRNGKey(0)
        state = init_train_state(cfg, tcfg, key, mesh=mesh)
        step = make_train_step(cfg, tcfg, mesh=mesh)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, 64), 0, cfg.vocab_size
        )
        bs = batch_shardings(mesh)
        batch = {"inputs": jax.device_put(tokens, bs),
                 "targets": jax.device_put(tokens, bs)}
        losses = []
        for _ in range(3):
            state, metrics = step(state, batch)
            assert float(metrics["moe_dropped_frac"]) == 0.0
            losses.append(float(metrics["loss"]))
        assert all(np.isfinite(losses))
        assert losses[-1] != losses[0]


class TestMoEFFN:
    def test_identity_experts_equal_dense(self):
        """With all experts identical and capacity ample, MoE == dense SwiGLU."""
        rng = np.random.default_rng(0)
        d, f, e = 16, 32, 4
        x = jnp.asarray(rng.normal(size=(2, 8, d)), jnp.float32)
        wg1 = jnp.asarray(rng.normal(size=(d, f)) * 0.1, jnp.float32)
        wu1 = jnp.asarray(rng.normal(size=(d, f)) * 0.1, jnp.float32)
        wd1 = jnp.asarray(rng.normal(size=(f, d)) * 0.1, jnp.float32)
        cfg = MoEConfig(num_experts=e, num_experts_per_token=2, capacity_factor=8.0)
        out, aux, _ = moe_ffn(
            x,
            jnp.zeros((d, e), jnp.float32),
            jnp.broadcast_to(wg1, (e, d, f)),
            jnp.broadcast_to(wu1, (e, d, f)),
            jnp.broadcast_to(wd1, (e, f, d)),
            cfg,
        )
        want = (jax.nn.silu(x @ wg1) * (x @ wu1)) @ wd1
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(want), rtol=1e-4, atol=1e-5
        )


class TestMoEModel:
    def _cfg(self):
        return get_model_config("tiny-moe").replace(dtype="float32")

    def test_forward_and_aux(self):
        cfg = self._cfg()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size)
        logits, aux = transformer.forward(cfg, params, tokens, return_aux=True)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert float(aux["aux"]) > 0
        assert float(aux["balance_loss"]) > 0

    def test_training_decreases_loss(self):
        cfg = self._cfg()
        tcfg = TrainConfig(warmup_steps=0, learning_rate=3e-3)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
        step = make_train_step(cfg, tcfg)
        batch = {"inputs": tokens, "targets": tokens}
        state, m0 = step(state, batch)
        for _ in range(9):
            state, m = step(state, batch)
        assert float(m["loss"]) < float(m0["loss"]) - 0.5
        assert "moe_aux_loss" in m

    def test_sharded_matches_unsharded(self):
        cfg = self._cfg()
        tcfg = TrainConfig(warmup_steps=0, learning_rate=1e-3)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)
        batch = {"inputs": tokens, "targets": tokens}

        state_u = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
        step_u = make_train_step(cfg, tcfg)
        state_u, mu = step_u(state_u, batch)

        mesh = make_mesh(ParallelConfig(fsdp=4, tp=2))
        state_s = init_train_state(cfg, tcfg, jax.random.PRNGKey(0), mesh=mesh)
        # Experts shard over (ep, fsdp); with ep=1 that is fsdp sharding.
        assert state_s.params["layers"]["w_gate"].sharding.spec[1] == (
            "ep", "fsdp",
        )
        step_s = make_train_step(cfg, tcfg, mesh=mesh)
        bs = batch_shardings(mesh)
        batch_s = jax.tree.map(lambda x: jax.device_put(x, bs), batch)
        state_s, ms = step_s(state_s, batch_s)
        np.testing.assert_allclose(
            float(mu["loss"]), float(ms["loss"]), rtol=1e-4
        )

    def test_cached_decode_matches_full(self):
        from shellac_tpu.inference import init_cache

        # Capacity must be ample: C scales with dispatch size T, so a
        # token dropped at prefill-T but kept at decode-T (or vice versa)
        # would legitimately change outputs. cf=8 => no drops either way.
        cfg = self._cfg().replace(
            moe=MoEConfig(num_experts=4, num_experts_per_token=2,
                          capacity_factor=8.0)
        )
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 8), 0, cfg.vocab_size)
        full = transformer.forward(cfg, params, tokens)
        cache = init_cache(cfg, 1, 16)
        _, cache = transformer.forward_with_cache(cfg, params, tokens[:, :4], cache)
        outs = []
        for i in range(4, 8):
            logits, cache = transformer.forward_with_cache(
                cfg, params, tokens[:, i : i + 1], cache
            )
            outs.append(logits[:, 0])
        got = jnp.stack(outs, axis=1)
        # NOTE: routing capacity differs between prefill (T=8) and
        # decode (T=1) only when tokens are dropped; with the default
        # capacity_factor and tiny T, capacity is ample so results match.
        np.testing.assert_allclose(
            np.asarray(full[:, 4:]), np.asarray(got), rtol=1e-4, atol=1e-4
        )
