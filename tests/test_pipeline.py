"""Pipeline parallelism: parity with dense execution on virtual meshes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import ParallelConfig, get_model_config, make_mesh
from shellac_tpu.config import TrainConfig
from shellac_tpu.models import transformer
from shellac_tpu.training import batch_shardings, init_train_state, make_train_step


def _cfg(**kw):
    base = dict(d_model=64, n_heads=4, vocab_size=512, dtype="float32", n_layers=4)
    base.update(kw)
    return get_model_config("tiny").replace(**base)


@pytest.fixture(scope="module")
def mesh_pp4():
    return make_mesh(ParallelConfig(dp=2, pp=4))


@pytest.fixture(scope="module")
def mesh_all_axes():
    # Every parallelism style at once: dp would need 16 devices, so use
    # pp=2, sp=2, tp=2 to cover the interactions on 8 devices.
    return make_mesh(ParallelConfig(pp=2, sp=2, tp=2))


class TestPipeline:
    def test_forward_matches_dense(self, mesh_pp4):
        cfg = _cfg()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size)
        dense = transformer.forward(cfg, params, tokens)
        piped = jax.jit(
            lambda p, t: transformer.forward(cfg, p, t, mesh=mesh_pp4)
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(dense), np.asarray(piped), rtol=1e-4, atol=1e-4
        )

    def test_more_microbatches_than_stages(self, mesh_pp4):
        cfg = _cfg()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size)
        dense = transformer.forward(cfg, params, tokens)
        piped = jax.jit(
            lambda p, t: transformer.forward(
                cfg, p, t, mesh=mesh_pp4, pipeline_microbatches=8
            )
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(dense), np.asarray(piped), rtol=1e-4, atol=1e-4
        )

    def test_training_matches_unsharded(self, mesh_pp4):
        cfg = _cfg()
        tcfg = TrainConfig(warmup_steps=0, learning_rate=1e-3)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size)

        state_u = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
        step_u = make_train_step(cfg, tcfg)
        batch_u = {"inputs": tokens, "targets": tokens}
        lu = []
        for _ in range(3):
            state_u, m = step_u(state_u, batch_u)
            lu.append(float(m["loss"]))

        state_p = init_train_state(cfg, tcfg, jax.random.PRNGKey(0), mesh=mesh_pp4)
        assert state_p.params["layers"]["wq"].sharding.spec[0] == "pp"
        step_p = make_train_step(cfg, tcfg, mesh=mesh_pp4)
        bs = batch_shardings(mesh_pp4)
        batch_p = jax.tree.map(lambda x: jax.device_put(x, bs), batch_u)
        lp = []
        for _ in range(3):
            state_p, m = step_p(state_p, batch_p)
            lp.append(float(m["loss"]))

        np.testing.assert_allclose(lu, lp, rtol=1e-4)

    def test_all_axes_combined(self, mesh_all_axes):
        """pp + sp (ring attention) + tp in one program."""
        cfg = _cfg()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)
        dense = transformer.forward(cfg, params, tokens)
        combined = jax.jit(
            lambda p, t: transformer.forward(cfg, p, t, mesh=mesh_all_axes)
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(dense), np.asarray(combined), rtol=1e-4, atol=1e-4
        )

    def test_indivisible_layers_raises(self):
        mesh = make_mesh(ParallelConfig(pp=8))
        cfg = _cfg(n_layers=6)
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.zeros((8, 16), jnp.int32)
        with pytest.raises(ValueError, match="not divisible by pp"):
            transformer.forward(cfg, params, tokens, mesh=mesh)

    def test_batch_indivisible_raises(self, mesh_pp4):
        cfg = _cfg()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.zeros((6, 16), jnp.int32)
        with pytest.raises(ValueError, match="not divisible by n_micro"):
            transformer.forward(cfg, params, tokens, mesh=mesh_pp4)


class TestPipelineMoE:
    def test_moe_forward_and_aux_match_dense(self, mesh_pp4):
        from shellac_tpu.config import MoEConfig

        cfg = _cfg(moe=MoEConfig(num_experts=4, num_experts_per_token=2,
                                 dropless=True))
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size
        )
        dense, aux_d = transformer.forward(
            cfg, params, tokens, return_aux=True
        )
        piped, aux_p = jax.jit(
            lambda p, t: transformer.forward(
                cfg, p, t, mesh=mesh_pp4, return_aux=True
            )
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(dense), np.asarray(piped), rtol=1e-4, atol=1e-4
        )
        # Microbatching changes the population each balance loss is
        # computed over, so the aux estimate differs slightly from the
        # full-batch number — but it must be finite, positive, and in
        # the same ballpark.
        for k in ("aux", "balance_loss", "router_z_loss"):
            a, b = float(aux_d[k]), float(aux_p[k])
            assert np.isfinite(b), k
            assert b > 0.0, k
            np.testing.assert_allclose(a, b, rtol=0.5)

    def test_moe_training_step_pp(self, mesh_pp4):
        from shellac_tpu.config import MoEConfig

        cfg = _cfg(moe=MoEConfig(num_experts=4, num_experts_per_token=2,
                                 dropless=True), remat=True)
        tcfg = TrainConfig(warmup_steps=1, total_steps=10)
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0), mesh=mesh_pp4)
        step = make_train_step(cfg, tcfg, mesh=mesh_pp4)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size
        )
        bs = batch_shardings(mesh_pp4)
        batch = {
            "inputs": jax.device_put(tokens, bs),
            "targets": jax.device_put(tokens, bs),
        }
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))


class TestPipelineInterleaved:
    """pp over interleaved dense/MoE stacks: the pipeline unit is a
    whole (dense^(every-1), moe) group, sharded over pp."""

    @pytest.fixture(scope="class")
    def mesh_pp2(self):
        return make_mesh(ParallelConfig(dp=2, pp=2, tp=2))

    def _icfg(self, **kw):
        from shellac_tpu.config import MoEConfig

        # dropless: capacity dropping is population-dependent, so a
        # microbatched pipeline would legitimately diverge from the
        # full-batch reference (same reason TestPipelineMoE uses it).
        return get_model_config("tiny-moe-interleaved").replace(
            dtype="float32",
            moe=MoEConfig(num_experts=4, num_experts_per_token=2,
                          dropless=True),
            **kw,
        )

    def test_forward_and_aux_match_dense(self, mesh_pp2):
        cfg = self._icfg()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size
        )
        dense, aux_d = transformer.forward(
            cfg, params, tokens, return_aux=True
        )
        piped, aux_p = jax.jit(
            lambda p, t: transformer.forward(
                cfg, p, t, mesh=mesh_pp2, return_aux=True
            )
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(dense), np.asarray(piped), rtol=1e-4, atol=1e-4
        )
        for k in ("aux", "balance_loss", "router_z_loss"):
            b = float(aux_p[k])
            assert np.isfinite(b) and b > 0.0, k
            np.testing.assert_allclose(float(aux_d[k]), b, rtol=0.5)

    def test_training_step(self, mesh_pp2):
        cfg = self._icfg(remat=True)
        tcfg = TrainConfig(warmup_steps=1, total_steps=10)
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0),
                                 mesh=mesh_pp2)
        step = make_train_step(cfg, tcfg, mesh=mesh_pp2)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size
        )
        bs = batch_shardings(mesh_pp2)
        batch = {
            "inputs": jax.device_put(tokens, bs),
            "targets": jax.device_put(tokens, bs),
        }
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))

    def test_indivisible_groups_raises(self):
        mesh = make_mesh(ParallelConfig(pp=4, dp=2))
        cfg = self._icfg()  # 2 groups, pp=4
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.zeros((8, 16), jnp.int32)
        with pytest.raises(ValueError, match="groups not divisible"):
            transformer.forward(cfg, params, tokens, mesh=mesh)


class TestPipelinePacked:
    """pp composes with packed segments and custom positions: the RoPE
    tables and segment ids ride the stage shift register per
    microbatch (pipeline_apply extras)."""

    def test_packed_forward_matches_dense(self, mesh_pp4):
        cfg = _cfg()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size
        )
        # Different document boundaries per row.
        seg = np.zeros((8, 32), np.int32)
        for i in range(8):
            seg[i, 10 + i:] = 1
            seg[i, 25 + (i % 4):] = 2
        seg = jnp.asarray(seg)
        dense = transformer.forward(cfg, params, tokens, segment_ids=seg)
        piped = jax.jit(
            lambda p, t: transformer.forward(
                cfg, p, t, mesh=mesh_pp4, segment_ids=seg
            )
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(dense), np.asarray(piped), rtol=1e-4, atol=1e-4
        )

    def test_custom_positions_match_dense(self, mesh_pp4):
        cfg = _cfg()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size
        )
        pos = jnp.asarray(
            np.cumsum(np.ones((8, 32), np.int32), axis=1) - 1 + np.arange(8)[:, None]
        )
        dense = transformer.forward(cfg, params, tokens, positions=pos)
        piped = jax.jit(
            lambda p, t: transformer.forward(
                cfg, p, t, mesh=mesh_pp4, positions=pos
            )
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(dense), np.asarray(piped), rtol=1e-4, atol=1e-4
        )

    def test_packed_training_matches_unsharded(self, mesh_pp4):
        cfg = _cfg()
        tcfg = TrainConfig(learning_rate=1e-3, warmup_steps=1, total_steps=10)
        tokens = jax.random.randint(
            jax.random.PRNGKey(2), (8, 32), 0, cfg.vocab_size
        )
        seg = np.zeros((8, 32), np.int32)
        seg[:, 16:] = 1
        batch = {
            "inputs": tokens, "targets": tokens,
            "segment_ids": jnp.asarray(seg),
        }
        state_d = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
        step_d = make_train_step(cfg, tcfg)
        state_d, md = step_d(state_d, batch)

        state_p = init_train_state(cfg, tcfg, jax.random.PRNGKey(0), mesh=mesh_pp4)
        step_p = make_train_step(cfg, tcfg, mesh=mesh_pp4)
        bs = batch_shardings(mesh_pp4)
        batch_p = {k: jax.device_put(v, bs) for k, v in batch.items()}
        state_p, mp = step_p(state_p, batch_p)
        np.testing.assert_allclose(
            float(md["loss"]), float(mp["loss"]), rtol=1e-4
        )
