"""Interleaved dense/MoE stacks (moe_every > 1): the grouped two-stack
layout (models/transformer.py::grouped_moe) must behave exactly like a
model — forward, training, sharding, and KV-cache decode all compose.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import ParallelConfig, get_model_config, make_mesh
from shellac_tpu.config import TrainConfig
from shellac_tpu.inference.engine import Engine
from shellac_tpu.models import transformer
from shellac_tpu.training import batch_shardings, init_train_state, make_train_step


def _cfg(**kw):
    return get_model_config("tiny-moe-interleaved").replace(
        dtype="float32", **kw
    )


@pytest.fixture(scope="module")
def setup():
    cfg = _cfg()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


class TestInterleavedStructure:
    def test_param_layout(self, setup):
        cfg, params = setup
        ng = cfg.n_layers // cfg.moe_every
        layers = params["layers"]
        assert set(layers) == {"dense", "moe"}
        # Dense sub-stack: (ng, every-1, ...); plain gated MLP weights.
        assert layers["dense"]["w_gate"].shape[:2] == (ng, cfg.moe_every - 1)
        assert layers["dense"]["w_gate"].ndim == 4  # no expert axis
        # MoE stack: (ng, E, ...) expert weights + router.
        assert layers["moe"]["w_router"].shape == (
            ng, cfg.d_model, cfg.moe.num_experts
        )
        assert layers["moe"]["w_gate"].shape[:2] == (
            ng, cfg.moe.num_experts
        )

    def test_indivisible_layers_raises(self):
        cfg = _cfg(n_layers=3)
        with pytest.raises(ValueError, match="groups of"):
            transformer.init_params(cfg, jax.random.PRNGKey(0))

    def test_axes_match_params(self, setup):
        cfg, params = setup
        axes = transformer.logical_axes(cfg)
        jax.tree.map(
            lambda p, a: None
            if p.ndim == len(a)
            else pytest.fail(f"{p.shape} vs {a}"),
            params, axes, is_leaf=lambda x: isinstance(x, tuple),
        )


class TestInterleavedForward:
    def test_forward_and_aux(self, setup):
        cfg, params = setup
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (2, 16), 0, cfg.vocab_size
        )
        logits, aux = transformer.forward(
            cfg, params, tokens, return_aux=True
        )
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert np.isfinite(np.asarray(logits)).all()
        # Routers exist only in MoE layers; aux must be finite & nonzero.
        assert np.isfinite(float(aux["aux"]))
        assert float(aux["balance_loss"]) > 0

    def test_dense_layers_are_actually_dense(self, setup):
        """A grouped model with router weights zeroed must still mix
        tokens through its dense sub-layers (aux becomes uniform)."""
        cfg, params = setup
        tokens = jax.random.randint(
            jax.random.PRNGKey(2), (1, 8), 0, cfg.vocab_size
        )
        _, aux = transformer.forward(cfg, params, tokens, return_aux=True)
        # balance loss of a 2-of-4 router on random init is near the
        # uniform optimum (1.0 normalized); wildly larger means the
        # dense stack leaked into the router accounting.
        assert float(aux["balance_loss"]) < 4.0

    def test_cached_decode_matches_forward(self, setup):
        """Greedy generation (grouped cache scan) == full-forward argmax."""
        cfg, params = setup
        prompt = jax.random.randint(
            jax.random.PRNGKey(3), (2, 7), 0, cfg.vocab_size
        )
        eng = Engine(cfg, params, temperature=0.0)
        out = eng.generate(prompt, max_new_tokens=4)
        toks = np.asarray(out.tokens)

        # Replay: the first generated token must equal the argmax of the
        # full forward at the last prompt position, and subsequent ones
        # must be self-consistent under teacher forcing.
        seq = np.asarray(prompt)
        for i in range(4):
            logits = transformer.forward(cfg, params, jnp.asarray(seq))
            nxt = np.asarray(jnp.argmax(logits[:, -1], -1))
            np.testing.assert_array_equal(nxt, toks[:, i])
            seq = np.concatenate([seq, nxt[:, None]], axis=1)


class TestInterleavedSharded:
    def test_sharded_training_matches_unsharded(self):
        cfg = _cfg()
        mesh = make_mesh(ParallelConfig(fsdp=2, sp=2, tp=2))
        tcfg = TrainConfig(learning_rate=1e-3, warmup_steps=1, total_steps=10)
        tokens = jax.random.randint(
            jax.random.PRNGKey(4), (4, 32), 0, cfg.vocab_size
        )
        batch = {"inputs": tokens, "targets": tokens}

        state_d = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
        step_d = make_train_step(cfg, tcfg)
        state_d, md = step_d(state_d, batch)

        state_s = init_train_state(cfg, tcfg, jax.random.PRNGKey(0), mesh=mesh)
        step_s = make_train_step(cfg, tcfg, mesh=mesh)
        bs = batch_shardings(mesh)
        batch_s = {k: jax.device_put(v, bs) for k, v in batch.items()}
        state_s, ms = step_s(state_s, batch_s)
        np.testing.assert_allclose(
            float(md["loss"]), float(ms["loss"]), rtol=2e-4
        )

    def test_pp_runs(self):
        """pp over interleaved stacks is supported (group-granular
        stages; parity tested in test_pipeline.py)."""
        cfg = _cfg()
        mesh = make_mesh(ParallelConfig(pp=2, tp=2, sp=2))
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.zeros((4, 16), jnp.int32)
        logits = jax.jit(
            lambda p, t: transformer.forward(cfg, p, t, mesh=mesh)
        )(params, tokens)
        assert np.isfinite(np.asarray(logits)).all()
