"""CLI and evaluation tests (all through the public entry points)."""

import json

import jax
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.cli import main
from shellac_tpu.models import transformer
from shellac_tpu.training.data import token_batches, write_token_shard
from shellac_tpu.training.evaluate import evaluate


def _run(capsys, argv):
    rc = main(argv)
    assert rc == 0
    return json.loads(capsys.readouterr().out.strip())


class TestEvaluate:
    def test_perplexity_of_uniform_model(self):
        """A zero-logit model must score exactly log(V) nats/token."""
        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        # Zero the output path: tied embeddings -> zero embed kills the
        # logits entirely (and the forward input too, but NLL of a
        # uniform softmax is log V regardless of the input).
        params["embed"] = params["embed"] * 0.0
        corpus = np.arange(2048, dtype=np.int32) % cfg.vocab_size
        out = evaluate(
            cfg, params,
            token_batches(corpus, batch_size=4, seq_len=32, num_batches=4),
        )
        assert out["loss"] == pytest.approx(np.log(cfg.vocab_size), rel=1e-4)
        assert out["tokens"] == 4 * 4 * 32

    def test_mask_weighting(self):
        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        batch = {
            "inputs": np.ones((2, 16), np.int32),
            "targets": np.ones((2, 16), np.int32),
            "mask": np.concatenate(
                [np.ones((2, 8), np.float32), np.zeros((2, 8), np.float32)], 1
            ),
        }
        out = evaluate(cfg, params, iter([batch]))
        assert out["tokens"] == 16  # only unmasked positions count


class TestCLI:
    def test_info_lists_presets(self, capsys):
        out = _run(capsys, ["info"])
        assert "tiny" in out and "shellac-1b" in out

    def test_info_model(self, capsys):
        out = _run(capsys, ["info", "--model", "tiny"])
        assert out["params"] > 0
        assert out["config"]["d_model"] == 64

    def test_train_eval_generate_roundtrip(self, tmp_path, capsys):
        """Train on shards, checkpoint, eval the checkpoint, generate."""
        rng = np.random.default_rng(0)
        corpus = (np.arange(1 << 14) % 97).astype(np.int32)
        shard = tmp_path / "shard0.bin"
        write_token_shard(str(shard), corpus)
        ckpt = tmp_path / "ckpt"

        out = _run(capsys, [
            "train", "--model", "tiny", "--steps", "30",
            "--batch", "4", "--seq", "64",
            "--data", str(shard), "--ckpt-dir", str(ckpt),
            "--learning-rate", "3e-3",
        ])
        assert out["final_step"] == 30

        ev = _run(capsys, [
            "eval", "--model", "tiny", "--ckpt-dir", str(ckpt),
            "--data", str(shard), "--batches", "4",
            "--batch", "4", "--seq", "64",
        ])
        # 30 steps on a period-97 ramp: far below uniform log(256)=5.55.
        assert ev["loss"] < 5.0
        assert ev["tokens"] == 4 * 4 * 64

        gen = _run(capsys, [
            "generate", "--model", "tiny", "--ckpt-dir", str(ckpt),
            "--prompt", "1,2,3,4,5", "--max-new", "8",
            "--temperature", "0",
        ])
        assert len(gen["tokens"]) == 8

    def test_lora_finetune_roundtrip(self, tmp_path, capsys):
        """train --lora-rank over a frozen base, then eval/generate
        --lora-dir merge the adapters; adapters must actually help."""
        corpus = (np.arange(1 << 14) % 97).astype(np.int32)
        shard = tmp_path / "shard0.bin"
        write_token_shard(str(shard), corpus)
        base = tmp_path / "base"
        lora = tmp_path / "lora"

        # A briefly-trained base the adapters will specialize.
        _run(capsys, [
            "train", "--model", "tiny", "--steps", "10",
            "--batch", "4", "--seq", "64",
            "--data", str(shard), "--ckpt-dir", str(base),
            "--learning-rate", "3e-3",
        ])
        base_ev = _run(capsys, [
            "eval", "--model", "tiny", "--ckpt-dir", str(base),
            "--data", str(shard), "--batches", "4",
            "--batch", "4", "--seq", "64",
        ])

        out = _run(capsys, [
            "train", "--model", "tiny", "--steps", "40",
            "--batch", "4", "--seq", "64",
            "--data", str(shard),
            "--lora-rank", "4", "--lora-targets", "wq,wv,w_down",
            "--base-ckpt", str(base), "--ckpt-dir", str(lora),
            "--learning-rate", "1e-2",
        ])
        assert out["final_step"] == 40
        assert out["adapter_params"] > 0

        ev = _run(capsys, [
            "eval", "--model", "tiny", "--ckpt-dir", str(base),
            "--lora-dir", str(lora),
            "--data", str(shard), "--batches", "4",
            "--batch", "4", "--seq", "64",
        ])
        assert ev["loss"] < base_ev["loss"]

        gen = _run(capsys, [
            "generate", "--model", "tiny", "--ckpt-dir", str(base),
            "--lora-dir", str(lora),
            "--prompt", "1,2,3,4,5", "--max-new", "8",
            "--temperature", "0",
        ])
        assert len(gen["tokens"]) == 8

        # Adapters trained on a MESH must merge into a host-restored
        # base (sharded-save -> unsharded-merge crossed placements
        # before being pulled to host).
        lora_mesh = tmp_path / "lora_mesh"
        _run(capsys, [
            "train", "--model", "tiny", "--steps", "10",
            "--batch", "8", "--seq", "64", "--data", str(shard),
            "--lora-rank", "4", "--mesh", "fsdp=4,tp=2",
            "--base-ckpt", str(base), "--ckpt-dir", str(lora_mesh),
            "--learning-rate", "1e-2",
        ])
        gen = _run(capsys, [
            "generate", "--model", "tiny", "--ckpt-dir", str(base),
            "--lora-dir", str(lora_mesh),
            "--prompt", "1,2,3", "--max-new", "4", "--temperature", "0",
        ])
        assert len(gen["tokens"]) == 4

        # Resuming with mismatched flags must refuse rather than
        # clobber the adapter dir's metadata.
        with pytest.raises(SystemExit, match="adapters trained with"):
            main([
                "train", "--model", "tiny", "--steps", "50",
                "--batch", "4", "--seq", "64", "--data", str(shard),
                "--lora-rank", "4", "--base-ckpt", str(base),
                "--ckpt-dir", str(lora),  # default targets != original
            ])
        # And unsupported knobs are rejected loudly.
        with pytest.raises(SystemExit, match="grad-accum"):
            main([
                "train", "--model", "tiny", "--steps", "5",
                "--lora-rank", "4", "--grad-accum", "4",
            ])

    def test_generate_quantized(self, capsys):
        gen = _run(capsys, [
            "generate", "--model", "tiny", "--prompt", "1,2,3",
            "--max-new", "4", "--quantize", "--temperature", "0",
        ])
        assert len(gen["tokens"]) == 4

    def test_generate_speculative(self, capsys):
        gen = _run(capsys, [
            "generate", "--model", "tiny", "--prompt", "1,2,3",
            "--max-new", "6", "--draft-model", "tiny", "--gamma", "2",
            "--temperature", "0",
        ])
        assert len(gen["tokens"]) == 6
        assert 0.0 <= gen["accept_rate"] <= 1.0

    def test_config_json_override(self, tmp_path, capsys):
        cfg_file = tmp_path / "m.json"
        cfg_file.write_text(json.dumps({"preset": "tiny", "n_layers": 3}))
        out = _run(capsys, ["info", "--config", str(cfg_file)])
        assert out["config"]["n_layers"] == 3

    def test_train_with_mesh(self, tmp_path, capsys):
        out = _run(capsys, [
            "train", "--model", "tiny", "--steps", "3",
            "--batch", "8", "--seq", "32", "--mesh", "dp=4,tp=2",
        ])
        assert out["final_step"] == 3


def test_data_skip_resumes_stream():
    """skip=N must continue the same deterministic stream at batch N."""
    import numpy as np

    from shellac_tpu.training.data import token_batches

    corpus = np.arange(10_000, dtype=np.int32) % 251
    full = list(token_batches(
        corpus, batch_size=2, seq_len=32, seed=7, num_batches=6
    ))
    tail = list(token_batches(
        corpus, batch_size=2, seq_len=32, seed=7, num_batches=3, skip=3
    ))
    for a, b in zip(full[3:], tail):
        np.testing.assert_array_equal(a["inputs"], b["inputs"])
        np.testing.assert_array_equal(a["targets"], b["targets"])


def test_generate_cli_stop_sequences(capsys):
    """--stop truncates on both the plain and speculative paths."""
    import json

    from shellac_tpu.cli import main

    def run(argv):
        main(["generate", "--model", "tiny", "--prompt", "1,2,3",
              "--max-new", "6", "--seed", "0"] + argv)
        return json.loads(capsys.readouterr().out.strip().splitlines()[-1])

    full = run([])["tokens"]
    assert len(full) == 6
    # Stop on the first generated token: everything truncated.
    got = run(["--stop", str(full[0])])["tokens"]
    assert got == []
    # Stop on a 2-token sequence mid-output.
    got = run(["--stop", f"{full[2]},{full[3]}"])["tokens"]
    assert got == full[:2]
    # Speculative path honors the same flag.
    spec = run(["--draft-model", "tiny", "--gamma", "2",
                "--stop", str(full[0])])
    assert spec["tokens"] == [] or spec["tokens"][0] != full[0]

    import pytest

    with pytest.raises(SystemExit, match="bad token-id"):
        run(["--stop", "13,,10"])


def test_batch_cli(tmp_path, capsys):
    """Offline batch generation: JSONL in -> ordered JSONL out; row
    overrides (max_tokens, seed) apply; greedy rows match the Engine."""
    import json

    import jax
    import numpy as np

    from shellac_tpu import get_model_config
    from shellac_tpu.cli import main
    from shellac_tpu.inference.engine import Engine
    from shellac_tpu.models import transformer
    from shellac_tpu.training.tokenizer import ByteTokenizer

    inp = tmp_path / "in.jsonl"
    outp = tmp_path / "out.jsonl"
    rows = [
        {"prompt": "hello", "max_tokens": 6},
        {"prompt": [5, 9, 2], "max_tokens": 4, "seed": 7,
         "temperature": 0.9},
        {"prompt": "abc"},
    ]
    inp.write_text("\n".join(json.dumps(r) for r in rows))
    rc = main([
        "batch", "--model", "tiny", "--input", str(inp),
        "--output", str(outp), "--max-new", "5", "--slots", "2",
    ])
    assert rc == 0
    got = [json.loads(line) for line in outp.read_text().splitlines()]
    assert [g["index"] for g in got] == [0, 1, 2]
    assert [len(g["tokens"]) for g in got] == [6, 4, 5]
    # greedy row 0 equals the single-request Engine (same seed=0 init
    # the CLI uses for a random --model tiny)
    cfg = get_model_config("tiny").replace(dtype="float32")
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    ids = ByteTokenizer().encode("hello")
    ref = Engine(cfg, params, temperature=0.0).generate(
        np.asarray([ids], np.int32), max_new_tokens=6
    ).tokens[0]
    assert got[0]["tokens"] == list(np.asarray(ref))


def test_batch_cli_row_errors_and_scalar_stop(tmp_path):
    import json

    import pytest

    from shellac_tpu.cli import main

    inp = tmp_path / "in.jsonl"
    outp = tmp_path / "out.jsonl"
    # Scalar stop is ONE sequence (not per-character): stopping on "xyz"
    # can never trigger in 4 tokens of a 256-vocab byte model, so the
    # output keeps its full length (per-char stop on 'x'|'y'|'z' would
    # truncate with high probability over many tokens).
    inp.write_text(json.dumps(
        {"prompt": "hello", "max_tokens": 4, "stop": "xyz"}
    ))
    rc = main(["batch", "--model", "tiny", "--input", str(inp),
               "--output", str(outp)])
    assert rc == 0
    got = json.loads(outp.read_text())
    assert len(got["tokens"]) == 4

    # A malformed row names itself and exits cleanly before compute.
    inp.write_text(json.dumps({"prompt": ""}))
    with pytest.raises(SystemExit, match="row 0"):
        main(["batch", "--model", "tiny", "--input", str(inp),
              "--output", str(outp)])
