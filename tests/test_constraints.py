"""Structured (grammar-constrained) decoding.

Conformance is the contract: every emitted sequence must decode to a
string the pattern accepts, under greedy AND sampled decoding, through
slot churn, multi-tick decode windows, and chunked prefill. The model
is untrained, so without the mask these outputs would be noise — the
tests fail loudly if the mask ever stops binding.
"""

import json
import threading
import urllib.request

import jax
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.batching import BatchingEngine, PagedBatchingEngine
from shellac_tpu.inference.constraints import (
    CharDFA,
    compile_token_dfa,
    constraint_pattern,
)
from shellac_tpu.models import transformer
from shellac_tpu.training.tokenizer import ByteTokenizer

EOS = ByteTokenizer.EOS  # 257


def _cfg():
    # Vocab covers the byte tokenizer's specials so EOS is a real row.
    return get_model_config("tiny").replace(
        dtype="float32", vocab_size=ByteTokenizer.vocab_size
    )


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return cfg, transformer.init_params(cfg, jax.random.PRNGKey(0))


def _matcher(pattern):
    d = CharDFA(pattern)

    def m(s):
        st = d.start
        for ch in s:
            st = d.step(st, ch)
            if st is None:
                return False
        return d.accepting(st)

    return m


class TestRegexEngine:
    @pytest.mark.parametrize("pattern,yes,no", [
        (r"ab+c?", ["ab", "abbbc", "abc"], ["ac", "", "abcc", "b"]),
        (r"-?[0-9]{1,3}(\.[0-9]+)?", ["-12", "3.14", "999"],
         ["1234", "3.", "", "--1"]),
        (r"(red|green|blue)", ["red", "blue"], ["purple", "re", "redd"]),
        (r"[a-f]+@[a-f]+\.(com|org)", ["ab@cd.com", "f@e.org"],
         ["ab@cd.net", "@a.com", "ab@.com"]),
        (r'"[^"\\]*"', ['""', '"hi there"'], ['"', 'hi', '"a"b"']),
        (r"a{2,4}", ["aa", "aaaa"], ["a", "aaaaa", ""]),
    ])
    def test_matches(self, pattern, yes, no):
        m = _matcher(pattern)
        for s in yes:
            assert m(s), (pattern, s)
        for s in no:
            assert not m(s), (pattern, s)

    def test_schema_pattern_roundtrip(self):
        pat = constraint_pattern({"json_schema": {
            "type": "object",
            "properties": {"name": {"type": "string"},
                           "age": {"type": "integer"},
                           "ok": {"type": "boolean"}},
        }})
        m = _matcher(pat)
        assert m('{"name":"bo","age":41,"ok":true}')
        assert not m('{"age":41,"name":"bo","ok":true}')  # fixed order
        assert not m('{"name":"bo","age":41}')  # all properties required

    def test_enum_and_array(self):
        pat = constraint_pattern({"json_schema": {
            "type": "object",
            "properties": {
                "color": {"enum": ["red", "green"]},
                "tags": {"type": "array", "items": {"type": "string"}},
            },
        }})
        m = _matcher(pat)
        assert m('{"color":"red","tags":["a","b"]}')
        assert m('{"color":"green","tags":[]}')
        assert not m('{"color":"blue","tags":[]}')

    def test_bad_patterns_raise(self):
        for pat in ("(ab", "a{2", "[abc", "*a", "[z-a]"):
            with pytest.raises(ValueError):
                CharDFA(pat)

    def test_negated_class_complements_full_universe(self):
        """Standard semantics: only '.' excludes newline. [^x], \\D and
        \\S complement within the full universe (ADVICE.md round 5)."""
        assert _matcher("[^x]")("\n")
        assert _matcher(r"\D")("\n")
        assert _matcher(r"\S*")("")  # \S itself still excludes spaces
        assert not _matcher(r"\S")(" ")
        assert not _matcher(".")("\n")
        assert _matcher(r"[\s\S]")("\n")  # the 'anything' class idiom

    def test_string_pattern_alternation_stays_scoped(self):
        """A '|' inside a schema string "pattern" must not escape into
        the surrounding grammar (the pattern is grouped)."""
        pat = constraint_pattern({"json_schema": {
            "type": "object",
            "properties": {"x": {"type": "string", "pattern": "a|b"},
                           "y": {"type": "integer"}},
        }})
        m = _matcher(pat)
        assert m('{"x":"a","y":1}')
        assert m('{"x":"b","y":2}')
        assert not m('{"x":"a')
        assert not m('{"x":"ab","y":1}')

    def test_constraint_spec_validation(self):
        with pytest.raises(ValueError, match="exactly one"):
            constraint_pattern({})
        with pytest.raises(ValueError, match="exactly one"):
            constraint_pattern({"regex": "a", "json_object": True})


def _conforms(tokens, pattern):
    """Decode emitted ids (strip trailing EOS) and match the pattern."""
    toks = list(tokens)
    if toks and toks[-1] == EOS:
        toks = toks[:-1]
    s = bytes(int(t) for t in toks).decode("utf-8", errors="strict")
    assert _matcher(pattern)(s), f"output {s!r} violates {pattern!r}"
    return s


class TestUnicodeByteLevel:
    """The byte-level automaton: full Unicode classes and literals,
    multi-byte characters split across byte tokens."""

    def test_non_latin_literals_match(self):
        for pat, yes, no in (
            ("да|нет", ["да", "нет"], ["da", "д", "данет"]),
            ("[א-ת]{2,4}", ["שלום", "אב"], ["ab", "א", "שלוםם"]),
            ("日本語?", ["日本", "日本語"], ["日", "語"]),
            ("[^a]b", ["xb", "яb", "語b"], ["ab", "b"]),
            (".{2}", ["ab", "яз", "日本"], ["a", "abc"]),
        ):
            m = _matcher(pat)
            for s in yes:
                assert m(s), (pat, s)
            for s in no:
                assert not m(s), (pat, s)

    def test_multibyte_chars_split_across_byte_tokens(self, model):
        """ByteTokenizer emits one token per UTF-8 byte, so a Cyrillic
        answer spans 2 tokens per character — the DFA must advance
        mid-character. Conformance through the real engine."""
        cfg, params = model
        dfa = compile_token_dfa("(да|нет)", ByteTokenizer(),
                               cfg.vocab_size, eos_id=EOS)
        assert dfa.n_states > 1
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, eos_id=EOS)
        eng.submit(0, [3, 5, 7], 12, constraint=dfa)
        done = {}
        while eng.pending:
            done.update(eng.step())
        s = _conforms(done[0], "(да|нет)")
        assert s in ("да", "нет")

    def test_token_bytes_protocol_enables_partial_utf8(self):
        # Without token_bytes, a lone continuation byte decodes to
        # U+FFFD and would be disabled; with it, the byte advances the
        # automaton exactly.
        from shellac_tpu.inference.constraints import _token_bytes

        tb = _token_bytes(ByteTokenizer(), 259, EOS)
        assert tb[0xD0] == b"\xd0"  # first byte of 'д'
        assert tb[0xB0] == b"\xb0"  # continuation byte
        assert tb[EOS] is None

    def test_walk_budget_fallback_identical_tables(self, monkeypatch):
        """Over the walk-precompute budget, compilation switches to
        per-state token walking — same tables, bounded memory."""
        import shellac_tpu.inference.constraints as C

        tok = ByteTokenizer()
        for pat in (r'\{"x":[0-9]{1,4}\}', "(да|нет)", "[a-z]{2,8}"):
            fast = compile_token_dfa(pat, tok, 259, eos_id=EOS)
            monkeypatch.setattr(C, "MAX_WALK_ENTRIES", 1)
            slow = compile_token_dfa(pat, tok, 259, eos_id=EOS)
            monkeypatch.undo()
            assert np.array_equal(fast.trans, slow.trans), pat

    def test_minimization_shrinks_counting_patterns(self):
        from shellac_tpu.inference.constraints import (
            _byte_dfa,
            _minimize,
        )

        trans, accept = _byte_dfa(CharDFA("[ab]{1,64}"))
        mtrans, _ = _minimize(trans, accept)
        assert mtrans.shape[0] <= trans.shape[0]
        # Equivalence spot-check after minimization.
        m = _matcher("[ab]{1,64}")
        assert m("ab" * 30) and not m("ab" * 33)


class TestSchemaV2:
    def test_optional_properties(self):
        schema = {"type": "object",
                  "properties": {"a": {"type": "integer"},
                                 "b": {"type": "boolean"},
                                 "c": {"type": "string"}},
                  "required": ["b"]}
        m = _matcher(_schema_regex_public(schema))
        assert m('{"b":true}')
        assert m('{"a":1,"b":false}')
        assert m('{"b":true,"c":"x"}')
        assert m('{"a":2,"b":true,"c":"y"}')
        assert not m('{"a":1}')          # missing required b
        assert not m('{"a":1,"c":"y"}')  # missing required b
        assert not m('{"b":true,}')      # trailing comma
        assert not m('{"c":"y","b":true}')  # fixed order

    def test_all_optional_object_can_be_empty(self):
        schema = {"type": "object",
                  "properties": {"a": {"type": "integer"}},
                  "required": []}
        m = _matcher(_schema_regex_public(schema))
        assert m("{}")
        assert m('{"a":3}')
        assert not m('{"a":}')

    def test_no_required_list_means_all_required(self):
        # Back-compat + the OpenAI structured-output norm.
        schema = {"type": "object",
                  "properties": {"a": {"type": "integer"},
                                 "b": {"type": "boolean"}}}
        m = _matcher(_schema_regex_public(schema))
        assert m('{"a":1,"b":true}')
        assert not m('{"a":1}')

    def test_anyof_and_const(self):
        schema = {"anyOf": [{"type": "integer"},
                            {"const": "miss"},
                            {"type": "object",
                             "properties": {"x": {"type": "null"}}}]}
        m = _matcher(_schema_regex_public(schema))
        assert m("42")
        assert m('"miss"')
        assert m('{"x":null}')
        assert not m('"hit"')

    def test_non_latin_enum_values(self):
        schema = {"enum": ["да", "нет", "可能"]}
        m = _matcher(_schema_regex_public(schema))
        assert m('"да"') and m('"可能"')
        assert not m('"da"')

    def test_json_strings_reject_raw_control_chars(self):
        # Constraint-conforming output must stay json.loads-able: raw
        # C0 control bytes are legal for the regex engine's universe
        # but forbidden inside JSON strings.
        schema = {"type": "object",
                  "properties": {"a": {"type": "string"}}}
        m = _matcher(_schema_regex_public(schema))
        assert m('{"a":"xy"}')
        assert not m('{"a":"x\x01y"}')
        assert not m('{"a":"x\ny"}')
        assert not m('{"a":"x\x1fy"}')

    def test_additional_properties_true_appends_generic_pairs(self):
        # v3: an open object is honored via the depth-limited generic-
        # JSON grammar — extra pairs append AFTER the declared fixed-
        # order sequence instead of being rejected.
        schema = {"type": "object", "additionalProperties": True,
                  "properties": {"a": {"type": "integer"}}}
        m = _matcher(_schema_regex_public(schema))
        assert m('{"a":1}')
        assert m('{"a":1,"extra":"y"}')
        assert m('{"a":1,"x":{"deep":[1,2]},"y":null}')
        assert not m('{"x":1}')       # required a still required
        assert not m('{"x":1,"a":1}')  # extras only after declared

    def test_additional_properties_schema_types_extras(self):
        schema = {"type": "object",
                  "properties": {"a": {"type": "boolean"}},
                  "required": [],
                  "additionalProperties": {"type": "integer"}}
        m = _matcher(_schema_regex_public(schema))
        assert m("{}")
        assert m('{"a":true}')
        assert m('{"x":3}')
        assert m('{"a":false,"x":3,"y":4}')
        assert not m('{"x":"s"}')  # extras typed by the AP schema

    def test_local_ref_resolution(self):
        schema = {
            "type": "object",
            "properties": {"who": {"$ref": "#/$defs/person"},
                           "n": {"$ref": "#/definitions/count"}},
            "$defs": {"person": {"enum": ["ann", "bo"]}},
            "definitions": {"count": {"type": "integer"}},
        }
        m = _matcher(_schema_regex_public(schema))
        assert m('{"who":"ann","n":4}')
        assert not m('{"who":"cy","n":4}')

    def test_cyclic_ref_rejected(self):
        schema = {"$ref": "#/$defs/node",
                  "$defs": {"node": {"anyOf": [
                      {"type": "null"},
                      {"$ref": "#/$defs/node"},
                  ]}}}
        with pytest.raises(ValueError, match="cyclic"):
            _schema_regex_public(schema)
        with pytest.raises(ValueError, match="not found"):
            _schema_regex_public({"$ref": "#/$defs/missing"})
        with pytest.raises(ValueError, match="local"):
            _schema_regex_public({"$ref": "https://x/schema.json"})

    def test_string_formats(self):
        for fmt, yes, no in (
            ("date", "2026-08-03", "2026-13-03"),
            ("date-time", "2026-08-03T09:15:00Z", "2026-08-03 09:15"),
            ("uuid", "123e4567-e89b-42d3-a456-426614174000", "123"),
            ("email", "a.b+c@ex-ample.org", "not-an-email"),
        ):
            m = _matcher(_schema_regex_public(
                {"type": "string", "format": fmt}
            ))
            assert m(json.dumps(yes)), (fmt, yes)
            assert not m(json.dumps(no)), (fmt, no)
        # Unknown formats stay annotations: free string grammar.
        m = _matcher(_schema_regex_public(
            {"type": "string", "format": "hostname"}
        ))
        assert m('"anything at all"')

    def test_unknown_required_name_rejected(self):
        schema = {"type": "object",
                  "properties": {"a": {"type": "integer"}},
                  "required": ["zz"]}
        with pytest.raises(ValueError, match="required"):
            _schema_regex_public(schema)


def _schema_regex_public(schema):
    return constraint_pattern({"json_schema": schema})


class TestConstrainedEngine:
    def _dfa(self, cfg, pattern):
        return compile_token_dfa(pattern, ByteTokenizer(), cfg.vocab_size,
                                 eos_id=EOS)

    def test_greedy_conformance_with_churn(self, model):
        """Constrained + unconstrained requests share the batch; every
        constrained output conforms through slot reuse."""
        cfg, params = model
        pattern = r'\{"x":[0-9]{1,4}\}'
        dfa = self._dfa(cfg, pattern)
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, eos_id=EOS)
        rng = np.random.default_rng(0)
        for i in range(5):
            prompt = rng.integers(1, 200, size=5 + i)
            eng.submit(("c", i), prompt, 24, constraint=dfa)
            eng.submit(("f", i), prompt, 8)
        done = {}
        while eng.pending:
            done.update(eng.step())
        for i in range(5):
            _conforms(done[("c", i)], pattern)
            assert len(done[("f", i)]) >= 1  # free requests unaffected

    def test_sampled_conformance_multi_tick(self, model):
        """Sampled (hot) decoding through a decode_ticks=4 window: the
        on-device DFA advance must hold inside the scan."""
        cfg, params = model
        pattern = r"(yes|no|maybe)( (yes|no|maybe)){0,3}"
        dfa = self._dfa(cfg, pattern)
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=1.5, eos_id=EOS, decode_ticks=4)
        for i in range(4):
            eng.submit(i, [65, 66, 67], 20, constraint=dfa, seed=i)
        done = {}
        while eng.pending:
            done.update(eng.step())
        outs = set()
        for i in range(4):
            outs.add(_conforms(done[i], pattern))
        assert len(outs) >= 1

    def test_seeded_determinism(self, model):
        cfg, params = model
        pattern = r"[a-z]{3,8}"
        dfa = self._dfa(cfg, pattern)

        def run():
            eng = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                                 temperature=1.0, eos_id=EOS)
            eng.submit("r", [1, 2, 3], 10, constraint=dfa, seed=7)
            done = {}
            while eng.pending:
                done.update(eng.step())
            return done["r"]

        assert run() == run()

    def test_paged_engine_conformance(self, model):
        cfg, params = model
        pattern = r'\[("[ab]+",)*"[ab]+"\]'
        dfa = self._dfa(cfg, pattern)
        eng = PagedBatchingEngine(cfg, params, n_slots=2, max_len=96,
                                  block_size=32, temperature=0.0,
                                  eos_id=EOS)
        eng.submit(0, [10, 20, 30], 30, constraint=dfa)
        done = {}
        while eng.pending:
            done.update(eng.step())
        _conforms(done[0], pattern)

    def test_chunked_prefill_conformance(self, model):
        cfg, params = model
        pattern = r"-?[0-9]{1,6}"
        dfa = self._dfa(cfg, pattern)
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=96,
                             temperature=0.0, eos_id=EOS,
                             prefill_chunk=16)
        prompt = np.arange(1, 41, dtype=np.int32)
        eng.submit("long", prompt, 10, constraint=dfa)
        done = {}
        while eng.pending:
            done.update(eng.step())
        _conforms(done["long"], pattern)

    def test_json_schema_end_to_end(self, model):
        """Bounded schema (enum + length-limited fields): every DFA
        path terminates within the budget, so strict conformance holds
        under sampling. (Unbounded string/number fields can always be
        truncated by max_new — that is inherent to constrained
        decoding, not a masking bug.)"""
        cfg, params = model
        pat = constraint_pattern({"json_schema": {
            "type": "object",
            "properties": {
                "name": {"type": "string", "pattern": "[a-z]{1,6}"},
                "kind": {"enum": ["cat", "dog"]},
                "n": {"type": "string", "pattern": "[0-9]{1,3}"},
            },
        }})
        dfa = self._dfa(cfg, pat)
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=128,
                             temperature=0.8, eos_id=EOS)
        eng.submit("js", [1, 2, 3], 60, constraint=dfa, seed=3)
        done = {}
        while eng.pending:
            done.update(eng.step())
        s = _conforms(done["js"], pat)
        v = json.loads(s)
        assert set(v) == {"name", "kind", "n"}
        assert v["kind"] in ("cat", "dog")

    def test_guards(self, model):
        cfg, params = model
        dfa = self._dfa(cfg, "[a-z]+")
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             eos_id=EOS)
        with pytest.raises(ValueError, match="TokenDFA"):
            eng.submit("bad", [1], 4, constraint={"regex": "a"})
        with pytest.raises(ValueError, match="min_tokens"):
            eng.submit("bad2", [1], 8, constraint=dfa, min_tokens=3)
        no_eos = BatchingEngine(cfg, params, n_slots=2, max_len=64)
        with pytest.raises(ValueError, match="eos_id"):
            no_eos.submit("bad3", [1], 4, constraint=dfa)
        from shellac_tpu.inference.spec_batching import (
            SpeculativeBatchingEngine,
        )

        spec = SpeculativeBatchingEngine(cfg, params, cfg, params,
                                         eos_id=EOS)
        with pytest.raises(ValueError, match="speculative"):
            spec.submit("bad4", [1], 4, constraint=dfa)


class TestBPETokenizerConstraints:
    def test_compiles_over_trained_bpe(self, tmp_path, model):
        """The token-DFA lift works over multi-character BPE tokens,
        not just single bytes: conformance holds when tokens span
        several pattern characters."""
        corpus = tmp_path / "c.txt"
        corpus.write_text(
            "red green blue red green 123 456 red blue 789\n" * 50
        )
        from shellac_tpu.training.tokenizer import BPETokenizer

        tok = BPETokenizer.train(
            [str(corpus)], 300, str(tmp_path / "bpe.json")
        )
        pattern = r"(red|green|blue)"
        dfa = compile_token_dfa(pattern, tok, tok.vocab_size,
                                eos_id=tok.eos_id)
        # Multi-char tokens must appear as legal moves somewhere (the
        # trained vocab merges these words), or the lift degenerated to
        # bytes only.
        legal = set()
        for row in dfa.trans:
            for tid in np.nonzero(row[:-1] >= 0)[0]:
                legal.add(tok.decode([int(tid)]))
        assert any(len(s) > 1 for s in legal), legal
        # Walk: any maximal-logprob-free greedy path conforms.
        st, out = 0, []
        for _ in range(10):
            row = dfa.trans[st]
            allowed = np.nonzero(row >= 0)[0]
            tid = int(allowed[-1])
            if tid == tok.vocab_size:
                break
            out.append(tid)
            st = int(row[tid])
        s = tok.decode(out)
        assert _matcher(pattern)(s), s


class TestServerAPI:
    @pytest.fixture(scope="class")
    def http_srv(self, model):
        from shellac_tpu.inference.server import (
            InferenceServer,
            make_http_server,
        )

        cfg, params = model
        srv = InferenceServer(
            cfg, params, tokenizer=ByteTokenizer(),
            n_slots=2, max_len=128, temperature=0.0, eos_id=EOS,
        )
        httpd = make_http_server(srv)
        t = threading.Thread(target=httpd.serve_forever, daemon=True)
        t.start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        yield base
        httpd.shutdown()
        srv.close()

    def _post(self, base, path, payload):
        req = urllib.request.Request(
            base + path, json.dumps(payload).encode(),
            {"Content-Type": "application/json"},
        )
        return json.loads(urllib.request.urlopen(req, timeout=300).read())

    def test_native_regex_constraint(self, http_srv):
        r = self._post(http_srv, "/generate", {
            "text": "give me a word: ",
            "max_new": 16,
            "constraint": {"regex": "[a-z]{2,6}"},
        })
        _conforms(r["tokens"], "[a-z]{2,6}")

    def test_openai_response_format_json_schema(self, http_srv):
        r = self._post(http_srv, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": "emit json"}],
            "max_tokens": 40,
            "temperature": 0,
            "response_format": {"type": "json_schema", "json_schema": {
                "name": "out",
                "schema": {"type": "object", "properties": {
                    "ok": {"type": "boolean"}}},
            }},
        })
        content = r["choices"][0]["message"]["content"]
        v = json.loads(content)
        assert isinstance(v["ok"], bool)

    def test_openai_schema_optional_and_non_latin(self, http_srv):
        """Structured-output v2 through the OpenAI endpoint: optional
        properties + a non-Latin enum value, decoded from byte tokens
        that split the Cyrillic characters."""
        r = self._post(http_srv, "/v1/chat/completions", {
            "messages": [{"role": "user", "content": "ответ?"}],
            "max_tokens": 48,
            "temperature": 0,
            "response_format": {"type": "json_schema", "json_schema": {
                "name": "out",
                "schema": {"type": "object", "properties": {
                    "ok": {"type": "boolean"},
                    "ответ": {"enum": ["да", "нет"]},
                    "note": {"type": "string",
                             "pattern": "[a-z]{1,4}"},
                }, "required": ["ответ"]},
            }},
        })
        content = r["choices"][0]["message"]["content"]
        v = json.loads(content)
        assert v["ответ"] in ("да", "нет")
        for key in v:
            assert key in ("ok", "ответ", "note")

    def test_streaming_conforms(self, http_srv):
        """ndjson streaming with a constraint: the assembled stream
        equals the final record and conforms to the pattern."""
        req = urllib.request.Request(
            http_srv + "/generate",
            json.dumps({"text": "go: ", "max_new": 12, "stream": True,
                        "constraint": {"regex": "[a-z]{2,6}"}}).encode(),
            {"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=300) as resp:
            records = [json.loads(x) for x in resp.read().splitlines()]
        final = records[-1]
        assert final.get("done")
        streamed = [t for r in records[:-1] for t in r["tokens"]]
        assert final["tokens"][:len(streamed)] == streamed
        _conforms(final["tokens"], "[a-z]{2,6}")

    def test_best_of_all_conform(self, http_srv):
        """Parallel sampling fan-out: every sampled candidate is
        independently constrained."""
        r = self._post(http_srv, "/generate", {
            "text": "word: ", "max_new": 12, "temperature": 1.2,
            "n": 2, "best_of": 2, "seed": 5,
            "constraint": {"regex": "(yes|no|maybe)"},
        })
        assert len(r["choices"]) == 2
        for c in r["choices"]:
            _conforms(c["tokens"], "(yes|no|maybe)")

    def test_bad_constraint_is_http_400(self, http_srv):
        import urllib.error

        with pytest.raises(urllib.error.HTTPError) as e:
            self._post(http_srv, "/generate", {
                "text": "x", "max_new": 4,
                "constraint": {"regex": "(unclosed"},
            })
        assert e.value.code == 400
