"""Recipe adoption requires the win to persist across two queue passes.

Pins VERDICT r4 item 9: a single drift-lucky sweep row must not set
bench.py's TPU headline recipe; the winning config needs two
measurements whose MINIMUM still beats the plain baseline by >1%.
"""

import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "adopt_recipe.py")

PLAIN_ROW = {
    "metric": "train_throughput_2048d16L_seq2048_tpu",
    "value": 19000.0,
    "detail": {"batch": 6, "fused_loss": None, "remat_policy": "none",
               "mfu": 0.55},
}


def sweep_row(tok_s, batch=8, policy="dots", fused=4096):
    return {"tok_s": tok_s, "batch": batch, "policy": policy,
            "fused": fused, "remat": True, "mfu": 0.6}


def run_adopt(tmp_path, rows):
    queue = tmp_path / "queue.jsonl"
    queue.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    env = dict(os.environ,
               SHELLAC_RECIPE_PATH=str(tmp_path / "bench_recipe.json"))
    out = subprocess.run(
        [sys.executable, SCRIPT, str(queue)],
        capture_output=True, text=True, check=True,
        cwd=str(tmp_path), env=env,
    )
    return json.loads(out.stdout)


def test_single_pass_win_keeps_existing_recipe(tmp_path):
    # A one-off win with NO second-pass data is inconclusive: a relay
    # wedge mid-queue must not silently revert an adopted recipe.
    (tmp_path / "bench_recipe.json").write_text(json.dumps(
        {"batch": 8, "fused_loss": None, "remat_policy": "none"}))
    result = run_adopt(tmp_path, [PLAIN_ROW, sweep_row(21000.0)])
    assert "unconfirmed" in result["adopt"]
    assert (tmp_path / "bench_recipe.json").exists()


def test_two_pass_win_is_adopted_with_floor(tmp_path):
    result = run_adopt(
        tmp_path,
        [PLAIN_ROW, sweep_row(21000.0), sweep_row(20500.0)])
    assert result["adopt"] == "recipe written"
    assert result["measured_floor_tok_s"] == 20500.0
    assert result["measured_passes"] == 2
    with open(tmp_path / "bench_recipe.json") as f:
        recipe = json.load(f)
    assert recipe["batch"] == 8
    assert recipe["remat_policy"] == "dots"


def test_mfu_comes_from_fastest_measurement(tmp_path):
    slow = dict(sweep_row(20500.0), mfu=0.58)
    fast = dict(sweep_row(21000.0), mfu=0.61)
    result = run_adopt(tmp_path, [PLAIN_ROW, slow, fast])
    assert result["adopt"] == "recipe written"
    assert result["measured_tok_s"] == 21000.0
    assert result["measured_mfu"] == 0.61


def test_regressing_second_pass_drops_stale_recipe(tmp_path):
    # Pass 2 DID run and the win did not hold: conclusive evidence
    # against — any previously adopted recipe goes.
    (tmp_path / "bench_recipe.json").write_text(json.dumps(
        {"batch": 8, "fused_loss": None, "remat_policy": "none"}))
    result = run_adopt(
        tmp_path,
        [PLAIN_ROW, sweep_row(21000.0), sweep_row(18000.0)])
    assert "failed second queue pass" in result["adopt"]
    assert not (tmp_path / "bench_recipe.json").exists()


def test_no_plain_baseline_never_adopts(tmp_path):
    result = run_adopt(
        tmp_path, [sweep_row(21000.0), sweep_row(21000.0)])
    assert "no plain baseline" in result["adopt"]
    assert not (tmp_path / "bench_recipe.json").exists()


def test_plain_config_sweep_row_is_not_pass2_evidence(tmp_path):
    # The plain config also appears as a sweep row (sweep_b6_none);
    # pairing it with the plain bench row must not count as "pass 2
    # ran" for an unrelated one-off winner.
    (tmp_path / "bench_recipe.json").write_text(json.dumps(
        {"batch": 8, "fused_loss": None, "remat_policy": "none"}))
    plain_as_sweep = sweep_row(19010.0, batch=6, policy="none",
                               fused=None)
    result = run_adopt(
        tmp_path, [PLAIN_ROW, plain_as_sweep, sweep_row(21000.0)])
    assert "unconfirmed" in result["adopt"]
    assert (tmp_path / "bench_recipe.json").exists()


def test_other_config_pass2_does_not_condemn_winner(tmp_path):
    # Another config completed both passes (without winning); the
    # one-off best was given up on after one measurement — still
    # inconclusive for THAT config, keep the recipe.
    (tmp_path / "bench_recipe.json").write_text(json.dumps(
        {"batch": 8, "fused_loss": None, "remat_policy": "none"}))
    loser1 = sweep_row(18000.0, batch=4)
    loser2 = sweep_row(18100.0, batch=4)
    result = run_adopt(
        tmp_path, [PLAIN_ROW, loser1, loser2, sweep_row(21000.0)])
    assert "unconfirmed" in result["adopt"]
    assert (tmp_path / "bench_recipe.json").exists()


def test_plain_config_itself_is_never_adopted(tmp_path):
    # Two sweep rows of the PLAIN config riding above the bench.py
    # baseline (cross-harness bias) must not produce a "recipe"
    # identical to the default.
    rows = [PLAIN_ROW,
            sweep_row(19400.0, batch=6, policy="none", fused=None),
            sweep_row(19400.0, batch=6, policy="none", fused=None)]
    result = run_adopt(tmp_path, rows)
    assert result["adopt"] != "recipe written"
    assert not (tmp_path / "bench_recipe.json").exists()


def test_remeasured_losing_recipe_dropped_despite_unconfirmed_one_off(
        tmp_path):
    # The adopted recipe's own config got both passes and lost to
    # plain; an unrelated config posted an unconfirmed one-off win.
    # The recipe is conclusively stale and must go.
    (tmp_path / "bench_recipe.json").write_text(
        json.dumps({"batch": 4, "fused_loss": 4096,
                    "remat_policy": "dots"}))
    recipe1 = sweep_row(18000.0, batch=4)
    recipe2 = sweep_row(18100.0, batch=4)
    result = run_adopt(
        tmp_path, [PLAIN_ROW, recipe1, recipe2, sweep_row(21000.0)])
    assert "no longer wins" in result["adopt"]
    assert not (tmp_path / "bench_recipe.json").exists()


def test_plain_sweep_rows_alone_keep_existing_recipe(tmp_path):
    # Two plain-config sweep rows riding cross-harness bias are the
    # ONLY rows besides the baseline: the adopted recipe's config got
    # zero measurements, so nothing may condemn it.
    (tmp_path / "bench_recipe.json").write_text(json.dumps(
        {"batch": 4, "fused_loss": None, "remat_policy": "dots"}))
    plain_sweep = lambda v: sweep_row(v, batch=6, policy="none",  # noqa: E731
                                      fused=None)
    result = run_adopt(
        tmp_path, [PLAIN_ROW, plain_sweep(19400.0), plain_sweep(19400.0)])
    assert "keeping recipe" in result["adopt"]
    assert (tmp_path / "bench_recipe.json").exists()


def test_nothing_beats_plain_drops_stale_recipe(tmp_path):
    (tmp_path / "bench_recipe.json").write_text(json.dumps(
        {"batch": 8, "fused_loss": None, "remat_policy": "none"}))
    result = run_adopt(tmp_path, [PLAIN_ROW, sweep_row(19050.0)])
    assert result["adopt"] == "plain recipe stands"
    assert not (tmp_path / "bench_recipe.json").exists()
