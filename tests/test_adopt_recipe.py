"""Recipe adoption requires the win to persist across two queue passes.

Pins VERDICT r4 item 9: a single drift-lucky sweep row must not set
bench.py's TPU headline recipe; the winning config needs two
measurements whose MINIMUM still beats the plain baseline by >1%.
"""

import importlib.util
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPT = os.path.join(REPO, "scripts", "adopt_recipe.py")

PLAIN_ROW = {
    "metric": "train_throughput_2048d16L_seq2048_tpu",
    "value": 19000.0,
    "detail": {"batch": 6, "fused_loss": None, "remat_policy": "none",
               "mfu": 0.55},
}


def sweep_row(tok_s, batch=8, policy="dots", fused=4096):
    return {"tok_s": tok_s, "batch": batch, "policy": policy,
            "fused": fused, "remat": True, "mfu": 0.6}


def run_adopt(tmp_path, rows):
    queue = tmp_path / "queue.jsonl"
    queue.write_text("\n".join(json.dumps(r) for r in rows) + "\n")
    out = subprocess.run(
        [sys.executable, SCRIPT, str(queue)],
        capture_output=True, text=True, check=True,
        cwd=str(tmp_path),  # recipe file still lands at REPO root
    )
    return json.loads(out.stdout)


def recipe_path():
    return os.path.join(REPO, "bench_recipe.json")


def cleanup():
    if os.path.exists(recipe_path()):
        os.remove(recipe_path())


def test_single_pass_win_is_not_adopted(tmp_path):
    cleanup()
    try:
        result = run_adopt(tmp_path, [PLAIN_ROW, sweep_row(21000.0)])
        assert "not persistent" in result["adopt"]
        assert not os.path.exists(recipe_path())
    finally:
        cleanup()


def test_two_pass_win_is_adopted_with_floor(tmp_path):
    cleanup()
    try:
        result = run_adopt(
            tmp_path,
            [PLAIN_ROW, sweep_row(21000.0), sweep_row(20500.0)])
        assert result["adopt"] == "recipe written"
        assert result["measured_floor_tok_s"] == 20500.0
        assert result["measured_passes"] == 2
        with open(recipe_path()) as f:
            recipe = json.load(f)
        assert recipe["batch"] == 8
        assert recipe["remat_policy"] == "dots"
    finally:
        cleanup()


def test_regressing_second_pass_blocks_adoption(tmp_path):
    cleanup()
    try:
        result = run_adopt(
            tmp_path,
            [PLAIN_ROW, sweep_row(21000.0), sweep_row(18000.0)])
        assert "not persistent" in result["adopt"]
        assert not os.path.exists(recipe_path())
    finally:
        cleanup()


def test_no_plain_baseline_never_adopts(tmp_path):
    cleanup()
    try:
        result = run_adopt(
            tmp_path, [sweep_row(21000.0), sweep_row(21000.0)])
        assert "no plain baseline" in result["adopt"]
        assert not os.path.exists(recipe_path())
    finally:
        cleanup()


def test_stale_recipe_dropped_when_nothing_persists(tmp_path):
    cleanup()
    try:
        with open(recipe_path(), "w") as f:
            json.dump({"batch": 8}, f)
        result = run_adopt(tmp_path, [PLAIN_ROW, sweep_row(21000.0)])
        assert "not persistent" in result["adopt"]
        assert not os.path.exists(recipe_path())
    finally:
        cleanup()
