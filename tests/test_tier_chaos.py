"""Tier-level chaos: the multi-replica serving tier under real
failures — real engines, real processes, real SIGKILL.

The acceptance scenarios (ISSUE 6 / docs/serving_tier.md):

  - With 3 replicas under sustained load, SIGKILL-ing one replica
    mid-stream causes ZERO failed non-streaming requests — every
    affected request is retried within its deadline on the survivors —
    while the severed stream itself fails LOUDLY (in-band,
    retryable=false), and the router's breaker ejects the dead
    replica. Asserted via the router's /metrics counters.
  - A /drain of a second replica under load completes every in-flight
    request (pending reaches 0 with zero sheds/faults) before the
    replica stops reporting ready-to-exit state, while the router
    bleeds traffic off it.
  - A wedged replica (wire-level stall) is ejected by the health
    breaker and readmitted by the half-open probe once released.

Runs in the isolated fault-injection CI job (these tests kill
subprocesses and stall sockets on purpose); the fast stub-level twin
is tests/test_tier.py.
"""

import json
import os
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.chaos import (
    ChaosProxy,
    LoadGenerator,
    ReplicaProc,
)
from shellac_tpu.inference.server import InferenceServer, make_http_server
from shellac_tpu.inference.tier import (
    TierRouter,
    make_tier_http_server,
)
from shellac_tpu.models import transformer
from shellac_tpu.obs import Registry


def _tiny():
    return get_model_config("tiny").replace(dtype="float32")


def wait_until(cond, timeout=60.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


def _post(url, payload, timeout=60):
    req = urllib.request.Request(
        url, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


class _LocalReplica:
    """In-process replica: a real tiny engine behind a real HTTP
    server, with its own registry so per-replica /metrics stay
    distinct inside one test process."""

    def __init__(self, cfg, params, **srv_kw):
        self.registry = Registry()
        self.srv = InferenceServer(
            cfg, params, registry=self.registry, n_slots=2, max_len=64,
            temperature=0.0, **srv_kw,
        )
        self.httpd = make_http_server(self.srv)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()
        self.srv.close()


@pytest.fixture(scope="module")
def local_trio():
    cfg = _tiny()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    reps = [_LocalReplica(cfg, params) for _ in range(3)]
    # Warm every engine's compile before any chaos clock starts.
    for rep in reps:
        _post(rep.url + "/generate",
              {"tokens": [1, 2, 3], "max_new": 2, "timeout": 300},
              timeout=300)
    yield reps
    for rep in reps:
        rep.close()


def _router_over(urls, **kw):
    kw.setdefault("registry", Registry())
    kw.setdefault("health_interval", 0.1)
    kw.setdefault("backoff_base", 0.02)
    kw.setdefault("default_timeout", 60.0)
    r = TierRouter(list(urls), **kw)
    wait_until(lambda: all(x.state == "healthy" for x in r.replicas),
               timeout=30, msg="replicas healthy")
    return r


class TestDrainUnderLoad:
    def test_drain_completes_in_flight_with_zero_drops(self, local_trio):
        router = _router_over([r.url for r in local_trio])
        httpd = make_tier_http_server(router)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        target = local_trio[1]
        lg = LoadGenerator(base, concurrency=3, timeout=60).start()
        try:
            wait_until(lambda: lg.total >= 6, timeout=60,
                       msg="load warmed up")
            out = router.drain_replica(target.url)
            assert out["state"] == "draining"
            # The drain completes IN-FLIGHT work: pending hits zero
            # while the replica still reports draining (not-ready), so
            # an operator who respects /health drops nothing by
            # stopping it now.
            wait_until(lambda: len(target.srv._pending) == 0,
                       timeout=60, msg="in-flight drained")
            h = _post(target.url + "/drain", {})  # idempotent snapshot
            assert h["status"] == "draining" and h["pending"] == 0
            assert target.srv.shed == 0
            assert target.srv._fatal is None
            # Router has bled traffic off: routed counters for the
            # drained replica freeze while load continues.
            reg = router._registry

            def routed_to_target():
                fam = reg._families.get("shellac_tier_routed_total")
                return sum(
                    int(inst.value)
                    for key, inst in fam.series.items()
                    if key[0] == target.url
                )

            time.sleep(0.5)  # let already-picked attempts settle
            before, total_before = routed_to_target(), lg.total
            wait_until(lambda: lg.total >= total_before + 6,
                       timeout=60, msg="load continued")
            assert routed_to_target() == before
            # Nothing in flight was dropped anywhere: the tally is
            # pure ok.
            counts = lg.stop()
            assert set(counts) == {"ok"}, counts
            # Zero drops asserted on the replica too: every request it
            # ever settled, it settled ok.
            assert target.registry.value(
                "shellac_requests_total", outcome="fault") in (None, 0)
            assert target.registry.value(
                "shellac_requests_total", outcome="shed") in (None, 0)
            # Resume for the next test: traffic returns.
            router.drain_replica(target.url, resume=True)
            wait_until(
                lambda: [x for x in router.replicas
                         if x.url == target.url][0].state == "healthy",
                timeout=30, msg="resume observed")
        finally:
            lg.stop()
            httpd.shutdown()
            router.close()

    def test_draining_replica_rejects_with_retry_after(self, local_trio):
        target = local_trio[2]
        target.srv.drain()
        try:
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(target.url + "/generate",
                      {"tokens": [1], "max_new": 2}, timeout=30)
            assert e.value.code == 503
            ra = e.value.headers.get("Retry-After")
            assert ra is not None and int(ra) >= 1
            assert b"draining" in e.value.read()
        finally:
            target.srv.resume_admission()


class TestWedgedReplica:
    def test_stalled_replica_ejected_then_readmitted(self, local_trio):
        # Route one replica through a wire-level stall: health checks
        # time out, the breaker trips, traffic fails over; releasing
        # the stall lets the half-open probe readmit it.
        victim = local_trio[0]
        survivor = local_trio[1]
        proxy = ChaosProxy("127.0.0.1", victim.url.rsplit(":", 1)[1])
        router = _router_over(
            [proxy.url, survivor.url],
            health_timeout=0.5, breaker_cooldown=0.5,
        )
        httpd = make_tier_http_server(router)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            proxy.stall()
            wait_until(
                lambda: [x for x in router.replicas
                         if x.url == proxy.url][0].state == "ejected",
                timeout=30, msg="wedged replica ejected")
            # Tier keeps serving from the survivor.
            for i in range(4):
                out = _post(base + "/generate",
                            {"tokens": [i + 1], "max_new": 2,
                             "timeout": 60})
                assert out["tokens"]
            reg = router._registry
            assert reg.value("shellac_tier_ejections_total",
                             replica=proxy.url) >= 1
            proxy.release_stalls()
            proxy.pass_through()
            wait_until(
                lambda: [x for x in router.replicas
                         if x.url == proxy.url][0].state == "healthy",
                timeout=30, msg="readmission")
            assert reg.value("shellac_tier_readmissions_total",
                             replica=proxy.url) >= 1
        finally:
            proxy.release_stalls()
            httpd.shutdown()
            router.close()
            proxy.close()


class TestKillReplicaAcceptance:
    """The ISSUE acceptance scenario, end to end with real processes:
    3 CLI-served replicas, sustained load, SIGKILL one mid-stream,
    then drain a second under the same load."""

    @pytest.fixture(scope="class")
    def config_path(self, tmp_path_factory):
        p = tmp_path_factory.mktemp("tier") / "tiny_f32.json"
        p.write_text(json.dumps({"preset": "tiny", "dtype": "float32"}))
        return str(p)

    def test_sigkill_mid_stream_zero_failed_requests_then_drain(
            self, config_path):
        procs = [
            ReplicaProc(config_path=config_path, seed=i, slots=4,
                        max_len=96)
            for i in range(3)
        ]
        router = None
        httpd = None
        lg = None
        try:
            for p in procs:
                p.wait_ready(timeout=180)
            # Warm each engine's compile directly, outside any clock.
            for p in procs:
                _post(p.url + "/generate",
                      {"tokens": [1, 2, 3], "max_new": 2,
                       "timeout": 300}, timeout=300)
            registry = Registry()
            router = TierRouter(
                [p.url for p in procs], registry=registry,
                health_interval=0.2, health_timeout=2.0,
                breaker_cooldown=2.0, backoff_base=0.05,
                default_timeout=30.0,
                # Pin affinity hard so the chosen session's stream
                # lands on the victim deterministically.
                affinity_tolerance=100.0,
            )
            wait_until(lambda: all(x.state == "healthy"
                                   for x in router.replicas),
                       timeout=60, msg="all replicas healthy")
            httpd = make_tier_http_server(router)
            threading.Thread(target=httpd.serve_forever,
                             daemon=True).start()
            base = f"http://127.0.0.1:{httpd.server_address[1]}"

            victim = procs[0]

            # Session keys that rendezvous-hash onto chosen replicas:
            # one load worker pinned per replica (so the kill lands on
            # traffic actually in flight there), plus the stream's key
            # on the victim.
            def session_for(url):
                return next(
                    f"k{i}" for i in range(1000)
                    if max((p.url for p in procs),
                           key=lambda u: TierRouter._rendezvous(
                               f"s:k{i}", u.rstrip("/"))) == url
                )

            session = session_for(victim.url)
            lg = LoadGenerator(
                base, concurrency=4, timeout=30,
                payloads=[
                    {"tokens": [1 + i, 2, 3], "max_new": 6,
                     "session": session_for(p.url)}
                    for i, p in enumerate(procs)
                ],
            ).start()
            wait_until(lambda: lg.total >= 8, timeout=120,
                       msg="sustained load flowing")

            # --- kill mid-stream -------------------------------------
            stream_lines = []
            first_delta = threading.Event()
            stream_done = threading.Event()

            def stream_client():
                req = urllib.request.Request(
                    base + "/generate",
                    data=json.dumps({
                        "tokens": [5, 6, 7], "max_new": 80,
                        "stream": True, "session": session,
                        "timeout": 60,
                    }).encode(),
                    headers={"Content-Type": "application/json"},
                )
                try:
                    with urllib.request.urlopen(req, timeout=90) as r:
                        for raw in r:
                            if raw.strip():
                                stream_lines.append(json.loads(raw))
                                first_delta.set()
                except OSError:
                    pass  # severed sockets are acceptable shapes too
                finally:
                    first_delta.set()
                    stream_done.set()

            t = threading.Thread(target=stream_client, daemon=True)
            t.start()
            assert first_delta.wait(90), "stream never started"
            assert not stream_done.is_set() or stream_lines, \
                "stream ended before the kill could land"
            victim.kill()  # SIGKILL: no drain, no goodbye
            assert stream_done.wait(120), "stream never terminated"
            # The severed stream fails LOUDLY: no done record, and
            # when the relay could still write, an in-band
            # non-retryable error.
            assert not any(l.get("done") for l in stream_lines), \
                stream_lines
            errs = [l for l in stream_lines if "error" in l]
            if errs:
                assert errs[-1]["error"]["retryable"] is False

            # Health breaker ejects the dead replica.
            wait_until(
                lambda: [x for x in router.replicas
                         if x.url == victim.url][0].state == "ejected",
                timeout=30, msg="dead replica ejected")

            # Load keeps flowing on the survivors.
            settled = lg.total
            wait_until(lambda: lg.total >= settled + 8, timeout=120,
                       msg="load flowing on survivors")

            # --- drain a second replica under the same load ----------
            drained = procs[1]
            out = router.drain_replica(drained.url)
            assert out["state"] == "draining"

            def drained_health():
                try:
                    with urllib.request.urlopen(
                            drained.url + "/health", timeout=5) as r:
                        return None
                except urllib.error.HTTPError as e:
                    return json.loads(e.read())

            # Every in-flight request completes (pending -> 0) while
            # the replica still reports not-ready ("draining").
            wait_until(
                lambda: (lambda h: h is not None
                         and h["status"] == "draining"
                         and h["pending"] == 0)(drained_health()),
                timeout=90, msg="drain completed in-flight work")
            h = drained_health()
            assert h["shed"] == 0, h

            # Router bled traffic off: routed counters for the drained
            # replica freeze while load continues.
            def routed_to(url):
                fam = registry._families.get("shellac_tier_routed_total")
                return sum(int(inst.value)
                           for key, inst in fam.series.items()
                           if key[0] == url)

            time.sleep(0.5)  # let already-picked attempts settle
            before, total_before = routed_to(drained.url), lg.total
            wait_until(lambda: lg.total >= total_before + 6,
                       timeout=120, msg="load continued post-drain")
            assert routed_to(drained.url) == before

            counts = lg.stop()
            lg = None
            # THE acceptance bar: zero failed non-streaming requests —
            # every request the kill or the drain touched was retried
            # within its deadline on a surviving replica.
            assert set(counts) == {"ok"}, counts

            # And the same, asserted via the router's /metrics.
            text = router.metrics_text()
            assert 'shellac_tier_requests_total{outcome="ok"}' in text
            for bad in ('outcome="failed"', 'outcome="deadline"',
                        'outcome="rejected"'):
                assert bad not in text, text
            assert registry.value("shellac_tier_ejections_total",
                                  replica=victim.url) >= 1
            retries = sum(
                int(i.value) for i in registry._families[
                    "shellac_tier_retries_total"].series.values()
            )
            assert retries >= 1
        finally:
            if lg is not None:
                lg.stop()
            if httpd is not None:
                httpd.shutdown()
            if router is not None:
                router.close()
            for p in procs:
                p.terminate()


class TestFederationUnderChaos:
    """ISSUE 11's federation chaos scenario: SIGKILL a replica while
    the tier is scraping it every poll — its federated series must
    keep serving last-known-good with a staleness stamp, and a
    revival on the SAME port must readmit it with FRESH (reset)
    series replacing the LKG."""

    @pytest.fixture(scope="class")
    def config_path(self, tmp_path_factory):
        p = tmp_path_factory.mktemp("fedchaos") / "tiny_f32.json"
        p.write_text(json.dumps({"preset": "tiny", "dtype": "float32"}))
        return str(p)

    def test_sigkill_keeps_lkg_then_fresh_series_on_revival(
            self, config_path):
        from shellac_tpu.obs import parse_prometheus_text

        procs = [
            ReplicaProc(config_path=config_path, seed=i, slots=2,
                        max_len=96)
            for i in range(2)
        ]
        router = None
        revived = None
        try:
            for p in procs:
                p.wait_ready(timeout=180)
            for p in procs:
                _post(p.url + "/generate",
                      {"tokens": [1, 2, 3], "max_new": 2,
                       "timeout": 300}, timeout=300)
            router = TierRouter(
                [p.url for p in procs], registry=Registry(),
                health_interval=0.2, health_timeout=2.0,
                breaker_cooldown=1.0, default_timeout=60.0,
                stale_after=1.0,
            )
            wait_until(lambda: all(x.state == "healthy"
                                   for x in router.replicas),
                       timeout=60, msg="replicas healthy")
            victim = procs[1]
            port = victim.url.rsplit(":", 1)[1]

            # Traffic through the router so the victim's counters are
            # non-trivial, then wait for its series to federate.
            for i in range(4):
                status, body, _ = router.forward_json(
                    "/generate", {"tokens": [1 + i, 2], "max_new": 2,
                                  "timeout": 60})
                assert status == 200, body

            def fed_ok(url):
                p = parse_prometheus_text(router.metrics_text())
                return p.value("shellac_requests_total",
                               replica=url, outcome="ok")

            wait_until(lambda: (fed_ok(victim.url) or 0) >= 1,
                       timeout=30, msg="victim series federated")
            lkg_ok = fed_ok(victim.url)

            victim.kill()  # SIGKILL mid-scrape: no drain, no goodbye
            wait_until(
                lambda: [x for x in router.replicas
                         if x.url == victim.url][0].state == "ejected",
                timeout=30, msg="dead replica ejected")
            wait_until(
                lambda: parse_prometheus_text(router.metrics_text())
                .value("shellac_fleet_scrape_stale",
                       replica=victim.url) == 1,
                timeout=30, msg="staleness stamped")
            parsed = parse_prometheus_text(router.metrics_text())
            # Last-known-good: the dead replica's FINAL numbers stay
            # visible on the tier's exposition.
            assert fed_ok(victim.url) == lkg_ok
            assert parsed.value("shellac_fleet_scrape_age_seconds",
                                replica=victim.url) > 0

            # Revive on the SAME port (argparse: last --port wins):
            # a restarted process with reset counters.
            revived = ReplicaProc(config_path=config_path, seed=7,
                                  slots=2, max_len=96,
                                  extra_args=["--port", port])
            revived.wait_ready(timeout=180)
            wait_until(
                lambda: [x for x in router.replicas
                         if x.url == victim.url][0].state == "healthy",
                timeout=60, msg="revived replica readmitted")
            # Readmission resumes FRESH series: the reset counters
            # replace the LKG snapshot (no requests settled yet, so
            # the ok series is absent or below the LKG value).
            wait_until(
                lambda: (fed_ok(victim.url) or 0) < lkg_ok,
                timeout=30, msg="fresh series replaced LKG")
            wait_until(
                lambda: parse_prometheus_text(router.metrics_text())
                .value("shellac_fleet_scrape_stale",
                       replica=victim.url) == 0,
                timeout=30, msg="staleness cleared")
        finally:
            if router is not None:
                router.close()
            for p in procs:
                p.terminate()
            if revived is not None:
                revived.terminate()


# The subprocess scenario needs a POSIX SIGKILL; everything above it
# runs anywhere the stdlib HTTP stack does.
pytestmark = pytest.mark.skipif(
    os.name != "posix", reason="chaos harness needs POSIX signals"
)
