"""Race detection: the native loader under ThreadSanitizer.

Builds dataloader.cpp + the stress driver with -fsanitize=thread and
runs shutdown-heavy producer/consumer cycles. Any data race, lock-order
inversion, or use-after-free in the C++ loader shows up as a TSan
report on stderr and fails the test.
"""

import os
import shutil
import subprocess

import numpy as np
import pytest

from shellac_tpu.training.data import write_token_shard

_CSRC = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
    "shellac_tpu", "runtime", "csrc",
)
_CXX = os.environ.get("CXX", "g++")


def _tsan_toolchain_works(tmp_path) -> bool:
    """Probe with a trivial TSan compile, so a broken dataloader.cpp
    FAILS the test while a toolchain without -fsanitize=thread skips."""
    probe_src = tmp_path / "probe.cpp"
    probe_src.write_text("int main() { return 0; }\n")
    proc = subprocess.run(
        [_CXX, "-fsanitize=thread", "-pthread", str(probe_src),
         "-o", str(tmp_path / "probe")],
        capture_output=True, text=True,
    )
    return proc.returncode == 0


def _build_stress(tmp_path):
    if not _tsan_toolchain_works(tmp_path):
        pytest.skip("toolchain lacks -fsanitize=thread")
    binary = str(tmp_path / "stress_loader")
    cmd = [
        _CXX, "-fsanitize=thread", "-O1", "-g", "-std=c++17", "-pthread",
        os.path.join(_CSRC, "dataloader.cpp"),
        os.path.join(_CSRC, "stress_loader.cpp"),
        "-o", binary,
    ]
    proc = subprocess.run(cmd, capture_output=True, text=True)
    assert proc.returncode == 0, f"stress build failed:\n{proc.stderr[:2000]}"
    return binary


@pytest.mark.skipif(shutil.which(_CXX) is None, reason="no C++ toolchain")
def test_loader_race_free_under_tsan(tmp_path):
    binary = _build_stress(tmp_path)
    shards = []
    rng = np.random.default_rng(0)
    for i in range(2):
        p = str(tmp_path / f"s{i}.bin")
        write_token_shard(p, rng.integers(0, 1000, 5000).astype(np.int32))
        shards.append(p)

    proc = subprocess.run(
        [binary, *shards],
        capture_output=True, text=True, timeout=300,
        env={**os.environ, "TSAN_OPTIONS": "halt_on_error=0 exitcode=66"},
    )
    assert "WARNING: ThreadSanitizer" not in proc.stderr, proc.stderr[:3000]
    assert proc.returncode == 0, (proc.returncode, proc.stderr[:1000])
    assert "stress ok" in proc.stdout
