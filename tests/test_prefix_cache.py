"""Automatic prefix caching over the paged block pool.

Invariants:
  - caching is invisible to the math: greedy output for every request
    is bit-identical to the single-request Engine, whether its prefix
    was computed or reused, shared blocks live or released;
  - full prompt blocks persist after release and later prompts attach
    the longest chain (stats prove blocks were actually reused);
  - refcounted sharing: concurrent requests on the same prefix never
    rewrite a shared block;
  - LRU eviction reclaims unreferenced cached blocks when the free
    list runs dry, and evicted content simply misses (recompute, same
    output).
"""

import jax.numpy as jnp
import numpy as np
import pytest

import jax
from shellac_tpu import get_model_config
from shellac_tpu.inference.batching import PagedBatchingEngine
from shellac_tpu.inference.engine import Engine
from shellac_tpu.models import transformer


def _tiny(**kw):
    return get_model_config("tiny").replace(dtype="float32", **kw)


@pytest.fixture(scope="module")
def setup():
    cfg = _tiny()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _ref(cfg, params, tokens, max_new):
    eng = Engine(cfg, params, temperature=0.0)
    out = eng.generate(
        jnp.asarray(np.asarray(tokens, np.int32)[None]), max_new_tokens=max_new
    )
    return np.asarray(out.tokens)[0].tolist()


def _engine(cfg, params, **kw):
    kw.setdefault("n_slots", 4)
    kw.setdefault("max_len", 128)
    kw.setdefault("block_size", 8)
    kw.setdefault("prefix_cache", True)
    return PagedBatchingEngine(cfg, params, temperature=0.0, **kw)


def _prompts(shared_len=40, n=4, tail=6, seed=3):
    """Prompts sharing a long common prefix with distinct tails."""
    rng = np.random.default_rng(seed)
    shared = rng.integers(0, 256, size=shared_len)
    return [
        np.concatenate([shared, rng.integers(0, 256, size=tail)]).astype(
            np.int32
        )
        for _ in range(n)
    ]


class TestPrefixReuse:
    def test_sequential_same_prompt_bit_match(self, setup):
        """Second submission of a prompt hits the cache and still
        matches the single-request engine exactly."""
        cfg, params = setup
        eng = _engine(cfg, params)
        prompt = _prompts(n=1)[0]
        want = _ref(cfg, params, prompt, 12)
        r1 = eng.run([("a", prompt, 12)])
        assert eng.stats["prefix_hit_tokens"] == 0
        r2 = eng.run([("b", prompt, 12)])
        # Full blocks minus the last (>=1 computed token rule): the
        # prompt has 46 tokens, bs=8 -> 5 full blocks, all matchable.
        assert eng.stats["prefix_hit_tokens"] == 40
        assert r1["a"] == want
        assert r2["b"] == want

    def test_shared_prefix_across_tails(self, setup):
        """Different tails on one system prefix: all bit-match, later
        requests reuse the shared chain."""
        cfg, params = setup
        eng = _engine(cfg, params)
        prompts = _prompts(shared_len=40, n=4)
        for i, p in enumerate(prompts):
            got = eng.run([(i, p, 10)])[i]
            assert got == _ref(cfg, params, p, 10), f"prompt {i}"
        # Requests 1..3 each matched the 40-token shared chain.
        assert eng.stats["prefix_hit_tokens"] == 3 * 40

    def test_concurrent_shared_prefix(self, setup):
        """All requests in flight at once: shared blocks are attached
        read-only to several slots simultaneously."""
        cfg, params = setup
        eng = _engine(cfg, params)
        prompts = _prompts(shared_len=32, n=4)
        # Warm the cache so the concurrent batch all hits.
        eng.run([("warm", prompts[0], 4)])
        results = eng.run([(i, p, 10) for i, p in enumerate(prompts)])
        for i, p in enumerate(prompts):
            assert results[i] == _ref(cfg, params, p, 10), f"prompt {i}"
        assert eng.stats["prefix_hit_tokens"] >= 4 * 32

    def test_exact_multiple_of_block_size(self, setup):
        """Prompt length a multiple of bs: the last full block is NOT
        matched (one token must be computed for its logits)."""
        cfg, params = setup
        eng = _engine(cfg, params)
        prompt = _prompts(shared_len=32, n=1, tail=0)[0]
        assert prompt.size == 32
        want = _ref(cfg, params, prompt, 8)
        assert eng.run([("a", prompt, 8)])["a"] == want
        assert eng.run([("b", prompt, 8)])["b"] == want
        # 4 full blocks, cap at 3: 24 tokens reused, 8 computed.
        assert eng.stats["prefix_hit_tokens"] == 24

    def test_short_prompt_never_matches(self, setup):
        """Prompts shorter than bs+1 can't reuse (no full block leaves
        a computable token) but must still work."""
        cfg, params = setup
        eng = _engine(cfg, params)
        prompt = np.arange(5, dtype=np.int32) + 1
        want = _ref(cfg, params, prompt, 6)
        assert eng.run([("a", prompt, 6)])["a"] == want
        assert eng.run([("b", prompt, 6)])["b"] == want
        assert eng.stats["prefix_hit_tokens"] == 0

    def test_disabled_by_default(self, setup):
        cfg, params = setup
        eng = PagedBatchingEngine(
            cfg, params, temperature=0.0, n_slots=2, max_len=64,
            block_size=8,
        )
        prompt = _prompts(n=1)[0]
        eng.run([("a", prompt, 4)])
        eng.run([("b", prompt, 4)])
        assert "prefix_hit_tokens" not in eng.stats


class TestBlockAccounting:
    def test_release_keeps_cached_blocks_pooled(self, setup):
        """After drain, every block is either free or cached with
        refcount 0; the pool never leaks."""
        cfg, params = setup
        eng = _engine(cfg, params)
        prompts = _prompts(shared_len=24, n=3)
        eng.run([(i, p, 6) for i, p in enumerate(prompts)])
        n_blocks = eng._cache.k.shape[1]
        cached = set(eng._hash_to_block.values())
        assert all(r == 0 for r in eng._block_ref.values())
        assert len(set(eng._free) | cached) == n_blocks - 1  # minus scratch
        assert not (set(eng._free) & cached)

    def test_eviction_reclaims_lru(self, setup):
        """A pool too small to cache everything evicts LRU chains; old
        prompts then miss but still produce exact output."""
        cfg, params = setup
        # Tiny pool: 2 slots' worth of tokens.
        eng = _engine(cfg, params, n_slots=2, max_len=64,
                      pool_tokens=128)
        rng = np.random.default_rng(0)
        prompts = [rng.integers(0, 256, size=33).astype(np.int32)
                   for _ in range(6)]
        for i, p in enumerate(prompts):
            assert eng.run([(i, p, 6)])[i] == _ref(cfg, params, p, 6), i
        assert eng.stats["prefix_evictions"] > 0
        # The first prompt's chain was evicted: re-running it misses
        # (no new hits) yet still matches.
        hits = eng.stats["prefix_hit_tokens"]
        assert eng.run([("re", prompts[0], 6)])["re"] == _ref(
            cfg, params, prompts[0], 6
        )
        assert eng.stats["prefix_hit_tokens"] == hits

    def test_deep_hit_near_max_len(self, setup):
        """Suffix pad must not run past the block table: a 120-token
        cached prefix of a 126-token prompt at max_len=128 once wrote
        padded positions through gather-clamp onto the last real
        block, corrupting live suffix KV."""
        cfg, params = setup
        rng = np.random.default_rng(7)
        base = rng.integers(0, 256, size=121).astype(np.int32)
        long = np.concatenate(
            [base[:120], rng.integers(0, 256, size=6)]
        ).astype(np.int32)
        eng = _engine(cfg, params, n_slots=2, max_len=128)
        assert eng.run([("w", base, 1)])["w"] == _ref(cfg, params, base, 1)
        hits = eng.stats["prefix_hit_tokens"]
        got = eng.run([("x", long, 1)])["x"]
        assert eng.stats["prefix_hit_tokens"] - hits == 120
        assert got == _ref(cfg, params, long, 1)

    def test_pool_exhaustion_requeues_with_prefix(self, setup):
        """Admission rolls back a matched prefix cleanly when the pool
        can't cover the rest, and the request completes later."""
        cfg, params = setup
        eng = _engine(cfg, params, n_slots=2, max_len=64, pool_tokens=96)
        rng = np.random.default_rng(1)
        shared = rng.integers(0, 256, size=24)
        prompts = [
            np.concatenate([shared, rng.integers(0, 256, size=4)]).astype(
                np.int32
            )
            for _ in range(4)
        ]
        results = eng.run([(i, p, 24) for i, p in enumerate(prompts)])
        for i, p in enumerate(prompts):
            assert results[i] == _ref(cfg, params, p, 24), f"prompt {i}"


class TestPrefixVariants:
    def test_gqa_model(self):
        cfg = get_model_config("tiny-gqa").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = _engine(cfg, params)
        prompt = _prompts(n=1)[0]
        want = _ref(cfg, params, prompt, 8)
        assert eng.run([("a", prompt, 8)])["a"] == want
        assert eng.run([("b", prompt, 8)])["b"] == want
        assert eng.stats["prefix_hit_tokens"] > 0

    def test_windowed_model(self):
        cfg = _tiny(attn_window=16)
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = _engine(cfg, params)
        prompt = _prompts(n=1)[0]
        want = _ref(cfg, params, prompt, 8)
        assert eng.run([("a", prompt, 8)])["a"] == want
        assert eng.run([("b", prompt, 8)])["b"] == want
        assert eng.stats["prefix_hit_tokens"] > 0
