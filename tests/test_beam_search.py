"""Beam search on the single-request Engine.

The correctness bar is an exact reference: a host-side beam loop over
the full (uncached) forward must produce the same sequences and scores
as the device implementation (cached forward + flat top-k + cache-row
reordering inside a lax.scan).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.engine import Engine
from shellac_tpu.models import transformer


def _cfg(**kw):
    return get_model_config("tiny").replace(dtype="float32", **kw)


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return cfg, transformer.init_params(cfg, jax.random.PRNGKey(0))


def _ref_beam(cfg, params, prompt, k, steps, eos_id=None,
              length_penalty=1.0):
    """Host beam search over the full forward (no cache): the oracle."""
    beams = [(list(map(int, prompt)), 0.0, False)]  # (tokens, score, done)
    neg = -1e30
    for _ in range(steps):
        cand = []
        for toks, score, done in beams:
            if done:
                cand.append((toks, score, True, None))
                continue
            logits = transformer.forward(
                cfg, params, jnp.asarray([toks], jnp.int32)
            )[0, -1]
            lp = np.asarray(jax.nn.log_softmax(logits.astype(jnp.float32)))
            for t in np.argsort(-lp)[: 2 * k]:
                cand.append((toks, score + float(lp[t]), False, int(t)))
        cand.sort(key=lambda c: c[1], reverse=True)
        new = []
        for toks, score, done, t in cand[:k] if len(beams) > 1 else cand:
            if len(new) == k:
                break
            if done:
                new.append((toks, score, True))
            else:
                nt = toks + [t]
                new.append((nt, score,
                            eos_id is not None and t == eos_id))
        beams = new
        if all(d for _, _, d in beams):
            break
    out = []
    plen = len(prompt)
    for toks, score, _ in beams:
        gen = toks[plen:]
        out.append((gen, score / (len(gen) ** length_penalty)))
    out.sort(key=lambda c: c[1], reverse=True)
    return out


class TestBeamSearch:
    def test_matches_reference(self, model):
        cfg, params = model
        eng = Engine(cfg, params, temperature=0.0, max_len=64)
        prompt = [7, 23, 5]
        k, steps = 3, 5
        got_seqs, got_scores = eng.beam_search(
            prompt, num_beams=k, max_new_tokens=steps, length_penalty=1.0
        )
        ref = _ref_beam(cfg, params, prompt, k, steps)
        # The TOP beam must match exactly (lower beams can differ by
        # tie-breaks between equal-score candidates).
        assert got_seqs[0] == ref[0][0], (got_seqs[0], ref[0][0])
        np.testing.assert_allclose(got_scores[0], ref[0][1], rtol=1e-4)
        # Scores must be sorted best-first.
        assert got_scores == sorted(got_scores, reverse=True)

    def test_beam1_equals_greedy(self, model):
        cfg, params = model
        eng = Engine(cfg, params, temperature=0.0, max_len=64)
        prompt = jnp.asarray([[3, 9, 17]], jnp.int32)
        greedy = np.asarray(
            eng.generate(prompt, max_new_tokens=6).tokens
        )[0].tolist()
        seqs, _ = eng.beam_search([3, 9, 17], num_beams=1,
                                  max_new_tokens=6)
        assert seqs[0] == greedy

    def test_eos_finishes_and_freezes(self, model):
        """Declare the model's own top first token to be EOS: that beam
        finishes at length 1, and with raw-sum scoring
        (length_penalty=0) no longer sequence can beat it — every
        continuation only ADDS negative log-probs to a start that was
        already <= the best single step."""
        cfg, params = model
        eng = Engine(cfg, params, temperature=0.0, max_len=64)
        prompt = [1, 2]
        greedy = np.asarray(
            eng.generate(jnp.asarray([prompt], jnp.int32),
                         max_new_tokens=1).tokens
        )[0, 0]
        eos = int(greedy)
        seqs, scores = eng.beam_search(
            prompt, num_beams=3, max_new_tokens=8, eos_id=eos,
            length_penalty=0.0,
        )
        assert seqs[0] == [eos]
        # The frozen beam's score is exactly the single-step logprob —
        # it must not have accumulated anything while frozen.
        logits = transformer.forward(
            cfg, params, jnp.asarray([prompt], jnp.int32)
        )[0, -1]
        lp0 = float(jax.nn.log_softmax(logits.astype(jnp.float32))[eos])
        np.testing.assert_allclose(scores[0], lp0, rtol=1e-4)

    def test_length_penalty_changes_ranking(self, model):
        cfg, params = model
        eng = Engine(cfg, params, temperature=0.0, max_len=64)
        raw_seqs, raw = eng.beam_search([4, 8], num_beams=4,
                                        max_new_tokens=6,
                                        length_penalty=0.0)
        mean_seqs, mean = eng.beam_search([4, 8], num_beams=4,
                                          max_new_tokens=6,
                                          length_penalty=1.0)
        # Same candidate set; alpha=1 divides by length (all beams run
        # the full budget without EOS, so scores scale by 1/6).
        np.testing.assert_allclose(
            sorted(np.asarray(raw) / 6.0), sorted(mean), rtol=1e-5
        )

    def test_paged_matches_dense_bit_exact(self, model):
        """CoW paged beams vs the dense-cache beam: identical
        sequences AND scores — the block-table gather + partial-tail
        copy is invisible to the math."""
        from shellac_tpu.inference.batching import PagedBatchingEngine

        cfg, params = model
        dense = Engine(cfg, params, temperature=0.0, max_len=64)
        paged = PagedBatchingEngine(cfg, params, n_slots=2, max_len=64,
                                    block_size=4, temperature=0.0)
        for prompt, k, steps, eos, alpha in (
            ([7, 23, 5], 3, 5, None, 1.0),        # partial prompt tail
            ([7, 23, 5, 9], 4, 9, None, 0.0),     # block-aligned prompt
            ([1, 2], 3, 12, None, 1.0),           # multi-crossing run
            ([4, 8, 15, 16, 23], 2, 1, None, 1.0),  # no decode writes
        ):
            want = dense.beam_search(prompt, num_beams=k,
                                     max_new_tokens=steps, eos_id=eos,
                                     length_penalty=alpha)
            got = paged.beam_search(prompt, num_beams=k,
                                    max_new_tokens=steps, eos_id=eos,
                                    length_penalty=alpha)
            assert got[0] == want[0], (prompt, k, steps)
            np.testing.assert_allclose(got[1], want[1], rtol=1e-5)

    def test_paged_eos_freeze_matches_dense(self, model):
        from shellac_tpu.inference.batching import PagedBatchingEngine

        cfg, params = model
        dense = Engine(cfg, params, temperature=0.0, max_len=64)
        paged = PagedBatchingEngine(cfg, params, n_slots=2, max_len=64,
                                    block_size=4, temperature=0.0)
        prompt = [1, 2]
        greedy = np.asarray(
            dense.generate(jnp.asarray([prompt], jnp.int32),
                           max_new_tokens=1).tokens
        )[0, 0]
        eos = int(greedy)
        want = dense.beam_search(prompt, num_beams=3, max_new_tokens=8,
                                 eos_id=eos, length_penalty=0.0)
        got = paged.beam_search(prompt, num_beams=3, max_new_tokens=8,
                                eos_id=eos, length_penalty=0.0)
        assert got[0] == want[0]
        np.testing.assert_allclose(got[1], want[1], rtol=1e-5)

    def test_paged_beam_churn_through_allocator(self, model):
        """Beam searches interleaved with live paged requests: the
        borrowed blocks come from (and return to) the same pool the
        slots use, block accounting balances, and neither the beams
        nor the requests' greedy outputs move."""
        from shellac_tpu.inference.batching import PagedBatchingEngine

        cfg, params = model
        dense = Engine(cfg, params, temperature=0.0, max_len=64)
        eng = PagedBatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  block_size=4, temperature=0.0,
                                  prefix_cache=True)
        rng = np.random.default_rng(11)
        reqs = [(i, rng.integers(1, cfg.vocab_size, size=5 + i).tolist(), 6)
                for i in range(4)]
        ref_engine = PagedBatchingEngine(cfg, params, n_slots=2,
                                         max_len=64, block_size=4,
                                         temperature=0.0)
        want_reqs = ref_engine.run(reqs)
        want_beam = dense.beam_search([7, 23, 5], num_beams=3,
                                      max_new_tokens=5)

        for rid, toks, n in reqs:
            eng.submit(rid, toks, n)
        got_reqs = {}
        beams = []
        free_before = len(eng._free) + eng._evictable()
        while eng.pending:
            for rid, out in eng.step():
                got_reqs[rid] = out
            # A beam search between engine steps — mid-churn.
            beams.append(eng.beam_search([7, 23, 5], num_beams=3,
                                         max_new_tokens=5))
        assert got_reqs == want_reqs
        for got_beam in beams:
            assert got_beam[0] == want_beam[0]
            np.testing.assert_allclose(got_beam[1], want_beam[1],
                                       rtol=1e-5)
        # Everything borrowed came back (slots freed theirs on finish).
        assert len(eng._free) + eng._evictable() == free_before

    def test_paged_beam_reuses_prefix_cache(self, model):
        """A beam prompt sharing a cached prefix attaches the cached
        blocks read-only and computes only the suffix — beams stay
        bit-identical to the dense beam, prefix_hit_tokens counts the
        reuse, and the cached blocks' refcounts are restored."""
        from shellac_tpu.inference.batching import PagedBatchingEngine

        cfg, params = model
        dense = Engine(cfg, params, temperature=0.0, max_len=64)
        eng = PagedBatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  block_size=4, pool_tokens=1024,
                                  temperature=0.0, prefix_cache=True)
        rng = np.random.default_rng(21)
        prefix = rng.integers(1, cfg.vocab_size, size=12).tolist()
        # Seed the cache: one request whose prompt IS the prefix
        # (plus a tail so full blocks register).
        eng.run([("seed", prefix + [5, 7], 4)])
        assert eng._hash_to_block, "prefix blocks should be registered"
        refs_before = dict(eng._block_ref)

        prompt = prefix + [9, 11, 13]
        hits0 = eng.stats["prefix_hit_tokens"]
        want = dense.beam_search(prompt, num_beams=3, max_new_tokens=6)
        got = eng.beam_search(prompt, num_beams=3, max_new_tokens=6)
        assert got[0] == want[0]
        np.testing.assert_allclose(got[1], want[1], rtol=1e-5)
        assert eng.stats["prefix_hit_tokens"] - hits0 >= 12 // 4 * 4
        assert eng._block_ref == refs_before  # attach fully released

    def test_paged_beam_prompt_fills_whole_table(self, model):
        """Prompt long enough that its pad bucket exceeds max_len AND
        its blocks fill the whole table row: unclamped pad writes
        would gather-clamp onto the last real block and corrupt
        just-written prompt KV (the pad cap guards this)."""
        from shellac_tpu.inference.batching import PagedBatchingEngine

        cfg, params = model
        dense = Engine(cfg, params, temperature=0.0, max_len=96)
        paged = PagedBatchingEngine(cfg, params, n_slots=2, max_len=96,
                                    block_size=4, pool_tokens=2048,
                                    temperature=0.0)
        rng = np.random.default_rng(31)
        prompt = rng.integers(1, cfg.vocab_size, size=93).tolist()
        want = dense.beam_search(prompt, num_beams=2, max_new_tokens=2)
        got = paged.beam_search(prompt, num_beams=2, max_new_tokens=2)
        assert got[0] == want[0]
        np.testing.assert_allclose(got[1], want[1], rtol=1e-5)

    def test_paged_beam_pool_exhaustion_is_loud(self, model):
        from shellac_tpu.inference.batching import PagedBatchingEngine

        cfg, params = model
        eng = PagedBatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  block_size=4, pool_tokens=32,
                                  temperature=0.0)
        with pytest.raises(RuntimeError, match="pool exhausted"):
            eng.beam_search(list(range(1, 9)), num_beams=8,
                            max_new_tokens=32)

    def test_paged_mla_matches_dense_mla_beam(self):
        """MLA latent-row pools compose: the CoW copy moves latent
        blocks like any value block (v pool is zero-width), so paged
        MLA beams equal the dense MLA beam exactly."""
        from shellac_tpu.inference.batching import PagedBatchingEngine

        mcfg = get_model_config("tiny-mla").replace(dtype="float32")
        params = transformer.init_params(mcfg, jax.random.PRNGKey(0))
        dense = Engine(mcfg, params, temperature=0.0, max_len=64)
        paged = PagedBatchingEngine(mcfg, params, n_slots=2, max_len=64,
                                    block_size=4, pool_tokens=1024,
                                    temperature=0.0)
        for prompt, k, steps in (([3, 5, 7], 3, 9), ([1, 2], 2, 12)):
            want = dense.beam_search(prompt, num_beams=k,
                                     max_new_tokens=steps)
            got = paged.beam_search(prompt, num_beams=k,
                                    max_new_tokens=steps)
            assert got[0] == want[0], (prompt, k, steps)
            np.testing.assert_allclose(got[1], want[1], rtol=1e-5)

    def test_paged_int8_matches_dense_int8_beam(self, model):
        """int8 pools compose: the CoW copy moves the scale pools in
        lockstep with the value pools, so paged int8 beams equal the
        dense int8-cache beam exactly."""
        from shellac_tpu.inference.batching import PagedBatchingEngine

        cfg, params = model
        dense = Engine(cfg, params, temperature=0.0, max_len=64,
                       kv_quant="int8")
        paged = PagedBatchingEngine(cfg, params, n_slots=2, max_len=64,
                                    block_size=32, kv_quant="int8",
                                    pool_tokens=1024, temperature=0.0)
        for prompt, k, steps in (
            ([7, 23, 5], 3, 6),        # within one block
            ([1, 2], 2, 34),           # crosses a block boundary
        ):
            want = dense.beam_search(prompt, num_beams=k,
                                     max_new_tokens=steps)
            got = paged.beam_search(prompt, num_beams=k,
                                    max_new_tokens=steps)
            assert got[0] == want[0], (prompt, k, steps)
            np.testing.assert_allclose(got[1], want[1], rtol=1e-5)

    def test_int8_cache_composes(self, model):
        """Beam search over the int8 cache: correct shape/ordering and
        a top score within the int8 rounding envelope of bf16 (near-tie
        beams may legitimately swap — cache rounding shifts scores by
        ~1e-2 on this model, so sequence equality is NOT the contract)."""
        cfg, params = model
        a, sa = Engine(cfg, params, temperature=0.0,
                       max_len=64).beam_search([6, 6, 2], num_beams=3,
                                               max_new_tokens=5)
        b, sb = Engine(cfg, params, temperature=0.0, max_len=64,
                       kv_quant="int8").beam_search([6, 6, 2],
                                                    num_beams=3,
                                                    max_new_tokens=5)
        assert len(b) == 3 and sb == sorted(sb, reverse=True)
        np.testing.assert_allclose(sa[0], sb[0], atol=0.05)

    def test_guards(self, model):
        cfg, params = model
        eng = Engine(cfg, params, max_len=32)
        with pytest.raises(ValueError, match="num_beams"):
            eng.beam_search([1], num_beams=0, max_new_tokens=4)
        with pytest.raises(ValueError, match="max_len"):
            eng.beam_search(list(range(30)), num_beams=2,
                            max_new_tokens=8)
