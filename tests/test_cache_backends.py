"""The pluggable KV-cache backend subsystem (inference/cache).

Three suites:

  1. TestRegistry — the name->backend registry is the ONE resolution
     path: legacy flags map onto it, conflicts are loud, and engine
     classes refuse backends outside their family.
  2. TestBackendParity — the matrix: greedy AND per-request-seeded
     sampled token streams are identical across storage policies
     (dense vs paged within each precision; spec engines included),
     because storage is a schedule, not an algorithm.
  3. TestExclusionMatrix — every remaining spec-engine exclusion has
     (a) a manifest entry in spec_batching.EXCLUSIONS/PINNED, (b) a
     tagged raise in the module, and (c) a dedicated test here; the
     meta-test asserts the three stay in lockstep AND that every
     untagged validation raise in spec_batching.py has a covering
     test, so exclusions can neither rot silently nor be removed
     without their tests noticing.

Distribution note (spec x top-k/top-p): rejection sampling over the
IDENTICALLY filtered draft/target distributions reproduces the
filtered target distribution — the same thing sequential sampling
draws from. test_verify_round_targets_filtered_distribution checks
this empirically (support containment is the sharp part: one emitted
token outside the filtered support fails the test outright).
"""

import ast
import pathlib
import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import ParallelConfig, get_model_config, make_mesh
from shellac_tpu.inference import spec_batching
from shellac_tpu.inference.batching import BatchingEngine, PagedBatchingEngine
from shellac_tpu.inference.cache import (
    BACKENDS,
    DenseBackend,
    backend_flags,
    engine_class,
    make_backend,
    resolve_backend_name,
)
from shellac_tpu.inference.spec_batching import (
    EXCLUSIONS,
    PINNED,
    PagedSpeculativeBatchingEngine,
    SpeculativeBatchingEngine,
)
from shellac_tpu.models import transformer
from shellac_tpu.ops.sampling import filter_logits_batched

ALL_NAMES = ("dense", "dense-int8", "paged", "paged-int8", "rolling",
             "rolling-int8")


def _tiny(**kw):
    return get_model_config("tiny").replace(dtype="float32", **kw)


@pytest.fixture(scope="module")
def setup():
    cfg = _tiny()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    dcfg = _tiny()
    dparams = transformer.init_params(dcfg, jax.random.PRNGKey(7))
    return cfg, params, dcfg, dparams


# ---------------------------------------------------------------------
# 1. Registry
# ---------------------------------------------------------------------

class TestRegistry:
    def test_registry_and_flags_agree(self):
        assert set(BACKENDS) == set(ALL_NAMES)
        for name in BACKENDS:
            paged, kvq, rolling = backend_flags(name)
            # Legacy flags alone round-trip to the same name.
            assert resolve_backend_name(
                None, paged=paged, kv_quant=kvq, rolling_window=rolling
            ) == name
            # An explicit name AGREEING with its own flags passes.
            assert resolve_backend_name(
                name, paged=paged, kv_quant=kvq, rolling_window=rolling
            ) == name

    def test_unset_legacy_flags_impose_nothing(self):
        # dense defaults (paged=False etc.) conflict with nothing.
        for name in BACKENDS:
            assert resolve_backend_name(name) == name

    def test_conflicts_are_loud(self):
        with pytest.raises(ValueError, match="conflicts"):
            resolve_backend_name("dense", paged=True)
        with pytest.raises(ValueError, match="conflicts"):
            resolve_backend_name("paged", kv_quant="int8")
        with pytest.raises(ValueError, match="conflicts"):
            resolve_backend_name("paged-int8", rolling_window=True)
        with pytest.raises(ValueError, match="rolling_window"):
            resolve_backend_name(None, paged=True, rolling_window=True)
        with pytest.raises(ValueError, match="unknown"):
            resolve_backend_name("block-pool")

    def test_engine_class_resolution(self):
        assert engine_class("dense") is BatchingEngine
        assert engine_class("rolling-int8") is BatchingEngine
        assert engine_class("paged") is PagedBatchingEngine
        assert engine_class("paged-int8") is PagedBatchingEngine
        assert engine_class("dense", speculative=True) \
            is SpeculativeBatchingEngine
        assert engine_class("paged-int8", speculative=True) \
            is PagedSpeculativeBatchingEngine

    def test_engine_refuses_foreign_backend(self, setup):
        cfg, params = setup[:2]
        with pytest.raises(ValueError, match="engine"):
            BatchingEngine(cfg, params, cache_backend="paged")
        with pytest.raises(ValueError, match="engine"):
            PagedBatchingEngine(cfg, params, cache_backend="dense")

    def test_backend_instance_single_owner(self, setup):
        cfg, params = setup[:2]
        be = DenseBackend(cfg, 2, 64)
        e1 = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                            cache_backend=be)
        assert e1.cache_backend is be
        with pytest.raises(ValueError, match="bound"):
            BatchingEngine(cfg, params, n_slots=2, max_len=64,
                           cache_backend=be)

    def test_backend_instance_conflicts_are_loud(self, setup):
        """Engine kwargs that contradict a constructed backend
        instance refuse instead of being silently dropped — geometry,
        policy flags, and paged pool knobs alike."""
        cfg, params = setup[:2]
        with pytest.raises(ValueError, match="geometry"):
            BatchingEngine(cfg, params, n_slots=4, max_len=64,
                           cache_backend=DenseBackend(cfg, 2, 64))
        with pytest.raises(ValueError, match="rolling_window"):
            BatchingEngine(cfg, params, n_slots=2, max_len=64,
                           cache_backend=DenseBackend(cfg, 2, 64),
                           rolling_window=True)
        paged_be = make_backend("paged", cfg, 2, 64, block_size=16)
        with pytest.raises(ValueError, match="block_size"):
            PagedBatchingEngine(cfg, params, n_slots=2, max_len=64,
                                cache_backend=paged_be, block_size=32)
        with pytest.raises(ValueError, match="pool_tokens"):
            PagedBatchingEngine(cfg, params, n_slots=2, max_len=64,
                                cache_backend=paged_be, pool_tokens=256)
        with pytest.raises(ValueError, match="prefix_cache"):
            PagedBatchingEngine(cfg, params, n_slots=2, max_len=64,
                                cache_backend=paged_be,
                                prefix_cache=True)

    def test_make_backend_rejects_unknown_knobs(self, setup):
        cfg = setup[0]
        # A silently dropped pool size is a capacity incident: dense
        # takes no block_size.
        with pytest.raises(TypeError):
            make_backend("dense", cfg, 2, 64, block_size=16)

    def test_residency_is_json_serializable(self, setup):
        import json

        cfg, params = setup[:2]
        for name in ("dense", "paged", "paged-int8"):
            eng = engine_class(name)(
                cfg, params, n_slots=2, max_len=64, cache_backend=name
            )
            r = eng.cache_backend.residency()
            assert r["backend"] == name
            json.dumps(r)  # the disaggregation seam: must serialize
            assert 0.0 <= eng.cache_backend.utilization() <= 1.0

    def test_engine_stats_name_the_backend(self, setup):
        cfg, params = setup[:2]
        eng = PagedBatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  kv_quant="int8")
        assert eng.stats["cache_backend"] == "paged-int8"
        # Legacy compatibility attributes derive from the backend.
        assert eng.kv_quant == "int8"
        assert eng.rolling_window is False


# ---------------------------------------------------------------------
# 2. The parity matrix
# ---------------------------------------------------------------------

def _stream(cfg):
    """The shared request stream: two greedy, two seeded-sampled (the
    sampled rows carry top-k/top-p/min-p so the filtered-identity path
    is exercised, and per-request seeds so outputs are deterministic
    and backend-comparable)."""
    rng = np.random.default_rng(42)
    v = cfg.vocab_size
    return [
        ("g0", rng.integers(0, v, 5), 8, dict(temperature=0.0)),
        ("g1", rng.integers(0, v, 11), 6, dict(temperature=0.0)),
        ("s0", rng.integers(0, v, 7), 8,
         dict(temperature=1.1, top_k=12, top_p=0.9, seed=123)),
        ("s1", rng.integers(0, v, 4), 6,
         dict(temperature=0.8, min_p=0.05, seed=7)),
    ]


def _drive(eng, reqs):
    for rid, toks, max_new, kw in reqs:
        eng.submit(rid, toks, max_new, **kw)
    out = {}
    while eng.pending:
        out.update(eng.step())
    return out


def _seq_engine(setup, name):
    cfg, params = setup[:2]
    return engine_class(name)(cfg, params, n_slots=2, max_len=96,
                              cache_backend=name)


def _spec_engine(setup, name, **kw):
    cfg, params, dcfg, dparams = setup
    kw.setdefault("n_slots", 2)
    kw.setdefault("max_len", 96)
    kw.setdefault("gamma", 3)
    return engine_class(name, speculative=True)(
        cfg, params, dcfg, dparams, cache_backend=name, **kw
    )


@pytest.mark.slow
class TestBackendParity:
    """~160s of engine builds: excluded from the tier-1 window (early-
    alphabet placement would displace ~19% of it) and run in full by
    the dedicated cache-backends CI job, which drops the marker
    filter."""

    @pytest.mark.parametrize("quant", [None, "int8"])
    def test_sequential_dense_paged_identity(self, setup, quant):
        """Same precision, different storage policy: token-identical
        for the whole stream — greedy and seeded-sampled rows."""
        cfg = setup[0]
        a = _drive(_seq_engine(setup, "dense-int8" if quant else "dense"),
                   _stream(cfg))
        b = _drive(_seq_engine(setup, "paged-int8" if quant else "paged"),
                   _stream(cfg))
        assert a == b

    @pytest.mark.parametrize("name", ["dense", "dense-int8", "paged",
                                      "paged-int8"])
    def test_spec_greedy_matches_sequential(self, setup, name):
        """The acceptance bar: the spec engine on EVERY supported
        backend emits greedy tokens identical to the sequential engine
        on the same backend (speculation is invisible to the math)."""
        cfg = setup[0]
        greedy = [r for r in _stream(cfg) if r[3]["temperature"] == 0.0]
        want = _drive(_seq_engine(setup, name), greedy)
        spec = _spec_engine(setup, name)
        got = _drive(spec, greedy)
        assert got == want
        assert spec.stats["spec_rounds"] > 0

    @pytest.mark.parametrize("pair", [("dense", "paged"),
                                      ("dense-int8", "paged-int8")])
    def test_spec_seeded_cross_backend_identity(self, setup, pair):
        """Seeded sampled requests through the spec engine are
        deterministic per request and IDENTICAL across cache backends
        (per-row key fan depends only on seed + tokens generated) —
        which also forces acceptance-RATE parity, asserted on the
        round counters."""
        cfg = setup[0]
        a_eng, b_eng = (_spec_engine(setup, n) for n in pair)
        a = _drive(a_eng, _stream(cfg))
        b = _drive(b_eng, _stream(cfg))
        assert a == b
        for k in ("spec_rounds", "spec_proposed", "spec_accepted"):
            assert a_eng.stats[k] == b_eng.stats[k], k

    def test_spec_on_paged_with_prefix_cache(self, setup):
        """Spec decode composes with prefix caching: the second
        same-prefix request hits the cache (target prefills the
        suffix; the draft covers the prompt from 0) and stays greedy
        token-identical."""
        cfg = setup[0]
        rng = np.random.default_rng(3)
        prefix = rng.integers(0, cfg.vocab_size, 32)
        tail = rng.integers(0, cfg.vocab_size, 3)
        p1 = np.concatenate([prefix, tail])
        want = _drive(_seq_engine(setup, "dense"),
                      [("a", prefix, 6, dict(temperature=0.0)),
                       ("b", p1, 6, dict(temperature=0.0))])
        spec = _spec_engine(setup, "paged", n_slots=1, max_len=96,
                            prefix_cache=True, block_size=16)
        got = _drive(spec, [("a", prefix, 6, dict(temperature=0.0))])
        got.update(_drive(spec, [("b", p1, 6, dict(temperature=0.0))]))
        assert got == want
        assert spec.stats["prefix_hit_tokens"] > 0

    def test_spec_topk1_equals_greedy(self, setup):
        """top_k=1 at temperature 1.0 collapses the filtered
        distribution to the argmax token: the sampled spec engine must
        emit exactly the greedy sequence — the exact corner of the
        filtered-identity argument, with zero statistical slack."""
        cfg = setup[0]
        rng = np.random.default_rng(11)
        prompt = rng.integers(0, cfg.vocab_size, 6)
        want = _drive(_seq_engine(setup, "dense"),
                      [("x", prompt, 10, dict(temperature=0.0))])
        got = _drive(
            _spec_engine(setup, "dense"),
            [("x", prompt, 10, dict(temperature=1.0, top_k=1, seed=5))],
        )
        assert got == want

    def test_verify_round_targets_filtered_distribution(self, setup):
        """spec x top-k distribution equivalence vs the sequential
        sampler, empirically: with top_k=2, every emitted token must
        lie in the FILTERED support (sharp — an unfiltered target or
        draft side emits out-of-support tokens almost surely), and
        the conditional frequency of the top token matches the
        filtered softmax within binomial tolerance."""
        cfg, params = setup[:2]
        rng = np.random.default_rng(13)
        prompt = rng.integers(0, cfg.vocab_size, 6).astype(np.int32)

        def filtered(prefix):
            logits = transformer.forward(
                cfg, params, jnp.asarray(np.asarray(prefix, np.int32)[None])
            )[0, -1]
            x = filter_logits_batched(
                logits[None], jnp.ones(1), jnp.full((1,), 2, jnp.int32),
                jnp.ones(1), jnp.zeros(1),
            )[0]
            p = np.asarray(jax.nn.softmax(x))
            sup = np.nonzero(p > 0)[0]
            return {int(t): float(p[t]) for t in sup}

        p0 = filtered(prompt)
        assert len(p0) == 2  # top-2 support (no boundary tie on tiny)
        n = 120
        eng = _spec_engine(setup, "dense", n_slots=4, gamma=2)
        reqs = [(i, prompt, 2, dict(temperature=1.0, top_k=2))
                for i in range(n)]
        results = _drive(eng, reqs)
        pairs = [tuple(results[i]) for i in range(n)]
        # Support containment: position 0 (prefill sample) and
        # position 1 (verify round) both within the filtered support.
        conds = {t0: filtered(np.append(prompt, t0)) for t0 in p0}
        c0 = {t0: 0 for t0 in p0}
        c1 = {t0: {t1: 0 for t1 in conds[t0]} for t0 in p0}
        for t0, t1 in pairs:
            assert t0 in p0, f"t0={t0} outside filtered support {p0}"
            assert t1 in conds[t0], (
                f"t1={t1} outside filtered support {conds[t0]} after "
                f"t0={t0} — the verify round is not sampling the "
                "filtered target distribution"
            )
            c0[t0] += 1
            c1[t0][t1] += 1
        # Frequencies within 4.5 sigma of the filtered probabilities.
        for t0, p in p0.items():
            tol = 4.5 * np.sqrt(p * (1 - p) / n)
            assert abs(c0[t0] / n - p) < tol + 1e-9, (t0, c0, p0)
        for t0 in p0:
            m = c0[t0]
            if m < 25:
                continue  # too few samples for a frequency claim
            for t1, p in conds[t0].items():
                tol = 4.5 * np.sqrt(p * (1 - p) / m)
                assert abs(c1[t0][t1] / m - p) < tol + 1e-9, \
                    (t0, t1, c1, conds[t0])

    @pytest.mark.parametrize("name", ["dense", "paged"])
    def test_spec_min_tokens_logit_bias_prompt_logprobs(self, setup, name):
        """The other three burned-down compositions, pinned so a
        regression cannot ship silently: min_tokens (EOS banned in
        BOTH draft and target until N tokens), logit_bias (identical
        adjustment on both distributions), and prompt_logprobs (the
        target prefill scores the prompt) — token streams AND prompt
        scores must match the sequential engine on the same backend."""
        cfg, params, dcfg, dparams = setup
        prompt = np.asarray([5, 9, 2, 31, 7], np.int32)
        eos = 3
        kwargs = dict(n_slots=1, max_len=96, temperature=0.0, eos_id=eos)
        sub = dict(min_tokens=4, logit_bias={eos: 1e9},
                   prompt_logprobs=True)

        def drive(eng):
            eng.submit("r", prompt, 10, **sub)
            out = {}
            while eng.pending:
                out.update(eng.step())
            return out["r"], eng.finished_prompt_logprobs.pop("r")

        seq_t, seq_p = drive(engine_class(name)(
            cfg, params, cache_backend=name, **kwargs))
        spec_t, spec_p = drive(engine_class(name, speculative=True)(
            cfg, params, dcfg, dparams, gamma=3, cache_backend=name,
            **kwargs))
        # The bias forces EOS the instant the min_tokens ban lifts:
        # 4 ordinary greedy tokens, then EOS — on both engines.
        assert seq_t == spec_t
        assert len(seq_t) == 5 and seq_t[4] == eos
        assert all(t != eos for t in seq_t[:4])
        np.testing.assert_allclose(seq_p, spec_p, rtol=1e-6)

    def test_rolling_backend_unchanged_by_registry(self):
        """The rolling backend rides the same registry: a windowed
        model through cache_backend='rolling' matches the legacy
        rolling_window=True construction token-for-token."""
        cfg = _tiny(attn_window=16)
        params = transformer.init_params(cfg, jax.random.PRNGKey(2))
        rng = np.random.default_rng(4)
        reqs = [("x", rng.integers(0, cfg.vocab_size, 9), 8,
                 dict(temperature=0.0))]
        a = _drive(BatchingEngine(cfg, params, n_slots=1, max_len=96,
                                  cache_backend="rolling"), reqs)
        b = _drive(BatchingEngine(cfg, params, n_slots=1, max_len=96,
                                  rolling_window=True), reqs)
        assert a == b


@pytest.mark.slow
class TestMigrationConformance:
    """The residency()/KV-migration round trip, for EVERY registered
    backend: prefill-only on engine A -> export_slot -> serialize ->
    deserialize -> import_blob onto a FRESH engine B -> B's
    continuation is token-identical to the unmigrated run. This is
    the conformance contract inference/disagg.py (the disaggregated
    serving seam) holds against the registry — a new backend must
    either migrate correctly or be added to disagg's loud refusals."""

    @pytest.mark.parametrize("name", ALL_NAMES)
    def test_round_trip_continuation_identity(self, name):
        from shellac_tpu.inference import disagg

        cfg = (_tiny(attn_window=16) if name.startswith("rolling")
               else _tiny())
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(9)
        reqs = [
            ("g", rng.integers(0, cfg.vocab_size, 11), 6,
             dict(temperature=0.0)),
            ("s", rng.integers(0, cfg.vocab_size, 7), 6,
             dict(temperature=1.1, top_k=12, top_p=0.9, seed=123)),
        ]
        kind = engine_class(name)
        expected = _drive(kind(cfg, params, n_slots=2, max_len=96,
                               cache_backend=name), reqs)

        a = kind(cfg, params, n_slots=2, max_len=96,
                 cache_backend=name)
        for rid, toks, max_new, kw in reqs:
            a.submit(rid, toks, max_new, prefill_only=True, **kw)
        while len(a.frozen_prefills) < len(reqs):
            a.step()
        blobs = {}
        for rid, slot in list(a.frozen_prefills.items()):
            blob = disagg.export_slot(a, slot, a._slots[slot])
            # residency() is the wire manifest: JSON round trip held.
            assert blob.header["residency"]["backend"] == name
            blobs[rid] = disagg.MigrationBlob.deserialize(
                blob.serialize()
            )
            a.release_frozen(rid)
        assert not a.pending  # every frozen slot released cleanly

        b = kind(cfg, params, n_slots=2, max_len=96,
                 cache_backend=name)
        for rid, blob in blobs.items():
            disagg.import_blob(b, blob, rid=rid)
        got = {}
        while b.pending:
            got.update(b.step())
        assert got == expected


# ---------------------------------------------------------------------
# 3. The exclusion matrix, meta-tested
# ---------------------------------------------------------------------

_SPEC_SRC = pathlib.Path(spec_batching.__file__).read_text()

# Untagged validation raises in spec_batching.py: plain input checks,
# not exclusions — each must still have a covering test (named here;
# the meta-test asserts the name exists in this file or in
# tests/test_spec_batching.py). A new raise in spec_batching.py that
# is neither tagged nor listed here fails the meta-test.
VALIDATION_RAISES = {
    "vocab mismatch": "test_vocab_mismatch",
    "gamma must be": "test_gamma_validated",
    "draft model heads": "test_draft_heads_must_divide_tp",
    "speculative slack": "test_slack_budget_enforced",
}


def _raise_messages():
    msgs = []
    for node in ast.walk(ast.parse(_SPEC_SRC)):
        if isinstance(node, ast.Raise) and node.exc is not None:
            parts = [c.value for c in ast.walk(node.exc)
                     if isinstance(c, ast.Constant)
                     and isinstance(c.value, str)]
            msgs.append("".join(parts))
    return msgs


class TestExclusionMatrix:
    # -- the exclusions themselves (one dedicated test per entry) -----

    def test_excluded_rolling_window(self, setup):
        cfg, params, dcfg, dparams = setup
        with pytest.raises(ValueError,
                           match=r"\[excluded: rolling_window\]"):
            SpeculativeBatchingEngine(cfg, params, dcfg, dparams,
                                      rolling_window=True)
        with pytest.raises(ValueError,
                           match=r"\[excluded: rolling_window\]"):
            SpeculativeBatchingEngine(cfg, params, dcfg, dparams,
                                      cache_backend="rolling-int8")

    def test_excluded_overlap_decode(self, setup):
        cfg, params, dcfg, dparams = setup
        with pytest.raises(ValueError,
                           match=r"\[excluded: overlap_decode\]"):
            SpeculativeBatchingEngine(cfg, params, dcfg, dparams,
                                      overlap_decode=True)

    def test_excluded_overlap_prefill(self, setup):
        cfg, params, dcfg, dparams = setup
        with pytest.raises(ValueError,
                           match=r"\[excluded: overlap_prefill\]"):
            SpeculativeBatchingEngine(cfg, params, dcfg, dparams,
                                      overlap_prefill=True)

    def test_excluded_pp_pipeline(self, setup):
        cfg, params, dcfg, dparams = setup
        with pytest.raises(ValueError,
                           match=r"\[excluded: pp_pipeline\]"):
            SpeculativeBatchingEngine(cfg, params, dcfg, dparams,
                                      pp_pipeline=True)

    def test_excluded_constraint(self, setup):
        srv = _spec_engine(setup, "dense")
        with pytest.raises(ValueError, match=r"\[excluded: constraint\]"):
            srv.submit("x", np.array([1], np.int32), 4,
                       constraint=object())

    def test_excluded_penalties(self, setup):
        srv = _spec_engine(setup, "dense")
        with pytest.raises(ValueError, match=r"\[excluded: penalties\]"):
            srv.submit("x", np.array([1], np.int32), 4,
                       presence_penalty=0.5)
        with pytest.raises(ValueError, match=r"\[excluded: penalties\]"):
            srv.submit("x", np.array([1], np.int32), 4,
                       frequency_penalty=0.2)

    def test_pinned_decode_ticks(self, setup):
        cfg, params, dcfg, dparams = setup
        with pytest.raises(ValueError,
                           match=r"\[pinned: decode_ticks\]"):
            SpeculativeBatchingEngine(cfg, params, dcfg, dparams,
                                      decode_ticks=2)
        # "auto" (the serving default) resolves to 1 instead of raising,
        # and the engine opts out of post-construction retuning.
        eng = SpeculativeBatchingEngine(cfg, params, dcfg, dparams,
                                        decode_ticks="auto")
        assert eng.decode_ticks == 1
        assert eng._decode_ticks_tunable is False

    # -- untagged validation raises -----------------------------------

    def test_gamma_validated(self, setup):
        cfg, params, dcfg, dparams = setup
        with pytest.raises(ValueError, match="gamma"):
            SpeculativeBatchingEngine(cfg, params, dcfg, dparams, gamma=0)

    def test_draft_heads_must_divide_tp(self, setup):
        cfg, params = setup[:2]
        if jax.device_count() < 2:
            pytest.skip("needs >= 2 devices for a tp mesh")
        mesh = make_mesh(ParallelConfig(tp=2), devices=jax.devices()[:2])
        dcfg = _tiny(n_heads=1, n_kv_heads=1)
        with pytest.raises(ValueError, match="draft model heads"):
            SpeculativeBatchingEngine(cfg, params, dcfg, params,
                                      mesh=mesh)

    # -- the meta-test: manifest, raises, and tests in lockstep -------

    def test_matrix_cannot_rot(self):
        msgs = _raise_messages()
        tagged = {}
        for m in msgs:
            for kind, key in re.findall(r"\[(excluded|pinned): (\w+)\]", m):
                tagged.setdefault(kind, set()).add(key)
        # (a) every manifest entry has a tagged raise, and vice versa.
        assert tagged.get("excluded", set()) == set(EXCLUSIONS)
        assert tagged.get("pinned", set()) == set(PINNED)
        # (b) every manifest entry has its dedicated test in this class.
        for key in EXCLUSIONS:
            assert hasattr(TestExclusionMatrix, f"test_excluded_{key}"), \
                f"exclusion {key!r} has no test_excluded_{key}"
        for key in PINNED:
            assert hasattr(TestExclusionMatrix, f"test_pinned_{key}"), \
                f"pinned knob {key!r} has no test_pinned_{key}"
        # (c) every UNTAGGED raise is a known validation raise with a
        # covering test that actually exists.
        here = pathlib.Path(__file__).read_text()
        sibling = (pathlib.Path(__file__).parent
                   / "test_spec_batching.py").read_text()
        for m in msgs:
            if re.search(r"\[(excluded|pinned): \w+\]", m):
                continue
            hits = [s for s in VALIDATION_RAISES if s in m]
            assert hits, (
                f"untagged raise {m!r} in spec_batching.py: tag it "
                "[excluded: <key>] / [pinned: <key>] with a manifest "
                "entry, or register it in VALIDATION_RAISES with a "
                "covering test"
            )
            test_name = VALIDATION_RAISES[hits[0]]
            assert (f"def {test_name}(" in here
                    or f"def {test_name}(" in sibling), \
                f"{test_name} (covering {hits[0]!r}) does not exist"
        # (d) the burn-down is real: the matrix stays at or below the
        # six survivors documented in docs/inference.md (PR 9's five
        # plus overlap_prefill, which joined with the admission
        # pipeline — the same no-sync-to-defer class as
        # overlap_decode).
        assert len(EXCLUSIONS) <= 6


# ---------------------------------------------------------------------
# Observability: the backend is visible at /stats and /metrics
# ---------------------------------------------------------------------

class TestObservability:
    def test_backend_info_gauge_and_stats(self, setup):
        from shellac_tpu.inference.server import InferenceServer
        from shellac_tpu.obs import Registry

        cfg, params = setup[:2]
        reg = Registry()
        eng = PagedBatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  registry=reg)
        srv = InferenceServer(cfg, params, engine=eng, registry=reg)
        try:
            assert eng.stats["cache_backend"] == "paged"
            text = srv.metrics_text()
            assert ('shellac_engine_cache_backend_info'
                    '{backend="paged"} 1') in text
        finally:
            srv.close()
