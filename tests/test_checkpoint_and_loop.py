"""Checkpoint/resume, failure detection, data pipeline, training loop."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.config import TrainConfig
from shellac_tpu.training import (
    batch_shardings,
    fit,
    init_train_state,
    make_train_step,
)
from shellac_tpu.training.checkpoint import Checkpointer
from shellac_tpu.training.data import (
    device_prefetch,
    read_token_shard,
    shard_batches,
    token_batches,
    write_token_shard,
)
from shellac_tpu.utils.failure import (
    FailureDetector,
    Heartbeat,
    RestartBudget,
    all_finite,
    guard_update,
    heartbeat_age,
)


def _cfg():
    return get_model_config("tiny").replace(dtype="float32")


class TestCheckpoint:
    def test_roundtrip(self, tmp_path):
        cfg = _cfg()
        tcfg = TrainConfig(warmup_steps=0, learning_rate=1e-3)
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
        ckpt = Checkpointer(str(tmp_path / "ckpt"))
        ckpt.save(0, state, wait=True)
        restored = ckpt.restore(abstract_state=jax.eval_shape(lambda s: s, state))
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            state.params, restored.params,
        )
        ckpt.close()

    def test_restore_across_mu_dtype_change(self, tmp_path):
        """A checkpoint written with fp32 adam mu restores under a bf16-mu
        config (and vice versa): saved dtypes are cast to the requested."""
        cfg = _cfg()
        old = TrainConfig(warmup_steps=0, mu_dtype="float32")
        state = init_train_state(cfg, old, jax.random.PRNGKey(0))
        ckpt = Checkpointer(str(tmp_path / "ckpt"))
        ckpt.save(0, state, wait=True)

        new = TrainConfig(warmup_steps=0, mu_dtype="bfloat16")
        template = init_train_state(cfg, new, jax.random.PRNGKey(1))
        restored = ckpt.restore(
            abstract_state=jax.eval_shape(lambda s: s, template)
        )
        for want, got in zip(
            jax.tree.leaves(jax.eval_shape(lambda s: s, template)),
            jax.tree.leaves(restored),
        ):
            assert want.dtype == got.dtype
        # Params (dtype-stable leaves) survive the fallback path intact.
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            state.params, restored.params,
        )
        ckpt.close()

    def test_sharded_roundtrip(self, tmp_path, mesh8):
        cfg = _cfg().replace(d_model=128, vocab_size=512)
        tcfg = TrainConfig()
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0), mesh=mesh8)
        ckpt = Checkpointer(str(tmp_path / "ckpt"))
        ckpt.save(3, state, wait=True)
        abstract = jax.eval_shape(lambda s: s, state)
        restored = ckpt.restore(
            abstract_state=abstract, mesh=mesh8, model_cfg=cfg
        )
        # Restored arrays carry the mesh shardings and equal values.
        assert (
            restored.params["layers"]["wq"].sharding
            == state.params["layers"]["wq"].sharding
        )
        np.testing.assert_array_equal(
            np.asarray(state.params["embed"]), np.asarray(restored.params["embed"])
        )
        assert ckpt.latest_step() == 3
        ckpt.close()

    def test_restore_missing_raises(self, tmp_path):
        ckpt = Checkpointer(str(tmp_path / "empty"))
        with pytest.raises(FileNotFoundError):
            ckpt.restore()
        ckpt.close()


class TestFailureTools:
    def test_all_finite(self):
        assert bool(all_finite({"a": jnp.ones(3), "b": jnp.zeros(2)}))
        assert not bool(all_finite({"a": jnp.array([1.0, jnp.nan])}))
        assert not bool(all_finite({"a": jnp.array([jnp.inf])}))
        # int leaves are ignored
        assert bool(all_finite({"a": jnp.array([1, 2, 3])}))

    def test_guard_update(self):
        old = {"w": jnp.zeros(2), "n": jnp.array(0)}
        new = {"w": jnp.ones(2), "n": jnp.array(1)}
        kept = guard_update(old, new, jnp.array(False))
        np.testing.assert_array_equal(np.asarray(kept["w"]), [0.0, 0.0])
        assert int(kept["n"]) == 0
        taken = guard_update(old, new, jnp.array(True))
        np.testing.assert_array_equal(np.asarray(taken["w"]), [1.0, 1.0])

    def test_nan_batch_skips_update(self):
        """A poisoned batch must leave params bit-identical."""
        cfg = _cfg()
        tcfg = TrainConfig(warmup_steps=0, learning_rate=1e-3)
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
        step = make_train_step(cfg, tcfg)
        tokens = jnp.zeros((2, 16), jnp.int32)
        bad = {
            "inputs": tokens,
            "targets": tokens,
            "mask": jnp.full((2, 16), jnp.nan, jnp.float32),
        }
        before = jax.device_get(state.params["embed"])
        state, metrics = step(state, bad)
        assert float(metrics["update_skipped"]) == 1.0
        np.testing.assert_array_equal(before, jax.device_get(state.params["embed"]))

    def test_failure_detector(self):
        det = FailureDetector(patience=2)
        for _ in range(10):
            assert det.check(1.0) is None
        assert det.check(float("nan")) is None  # first strike
        reason = det.check(float("nan"))  # second strike trips
        assert reason is not None and "non-finite" in reason
        det.reset()
        assert det.check(1.0) is None
        # explosion detection
        det2 = FailureDetector(patience=1, loss_explosion_factor=5.0)
        for _ in range(5):
            det2.check(2.0)
        assert det2.check(100.0) is not None

    def test_heartbeat(self, tmp_path):
        path = str(tmp_path / "hb" / "heart.json")
        hb = Heartbeat(path, process_index=0)
        assert hb.age() is None
        hb.beat(7)
        assert hb.age() < 5.0
        assert not Heartbeat.is_stale(path, timeout=60.0)
        assert Heartbeat.is_stale(str(tmp_path / "nope.json"), timeout=1.0)
        # The path-based helper needs no instance at all (external
        # watchdogs call it on files other processes own).
        assert heartbeat_age(path) < 5.0
        assert heartbeat_age(str(tmp_path / "nope.json")) is None
        corrupt = str(tmp_path / "corrupt.json")
        with open(corrupt, "w") as f:
            f.write("{not json")
        assert heartbeat_age(corrupt) is None
        assert Heartbeat.is_stale(corrupt, timeout=60.0)

    def test_restart_budget(self):
        b = RestartBudget(2, window=100.0)
        assert b.used == 0
        assert b.allow(now=0.0)
        assert b.allow(now=1.0)
        assert not b.allow(now=2.0)  # 2 restarts already in window
        assert not b.allow(now=50.0)
        # Both early attempts age out of the sliding window; denied
        # attempts were never recorded, so they don't extend it.
        assert b.allow(now=101.0)
        assert b.allow(now=101.5)
        assert not b.allow(now=102.0)
        # A zero budget never allows (recovery disabled, stay fatal).
        assert not RestartBudget(0, window=10.0).allow(now=0.0)
        with pytest.raises(ValueError):
            RestartBudget(-1)
        with pytest.raises(ValueError):
            RestartBudget(1, window=0.0)


class TestData:
    def test_shard_roundtrip(self, tmp_path):
        toks = np.arange(1000, dtype=np.int32)
        p = str(tmp_path / "shard0.bin")
        write_token_shard(p, toks)
        np.testing.assert_array_equal(read_token_shard(p), toks)

    def test_bad_magic_raises(self, tmp_path):
        p = str(tmp_path / "junk.bin")
        with open(p, "wb") as f:
            f.write(b"JUNKJUNKJUNKJUNKJUNK")
        with pytest.raises(ValueError, match="bad magic"):
            read_token_shard(p)

    def test_token_batches_shapes(self):
        it = token_batches(
            np.arange(500, dtype=np.int32), batch_size=4, seq_len=16, num_batches=3
        )
        batches = list(it)
        assert len(batches) == 3
        for b in batches:
            assert b["inputs"].shape == (4, 16)
            np.testing.assert_array_equal(b["inputs"][:, 1:], b["targets"][:, :-1])

    def test_shard_batches_python_fallback(self, tmp_path):
        paths = []
        for i in range(2):
            p = str(tmp_path / f"s{i}.bin")
            write_token_shard(p, np.arange(300, dtype=np.int32) + 300 * i)
            paths.append(p)
        batches = list(
            shard_batches(paths, batch_size=2, seq_len=8, num_batches=2)
        )
        assert len(batches) == 2
        assert batches[0]["inputs"].dtype == np.int32

    def test_device_prefetch(self):
        it = token_batches(
            np.arange(200, dtype=np.int32), batch_size=2, seq_len=8, num_batches=4
        )
        out = list(device_prefetch(it))
        assert len(out) == 4
        assert isinstance(out[0]["inputs"], jax.Array)

    def test_device_prefetch_abandoned_consumer_frees_worker(self):
        """Closing the generator early must release the prefetch
        thread — a worker parked in q.put() forever leaks into the
        rest of the process (the full-suite segfaults showed one)."""
        import threading
        import time

        before = threading.active_count()
        it = token_batches(
            np.arange(4000, dtype=np.int32), batch_size=2, seq_len=8,
            num_batches=100,
        )
        gen = device_prefetch(it)
        next(gen)  # start the worker, consume one batch
        gen.close()  # abandon mid-stream
        deadline = time.time() + 10
        while threading.active_count() > before and time.time() < deadline:
            time.sleep(0.05)
        assert threading.active_count() <= before, "prefetch thread leaked"


class TestFit:
    def test_fit_end_to_end_with_resume(self, tmp_path):
        cfg = _cfg()
        tcfg = TrainConfig(
            warmup_steps=0, learning_rate=3e-3, total_steps=6
        )
        data = token_batches(
            np.tile(np.arange(32, dtype=np.int32), 50),
            batch_size=2, seq_len=16, num_batches=100,
        )
        ckdir = str(tmp_path / "run")
        state = fit(
            cfg, tcfg, data,
            checkpoint_dir=ckdir, checkpoint_every=3, log_every=2,
            log_path=str(tmp_path / "log.jsonl"),
            heartbeat_path=str(tmp_path / "hb.json"),
        )
        assert int(jax.device_get(state.step)) == 6
        assert os.path.exists(str(tmp_path / "log.jsonl"))

        # Resume: raise total_steps and continue from the saved step 6.
        tcfg2 = tcfg.replace(total_steps=8)
        data2 = token_batches(
            np.tile(np.arange(32, dtype=np.int32), 50),
            batch_size=2, seq_len=16, num_batches=100,
        )
        state2 = fit(cfg, tcfg2, data2, checkpoint_dir=ckdir, log_every=2)
        assert int(jax.device_get(state2.step)) == 8

    def test_fit_sharded(self, mesh_fsdp8):
        cfg = _cfg().replace(d_model=128, vocab_size=512)
        tcfg = TrainConfig(warmup_steps=0, total_steps=3)
        bs = batch_shardings(mesh_fsdp8)
        from shellac_tpu.training.data import device_prefetch, token_batches

        data = device_prefetch(
            token_batches(
                np.arange(5000, dtype=np.int32) % 512,
                batch_size=8, seq_len=16, num_batches=10,
            ),
            sharding=bs,
        )
        state = fit(cfg, tcfg, data, mesh=mesh_fsdp8, log_every=1)
        assert int(jax.device_get(state.step)) == 3
