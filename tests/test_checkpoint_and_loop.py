"""Checkpoint/resume, failure detection, data pipeline, training loop."""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.config import TrainConfig
from shellac_tpu.obs import Registry, set_default_registry
from shellac_tpu.training import (
    AnomalySentinel,
    batch_shardings,
    chaos,
    fit,
    init_train_state,
    make_train_step,
)
from shellac_tpu.training.checkpoint import Checkpointer
from shellac_tpu.training.data import (
    device_prefetch,
    read_token_shard,
    shard_batches,
    token_batches,
    write_token_shard,
)
from shellac_tpu.utils.failure import (
    FailureDetector,
    Heartbeat,
    RestartBudget,
    all_finite,
    guard_update,
    heartbeat_age,
)


def _cfg():
    return get_model_config("tiny").replace(dtype="float32")


class TestCheckpoint:
    def test_roundtrip(self, tmp_path):
        cfg = _cfg()
        tcfg = TrainConfig(warmup_steps=0, learning_rate=1e-3)
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
        ckpt = Checkpointer(str(tmp_path / "ckpt"))
        ckpt.save(0, state, wait=True)
        restored = ckpt.restore(abstract_state=jax.eval_shape(lambda s: s, state))
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            state.params, restored.params,
        )
        ckpt.close()

    def test_restore_across_mu_dtype_change(self, tmp_path):
        """A checkpoint written with fp32 adam mu restores under a bf16-mu
        config (and vice versa): saved dtypes are cast to the requested."""
        cfg = _cfg()
        old = TrainConfig(warmup_steps=0, mu_dtype="float32")
        state = init_train_state(cfg, old, jax.random.PRNGKey(0))
        ckpt = Checkpointer(str(tmp_path / "ckpt"))
        ckpt.save(0, state, wait=True)

        new = TrainConfig(warmup_steps=0, mu_dtype="bfloat16")
        template = init_train_state(cfg, new, jax.random.PRNGKey(1))
        restored = ckpt.restore(
            abstract_state=jax.eval_shape(lambda s: s, template)
        )
        for want, got in zip(
            jax.tree.leaves(jax.eval_shape(lambda s: s, template)),
            jax.tree.leaves(restored),
        ):
            assert want.dtype == got.dtype
        # Params (dtype-stable leaves) survive the fallback path intact.
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
            state.params, restored.params,
        )
        ckpt.close()

    def test_sharded_roundtrip(self, tmp_path, mesh8):
        cfg = _cfg().replace(d_model=128, vocab_size=512)
        tcfg = TrainConfig()
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0), mesh=mesh8)
        ckpt = Checkpointer(str(tmp_path / "ckpt"))
        ckpt.save(3, state, wait=True)
        abstract = jax.eval_shape(lambda s: s, state)
        restored = ckpt.restore(
            abstract_state=abstract, mesh=mesh8, model_cfg=cfg
        )
        # Restored arrays carry the mesh shardings and equal values.
        assert (
            restored.params["layers"]["wq"].sharding
            == state.params["layers"]["wq"].sharding
        )
        np.testing.assert_array_equal(
            np.asarray(state.params["embed"]), np.asarray(restored.params["embed"])
        )
        assert ckpt.latest_step() == 3
        ckpt.close()

    def test_restore_missing_raises(self, tmp_path):
        ckpt = Checkpointer(str(tmp_path / "empty"))
        with pytest.raises(FileNotFoundError):
            ckpt.restore()
        ckpt.close()


@pytest.fixture
def fresh_registry():
    """Swap the process-global obs registry so counter assertions see
    only this test's events."""
    reg = Registry()
    old = set_default_registry(reg)
    yield reg
    set_default_registry(old)


class TestCheckpointIntegrity:
    """The manifest / verify / quarantine / fallback-restore contract
    (docs/training.md, "Failure semantics")."""

    def _saved(self, tmp_path, steps=(1, 2, 3)):
        cfg = _cfg()
        tcfg = TrainConfig(warmup_steps=0)
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
        d = str(tmp_path / "ck")
        ckpt = Checkpointer(d, max_to_keep=len(steps) + 2)
        for s in steps:
            ckpt.save(s, state, wait=True)
        abstract = jax.eval_shape(lambda s: s, state)
        return d, ckpt, state, abstract

    def test_manifest_roundtrip_and_verify(self, tmp_path):
        d, ckpt, state, _ = self._saved(tmp_path)
        for s in (1, 2, 3):
            assert os.path.exists(
                os.path.join(d, "manifests", f"{s}.json")
            )
            assert ckpt.verify(s) is None
        assert ckpt.verify(99) is not None  # absent step never passes
        ckpt.close()

    def test_verify_rejects_tampered_manifest(self, tmp_path):
        d, ckpt, _, _ = self._saved(tmp_path)
        chaos.tamper_manifest(d, 2, leaf_count=999)
        assert "leaf count" in ckpt.verify(2)
        chaos.tamper_manifest(d, 3, tree_digest="deadbeef")
        assert ckpt.verify(3) is not None
        assert ckpt.verify(1) is None  # untouched sibling still passes
        ckpt.close()

    def test_fallback_quarantines_corrupt_latest(self, tmp_path,
                                                 fresh_registry):
        d, ckpt, state, abstract = self._saved(tmp_path)
        chaos.scramble_step(d, 3)
        restored = ckpt.restore(abstract_state=abstract, fallback=True)
        # Walked back to the newest intact step and got real data.
        jax.tree.map(
            lambda a, b: np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b)
            ),
            state.params, restored.params,
        )
        assert ckpt.latest_step() == 2
        assert os.path.isdir(os.path.join(d, "3.corrupt"))
        assert os.path.exists(
            os.path.join(d, "3.corrupt", "QUARANTINE.json")
        )
        assert fresh_registry.value(
            "shellac_train_ckpt_quarantined_total") == 1
        assert fresh_registry.value(
            "shellac_train_ckpt_fallback_restores_total") == 1
        assert fresh_registry.value("shellac_train_last_good_step") == 2
        ckpt.close()
        # The rename is durable: a NEW Checkpointer (fresh process)
        # never re-selects the quarantined step either.
        ckpt2 = Checkpointer(d)
        assert ckpt2.latest_step() == 2
        assert ckpt2.verify(3) is not None
        ckpt2.close()

    def test_fallback_exhausted_raises(self, tmp_path, fresh_registry):
        d, ckpt, _, abstract = self._saved(tmp_path, steps=(1, 2))
        chaos.scramble_step(d, 1)
        chaos.scramble_step(d, 2)
        with pytest.raises(FileNotFoundError, match="no intact"):
            ckpt.restore(abstract_state=abstract, fallback=True)
        assert fresh_registry.value(
            "shellac_train_ckpt_quarantined_total") == 2
        ckpt.close()

    def test_startup_sweep_removes_interrupted_save_debris(self, tmp_path):
        d, ckpt, _, _ = self._saved(tmp_path)
        ckpt.close()
        debris = chaos.fake_interrupted_save(d, 9)
        # An ABANDONED orphan manifest (its save never committed) goes
        # too — backdated past the TTL; a young one could belong to a
        # concurrent trainer's still-in-flight save and must survive.
        import time as _time

        orphan = os.path.join(d, "manifests", "7.json")
        with open(orphan, "w") as f:
            f.write("{}")
        old = _time.time() - 2 * 3600
        os.utime(orphan, (old, old))
        # Young debris could be a CONCURRENT process's live async save
        # (eval opening the dir mid-train) — the sweep leaves it alone.
        live = chaos.fake_interrupted_save(d, 11, age_s=0.0)
        ckpt2 = Checkpointer(d)
        assert not os.path.exists(debris)
        assert not os.path.exists(orphan)
        assert os.path.exists(live)
        assert ckpt2.latest_step() == 3  # intact steps untouched
        assert ckpt2.verify(3) is None
        ckpt2.close()

    def test_request_mismatch_raises_instead_of_quarantining(
            self, tmp_path, fresh_registry):
        """Resuming with the WRONG config (different shapes) must raise
        the restore error, not quarantine the healthy step — otherwise
        a config typo walks the entire checkpoint history into
        *.corrupt."""
        d, ckpt, state, _ = self._saved(tmp_path, steps=(1, 2))
        other = _cfg().replace(d_model=128, vocab_size=512)
        bad_abstract = jax.eval_shape(
            lambda: init_train_state(
                other, TrainConfig(warmup_steps=0), jax.random.PRNGKey(0)
            )
        )
        with pytest.raises(ValueError, match="does not match"):
            ckpt.restore(abstract_state=bad_abstract, fallback=True)
        # Nothing was quarantined; the run's history is intact.
        assert ckpt.latest_step() == 2
        assert ckpt.verify(2) is None
        assert not os.path.isdir(os.path.join(d, "2.corrupt"))
        assert not fresh_registry.value(
            "shellac_train_ckpt_quarantined_total")

    def test_requarantine_of_resaved_step_gets_unique_name(
            self, tmp_path, fresh_registry):
        """A step number quarantined, re-saved, and re-corrupted must be
        quarantined AGAIN under a unique name — a silently failed rename
        would leave the bad step selectable as latest forever."""
        d, ckpt, state, abstract = self._saved(tmp_path, steps=(1, 2))
        chaos.scramble_step(d, 2)
        ckpt.restore(abstract_state=abstract, fallback=True)
        assert os.path.isdir(os.path.join(d, "2.corrupt"))
        # Re-save step 2 (healthy again), then corrupt and re-walk.
        ckpt.save(2, state, wait=True)
        assert ckpt.latest_step() == 2
        chaos.scramble_step(d, 2)
        ckpt.restore(abstract_state=abstract, fallback=True)
        assert ckpt.latest_step() == 1
        assert os.path.isdir(os.path.join(d, "2.corrupt.2"))
        # A fresh process sees neither corrupt incarnation as a step.
        ckpt.close()
        ckpt2 = Checkpointer(d)
        assert ckpt2.latest_step() == 1
        ckpt2.close()

    def test_latest_step_on_disk(self, tmp_path):
        from shellac_tpu.training.checkpoint import latest_step_on_disk

        assert latest_step_on_disk(str(tmp_path / "nope")) is None
        d, ckpt, _, _ = self._saved(tmp_path)
        ckpt.close()
        assert latest_step_on_disk(d) == 3
        # Quarantined and debris names never count.
        os.rename(os.path.join(d, "3"), os.path.join(d, "3.corrupt"))
        chaos.fake_interrupted_save(d, 9)
        assert latest_step_on_disk(d) == 2

    def test_structural_corruption_surfaces_original_error(self, tmp_path):
        """The dtype-drift probe must not mask the real failure: a step
        whose item payload is gone raises the ORIGINAL restore error
        (orbax's missing-item KeyError), not an exception from the
        probe's item_metadata call."""
        d, ckpt, _, abstract = self._saved(tmp_path, steps=(1,))
        chaos.drop_item(d, 1)
        with pytest.raises(KeyError, match="default"):
            ckpt.restore(1, abstract_state=abstract)
        ckpt.close()


class TestAnomalySentinel:
    def test_nonfinite_loss_trips_immediately(self):
        s = AnomalySentinel(action="rollback", registry=Registry())
        assert s.observe(1, 1.0) is None
        a = s.observe(2, float("nan"))
        assert a is not None and a.kind == "nonfinite_loss"
        assert a.action == "rollback"

    def test_nonfinite_grad_trips(self):
        s = AnomalySentinel(registry=Registry())
        a = s.observe(1, 1.0, grad_norm=float("inf"))
        assert a is not None and a.kind == "nonfinite_grad"

    def test_spike_needs_warmup(self):
        s = AnomalySentinel(spike_factor=10.0, warmup=5,
                            registry=Registry())
        # Spikes before the EMA warms up are NOT flagged (early
        # training loss moves fast legitimately).
        assert s.observe(1, 1.0) is None
        assert s.observe(2, 50.0) is None
        s2 = AnomalySentinel(spike_factor=10.0, warmup=5,
                             registry=Registry())
        for i in range(6):
            assert s2.observe(i, 2.0) is None
        a = s2.observe(7, 100.0)
        assert a is not None and a.kind == "loss_spike"

    def test_anomalous_losses_never_pollute_ema(self):
        s = AnomalySentinel(action="warn", spike_factor=5.0, warmup=3,
                            registry=Registry())
        for i in range(5):
            s.observe(i, 1.0)
        ema = s.loss_ema
        # A stream of spikes keeps flagging: the reference EMA must not
        # ramp up toward the bad values and go blind.
        for i in range(5, 10):
            assert s.observe(i, 100.0) is not None
        assert s.loss_ema == ema

    def test_patience(self):
        s = AnomalySentinel(patience=2, registry=Registry())
        assert s.observe(1, float("nan")) is None  # first strike
        assert s.observe(2, float("nan")) is not None  # second trips
        # A healthy value in between resets the streak.
        s2 = AnomalySentinel(patience=2, registry=Registry())
        assert s2.observe(1, float("nan")) is None
        assert s2.observe(2, 1.0) is None
        assert s2.observe(3, float("nan")) is None

    def test_budget_escalates_to_fatal(self):
        reg = Registry()
        s = AnomalySentinel(
            action="rollback", budget=RestartBudget(1, window=1000.0),
            registry=reg,
        )
        assert s.observe(1, float("nan")).action == "rollback"
        second = s.observe(2, float("nan"))
        assert second.action == "fatal"
        assert "budget spent" in second.detail
        assert reg.value("shellac_train_anomalies_total",
                         kind="nonfinite_loss", action="rollback") == 1
        assert reg.value("shellac_train_anomalies_total",
                         kind="nonfinite_loss", action="fatal") == 1

    def test_warn_never_escalates(self):
        s = AnomalySentinel(action="warn",
                            budget=RestartBudget(1, window=1000.0),
                            registry=Registry())
        for i in range(5):
            assert s.observe(i, float("nan")).action == "warn"

    def test_detect_flag_split_for_multihost(self):
        """detect() is side-effect-free on anomalies (no budget draw,
        no metrics) so hosts can agree before acting via flag()."""
        reg = Registry()
        s = AnomalySentinel(action="rollback",
                            budget=RestartBudget(1, window=1000.0),
                            registry=reg)
        pending = s.detect(1, float("nan"))
        assert pending is not None
        assert reg.value("shellac_train_anomalies_total",
                         kind="nonfinite_loss",
                         action="rollback") is None
        # A host whose local stream looked fine still acts on the
        # agreed verdict.
        a = s.flag(1, "peer", "anomaly flagged by another host")
        assert a.action == "rollback"
        assert reg.value("shellac_train_anomalies_total", kind="peer",
                         action="rollback") == 1

    def test_reset_clears_detection_not_budget(self):
        s = AnomalySentinel(action="rollback",
                            budget=RestartBudget(1, window=1000.0),
                            registry=Registry())
        assert s.observe(1, float("nan")).action == "rollback"
        s.reset()
        assert s.loss_ema is None
        # The budget survives the reset — otherwise escalation could
        # never trip across rollbacks.
        assert s.observe(2, float("nan")).action == "fatal"

    def test_validation(self):
        with pytest.raises(ValueError):
            AnomalySentinel(action="explode")
        with pytest.raises(ValueError):
            AnomalySentinel(spike_factor=0.5)
        with pytest.raises(ValueError):
            AnomalySentinel(ema_decay=1.5)


class TestFailureTools:
    def test_all_finite(self):
        assert bool(all_finite({"a": jnp.ones(3), "b": jnp.zeros(2)}))
        assert not bool(all_finite({"a": jnp.array([1.0, jnp.nan])}))
        assert not bool(all_finite({"a": jnp.array([jnp.inf])}))
        # int leaves are ignored
        assert bool(all_finite({"a": jnp.array([1, 2, 3])}))

    def test_guard_update(self):
        old = {"w": jnp.zeros(2), "n": jnp.array(0)}
        new = {"w": jnp.ones(2), "n": jnp.array(1)}
        kept = guard_update(old, new, jnp.array(False))
        np.testing.assert_array_equal(np.asarray(kept["w"]), [0.0, 0.0])
        assert int(kept["n"]) == 0
        taken = guard_update(old, new, jnp.array(True))
        np.testing.assert_array_equal(np.asarray(taken["w"]), [1.0, 1.0])

    def test_nan_batch_skips_update(self):
        """A poisoned batch must leave params bit-identical."""
        cfg = _cfg()
        tcfg = TrainConfig(warmup_steps=0, learning_rate=1e-3)
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
        step = make_train_step(cfg, tcfg)
        tokens = jnp.zeros((2, 16), jnp.int32)
        bad = {
            "inputs": tokens,
            "targets": tokens,
            "mask": jnp.full((2, 16), jnp.nan, jnp.float32),
        }
        before = jax.device_get(state.params["embed"])
        state, metrics = step(state, bad)
        assert float(metrics["update_skipped"]) == 1.0
        np.testing.assert_array_equal(before, jax.device_get(state.params["embed"]))

    def test_failure_detector(self):
        det = FailureDetector(patience=2)
        for _ in range(10):
            assert det.check(1.0) is None
        assert det.check(float("nan")) is None  # first strike
        reason = det.check(float("nan"))  # second strike trips
        assert reason is not None and "non-finite" in reason
        det.reset()
        assert det.check(1.0) is None
        # explosion detection
        det2 = FailureDetector(patience=1, loss_explosion_factor=5.0)
        for _ in range(5):
            det2.check(2.0)
        assert det2.check(100.0) is not None

    def test_heartbeat(self, tmp_path):
        path = str(tmp_path / "hb" / "heart.json")
        hb = Heartbeat(path, process_index=0)
        assert hb.age() is None
        hb.beat(7)
        assert hb.age() < 5.0
        assert not Heartbeat.is_stale(path, timeout=60.0)
        assert Heartbeat.is_stale(str(tmp_path / "nope.json"), timeout=1.0)
        # The path-based helper needs no instance at all (external
        # watchdogs call it on files other processes own).
        assert heartbeat_age(path) < 5.0
        assert heartbeat_age(str(tmp_path / "nope.json")) is None
        corrupt = str(tmp_path / "corrupt.json")
        with open(corrupt, "w") as f:
            f.write("{not json")
        assert heartbeat_age(corrupt) is None
        assert Heartbeat.is_stale(corrupt, timeout=60.0)

    def test_restart_budget(self):
        b = RestartBudget(2, window=100.0)
        assert b.used == 0
        assert b.allow(now=0.0)
        assert b.allow(now=1.0)
        assert not b.allow(now=2.0)  # 2 restarts already in window
        assert not b.allow(now=50.0)
        # Both early attempts age out of the sliding window; denied
        # attempts were never recorded, so they don't extend it.
        assert b.allow(now=101.0)
        assert b.allow(now=101.5)
        assert not b.allow(now=102.0)
        # A zero budget never allows (recovery disabled, stay fatal).
        assert not RestartBudget(0, window=10.0).allow(now=0.0)
        with pytest.raises(ValueError):
            RestartBudget(-1)
        with pytest.raises(ValueError):
            RestartBudget(1, window=0.0)


class TestData:
    def test_shard_roundtrip(self, tmp_path):
        toks = np.arange(1000, dtype=np.int32)
        p = str(tmp_path / "shard0.bin")
        write_token_shard(p, toks)
        np.testing.assert_array_equal(read_token_shard(p), toks)

    def test_bad_magic_raises(self, tmp_path):
        p = str(tmp_path / "junk.bin")
        with open(p, "wb") as f:
            f.write(b"JUNKJUNKJUNKJUNKJUNK")
        with pytest.raises(ValueError, match="bad magic"):
            read_token_shard(p)

    def test_token_batches_shapes(self):
        it = token_batches(
            np.arange(500, dtype=np.int32), batch_size=4, seq_len=16, num_batches=3
        )
        batches = list(it)
        assert len(batches) == 3
        for b in batches:
            assert b["inputs"].shape == (4, 16)
            np.testing.assert_array_equal(b["inputs"][:, 1:], b["targets"][:, :-1])

    def test_shard_batches_python_fallback(self, tmp_path):
        paths = []
        for i in range(2):
            p = str(tmp_path / f"s{i}.bin")
            write_token_shard(p, np.arange(300, dtype=np.int32) + 300 * i)
            paths.append(p)
        batches = list(
            shard_batches(paths, batch_size=2, seq_len=8, num_batches=2)
        )
        assert len(batches) == 2
        assert batches[0]["inputs"].dtype == np.int32

    def test_device_prefetch(self):
        it = token_batches(
            np.arange(200, dtype=np.int32), batch_size=2, seq_len=8, num_batches=4
        )
        out = list(device_prefetch(it))
        assert len(out) == 4
        assert isinstance(out[0]["inputs"], jax.Array)

    def test_device_prefetch_abandoned_consumer_frees_worker(self):
        """Closing the generator early must release the prefetch
        thread — a worker parked in q.put() forever leaks into the
        rest of the process (the full-suite segfaults showed one)."""
        import threading
        import time

        before = threading.active_count()
        it = token_batches(
            np.arange(4000, dtype=np.int32), batch_size=2, seq_len=8,
            num_batches=100,
        )
        gen = device_prefetch(it)
        next(gen)  # start the worker, consume one batch
        gen.close()  # abandon mid-stream
        deadline = time.time() + 10
        while threading.active_count() > before and time.time() < deadline:
            time.sleep(0.05)
        assert threading.active_count() <= before, "prefetch thread leaked"


class TestFit:
    def test_fit_end_to_end_with_resume(self, tmp_path):
        cfg = _cfg()
        tcfg = TrainConfig(
            warmup_steps=0, learning_rate=3e-3, total_steps=6
        )
        data = token_batches(
            np.tile(np.arange(32, dtype=np.int32), 50),
            batch_size=2, seq_len=16, num_batches=100,
        )
        ckdir = str(tmp_path / "run")
        state = fit(
            cfg, tcfg, data,
            checkpoint_dir=ckdir, checkpoint_every=3, log_every=2,
            log_path=str(tmp_path / "log.jsonl"),
            heartbeat_path=str(tmp_path / "hb.json"),
        )
        assert int(jax.device_get(state.step)) == 6
        assert os.path.exists(str(tmp_path / "log.jsonl"))

        # Resume: raise total_steps and continue from the saved step 6.
        tcfg2 = tcfg.replace(total_steps=8)
        data2 = token_batches(
            np.tile(np.arange(32, dtype=np.int32), 50),
            batch_size=2, seq_len=16, num_batches=100,
        )
        state2 = fit(cfg, tcfg2, data2, checkpoint_dir=ckdir, log_every=2)
        assert int(jax.device_get(state2.step)) == 8

    def test_fit_warn_action_continues(self, fresh_registry):
        """anomaly_action='warn': the poisoned step is logged and
        counted but training runs to completion (the in-jit guard
        already kept the bad update out of the state)."""
        cfg = _cfg()
        tcfg = TrainConfig(warmup_steps=0, learning_rate=1e-3,
                           total_steps=5)
        data = chaos.poison_batches(
            token_batches(
                np.tile(np.arange(32, dtype=np.int32), 50),
                batch_size=2, seq_len=16, num_batches=100,
            ),
            at_step=3,
        )
        state = fit(cfg, tcfg, data, log_every=1, anomaly_action="warn")
        assert int(jax.device_get(state.step)) == 5
        assert fresh_registry.value(
            "shellac_train_anomalies_total",
            kind="nonfinite_loss", action="warn",
        ) == 1

    def test_fit_fatal_action_raises(self, fresh_registry):
        cfg = _cfg()
        tcfg = TrainConfig(warmup_steps=0, learning_rate=1e-3,
                           total_steps=5)
        data = chaos.poison_batches(
            token_batches(
                np.tile(np.arange(32, dtype=np.int32), 50),
                batch_size=2, seq_len=16, num_batches=100,
            ),
            at_step=3,
        )
        with pytest.raises(RuntimeError, match="action=fatal"):
            fit(cfg, tcfg, data, log_every=1, anomaly_action="fatal")

    def test_fit_rollback_without_checkpoint_is_fatal(self,
                                                      fresh_registry):
        cfg = _cfg()
        tcfg = TrainConfig(warmup_steps=0, learning_rate=1e-3,
                           total_steps=5)
        data = chaos.poison_batches(
            token_batches(
                np.tile(np.arange(32, dtype=np.int32), 50),
                batch_size=2, seq_len=16, num_batches=100,
            ),
            at_step=3,
        )
        with pytest.raises(RuntimeError, match="no checkpoint"):
            fit(cfg, tcfg, data, log_every=1, anomaly_action="rollback")

    def test_fit_heartbeat_beats_at_step_boundary(self, tmp_path):
        """train --heartbeat-file semantics: the loop beats the file at
        step boundaries (1 Hz rate-limited), not just at log
        boundaries — log_every here is larger than the run, and the
        beat still lands."""
        import json as _json

        cfg = _cfg()
        tcfg = TrainConfig(warmup_steps=0, learning_rate=1e-3,
                           total_steps=3)
        data = token_batches(
            np.tile(np.arange(32, dtype=np.int32), 50),
            batch_size=2, seq_len=16, num_batches=100,
        )
        hb = str(tmp_path / "hb.json")
        fit(cfg, tcfg, data, log_every=1000, heartbeat_path=hb)
        with open(hb) as f:
            beat = _json.load(f)
        assert beat["step"] >= 1
        assert heartbeat_age(hb) < 60.0

    def test_fit_sharded(self, mesh_fsdp8):
        cfg = _cfg().replace(d_model=128, vocab_size=512)
        tcfg = TrainConfig(warmup_steps=0, total_steps=3)
        bs = batch_shardings(mesh_fsdp8)
        from shellac_tpu.training.data import device_prefetch, token_batches

        data = device_prefetch(
            token_batches(
                np.arange(5000, dtype=np.int32) % 512,
                batch_size=8, seq_len=16, num_batches=10,
            ),
            sharding=bs,
        )
        state = fit(cfg, tcfg, data, mesh=mesh_fsdp8, log_every=1)
        assert int(jax.device_get(state.step)) == 3
