"""Int8 KV cache: quantization, engine parity, kernel parity, guards."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import ParallelConfig, get_model_config, make_mesh
from shellac_tpu.inference.batching import BatchingEngine, PagedBatchingEngine
from shellac_tpu.inference.engine import Engine, shard_params
from shellac_tpu.inference.kvcache import (
    init_cache,
    init_quant_cache,
    quantize_kv,
)
from shellac_tpu.models import transformer


def _tiny():
    return get_model_config("tiny").replace(dtype="float32")


@pytest.fixture(scope="module")
def model():
    cfg = _tiny()
    return cfg, transformer.init_params(cfg, jax.random.PRNGKey(0))


class TestQuantization:
    def test_roundtrip_error_bound(self, rng):
        x = jnp.asarray(rng.normal(size=(2, 5, 4, 64)), jnp.float32)
        q, s = quantize_kv(x)
        assert q.dtype == jnp.int8 and s.shape == (2, 5, 4)
        back = q.astype(jnp.float32) * s[..., None]
        # Symmetric int8: error <= scale/2 per element.
        assert float(jnp.max(jnp.abs(back - x) / s[..., None])) <= 0.5 + 1e-6

    def test_zero_rows_stable(self):
        q, s = quantize_kv(jnp.zeros((1, 2, 3, 8)))
        assert float(jnp.abs(q).max()) == 0
        assert float(s.min()) == 1.0  # no div-by-zero scale


class TestForwardParity:
    def test_cached_forward_tracks_bf16(self, model):
        """Prefill + decode with the int8 cache stays close to exact."""
        cfg, params = model
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab_size
        )
        nl = jnp.array([12, 12], jnp.int32)

        def run(cache):
            logits, cache = transformer.forward_with_cache(
                cfg, params, toks, cache, fresh_cache=True, new_tokens_len=nl
            )
            cur = jnp.argmax(logits[:, -1], -1)
            outs = [cur]
            for _ in range(6):
                logits, cache = transformer.forward_with_cache(
                    cfg, params, cur[:, None], cache
                )
                cur = jnp.argmax(logits[:, 0], -1)
                outs.append(cur)
            return jnp.stack(outs, 1), logits

        t_ref, l_ref = run(init_cache(cfg, 2, 64))
        t_q, l_q = run(init_quant_cache(cfg, 2, 64))
        np.testing.assert_array_equal(np.asarray(t_q), np.asarray(t_ref))
        assert float(jnp.max(jnp.abs(l_q - l_ref))) < 0.05

    def test_kernel_parity_with_scales(self, rng):
        """Interpret-mode quant kernel == dequantized reference."""
        from shellac_tpu.ops.decode_attention import (
            _decode_ref,
            decode_attention,
        )

        B, L, H, HKV, D = 2, 256, 8, 4, 128
        ks = jax.random.split(jax.random.PRNGKey(3), 3)
        q = jax.random.normal(ks[0], (B, 1, H, D), jnp.float32)
        kf = jax.random.normal(ks[1], (B, L, HKV, D), jnp.float32)
        vf = jax.random.normal(ks[2], (B, L, HKV, D), jnp.float32)
        kq, ksc = quantize_kv(kf)
        vq, vsc = quantize_kv(vf)
        # head-major (B, Hkv, L, D) cache + (B, Hkv, L) scales
        ck, cv = kq.transpose(0, 2, 1, 3), vq.transpose(0, 2, 1, 3)
        kscale, vscale = ksc.transpose(0, 2, 1), vsc.transpose(0, 2, 1)
        index = jnp.array([19, L - 1], jnp.int32)
        for window in (None, 40):
            out = decode_attention(
                q, ck, cv, index, window=window, impl="flash",
                interpret=True, k_scale=kscale, v_scale=vscale,
            )
            ref = _decode_ref(
                q, ck, cv, index, window, D ** -0.5,
                k_scale=kscale, v_scale=vscale,
            )
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
            )

    def test_flash_rejects_quant_dh64(self):
        from shellac_tpu.ops.decode_attention import decode_attention

        q = jnp.zeros((1, 1, 4, 64))
        ck = jnp.zeros((1, 4, 128, 64), jnp.int8)
        sc = jnp.ones((1, 4, 128))
        with pytest.raises(ValueError, match="unsupported"):
            decode_attention(
                q, ck, ck, jnp.zeros((1,), jnp.int32), impl="flash",
                k_scale=sc, v_scale=sc,
            )


class TestEngines:
    def test_batching_matches_single_request(self, model):
        """Both engines quantize at the same write points, so greedy
        outputs are bit-identical between them (the serving parity
        invariant, kept under kv_quant)."""
        cfg, params = model
        rng = np.random.default_rng(5)
        prompts = [rng.integers(1, cfg.vocab_size, size=n).tolist()
                   for n in (3, 7, 5, 9)]
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             kv_quant="int8")
        got = eng.run([(i, p, 8) for i, p in enumerate(prompts)])

        single = Engine(cfg, params, temperature=0.0, max_len=64,
                        kv_quant="int8")
        for i, p in enumerate(prompts):
            res = single.generate(
                jnp.asarray([p], jnp.int32), max_new_tokens=8
            )
            assert got[i] == np.asarray(res.tokens)[0].tolist(), i

    def test_chunked_prefill_parity(self, model):
        cfg, params = model
        rng = np.random.default_rng(6)
        prompts = [rng.integers(1, cfg.vocab_size, size=40).tolist(),
                   rng.integers(1, cfg.vocab_size, size=23).tolist()]
        want = BatchingEngine(
            cfg, params, n_slots=2, max_len=96, kv_quant="int8"
        ).run([(i, p, 6) for i, p in enumerate(prompts)])
        got = BatchingEngine(
            cfg, params, n_slots=2, max_len=96, kv_quant="int8",
            prefill_chunk=16,
        ).run([(i, p, 6) for i, p in enumerate(prompts)])
        assert got == want

    def test_sharded_quant_engine(self, model):
        cfg, params = model
        mesh = make_mesh(ParallelConfig(dp=2, tp=4))
        sharded = shard_params(cfg, params, mesh)
        want = BatchingEngine(
            cfg, params, n_slots=2, max_len=64, kv_quant="int8"
        ).run([(0, [3, 5, 7], 6)])
        got = BatchingEngine(
            cfg, sharded, n_slots=2, max_len=64, kv_quant="int8", mesh=mesh
        ).run([(0, [3, 5, 7], 6)])
        assert got == want

    def test_guards(self, model):
        cfg, params = model
        # Int8 paged pools exist now; the remaining guard is the page
        # alignment (int8 sublane tiling), an actionable config error.
        # An unset block_size auto-resolves to the aligned 64, so the
        # guard only fires on an EXPLICIT misaligned page size.
        with pytest.raises(ValueError, match="block_size % 32"):
            PagedBatchingEngine(cfg, params, kv_quant="int8",
                                block_size=16)
        assert PagedBatchingEngine(
            cfg, params, kv_quant="int8"
        ).block_size == 64
        # spec x int8 is no longer excluded (the verify round reads
        # the same write-then-read int8 bits sequential decode does);
        # composition is pinned in test_spec_batching.py and the
        # cross-backend parity matrix in test_cache_backends.py.
        with pytest.raises(ValueError, match="kv_quant"):
            BatchingEngine(cfg, params, kv_quant="fp4")


class TestTwoStackInt8:
    """Int8 KV over the two-stack layer layouts (DeepSeek's
    first_k_dense and moe_every interleaving) — previously guarded
    out; now the quant scan mirrors the bf16 stack split."""

    @pytest.mark.parametrize("preset", ["tiny-deepseek",
                                        "tiny-moe-interleaved"])
    def test_batching_matches_single_request(self, preset):
        cfg = get_model_config(preset).replace(dtype="float32")
        if cfg.moe is not None and not cfg.moe.dropless:
            # Parity asserts need dropless MoE: routed capacity depends
            # on the padded token count, which differs between the
            # batching engine's buckets and the single-request pad.
            import dataclasses

            cfg = cfg.replace(
                moe=dataclasses.replace(cfg.moe, dropless=True)
            )
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(11)
        prompts = [rng.integers(1, cfg.vocab_size, size=n).tolist()
                   for n in (3, 9, 5)]
        got = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             kv_quant="int8").run(
            [(i, p, 6) for i, p in enumerate(prompts)]
        )
        single = Engine(cfg, params, temperature=0.0, max_len=64,
                        kv_quant="int8")
        for i, p in enumerate(prompts):
            res = single.generate(jnp.asarray([p], jnp.int32),
                                  max_new_tokens=6)
            assert got[i] == np.asarray(res.tokens)[0].tolist(), (preset, i)

    def test_deepseek_tracks_bf16(self):
        """Int8 rounding stays small on the DeepSeek latent + two-stack
        path: greedy tokens match bf16 on a short horizon."""
        cfg = get_model_config("tiny-deepseek").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jnp.asarray([[7, 23, 5, 11]], jnp.int32)
        exact = Engine(cfg, params, temperature=0.0,
                       max_len=64).generate(prompt, max_new_tokens=6)
        quant = Engine(cfg, params, temperature=0.0, max_len=64,
                       kv_quant="int8").generate(prompt, max_new_tokens=6)
        assert (np.asarray(exact.tokens) == np.asarray(quant.tokens)).all()


class TestPagedInt8:
    def test_paged_matches_single_request(self, model):
        """The serving parity invariant under the int8 pool: greedy
        outputs bit-identical to the single-request engine with the
        SAME cache quantization (both quantize at write, both
        dequantize the read path)."""
        cfg, params = model
        rng = np.random.default_rng(7)
        prompts = [rng.integers(1, cfg.vocab_size, size=n).tolist()
                   for n in (3, 37, 5, 61)]
        eng = PagedBatchingEngine(
            cfg, params, n_slots=2, max_len=96, block_size=32,
            kv_quant="int8",
        )
        got = eng.run([(i, p, 8) for i, p in enumerate(prompts)])
        single = Engine(cfg, params, temperature=0.0, max_len=96,
                        kv_quant="int8")
        for i, p in enumerate(prompts):
            res = single.generate(
                jnp.asarray([p], jnp.int32), max_new_tokens=8
            )
            assert got[i] == np.asarray(res.tokens)[0].tolist(), i

    def test_prefix_cache_composes(self, model):
        """Prefix-cached int8 pool: bit-identical outputs with real
        block reuse (scales ride with their blocks)."""
        cfg, params = model
        rng = np.random.default_rng(8)
        shared = rng.integers(1, cfg.vocab_size, size=64).tolist()
        reqs = [(i, shared + rng.integers(1, cfg.vocab_size, size=5).tolist(), 6)
                for i in range(4)]
        plain = PagedBatchingEngine(
            cfg, params, n_slots=2, max_len=128, block_size=32,
            kv_quant="int8",
        ).run(reqs)
        cached_eng = PagedBatchingEngine(
            cfg, params, n_slots=2, max_len=128, block_size=32,
            kv_quant="int8", prefix_cache=True,
        )
        cached = cached_eng.run(reqs)
        assert cached == plain
        assert cached_eng.stats["prefix_hit_tokens"] > 0

    def test_grouped_kernel_parity_interpret(self, rng):
        """Interpret-mode int8 grouped-gather kernel == gathered
        dequantized reference."""
        from shellac_tpu.inference.kvcache import (
            paged_gather_layer,
            paged_gather_scales,
        )
        from shellac_tpu.ops.decode_attention import (
            _decode_ref,
            paged_decode_attention,
        )

        B, H, HKV, D, bs, mb = 2, 8, 4, 128, 32, 8
        n_blocks = B * mb + 1
        ks = jax.random.split(jax.random.PRNGKey(9), 3)
        q = jax.random.normal(ks[0], (B, 1, H, D), jnp.float32)
        kf = jax.random.normal(ks[1], (n_blocks, bs, HKV, D), jnp.float32)
        vf = jax.random.normal(ks[2], (n_blocks, bs, HKV, D), jnp.float32)
        kq, ksc = quantize_kv(kf)
        vq, vsc = quantize_kv(vf)
        pool_k = kq.transpose(0, 2, 1, 3)  # (nb, HKV, bs, D) int8
        pool_v = vq.transpose(0, 2, 1, 3)
        pks = ksc.transpose(0, 2, 1)  # (nb, HKV, bs)
        pvs = vsc.transpose(0, 2, 1)
        perm = np.random.default_rng(0).permutation(n_blocks - 1) + 1
        tables = jnp.asarray(perm.reshape(B, mb), jnp.int32)
        index = jnp.array([45, mb * bs - 1], jnp.int32)
        for window in (None, 70):
            out = paged_decode_attention(
                q, pool_k, pool_v, tables, index, window=window,
                impl="flash", interpret=True, k_scale=pks, v_scale=pvs,
            )
            k_all, v_all = paged_gather_layer(pool_k, pool_v, tables)
            ref = _decode_ref(
                q, k_all, v_all, index, window, D ** -0.5,
                k_scale=paged_gather_scales(pks, tables),
                v_scale=paged_gather_scales(pvs, tables),
            )
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), atol=2e-5, rtol=2e-5
            )

    def test_chunked_prefill_parity(self, model):
        cfg, params = model
        rng = np.random.default_rng(10)
        prompts = [rng.integers(1, cfg.vocab_size, size=40).tolist(),
                   rng.integers(1, cfg.vocab_size, size=23).tolist()]
        want = PagedBatchingEngine(
            cfg, params, n_slots=2, max_len=96, block_size=32,
            kv_quant="int8",
        ).run([(i, p, 6) for i, p in enumerate(prompts)])
        got = PagedBatchingEngine(
            cfg, params, n_slots=2, max_len=96, block_size=32,
            kv_quant="int8", prefill_chunk=16,
        ).run([(i, p, 6) for i, p in enumerate(prompts)])
        assert got == want
