"""Regenerate the committed trace-report fixture captures.

Two small, deterministic Chrome-trace captures shaped like a TPU
`jax.profiler` dump (a `/device:TPU:0` process with an "XLA Ops"
thread, op events carrying `hlo_module` args, a host process with
python-function events):

  decode_base.trace.json.gz       the healthy baseline: the decode
      window's time runs mostly inside one big fusion, prefill is a
      small share, a little unattributed copy traffic.
  decode_regressed.trace.json.gz  the same workload with an INJECTED
      regression: the decode fusion broken apart into add/multiply/
      reduce (more distinct ops, less fused time), the dot 40%
      slower, and a new convert op — the three regression classes
      `trace-report --diff` exists to flag.

Run `python tests/fixtures/make_trace_fixtures.py` to rewrite both
files byte-identically (gzip mtime pinned to 0); the test suite
asserts the diff flags the regressed capture and passes the base
against itself.
"""

import gzip
import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))

_DEVICE_PID = 1
_HOST_PID = 9


def _meta():
    return [
        {"ph": "M", "pid": _DEVICE_PID, "name": "process_name",
         "args": {"name": "/device:TPU:0"}},
        {"ph": "M", "pid": _DEVICE_PID, "tid": 1,
         "name": "thread_name", "args": {"name": "XLA Ops"}},
        {"ph": "M", "pid": _HOST_PID, "name": "process_name",
         "args": {"name": "/host:CPU"}},
        {"ph": "M", "pid": _HOST_PID, "tid": 1, "name": "thread_name",
         "args": {"name": "python3"}},
    ]


def _ops(rows):
    """rows: (name, module, count, dur_us) -> laid-out X events."""
    events = []
    ts = 1000.0
    for name, module, count, dur in rows:
        for _ in range(count):
            ev = {"ph": "X", "pid": _DEVICE_PID, "tid": 1,
                  "ts": round(ts, 1), "dur": float(dur), "name": name}
            if module:
                ev["args"] = {"hlo_module": module}
            events.append(ev)
            ts += dur + 1.0
    return events


def _host_events():
    return [
        {"ph": "X", "pid": _HOST_PID, "tid": 1, "ts": 900.0,
         "dur": 50000.0, "name": "$batching.py:1596 step"},
        {"ph": "X", "pid": _HOST_PID, "tid": 1, "ts": 950.0,
         "dur": 400.0, "name": "$batching.py:1269 _fill_slots"},
    ]


BASE_OPS = [
    # The decode window: one dominant fusion + matmul + cache write.
    ("%fusion.1", "jit__decode_impl", 40, 100.0),
    ("%dot.3", "jit__decode_impl", 40, 50.0),
    ("%dynamic-update-slice.4", "jit__decode_impl", 40, 10.0),
    # Prefill programs: their own fusion + matmul.
    ("%fusion.2", "jit__prefill_impl", 4, 300.0),
    ("%dot.5", "jit__prefill_impl", 4, 100.0),
    # Unattributed device traffic (no module tag).
    ("%copy.6", None, 10, 20.0),
]

REGRESSED_OPS = [
    # INJECTED: the decode fusion broke apart (three distinct ops,
    # slower in aggregate than the fusion they replace)...
    ("%add.7", "jit__decode_impl", 40, 60.0),
    ("%multiply.8", "jit__decode_impl", 40, 50.0),
    ("%reduce.9", "jit__decode_impl", 40, 40.0),
    # ... the dot regressed 40% ...
    ("%dot.3", "jit__decode_impl", 40, 70.0),
    ("%dynamic-update-slice.4", "jit__decode_impl", 40, 10.0),
    ("%fusion.2", "jit__prefill_impl", 4, 300.0),
    ("%dot.5", "jit__prefill_impl", 4, 100.0),
    ("%copy.6", None, 10, 20.0),
    # ... and a new op appeared.
    ("%convert.11", "jit__decode_impl", 5, 30.0),
]


def _write(name, rows):
    doc = {
        "displayTimeUnit": "ns",
        "metadata": {"highres-ticks": True},
        "traceEvents": _meta() + _host_events() + _ops(rows),
    }
    data = json.dumps(doc, sort_keys=True).encode()
    path = os.path.join(HERE, name)
    # mtime=0 keeps the gzip byte-stable across regenerations.
    with open(path, "wb") as f:
        f.write(gzip.compress(data, mtime=0))
    print(f"wrote {path} ({os.path.getsize(path)} bytes)")


def main():
    _write("decode_base.trace.json.gz", BASE_OPS)
    _write("decode_regressed.trace.json.gz", REGRESSED_OPS)


if __name__ == "__main__":
    main()
