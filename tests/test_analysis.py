"""Tests for shellac_tpu.analysis: each SH rule triggers on a fixture,
stays quiet on the fixed form, respects suppressions — and the live
tree is lint-clean (the meta-test that keeps it that way)."""

from pathlib import Path

import pytest

from shellac_tpu.analysis import lint_files, lint_paths
from shellac_tpu.analysis.cli import main as lint_main

REPO = Path(__file__).resolve().parents[1]


def codes(findings):
    return sorted({f.rule for f in findings})


def lint_snippet(source, filename="mod.py", **kw):
    return lint_files({filename: source}, **kw)


# ---- SH001 missing donation ----------------------------------------


SH001_CALL = """
import jax

def train_step(state, batch):
    return state

step = jax.jit(train_step)
"""

SH001_DECORATED = """
import functools
import jax

@jax.jit
def decode_step(cache, tok):
    return cache
"""


def test_sh001_jit_call_without_donation():
    assert codes(lint_snippet(SH001_CALL)) == ["SH001"]


def test_sh001_decorator_without_donation():
    assert codes(lint_snippet(SH001_DECORATED)) == ["SH001"]


def test_sh001_donated_is_clean():
    fixed = SH001_CALL.replace(
        "jax.jit(train_step)", "jax.jit(train_step, donate_argnums=(0,))"
    )
    assert lint_snippet(fixed) == []


def test_sh001_partial_decorator_donated_is_clean():
    src = """
import functools
import jax

@functools.partial(jax.jit, donate_argnums=(0,))
def train_step(state, batch):
    return state
"""
    assert lint_snippet(src) == []


def test_sh001_resolves_through_partial_and_methods():
    src = """
import functools
import jax

class Engine:
    def _prefill_impl(self, params, cache):
        return cache

    def build(self):
        return jax.jit(functools.partial(self._prefill_impl, 0))
"""
    assert codes(lint_snippet(src)) == ["SH001"]


def test_sh001_non_state_function_not_flagged():
    src = """
import jax

def helper(x):
    return x

fn = jax.jit(helper)
"""
    assert lint_snippet(src) == []


# ---- SH002 host sync ------------------------------------------------


SH002_JIT = """
import jax
import numpy as np

def decode_body(cache, tok):
    n = int(cache.lengths.item())
    host = np.asarray(tok)
    return cache

fn = jax.jit(decode_body, donate_argnums=(0,))
"""


def test_sh002_host_sync_in_jitted_body():
    found = lint_snippet(SH002_JIT, select=["SH002"])
    assert codes(found) == ["SH002"]
    assert len(found) == 2  # .item() and np.asarray


def test_sh002_host_side_sync_is_fine():
    src = """
import numpy as np

def collect(out):
    return np.asarray(out).tolist()
"""
    assert lint_snippet(src, select=["SH002"]) == []


def test_sh002_sync_inside_decode_loop():
    src = """
import jax

def run_decode(engine, steps):
    out = []
    for _ in range(steps):
        tok = engine.step()
        out.append(jax.device_get(tok))
    return out
"""
    found = lint_snippet(src, select=["SH002"])
    assert codes(found) == ["SH002"]


def test_sh002_single_sync_outside_loop_is_fine():
    # The engine's designed idiom: K ticks on device, ONE sync after.
    src = """
import jax

def step_decode(engine):
    toks = engine.ticks()
    return jax.device_get(toks)
"""
    assert lint_snippet(src, select=["SH002"]) == []


SH002_ENGINE_PATH = """
import jax

class DemoEngine:
    def step(self):
        self._admit()
        return self._decode_tokens()

    def _admit(self):
        first = self._prefill()
        # Loop-free, helper-deep: invisible to the old loop heuristic.
        return jax.device_get(first)

    def _decode_tokens(self):
        return jax.device_get(self._w)

    def offline_report(self):
        # NOT reachable from step/_decode_tokens: no finding.
        return jax.device_get(self._w)
"""


def test_sh002_engine_call_path_flags_helper_syncs():
    """A sync anywhere on an Engine class's step()-reachable call-path
    is a per-window/per-admission round trip — flagged without needing
    a loop around it (the per-prefill top-logprobs pull hid exactly
    this way)."""
    found = lint_snippet(SH002_ENGINE_PATH, select=["SH002"])
    assert codes(found) == ["SH002"] and len(found) == 2
    assert all("call-path" in f.message for f in found)


def test_sh002_engine_call_path_subclass_override():
    """A subclass hook reached through an inherited step() is on the
    path too (module-local MRO merge)."""
    src = SH002_ENGINE_PATH + """

class PagedDemoEngine(DemoEngine):
    def _prefill(self):
        return jax.device_get(self._scratch)
"""
    found = lint_snippet(src, select=["SH002"])
    assert len(found) == 3
    assert any("PagedDemoEngine" in f.message for f in found)


def test_sh002_engine_call_path_respects_suppression():
    src = SH002_ENGINE_PATH.replace(
        "return jax.device_get(self._w)\n\n    def offline_report",
        "return jax.device_get(self._w)  "
        "# shellac: ignore[SH002] — the one designed sync\n\n"
        "    def offline_report",
    )
    found = lint_snippet(src, select=["SH002"])
    assert len(found) == 1  # only the _admit pull remains


def test_sh002_non_engine_class_step_not_flagged():
    src = """
import jax

class Router:
    def step(self):
        return jax.device_get(self._x)
"""
    assert lint_snippet(src, select=["SH002"]) == []


# ---- SH003 trace-time nondeterminism -------------------------------


def test_sh003_np_random_in_scan_body():
    src = """
import jax
import numpy as np

def outer(xs):
    def body(carry, x):
        noise = np.random.uniform()
        return carry + x + noise, x
    return jax.lax.scan(body, 0.0, xs)
"""
    assert codes(lint_snippet(src, select=["SH003"])) == ["SH003"]


def test_sh003_time_in_jitted_fn():
    src = """
import time
import jax

@jax.jit
def train_step(state):
    t = time.time()
    return state
"""
    found = lint_snippet(src, select=["SH003"])
    assert codes(found) == ["SH003"]


def test_sh003_jax_random_is_the_fix_not_the_hazard():
    src = """
import jax
from jax import random

@jax.jit
def train_step(state, key):
    key, sub = random.split(key)
    return state, jax.random.normal(sub, (4,))
"""
    assert lint_snippet(src, select=["SH003"]) == []


def test_sh003_host_side_rng_is_fine():
    src = """
import numpy as np

def make_batch(seed):
    return np.random.default_rng(seed).integers(0, 10, (8,))
"""
    assert lint_snippet(src, select=["SH003"]) == []


# ---- SH004 debug leftovers -----------------------------------------


SH004 = """
import jax

def forward(x):
    jax.debug.print("x = {}", x)
    breakpoint()
    return x
"""


def test_sh004_debug_aids_flagged():
    found = lint_snippet(SH004, select=["SH004"])
    assert codes(found) == ["SH004"]
    assert len(found) == 2


def test_sh004_allowed_in_tests():
    assert lint_snippet(SH004, filename="tests/test_forward.py") == []
    assert lint_snippet(SH004, filename="test_forward.py") == []


def test_sh004_pdb_import():
    found = lint_snippet("import pdb\n", select=["SH004"])
    assert codes(found) == ["SH004"]


# ---- SH005 set-iteration order -------------------------------------


def test_sh005_set_literal_iteration():
    src = """
def build(tree):
    return [tree[k] for k in {"a", "b"}]
"""
    assert codes(lint_snippet(src, select=["SH005"])) == ["SH005"]


def test_sh005_set_call_iteration():
    src = """
def build(names):
    out = {}
    for n in set(names):
        out[n] = 1
    return out
"""
    assert codes(lint_snippet(src, select=["SH005"])) == ["SH005"]


def test_sh005_sorted_set_is_clean():
    src = """
def build(names):
    return {n: 1 for n in sorted(set(names))}
"""
    assert lint_snippet(src, select=["SH005"]) == []


# ---- SH006 dead config fields --------------------------------------


SH006_CONFIG = """
from dataclasses import dataclass

@dataclass(frozen=True)
class ModelConfig:
    d_model: int = 512
    dead_flag: bool = False
    validated_only: bool = False

    def validate(self):
        if self.validated_only:
            raise ValueError("nope")
        return self
"""

SH006_USER = """
def width(cfg):
    return cfg.d_model * 4
"""


def test_sh006_dead_and_validate_only_fields():
    found = lint_files(
        {"pkg/config.py": SH006_CONFIG, "pkg/model.py": SH006_USER},
        select=["SH006"],
    )
    flagged = sorted(f.message.split()[2] for f in found)
    assert codes(found) == ["SH006"]
    assert flagged == [
        "ModelConfig.dead_flag", "ModelConfig.validated_only",
    ]


def test_sh006_getattr_read_counts():
    user = SH006_USER + """
def flag(cfg):
    return getattr(cfg, "dead_flag")

def other(cfg):
    return cfg.validated_only
"""
    found = lint_files(
        {"pkg/config.py": SH006_CONFIG, "pkg/model.py": user},
        select=["SH006"],
    )
    assert found == []


def test_sh006_no_config_file_no_findings():
    assert lint_snippet(SH006_USER, select=["SH006"]) == []


# ---- SH007 sharding-constraint asymmetry ---------------------------


SH007 = """
from shellac_tpu.parallel.sharding import constrain

def prefill_attn(x, mesh):
    return constrain(x, mesh, ("batch", "seq", None))

def decode_attn(x, mesh):
    return x
"""


def test_sh007_asymmetric_pair_flagged():
    found = lint_snippet(SH007, select=["SH007"])
    assert codes(found) == ["SH007"]
    assert len(found) == 1
    assert "decode_attn" in found[0].message


def test_sh007_symmetric_pair_clean():
    fixed = SH007.replace(
        "def decode_attn(x, mesh):\n    return x",
        "def decode_attn(x, mesh):\n"
        "    return constrain(x, mesh, (\"batch\", None, None))",
    )
    assert lint_snippet(fixed, select=["SH007"]) == []


def test_sh007_fwd_bwd_pair():
    src = """
import jax

def attn_fwd(x):
    return jax.lax.with_sharding_constraint(x, None)

def attn_bwd(g):
    return g
"""
    found = lint_snippet(src, select=["SH007"])
    assert codes(found) == ["SH007"]
    assert "attn_bwd" in found[0].message


# ---- suppressions ---------------------------------------------------


def test_line_suppression():
    src = SH001_CALL.replace(
        "step = jax.jit(train_step)",
        "step = jax.jit(train_step)  # shellac: ignore[SH001]",
    )
    assert lint_snippet(src) == []


def test_line_suppression_is_rule_specific():
    src = SH001_CALL.replace(
        "step = jax.jit(train_step)",
        "step = jax.jit(train_step)  # shellac: ignore[SH004]",
    )
    assert codes(lint_snippet(src)) == ["SH001"]


def test_file_level_suppression():
    src = "# shellac: ignore[SH001]\n" + SH001_CALL
    assert lint_snippet(src) == []


def test_file_level_suppression_multiple_rules():
    src = "# shellac: ignore[SH001, SH004]\n" + SH001_CALL + SH004
    assert lint_snippet(src) == []


def test_marker_inside_string_literal_does_not_suppress():
    # A suppression marker embedded in a string (e.g. worker source code
    # built inside a test) must not silence rules in the enclosing file.
    src = (
        'WORKER_SRC = "# shellac: ignore[SH001]"\n'
        + SH001_CALL
    )
    assert codes(lint_snippet(src)) == ["SH001"]


def test_marker_at_column_zero_inside_multiline_string():
    src = (
        'WORKER_SRC = """\n'
        "# shellac: ignore[SH001]\n"
        '"""\n'
        + SH001_CALL
    )
    assert codes(lint_snippet(src)) == ["SH001"]


# ---- engine plumbing ------------------------------------------------


def test_parse_error_is_reported():
    found = lint_snippet("def broken(:\n")
    assert codes(found) == ["SH000"]


def test_unknown_rule_code_raises():
    with pytest.raises(KeyError):
        lint_snippet("x = 1\n", select=["SH999"])


def test_select_and_ignore():
    src = SH001_CALL + SH004
    assert codes(lint_snippet(src)) == ["SH001", "SH004"]
    assert codes(lint_snippet(src, select=["SH004"])) == ["SH004"]
    assert codes(lint_snippet(src, ignore=["SH004"])) == ["SH001"]


def test_findings_are_sorted_and_located():
    found = lint_snippet(SH001_CALL)
    assert found == sorted(found)
    f = found[0]
    assert f.path == "mod.py" and f.line > 1 and f.col >= 1


# ---- CLI ------------------------------------------------------------


ALL_RULE_FIXTURES = {
    "sh001.py": SH001_CALL,
    "sh002.py": SH002_JIT,
    "sh003.py": """
import time
import jax

@jax.jit
def train_step(state):
    return state, time.time()
""",
    "sh004.py": SH004,
    "sh005.py": "vals = [k for k in {'a', 'b'}]\n",
    "config.py": SH006_CONFIG,
    "sh007.py": SH007,
}


@pytest.fixture(scope="module")
def fixture_tree(tmp_path_factory):
    root = tmp_path_factory.mktemp("lint_fixtures")
    for name, src in ALL_RULE_FIXTURES.items():
        (root / name).write_text(src)
    return root


def test_cli_exits_nonzero_on_each_rule(fixture_tree, capsys):
    rc = lint_main([str(fixture_tree)])
    out = capsys.readouterr().out
    assert rc == 1
    for code in ["SH001", "SH002", "SH003", "SH004", "SH005", "SH006",
                 "SH007"]:
        assert code in out, f"{code} missing from CLI output"


def test_cli_each_rule_fixture_fails_alone(fixture_tree):
    # config.py rides along for SH006 (a project rule needs it), but
    # every fixture must fail on its own rule via --select.
    by_rule = {
        "SH001": "sh001.py", "SH002": "sh002.py", "SH003": "sh003.py",
        "SH004": "sh004.py", "SH005": "sh005.py", "SH007": "sh007.py",
    }
    for code, name in by_rule.items():
        rc = lint_main([str(fixture_tree / name), "--select", code])
        assert rc == 1, f"{code} fixture did not fail"
    rc = lint_main([str(fixture_tree / "config.py"), "--select", "SH006"])
    assert rc == 1, "SH006 fixture did not fail"


def test_cli_json_report(fixture_tree, capsys):
    import json

    rc = lint_main([str(fixture_tree), "--format", "json"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert report["version"] == 1
    assert report["summary"]["findings"] == len(report["findings"])
    assert set(report["summary"]["by_rule"]) >= {"SH001", "SH006"}
    f = report["findings"][0]
    assert {"rule", "path", "line", "col", "message"} <= set(f)


def test_cli_clean_exit_zero(tmp_path, capsys):
    (tmp_path / "ok.py").write_text("x = 1\n")
    assert lint_main([str(tmp_path)]) == 0
    assert "clean" in capsys.readouterr().out


def test_cli_missing_path_exit_two(tmp_path):
    assert lint_main([str(tmp_path / "nope.xyz")]) == 2


def test_cli_list_rules(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for code in ["SH001", "SH002", "SH003", "SH004", "SH005", "SH006",
                 "SH007"]:
        assert code in out


# ---- lint_report.py diffing ----------------------------------------


def test_lint_report_diff(tmp_path):
    import json
    import subprocess
    import sys

    base = {"version": 1, "findings": [
        {"rule": "SH004", "path": "a.py", "line": 3, "col": 1,
         "message": "old"},
    ]}
    cur = {"version": 1, "findings": [
        {"rule": "SH004", "path": "a.py", "line": 9, "col": 1,
         "message": "old"},
        {"rule": "SH001", "path": "b.py", "line": 2, "col": 1,
         "message": "fresh"},
    ]}
    bp, cp = tmp_path / "base.json", tmp_path / "cur.json"
    bp.write_text(json.dumps(base))
    cp.write_text(json.dumps(cur))
    script = REPO / "scripts" / "lint_report.py"

    r = subprocess.run(
        [sys.executable, str(script), str(bp), str(cp), "--fail-on-new"],
        capture_output=True, text=True,
    )
    assert r.returncode == 1
    assert "fresh" in r.stdout
    # A finding that only moved lines is not "new".
    r = subprocess.run(
        [sys.executable, str(script), str(cp), str(cp), "--fail-on-new"],
        capture_output=True, text=True,
    )
    assert r.returncode == 0


# ---- the meta-test: the live tree stays clean ----------------------


def test_live_tree_is_lint_clean():
    findings = lint_paths([str(REPO / "shellac_tpu")])
    assert findings == [], "\n".join(f.render() for f in findings)


def test_scripts_and_bench_are_lint_clean():
    findings = lint_paths(
        [str(REPO / "scripts"), str(REPO / "bench.py")]
    )
    assert findings == [], "\n".join(f.render() for f in findings)
