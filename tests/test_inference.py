"""Inference-path tests: cache parity with full forward, sampling, engine."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference import Engine, init_cache
from shellac_tpu.models import transformer
from shellac_tpu.ops.sampling import sample, top_k_mask, top_p_mask


def _cfg():
    return get_model_config("tiny").replace(dtype="float32")


class TestCachedForward:
    def test_prefill_matches_full_forward(self):
        cfg = _cfg()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 12), 0, cfg.vocab_size)
        full = transformer.forward(cfg, params, tokens)
        cache = init_cache(cfg, 2, 32)
        cached, cache = transformer.forward_with_cache(cfg, params, tokens, cache)
        np.testing.assert_allclose(
            np.asarray(full), np.asarray(cached), rtol=1e-4, atol=1e-5
        )
        assert np.all(np.asarray(cache.lengths) == 12)

    def test_incremental_decode_matches_full(self):
        """Prefill + token-by-token decode == one full forward pass."""
        cfg = _cfg()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (1, 10), 0, cfg.vocab_size)
        full = transformer.forward(cfg, params, tokens)

        cache = init_cache(cfg, 1, 16)
        _, cache = transformer.forward_with_cache(cfg, params, tokens[:, :4], cache)
        outs = []
        for i in range(4, 10):
            logits, cache = transformer.forward_with_cache(
                cfg, params, tokens[:, i : i + 1], cache
            )
            outs.append(logits[:, 0])
        got = jnp.stack(outs, axis=1)
        np.testing.assert_allclose(
            np.asarray(full[:, 4:]), np.asarray(got), rtol=1e-4, atol=1e-4
        )

    def test_ragged_prefill_matches_per_sequence(self):
        """Right-padded ragged batch decodes like each sequence alone."""
        cfg = _cfg()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        t_short = jax.random.randint(jax.random.PRNGKey(1), (1, 5), 0, cfg.vocab_size)
        pad = jnp.zeros((1, 3), jnp.int32)
        batch_tokens = jnp.concatenate(
            [jnp.concatenate([t_short, pad], 1), jnp.zeros((1, 8), jnp.int32)], 0
        )
        batch_tokens = batch_tokens.at[1].set(
            jax.random.randint(jax.random.PRNGKey(2), (8,), 0, cfg.vocab_size)
        )
        lengths = jnp.array([5, 8], jnp.int32)

        cache = init_cache(cfg, 2, 16)
        logits, cache = transformer.forward_with_cache(
            cfg, params, batch_tokens, cache, new_tokens_len=lengths
        )
        # Sequence 0's logits at its last real position must match the
        # unbatched forward of just its 5 tokens.
        solo = transformer.forward(cfg, params, t_short)
        np.testing.assert_allclose(
            np.asarray(logits[0, 4]), np.asarray(solo[0, 4]), rtol=1e-4, atol=1e-4
        )
        # Decode one step for both: seq 0 writes at slot 5 (over pad).
        nxt = jnp.array([[3], [7]], jnp.int32)
        logits2, cache = transformer.forward_with_cache(cfg, params, nxt, cache)
        solo2 = transformer.forward(
            cfg, params, jnp.concatenate([t_short, nxt[:1]], 1)
        )
        np.testing.assert_allclose(
            np.asarray(logits2[0, 0]), np.asarray(solo2[0, 5]), rtol=1e-4, atol=1e-4
        )
        assert np.asarray(cache.lengths).tolist() == [6, 9]


class TestSampling:
    def test_greedy(self):
        logits = jnp.array([[1.0, 3.0, 2.0]])
        tok = sample(jax.random.PRNGKey(0), logits, temperature=0.0)
        assert int(tok[0]) == 1

    def test_top_k_masks_rest(self):
        logits = jnp.array([[1.0, 5.0, 3.0, 2.0]])
        masked = top_k_mask(logits, 2)
        assert np.asarray(masked[0, [0, 3]] < -1e29).all()
        np.testing.assert_allclose(np.asarray(masked[0, [1, 2]]), [5.0, 3.0])

    def test_top_p_keeps_top1(self):
        logits = jnp.array([[0.0, 10.0, 0.0]])
        masked = top_p_mask(logits, 0.1)
        assert float(masked[0, 1]) == 10.0
        assert np.asarray(masked[0, [0, 2]] < -1e29).all()

    def test_top_p_keeps_mass(self):
        logits = jnp.log(jnp.array([[0.5, 0.3, 0.15, 0.05]]))
        masked = top_p_mask(logits, 0.8)
        keep = np.asarray(masked[0] > -1e29)
        assert keep.tolist() == [True, True, False, False]

    def test_sampling_distribution(self):
        logits = jnp.log(jnp.array([0.7, 0.2, 0.1]))
        keys = jax.random.split(jax.random.PRNGKey(0), 2000)
        toks = jax.vmap(lambda k: sample(k, logits))(keys)
        freq = np.bincount(np.asarray(toks), minlength=3) / 2000
        np.testing.assert_allclose(freq, [0.7, 0.2, 0.1], atol=0.05)


class TestEngine:
    def test_generate_shapes(self):
        cfg = _cfg()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = Engine(cfg, params, max_len=64, temperature=1.0, top_k=50)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (2, 8), 0, cfg.vocab_size)
        res = eng.generate(prompt, max_new_tokens=5, key=jax.random.PRNGKey(2))
        assert res.tokens.shape == (2, 5)
        assert res.logprobs.shape == (2, 5)
        assert np.all(np.asarray(res.logprobs) <= 0)

    def test_greedy_matches_argmax_forward(self):
        """Greedy engine output == repeated argmax over full forwards."""
        cfg = _cfg()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = Engine(cfg, params, max_len=32, temperature=0.0)
        prompt = jax.random.randint(jax.random.PRNGKey(1), (1, 6), 0, cfg.vocab_size)
        res = eng.generate(prompt, max_new_tokens=4, key=jax.random.PRNGKey(2))

        toks = prompt
        want = []
        for _ in range(4):
            logits = transformer.forward(cfg, params, toks)
            nxt = jnp.argmax(logits[:, -1], axis=-1).astype(jnp.int32)
            want.append(int(nxt[0]))
            toks = jnp.concatenate([toks, nxt[:, None]], axis=1)
        assert np.asarray(res.tokens)[0].tolist() == want


def test_truncate_at_stop():
    import numpy as np

    from shellac_tpu.inference.engine import truncate_at_stop

    toks = np.array([[5, 7, 9, 11, 13], [1, 2, 3, 2, 3]])
    out = truncate_at_stop(toks, [[9, 11], [2, 3]])
    assert out == [[5, 7], [1]]
    # No match: untouched.
    assert truncate_at_stop(toks, [[99]]) == [toks[0].tolist(), toks[1].tolist()]
    import pytest

    with pytest.raises(ValueError, match="empty"):
        truncate_at_stop(toks, [[]])
