"""Multi-head latent attention (DeepSeek-style): training, serving,
sharding. Exact numerics vs HF are covered in test_hf_convert.py; here
the native stack is exercised end to end."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import ParallelConfig, get_model_config, make_mesh
from shellac_tpu.config import TrainConfig
from shellac_tpu.inference.batching import (
    BatchingEngine,
    PagedBatchingEngine,
)
from shellac_tpu.inference.engine import Engine, shard_params
from shellac_tpu.models import transformer


def _cfg():
    return get_model_config("tiny-mla").replace(dtype="float32")


@pytest.fixture(scope="module")
def model():
    cfg = _cfg()
    return cfg, transformer.init_params(cfg, jax.random.PRNGKey(0))


class TestTraining:
    def test_loss_decreases(self):
        from shellac_tpu.training import init_train_state, make_train_step

        cfg = _cfg()
        tcfg = TrainConfig(learning_rate=3e-3, warmup_steps=5,
                           total_steps=100)
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
        step = make_train_step(cfg, tcfg)
        toks = jnp.asarray(
            np.tile(np.array([5, 9, 13, 2]), 16)[None].repeat(4, 0),
            jnp.int32,
        )
        batch = {"inputs": toks, "targets": toks}
        first = last = None
        for _ in range(60):
            state, m = step(state, batch)
            if first is None:
                first = float(m["loss"])
            last = float(m["loss"])
        assert last < 0.1 * first, (first, last)

    def test_ring_attention_parity(self, mesh8, model):
        """MLA long-context training: the expanded attention dispatches
        through ring attention on sp meshes and matches unsharded."""
        cfg, params = model
        toks = jax.random.randint(jax.random.PRNGKey(3), (2, 64), 0,
                                  cfg.vocab_size)
        want = transformer.forward(cfg, params, toks)
        sharded = shard_params(cfg, params, mesh8)
        got = jax.jit(
            lambda p, t: transformer.forward(
                cfg, p, t, mesh=mesh8, attn_impl="ring"
            )
        )(sharded, toks)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), atol=2e-4, rtol=2e-4
        )

    def test_packed_segments(self, model):
        """Packed pretraining rows: each document computes as if alone
        (block-diagonal attention + per-segment rope restart)."""
        cfg, params = model
        a = jax.random.randint(jax.random.PRNGKey(5), (1, 12), 1,
                               cfg.vocab_size)
        bq = jax.random.randint(jax.random.PRNGKey(6), (1, 20), 1,
                                cfg.vocab_size)
        packed = jnp.concatenate([a, bq], axis=1)
        seg = jnp.concatenate(
            [jnp.zeros((1, 12), jnp.int32), jnp.ones((1, 20), jnp.int32)],
            axis=1,
        )
        out = transformer.forward(cfg, params, packed, segment_ids=seg)
        ref_a = transformer.forward(cfg, params, a)
        ref_b = transformer.forward(cfg, params, bq)
        np.testing.assert_allclose(
            np.asarray(out[:, :12]), np.asarray(ref_a), atol=2e-5,
            rtol=2e-5,
        )
        np.testing.assert_allclose(
            np.asarray(out[:, 12:]), np.asarray(ref_b), atol=2e-5,
            rtol=2e-5,
        )

    def test_trains_on_fsdp_mesh(self, mesh_fsdp8, model):
        from shellac_tpu.training import (
            batch_shardings,
            init_train_state,
            make_train_step,
        )

        cfg = _cfg()
        tcfg = TrainConfig(warmup_steps=1, total_steps=4)
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0),
                                 mesh=mesh_fsdp8)
        step = make_train_step(cfg, tcfg, mesh=mesh_fsdp8)
        toks = jax.random.randint(jax.random.PRNGKey(1), (8, 32), 0,
                                  cfg.vocab_size)
        bs = batch_shardings(mesh_fsdp8)
        batch = {"inputs": jax.device_put(toks, bs),
                 "targets": jax.device_put(toks, bs)}
        state, m = step(state, batch)
        assert np.isfinite(float(m["loss"]))


class TestServing:
    def test_batching_bit_matches_engine(self, model):
        """The serving invariant holds under MLA: continuous batching
        through the latent cache == single-request engine."""
        cfg, params = model
        rng = np.random.default_rng(11)
        prompts = [rng.integers(1, cfg.vocab_size, size=n).tolist()
                   for n in (3, 7, 5, 9)]
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=64)
        got = eng.run([(i, p, 8) for i, p in enumerate(prompts)])
        single = Engine(cfg, params, temperature=0.0, max_len=64)
        for i, p in enumerate(prompts):
            res = single.generate(jnp.asarray([p], jnp.int32),
                                  max_new_tokens=8)
            assert got[i] == np.asarray(res.tokens)[0].tolist(), i

    def test_latent_cache_shape(self, model):
        """The decode cache really is the latent: one row per token,
        kv_lora_rank + qk_rope_head_dim wide, zero-width v."""
        from shellac_tpu.inference.kvcache import init_cache

        cfg, _ = model
        cache = init_cache(cfg, 2, 32)
        assert cache.k.shape == (cfg.n_layers, 2, 1, 32, 40)  # 32 + 8
        assert cache.v.shape == (cfg.n_layers, 2, 1, 32, 0)

    def test_chunked_prefill_parity(self, model):
        cfg, params = model
        rng = np.random.default_rng(12)
        prompts = [rng.integers(1, cfg.vocab_size, size=40).tolist()]
        want = BatchingEngine(cfg, params, n_slots=1, max_len=96).run(
            [(0, prompts[0], 6)]
        )
        got = BatchingEngine(cfg, params, n_slots=1, max_len=96,
                             prefill_chunk=16).run([(0, prompts[0], 6)])
        assert got == want

    def test_sharded_tp_bit_matches(self, model):
        cfg, params = model
        mesh = make_mesh(ParallelConfig(dp=2, tp=4))
        want = BatchingEngine(cfg, params, n_slots=2, max_len=64).run(
            [(0, [3, 5, 7], 6), (1, [2, 9], 6)]
        )
        sharded = shard_params(cfg, params, mesh)
        got = BatchingEngine(cfg, sharded, n_slots=2, max_len=64,
                             mesh=mesh).run(
            [(0, [3, 5, 7], 6), (1, [2, 9], 6)]
        )
        assert got == want

    def test_speculative_bit_matches(self, model):
        """Speculative batching over MLA latent caches (self-draft):
        rollback-by-lengths works on the latent rows too."""
        from shellac_tpu.inference.spec_batching import (
            SpeculativeBatchingEngine,
        )

        cfg, params = model
        rng = np.random.default_rng(17)
        prompts = [rng.integers(1, cfg.vocab_size, size=n).tolist()
                   for n in (3, 6, 4)]
        want = BatchingEngine(cfg, params, n_slots=2, max_len=64).run(
            [(i, p, 8) for i, p in enumerate(prompts)]
        )
        eng = SpeculativeBatchingEngine(cfg, params, cfg, params, gamma=3,
                                        n_slots=2, max_len=64)
        got = eng.run([(i, p, 8) for i, p in enumerate(prompts)])
        assert got == want
        assert eng.stats["spec_accepted"] == eng.stats["spec_proposed"]

    def test_deepseek_layout_trains_and_serves(self):
        """tiny-deepseek (MLA + first-k-dense + MoE + shared expert):
        the native stack trains on a mesh and the serving parity
        invariant holds through the latent cache."""
        from shellac_tpu import ParallelConfig, make_mesh
        from shellac_tpu.training import (
            batch_shardings,
            init_train_state,
            make_train_step,
        )

        cfg = get_model_config("tiny-deepseek").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))

        # Training on an fsdp mesh (experts shard over fsdp).
        mesh = make_mesh(ParallelConfig(fsdp=4, tp=2))
        tcfg = TrainConfig(warmup_steps=1, total_steps=4)
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0),
                                 mesh=mesh)
        step = make_train_step(cfg, tcfg, mesh=mesh)
        toks = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0,
                                  cfg.vocab_size)
        bs = batch_shardings(mesh)
        batch = {"inputs": jax.device_put(toks, bs),
                 "targets": jax.device_put(toks, bs)}
        state, met = step(state, batch)
        assert np.isfinite(float(met["loss"]))

        # Serving: batching == single-request, greedy.
        rng = np.random.default_rng(13)
        prompts = [rng.integers(1, cfg.vocab_size, size=n).tolist()
                   for n in (3, 6)]
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=64)
        got = eng.run([(i, p, 6) for i, p in enumerate(prompts)])
        single = Engine(cfg, params, temperature=0.0, max_len=64)
        for i, p in enumerate(prompts):
            res = single.generate(jnp.asarray([p], jnp.int32),
                                  max_new_tokens=6)
            assert got[i] == np.asarray(res.tokens)[0].tolist(), i

    def test_paged_bit_matches(self, model):
        """Paged serving over latent-row pools == the dense engine,
        greedy, with prefix caching reusing latent blocks."""
        cfg, params = model
        rng = np.random.default_rng(19)
        common = rng.integers(1, cfg.vocab_size, size=16).tolist()
        prompts = [common + rng.integers(1, cfg.vocab_size, size=4).tolist()
                   for _ in range(4)]
        want = BatchingEngine(cfg, params, n_slots=2, max_len=64).run(
            [(i, p, 6) for i, p in enumerate(prompts)]
        )
        eng = PagedBatchingEngine(
            cfg, params, n_slots=2, max_len=64, block_size=16,
            prefix_cache=True,
        )
        got = eng.run([(i, p, 6) for i, p in enumerate(prompts)])
        assert got == want
        assert eng.stats["prefix_hit_tokens"] > 0

    def test_int8_latent_cache(self, model):
        """kv_quant='int8' quantizes the latent rows (one scale per
        row); batching stays bit-identical to the single-request
        engine, and greedy typically matches the bf16 cache."""
        cfg, params = model
        rng = np.random.default_rng(23)
        prompts = [rng.integers(1, cfg.vocab_size, size=n).tolist()
                   for n in (3, 7, 5)]
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             kv_quant="int8")
        got = eng.run([(i, p, 8) for i, p in enumerate(prompts)])
        single = Engine(cfg, params, temperature=0.0, max_len=64,
                        kv_quant="int8")
        for i, p in enumerate(prompts):
            res = single.generate(jnp.asarray([p], jnp.int32),
                                  max_new_tokens=8)
            assert got[i] == np.asarray(res.tokens)[0].tolist(), i
        from shellac_tpu.inference.kvcache import init_cache_for

        cache = init_cache_for(cfg, 2, 32, "int8")
        assert cache.k.dtype == jnp.int8
        assert cache.k.shape == (cfg.n_layers, 2, 1, 32, 40)
        assert cache.v.shape == (cfg.n_layers, 2, 1, 32, 0)


class TestLoRA:
    def test_mla_lora_trains_and_merges(self, model):
        """LoRA on MLA: the generic default resolves to the latent
        projections (wkv_b_* folded as their real matrices), adapters
        start as the identity, and a short run moves the loss."""
        from shellac_tpu.training.lora import (
            LoRAConfig,
            init_lora,
            init_lora_state,
            make_lora_train_step,
            merge_lora,
        )

        cfg, params = model
        lcfg = LoRAConfig(rank=4).validate(cfg)
        assert "wkv_b_k" in lcfg.targets and "wq_a" in lcfg.targets
        # q_lora_rank=None models resolve to the plain wq instead.
        cfg_noq = cfg.replace(
            mla=cfg.mla.__class__(**{
                **cfg.mla.__dict__, "q_lora_rank": None,
            })
        ).validate()
        lcfg_noq = LoRAConfig(rank=4).validate(cfg_noq)
        assert "wq" in lcfg_noq.targets
        assert "wq_a" not in lcfg_noq.targets
        import pytest as _pt
        with _pt.raises(ValueError, match="unknown LoRA targets"):
            LoRAConfig(rank=4, targets=("wq_a",)).validate(cfg_noq)

        lora = init_lora(cfg, lcfg, jax.random.PRNGKey(1))
        assert lora["layers"]["wkv_b_k"]["a"].shape == (2, 32, 4)
        assert lora["layers"]["wkv_b_k"]["b"].shape == (2, 4, 4, 16)
        # B = 0 -> merge is the identity.
        merged = merge_lora(params, lora, lcfg)
        toks = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0,
                                  cfg.vocab_size)
        np.testing.assert_allclose(
            np.asarray(transformer.forward(cfg, merged, toks)),
            np.asarray(transformer.forward(cfg, params, toks)),
            atol=1e-6,
        )

        tcfg = TrainConfig(learning_rate=3e-3, warmup_steps=2,
                           total_steps=30)
        state = init_lora_state(cfg, tcfg, lcfg, jax.random.PRNGKey(3))
        step = make_lora_train_step(cfg, tcfg, lcfg)
        batch = {"inputs": toks, "targets": toks}
        state, m0 = step(state, params, batch)
        for _ in range(15):
            state, m = step(state, params, batch)
        assert float(m["loss"]) < float(m0["loss"])

    def test_first_k_dense_lora(self):
        """LoRA over the two-stack first-k layout: per-stack adapters
        (dense MLP in the prefix, experts in the MoE suffix), identity
        at B=0, and a step that moves the loss."""
        from shellac_tpu.training.lora import (
            LoRAConfig,
            init_lora,
            init_lora_state,
            make_lora_train_step,
            merge_lora,
        )

        cfg = get_model_config("tiny-deepseek").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        lcfg = LoRAConfig(
            rank=4, targets=("wkv_a", "wo", "w_gate", "w_up", "w_down"),
        ).validate(cfg)
        lora = init_lora(cfg, lcfg, jax.random.PRNGKey(1))
        # Dense prefix: plain MLP adapters; MoE suffix: per-expert.
        assert lora["layers"]["dense"]["w_gate"]["a"].shape[:2] == (1, 64)
        assert lora["layers"]["moe"]["w_gate"]["a"].shape[:2] == (2, 4)
        toks = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0,
                                  cfg.vocab_size)
        merged = merge_lora(params, lora, lcfg)
        np.testing.assert_allclose(
            np.asarray(transformer.forward(cfg, merged, toks)),
            np.asarray(transformer.forward(cfg, params, toks)),
            atol=1e-6,
        )
        tcfg = TrainConfig(learning_rate=3e-3, warmup_steps=2,
                           total_steps=20)
        state = init_lora_state(cfg, tcfg, lcfg, jax.random.PRNGKey(3))
        step = make_lora_train_step(cfg, tcfg, lcfg)
        batch = {"inputs": toks, "targets": toks}
        state, m0 = step(state, params, batch)
        for _ in range(10):
            state, m = step(state, params, batch)
        assert float(m["loss"]) < float(m0["loss"])
