"""HF Llama conversion parity: our forward vs transformers' logits.

This is the strongest correctness test of the whole model stack — same
weights through two independent implementations must agree to float
tolerance (rope form, GQA expansion, rms eps placement, swiglu, tied
head all have to line up).
"""

import json

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from shellac_tpu.models import transformer  # noqa: E402
from shellac_tpu.models.convert import (  # noqa: E402
    config_from_hf,
    from_hf,
    params_from_state_dict,
)


def _tiny_llama(n_kv_heads=2, tie=False, vocab=128):
    cfg = transformers.LlamaConfig(
        vocab_size=vocab,
        hidden_size=64,
        intermediate_size=176,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=n_kv_heads,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=tie,
        attn_implementation="eager",
    )
    torch.manual_seed(0)
    model = transformers.LlamaForCausalLM(cfg).eval()
    return model


@pytest.mark.parametrize("n_kv, tie", [(4, False), (2, False), (2, True)])
def test_logits_parity(n_kv, tie):
    model = _tiny_llama(n_kv_heads=n_kv, tie=tie)
    cfg, params = from_hf(model)
    cfg = cfg.replace(dtype="float32")

    tokens = np.array([[3, 17, 42, 99, 7, 23, 56, 1]], np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(cfg, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-3)


def _tiny_deepseek(q_lora_rank=None, vocab=128):
    cfg = transformers.DeepseekV2Config(
        vocab_size=vocab,
        hidden_size=64,
        intermediate_size=176,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=4,
        kv_lora_rank=32,
        q_lora_rank=q_lora_rank,
        qk_nope_head_dim=16,
        qk_rope_head_dim=8,
        v_head_dim=16,
        head_dim=8,
        first_k_dense_replace=2,  # every layer dense-MLP
        n_routed_experts=None,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        rope_theta=10000.0,
        tie_word_embeddings=False,
        attn_implementation="eager",
        attention_bias=False,
    )
    torch.manual_seed(1)
    return transformers.DeepseekV2ForCausalLM(cfg).eval()


@pytest.mark.parametrize("q_lora_rank", [None, 24])
def test_deepseek_mla_logits_parity(q_lora_rank):
    """DeepSeek-V2 (multi-head latent attention) exact logits parity."""
    model = _tiny_deepseek(q_lora_rank=q_lora_rank)
    cfg, params = from_hf(model)
    cfg = cfg.replace(dtype="float32")
    assert cfg.mla is not None and cfg.mla.kv_lora_rank == 32
    assert cfg.mla.q_lora_rank == q_lora_rank

    tokens = np.array([[3, 17, 42, 99, 7, 23, 56, 1]], np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(cfg, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-3)


def test_deepseek_greedy_generation_parity():
    """Token-exact greedy generation vs HF through the LATENT cache —
    the absorbed-matrix decode must match HF's expanded-KV cache."""
    from shellac_tpu.inference.engine import Engine

    model = _tiny_deepseek(q_lora_rank=24)
    cfg, params = from_hf(model)
    cfg = cfg.replace(dtype="float32")
    prompt = np.array([[5, 9, 2, 31, 77]], np.int64)
    with torch.no_grad():
        ref = model.generate(
            torch.from_numpy(prompt), max_new_tokens=12, do_sample=False,
        ).numpy()[:, prompt.shape[1]:]
    out = Engine(cfg, params, temperature=0.0, max_len=64).generate(
        jnp.asarray(prompt, jnp.int32), max_new_tokens=12
    )
    np.testing.assert_array_equal(np.asarray(out.tokens), ref)


@pytest.mark.parametrize("q_lora_rank", [None, 24])
def test_deepseek_export_roundtrip(q_lora_rank):
    """jax -> DeepSeek state_dict -> torch logits match ours exactly
    (the kv_b_proj re-fusion must invert the import split)."""
    from shellac_tpu.models.convert import to_state_dict

    model = _tiny_deepseek(q_lora_rank=q_lora_rank)
    cfg, params = from_hf(model)
    cfg = cfg.replace(dtype="float32")
    sd = {k: torch.from_numpy(v) for k, v in to_state_dict(cfg, params).items()}
    model2 = _tiny_deepseek(q_lora_rank=q_lora_rank)
    model2.load_state_dict(sd)
    tokens = np.array([[4, 9, 77, 23, 5]], np.int64)
    with torch.no_grad():
        ref = model2(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(cfg, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-3)


def test_deepseek_yarn_logits_parity():
    """Yarn rope scaling (the long-context DeepSeek config) converts
    with exact logits parity — inv_freq blending AND the mscale
    attention factor both have to match HF."""
    cfg = transformers.DeepseekV2Config(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=4,
        kv_lora_rank=32, q_lora_rank=None, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16, head_dim=8,
        first_k_dense_replace=2, n_routed_experts=None,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        rope_theta=10000.0, tie_word_embeddings=False,
        attn_implementation="eager", attention_bias=False,
        rope_scaling={
            "rope_type": "yarn", "factor": 4.0,
            "original_max_position_embeddings": 64,
            "mscale": 0.707, "mscale_all_dim": 0.707,
            "beta_fast": 32, "beta_slow": 1,
        },
    )
    torch.manual_seed(2)
    model = transformers.DeepseekV2ForCausalLM(cfg).eval()
    ours_cfg, params = from_hf(model)
    ours_cfg = ours_cfg.replace(dtype="float32")
    assert ours_cfg.rope_yarn is not None
    assert ours_cfg.rope_yarn.factor == 4.0

    tokens = np.array([[3, 17, 42, 99, 7, 23, 56, 1, 88, 4]], np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(ours_cfg, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-3)


def _tiny_qwen3(tie=False):
    cfg = transformers.Qwen3Config(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16,
        max_position_embeddings=128, rms_norm_eps=1e-5,
        rope_theta=10000.0, tie_word_embeddings=tie,
        attn_implementation="eager",
    )
    torch.manual_seed(3)
    return transformers.Qwen3ForCausalLM(cfg).eval()


def test_qwen3_logits_parity():
    """Qwen3 (GQA + per-head-dim q/k RMSNorm before rope) exact parity."""
    model = _tiny_qwen3()
    cfg, params = from_hf(model)
    cfg = cfg.replace(dtype="float32")
    assert cfg.qk_norm and not cfg.attn_bias

    tokens = np.array([[3, 17, 42, 99, 7, 23, 56, 1]], np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(cfg, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-3)


def test_qwen3_yarn_logits_parity():
    """Yarn flows through the GENERIC conversion path too (long-context
    Qwen3 checkpoints ship it), not just DeepSeek's."""
    cfg = transformers.Qwen3Config(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16,
        max_position_embeddings=256, rms_norm_eps=1e-5,
        rope_theta=10000.0, attn_implementation="eager",
        rope_scaling={"rope_type": "yarn", "factor": 4.0,
                      "original_max_position_embeddings": 64},
    )
    torch.manual_seed(4)
    model = transformers.Qwen3ForCausalLM(cfg).eval()
    ours_cfg, params = from_hf(model)
    ours_cfg = ours_cfg.replace(dtype="float32")
    assert ours_cfg.rope_yarn is not None
    tokens = np.array([[3, 17, 42, 99, 7, 23]], np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(ours_cfg, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-3)


def test_llama3_rope_scaling_parity():
    """Llama-3.1 family rope scaling (banded frequency division)
    converts with exact logits parity."""
    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=256,
        rms_norm_eps=1e-5, rope_theta=10000.0,
        attn_implementation="eager",
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 64},
    )
    torch.manual_seed(9)
    model = transformers.LlamaForCausalLM(cfg).eval()
    ours_cfg, params = from_hf(model)
    ours_cfg = ours_cfg.replace(dtype="float32")
    assert ours_cfg.rope_llama3 is not None
    assert ours_cfg.rope_llama3.factor == 8.0

    tokens = np.array([[3, 17, 42, 99, 7, 23, 56, 1, 88, 4]], np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(ours_cfg, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-3)


def test_native_roundtrip_rehydrates_nested_configs(tmp_path):
    """convert -> _load_native must rebuild every nested config
    dataclass (rope_llama3/mla/moe), not leave raw dicts that crash at
    first forward."""
    import dataclasses as dc
    import json as _json

    import orbax.checkpoint as ocp

    from shellac_tpu.cli import _load_native
    from shellac_tpu.config import Llama3RopeConfig

    cfg = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, max_position_embeddings=256,
        attn_implementation="eager",
        rope_scaling={"rope_type": "llama3", "factor": 8.0,
                      "low_freq_factor": 1.0, "high_freq_factor": 4.0,
                      "original_max_position_embeddings": 64},
    )
    torch.manual_seed(10)
    model = transformers.LlamaForCausalLM(cfg).eval()
    ours_cfg, params = from_hf(model)
    out = str(tmp_path / "native")
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(out + "/params", params, force=True)
    ckptr.wait_until_finished()
    with open(tmp_path / "native" / "config.json", "w") as f:
        _json.dump(dc.asdict(ours_cfg), f)

    cfg2, params2 = _load_native(out)
    assert isinstance(cfg2.rope_llama3, Llama3RopeConfig)
    toks = jnp.asarray([[3, 9, 42, 7]], jnp.int32)
    np.testing.assert_allclose(
        np.asarray(transformer.forward(
            cfg2.replace(dtype="float32"), params2, toks)),
        np.asarray(transformer.forward(
            ours_cfg.replace(dtype="float32"), params, toks)),
        atol=1e-6,
    )


def test_unsupported_rope_scaling_rejected():
    cfg = transformers.LlamaConfig(
        vocab_size=64, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2,
        rope_scaling={"rope_type": "dynamic", "factor": 2.0},
    )
    with pytest.raises(NotImplementedError, match="rope_scaling"):
        config_from_hf(cfg)


def test_linear_rope_scaling_parity():
    """Classic position-interpolation (linear) rope scaling converts
    with exact logits parity for Llama-family checkpoints."""
    cfg_hf = transformers.LlamaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=256,
        rope_scaling={"rope_type": "linear", "factor": 4.0},
        attn_implementation="eager",
    )
    torch.manual_seed(11)
    model = transformers.LlamaForCausalLM(cfg_hf).eval()
    cfg, params = from_hf(model)
    assert cfg.rope_linear == 4.0
    cfg = cfg.replace(dtype="float32")
    tokens = np.random.RandomState(5).randint(0, 128, (1, 48))
    with torch.no_grad():
        ref = model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(cfg, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=3e-3)


def test_qwen3_generation_and_export():
    """Token-exact greedy generation through the cache, and the export
    round-trips (q_norm/k_norm included)."""
    from shellac_tpu.inference.engine import Engine
    from shellac_tpu.models.convert import to_state_dict

    model = _tiny_qwen3()
    cfg, params = from_hf(model)
    cfg = cfg.replace(dtype="float32")
    prompt = np.array([[5, 9, 2, 31]], np.int64)
    with torch.no_grad():
        ref = model.generate(
            torch.from_numpy(prompt), max_new_tokens=10, do_sample=False,
        ).numpy()[:, prompt.shape[1]:]
    out = Engine(cfg, params, temperature=0.0, max_len=64).generate(
        jnp.asarray(prompt, jnp.int32), max_new_tokens=10
    )
    np.testing.assert_array_equal(np.asarray(out.tokens), ref)

    sd = {k: torch.from_numpy(v)
          for k, v in to_state_dict(cfg, params).items()}
    model2 = _tiny_qwen3()
    model2.load_state_dict(sd)
    toks = np.array([[4, 9, 77]], np.int64)
    with torch.no_grad():
        ref2 = model2(torch.from_numpy(toks)).logits.numpy()
    ours2 = np.asarray(
        transformer.forward(cfg, params, jnp.asarray(toks, jnp.int32))
    )
    np.testing.assert_allclose(ours2, ref2, atol=2e-4, rtol=2e-3)


def _tiny_deepseek_moe(topk_method="greedy", n_group=1, topk_group=1,
                       routed_scaling_factor=1.0):
    cfg = transformers.DeepseekV2Config(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        moe_intermediate_size=48,
        num_hidden_layers=3, num_attention_heads=4,
        num_key_value_heads=4,
        kv_lora_rank=32, q_lora_rank=None, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16, head_dim=8,
        first_k_dense_replace=1,
        n_routed_experts=4, num_experts_per_tok=2, n_shared_experts=1,
        norm_topk_prob=False, routed_scaling_factor=routed_scaling_factor,
        topk_method=topk_method, n_group=n_group, topk_group=topk_group,
        scoring_func="softmax", moe_layer_freq=1,
        max_position_embeddings=128, rms_norm_eps=1e-5,
        rope_theta=10000.0, tie_word_embeddings=False,
        attn_implementation="eager", attention_bias=False,
    )
    torch.manual_seed(5)
    return transformers.DeepseekV2ForCausalLM(cfg).eval()


@pytest.mark.parametrize(
    "topk_method, n_group, topk_group, scale",
    [("greedy", 1, 1, 1.0), ("greedy", 1, 1, 2.5),
     ("group_limited_greedy", 2, 1, 1.0)],
)
def test_deepseek_moe_logits_parity(topk_method, n_group, topk_group, scale):
    """The FULL DeepSeek-V2 architecture — MLA + first-k-dense layout +
    MoE with shared experts, un-normalized scaled top-k, and (for the
    big variants) group-limited routing — converts with exact parity."""
    model = _tiny_deepseek_moe(
        topk_method=topk_method, n_group=n_group, topk_group=topk_group,
        routed_scaling_factor=scale,
    )
    cfg, params = from_hf(model)
    cfg = cfg.replace(dtype="float32")
    assert cfg.first_k_dense == 1 and cfg.moe is not None
    assert cfg.moe.d_ff_expert == 48
    assert cfg.moe.norm_topk_prob is False
    assert cfg.moe.routed_scaling_factor == scale
    assert cfg.moe.n_group == n_group

    tokens = np.array([[3, 17, 42, 99, 7, 23, 56, 1]], np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(cfg, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=2e-3)


def test_phi3_logits_and_generation_parity():
    """Phi-3 (fused qkv_proj / gate_up_proj) converts exactly; greedy
    generation through the cache is token-exact."""
    from shellac_tpu.inference.engine import Engine

    cfg = transformers.Phi3Config(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5,
        rope_theta=10000.0, tie_word_embeddings=False,
        attn_implementation="eager", sliding_window=None,
        pad_token_id=0,  # default 32000 overflows the tiny vocab
    )
    torch.manual_seed(8)
    model = transformers.Phi3ForCausalLM(cfg).eval()
    ours_cfg, params = from_hf(model)
    ours_cfg = ours_cfg.replace(dtype="float32")
    assert ours_cfg.kv_heads == 2

    tokens = np.array([[3, 17, 42, 99, 7, 23, 56, 1]], np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(ours_cfg, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-3)

    prompt = np.array([[5, 9, 2, 31]], np.int64)
    with torch.no_grad():
        gref = model.generate(
            torch.from_numpy(prompt), max_new_tokens=10, do_sample=False,
        ).numpy()[:, prompt.shape[1]:]
    out = Engine(ours_cfg, params, temperature=0.0, max_len=64).generate(
        jnp.asarray(prompt, jnp.int32), max_new_tokens=10
    )
    np.testing.assert_array_equal(np.asarray(out.tokens), gref)


def test_qwen3_moe_logits_parity():
    """Qwen3-MoE: qk-norm attention + uniform softmax top-k MoE with
    narrow experts, HF's mlp.* naming — exact parity."""
    cfg = transformers.Qwen3MoeConfig(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        moe_intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16,
        num_experts=4, num_experts_per_tok=2, norm_topk_prob=True,
        mlp_only_layers=[], decoder_sparse_step=1,
        max_position_embeddings=128, rms_norm_eps=1e-5,
        rope_theta=10000.0, tie_word_embeddings=False,
        attn_implementation="eager",
    )
    torch.manual_seed(7)
    model = transformers.Qwen3MoeForCausalLM(cfg).eval()
    ours_cfg, params = from_hf(model)
    ours_cfg = ours_cfg.replace(dtype="float32")
    assert ours_cfg.qk_norm and ours_cfg.moe is not None
    assert ours_cfg.moe.d_ff_expert == 48

    tokens = np.array([[3, 17, 42, 99, 7, 23, 56, 1]], np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(ours_cfg, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=2e-3)

    # Export round-trips with the Qwen3-MoE naming (strict reload).
    from shellac_tpu.models.convert import to_state_dict

    sd = {k: torch.from_numpy(v)
          for k, v in to_state_dict(ours_cfg, params).items()}
    model.load_state_dict(sd)


def test_deepseek_v3_logits_parity():
    """DeepSeek-V3 routing — sigmoid scores, e_score_correction_bias
    steering selection only, top-2-sum group ranking, normalized
    weights — converts with exact parity."""
    cfg = transformers.DeepseekV3Config(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        moe_intermediate_size=48,
        num_hidden_layers=3, num_attention_heads=4,
        num_key_value_heads=4,
        kv_lora_rank=32, q_lora_rank=24, qk_nope_head_dim=16,
        qk_rope_head_dim=8, v_head_dim=16, head_dim=8,
        first_k_dense_replace=1,
        n_routed_experts=8, num_experts_per_tok=2, n_shared_experts=1,
        norm_topk_prob=True, routed_scaling_factor=2.5,
        n_group=2, topk_group=1,
        max_position_embeddings=128, rms_norm_eps=1e-5,
        rope_theta=10000.0, tie_word_embeddings=False,
        attn_implementation="eager", attention_bias=False,
    )
    torch.manual_seed(6)
    model = transformers.DeepseekV3ForCausalLM(cfg).eval()
    # Random (nonzero) correction biases so the selection-vs-weight
    # distinction is actually exercised.
    with torch.no_grad():
        for layer in model.model.layers[1:]:
            layer.mlp.gate.e_score_correction_bias.uniform_(-0.2, 0.2)
    ours_cfg, params = from_hf(model)
    ours_cfg = ours_cfg.replace(dtype="float32")
    assert ours_cfg.moe.scoring == "sigmoid"
    assert ours_cfg.moe.norm_topk_prob is True
    assert ours_cfg.moe.n_group == 2

    tokens = np.array([[3, 17, 42, 99, 7, 23, 56, 1]], np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(ours_cfg, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=2e-3)


def test_deepseek_moe_greedy_generation():
    """Token-exact greedy generation for the full MoE architecture
    through the latent cache (dropless decode included)."""
    from shellac_tpu.inference.engine import Engine

    model = _tiny_deepseek_moe()
    cfg, params = from_hf(model)
    cfg = cfg.replace(dtype="float32")
    prompt = np.array([[5, 9, 2, 31, 77]], np.int64)
    with torch.no_grad():
        ref = model.generate(
            torch.from_numpy(prompt), max_new_tokens=10, do_sample=False,
        ).numpy()[:, prompt.shape[1]:]
    out = Engine(cfg, params, temperature=0.0, max_len=64).generate(
        jnp.asarray(prompt, jnp.int32), max_new_tokens=10
    )
    np.testing.assert_array_equal(np.asarray(out.tokens), ref)


def test_config_mapping():
    model = _tiny_llama()
    cfg = config_from_hf(model.config)
    assert cfg.d_model == 64
    assert cfg.n_layers == 2
    assert cfg.kv_heads == 2
    assert cfg.ff_dim == 176
    assert not cfg.tie_embeddings


def test_missing_key_message():
    model = _tiny_llama()
    cfg = config_from_hf(model.config)
    sd = {k: v for k, v in model.state_dict().items() if "q_proj" not in k}
    with pytest.raises(KeyError, match="q_proj"):
        params_from_state_dict(sd, cfg)


def _tiny_mixtral(vocab=128):
    cfg = transformers.MixtralConfig(
        vocab_size=vocab,
        hidden_size=64,
        intermediate_size=112,
        num_hidden_layers=2,
        num_attention_heads=4,
        num_key_value_heads=2,
        num_local_experts=4,
        num_experts_per_tok=2,
        max_position_embeddings=128,
        rms_norm_eps=1e-5,
        sliding_window=None,
        attn_implementation="eager",
    )
    torch.manual_seed(1)
    return transformers.MixtralForCausalLM(cfg).eval()


def test_mixtral_logits_parity():
    model = _tiny_mixtral()
    cfg, params = from_hf(model)
    assert cfg.moe is not None and cfg.moe.dropless
    cfg = cfg.replace(dtype="float32")
    tokens = np.array([[5, 9, 33, 77, 2, 41]], np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(cfg, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=3e-3)


def test_mistral_sliding_window_parity():
    cfg_hf = transformers.MistralConfig(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-5, sliding_window=4,
        attn_implementation="eager",
    )
    torch.manual_seed(2)
    model = transformers.MistralForCausalLM(cfg_hf).eval()
    cfg, params = from_hf(model)
    assert cfg.attn_window == 4
    cfg = cfg.replace(dtype="float32")
    tokens = np.arange(12, dtype=np.int64)[None] % 128
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(cfg, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=2e-3)


def test_qwen2_logits_parity():
    cfg_hf = transformers.Qwen2Config(
        vocab_size=128, hidden_size=64, intermediate_size=176,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=128, rms_norm_eps=1e-6,
        sliding_window=None, use_sliding_window=False,
        attn_implementation="eager",
    )
    torch.manual_seed(4)
    model = transformers.Qwen2ForCausalLM(cfg_hf).eval()
    # Qwen2 inits biases to zero; give them real values so the parity
    # test actually exercises the bias path.
    with torch.no_grad():
        for layer in model.model.layers:
            for proj in (layer.self_attn.q_proj, layer.self_attn.k_proj,
                         layer.self_attn.v_proj):
                proj.bias.normal_(0.0, 0.5)
    cfg, params = from_hf(model)
    assert cfg.attn_bias
    cfg = cfg.replace(dtype="float32")
    tokens = np.array([[7, 21, 63, 3, 9, 27]], np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(cfg, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=3e-3)


def test_gemma_logits_parity():
    cfg_hf = transformers.GemmaConfig(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16, max_position_embeddings=128, rms_norm_eps=1e-6,
        hidden_act="gelu_pytorch_tanh", hidden_activation="gelu_pytorch_tanh",
        attn_implementation="eager",
    )
    torch.manual_seed(3)
    model = transformers.GemmaForCausalLM(cfg_hf).eval()
    cfg, params = from_hf(model)
    assert cfg.activation == "geglu" and cfg.embed_scale
    assert cfg.tie_embeddings  # Gemma ties by default
    cfg = cfg.replace(dtype="float32")
    tokens = np.array([[3, 9, 27, 81, 11, 33]], np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(cfg, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=3e-3)


def test_export_roundtrip():
    """ours -> HF state_dict -> torch model -> logits parity."""
    from shellac_tpu.models.convert import to_state_dict

    model = _tiny_llama(n_kv_heads=2, tie=False)
    cfg, params = from_hf(model)
    sd = to_state_dict(cfg, params)
    model2 = _tiny_llama(n_kv_heads=2, tie=False)
    model2.load_state_dict({k: torch.from_numpy(v) for k, v in sd.items()})
    tokens = torch.randint(0, cfg.vocab_size, (1, 10))
    with torch.no_grad():
        np.testing.assert_allclose(
            model2(tokens).logits.numpy(), model(tokens).logits.numpy(),
            atol=1e-5,
        )


def test_convert_cli_roundtrip(tmp_path, capsys):
    """save_pretrained dir -> convert -> generate --native-dir."""
    from shellac_tpu.cli import main
    from shellac_tpu.inference.engine import Engine

    model = _tiny_llama(n_kv_heads=2, tie=False)
    hf_dir = tmp_path / "hf"
    model.save_pretrained(str(hf_dir))
    out_dir = tmp_path / "native"

    rc = main(["convert", "--hf-dir", str(hf_dir), "--out", str(out_dir)])
    assert rc == 0
    meta = json.loads(capsys.readouterr().out)
    assert meta["model_type"] == "dense" and meta["params"] > 0

    rc = main([
        "generate", "--native-dir", str(out_dir),
        "--prompt", "1,2,3,4", "--max-new", "6", "--temperature", "0",
    ])
    assert rc == 0
    gen = json.loads(capsys.readouterr().out)

    # Same cfg (incl. compute dtype) as the native path uses.
    cfg, params = from_hf(model)
    ref = Engine(cfg, params, temperature=0.0).generate(
        np.asarray([[1, 2, 3, 4]], np.int32), max_new_tokens=6
    )
    assert gen["tokens"] == np.asarray(ref.tokens)[0].tolist()


def test_preemption_checkpoint(tmp_path):
    """SIGTERM mid-fit writes a resumable checkpoint."""
    import os
    import signal
    import threading

    from shellac_tpu import get_model_config
    from shellac_tpu.config import TrainConfig
    from shellac_tpu.training.checkpoint import Checkpointer
    from shellac_tpu.training.data import token_batches
    from shellac_tpu.training.loop import fit

    cfg = get_model_config("tiny").replace(dtype="float32")
    tcfg = TrainConfig(warmup_steps=1, total_steps=10_000)
    corpus = np.arange(1 << 13, dtype=np.int32) % cfg.vocab_size

    def fire():
        os.kill(os.getpid(), signal.SIGTERM)

    # Fire after a few steps' worth of wall clock.
    timer = threading.Timer(6.0, fire)
    timer.start()
    try:
        state = fit(
            cfg, tcfg,
            token_batches(corpus, batch_size=2, seq_len=32),
            checkpoint_dir=str(tmp_path / "ck"),
            checkpoint_every=100_000, log_every=1,
        )
    finally:
        timer.cancel()
    stopped_at = int(np.asarray(state.step))
    assert 0 < stopped_at < 10_000  # preempted, not finished
    ck = Checkpointer(str(tmp_path / "ck"))
    assert ck.latest_step() == stopped_at


def test_generation_runs_on_converted():
    from shellac_tpu.inference.engine import Engine

    model = _tiny_llama()
    cfg, params = from_hf(model)
    cfg = cfg.replace(dtype="float32")
    eng = Engine(cfg, params, temperature=0.0, max_len=64)
    out = eng.generate(jnp.ones((1, 4), jnp.int32), max_new_tokens=8)
    assert out.tokens.shape == (1, 8)

    # Greedy continuation must also match HF's greedy generate.
    with torch.no_grad():
        ref = model.generate(
            torch.ones((1, 4), dtype=torch.long), max_new_tokens=8,
            do_sample=False, use_cache=True, pad_token_id=0,
        )
    np.testing.assert_array_equal(
        np.asarray(out.tokens)[0], ref.numpy()[0, 4:]
    )


def test_moe_export_roundtrip():
    """MoE params -> Mixtral state_dict -> torch model -> logits parity."""
    from shellac_tpu.models.convert import to_state_dict

    model = _tiny_mixtral()
    cfg, params = from_hf(model)
    sd = to_state_dict(cfg, params)
    model2 = _tiny_mixtral()
    model2.load_state_dict({k: torch.from_numpy(v) for k, v in sd.items()})
    tokens = torch.randint(0, cfg.vocab_size, (1, 10))
    with torch.no_grad():
        np.testing.assert_allclose(
            model2(tokens).logits.numpy(), model(tokens).logits.numpy(),
            atol=1e-5,
        )


def _tiny_gemma2(n_layers=4, sliding_window=8, tie=True):
    cfg_hf = transformers.Gemma2Config(
        vocab_size=128, hidden_size=64, intermediate_size=128,
        num_hidden_layers=n_layers, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, max_position_embeddings=128,
        rms_norm_eps=1e-6, sliding_window=sliding_window,
        attn_logit_softcapping=50.0, final_logit_softcapping=30.0,
        query_pre_attn_scalar=20, tie_word_embeddings=tie,
        attn_implementation="eager",
    )
    torch.manual_seed(6)
    return transformers.Gemma2ForCausalLM(cfg_hf).eval()


def test_gemma2_logits_parity():
    """Gemma-2 converts exactly: alternating local/global layers
    (attn_pattern), tanh soft-capping on scores AND final logits,
    sandwich norms, and the query_pre_attn_scalar score scale."""
    model = _tiny_gemma2()
    cfg, params = from_hf(model)
    assert cfg.attn_pattern == ("window", "full")
    assert cfg.attn_window == 8
    assert cfg.attn_softcap == 50.0 and cfg.logit_softcap == 30.0
    assert cfg.post_norms and cfg.activation == "geglu" and cfg.embed_scale
    assert abs(cfg.attn_scale - 20 ** -0.5) < 1e-12
    cfg = cfg.replace(dtype="float32")
    tokens = np.array([[3, 9, 27, 81, 11, 33, 7, 90, 2, 56, 14, 77]], np.int64)
    with torch.no_grad():
        ref = model(torch.from_numpy(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(cfg, params, jnp.asarray(tokens, jnp.int32))
    )
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=3e-3)


def test_gemma2_greedy_generation_parity():
    """Token-exact greedy decode vs HF — the cached decode path must
    apply the per-layer window pattern, score capping, and sandwich
    norms identically to the full forward."""
    from shellac_tpu.inference.engine import Engine

    model = _tiny_gemma2()
    cfg, params = from_hf(model)
    cfg = cfg.replace(dtype="float32")
    prompt = np.array([[5, 9, 2, 31, 77, 12]], np.int64)
    with torch.no_grad():
        ref = model.generate(
            torch.from_numpy(prompt), max_new_tokens=12, do_sample=False,
        ).numpy()[:, prompt.shape[1]:]
    out = Engine(cfg, params, temperature=0.0, max_len=64).generate(
        jnp.asarray(prompt, jnp.int32), max_new_tokens=12
    )
    np.testing.assert_array_equal(np.asarray(out.tokens), ref)


def test_gemma2_export_roundtrip():
    """ours -> Gemma-2 state_dict -> torch model -> logits parity (the
    four per-layer norms must land under their HF names with the native
    (1 + w) storage preserved)."""
    from shellac_tpu.models.convert import to_state_dict

    model = _tiny_gemma2()
    cfg, params = from_hf(model)
    sd = {k: torch.from_numpy(v) for k, v in to_state_dict(cfg, params).items()}
    model2 = _tiny_gemma2()
    model2.load_state_dict(sd)
    tokens = torch.randint(0, cfg.vocab_size, (1, 10))
    with torch.no_grad():
        np.testing.assert_allclose(
            model2(tokens).logits.numpy(), model(tokens).logits.numpy(),
            atol=1e-5,
        )


def _tiny_gemma3(n_layers=6, rope_scaling={"rope_type": "linear", "factor": 8.0}):
    cfg_hf = transformers.Gemma3TextConfig(
        vocab_size=151, hidden_size=48, intermediate_size=96,
        num_hidden_layers=n_layers, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, max_position_embeddings=256,
        sliding_window=8, rope_theta=1_000_000.0,
        rope_local_base_freq=10_000.0, query_pre_attn_scalar=24,
        rope_scaling=rope_scaling, attn_implementation="eager",
    )
    torch.manual_seed(9)
    return transformers.Gemma3ForCausalLM(cfg_hf).eval()


def test_gemma3_logits_parity():
    """Gemma-3 converts exactly: 5:1 local/global pattern, DUAL rope
    (local theta unscaled on window layers, linear-scaled global theta
    on full layers), qk-norm with the gemma (1+w) convention, sandwich
    norms, no softcaps."""
    model = _tiny_gemma3()
    cfg, params = from_hf(model)
    assert cfg.attn_pattern == ("window",) * 5 + ("full",)
    assert cfg.rope_local_theta == 10_000.0 and cfg.rope_theta == 1_000_000.0
    assert cfg.rope_linear == 8.0 and cfg.qk_norm and cfg.post_norms
    assert cfg.attn_softcap is None
    cfg = cfg.replace(dtype="float32")
    tokens = np.random.RandomState(3).randint(0, 151, (2, 24))
    with torch.no_grad():
        ref = model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(cfg, params, jnp.asarray(tokens, jnp.int32),
                            attn_impl="ref")
    )
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=3e-3)


def test_gemma3_greedy_generation_parity():
    """Token-exact greedy decode — the cached decode must pick the
    local/global rope table per layer kind exactly as the forward."""
    from shellac_tpu.inference.engine import Engine

    model = _tiny_gemma3()
    cfg, params = from_hf(model)
    cfg = cfg.replace(dtype="float32")
    prompt = np.array([[5, 9, 2, 31, 77, 12, 88]], np.int64)
    with torch.no_grad():
        ref = model.generate(
            torch.from_numpy(prompt), max_new_tokens=12, do_sample=False,
        ).numpy()[:, prompt.shape[1]:]
    out = Engine(cfg, params, temperature=0.0, max_len=64).generate(
        jnp.asarray(prompt, jnp.int32), max_new_tokens=12
    )
    np.testing.assert_array_equal(np.asarray(out.tokens), ref)


def test_gemma3_export_roundtrip():
    from shellac_tpu.models.convert import to_state_dict

    model = _tiny_gemma3()
    cfg, params = from_hf(model)
    sd = {k: torch.from_numpy(v) for k, v in to_state_dict(cfg, params).items()}
    model2 = _tiny_gemma3()
    model2.load_state_dict(sd)
    tokens = torch.randint(0, cfg.vocab_size, (1, 10))
    with torch.no_grad():
        np.testing.assert_allclose(
            model2(tokens).logits.numpy(), model(tokens).logits.numpy(),
            atol=1e-5,
        )


def _tiny_gptoss(n_layers=4):
    cfg_hf = transformers.GptOssConfig(
        vocab_size=173, hidden_size=64, intermediate_size=96,
        num_hidden_layers=n_layers, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, max_position_embeddings=256,
        sliding_window=8, num_local_experts=6, num_experts_per_tok=2,
        rope_theta=150000.0, attn_implementation="eager",
    )
    torch.manual_seed(12)
    return transformers.GptOssForCausalLM(cfg_hf).eval()


def test_gptoss_logits_parity():
    """GPT-OSS converts exactly: per-head attention SINKS, q/k/v/o
    biases, alternating sliding/full layers, yarn rope (truncate False),
    and the softmax-after-top-k MoE with biased experts and the clamped
    (up+1)*glu activation."""
    model = _tiny_gptoss()
    cfg, params = from_hf(model)
    assert cfg.attn_sink and cfg.attn_bias and cfg.attn_out_bias
    assert cfg.attn_pattern == ("window", "full")
    assert cfg.moe.scoring == "softmax_topk"
    assert cfg.moe.expert_bias and cfg.moe.gate_limit == 7.0
    assert cfg.moe.expert_act == "gptoss"
    assert cfg.rope_yarn is not None and not cfg.rope_yarn.truncate
    cfg = cfg.replace(dtype="float32")
    tokens = np.random.RandomState(7).randint(0, 173, (2, 20))
    with torch.no_grad():
        ref = model(torch.tensor(tokens)).logits.numpy()
    ours = np.asarray(
        transformer.forward(cfg, params, jnp.asarray(tokens, jnp.int32),
                            attn_impl="ref")
    )
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=3e-3)


def test_gptoss_greedy_generation_parity():
    """Token-exact greedy decode — the cached decode must apply sink
    logits, the window pattern, and dropless expert outputs identically
    to the full forward."""
    from shellac_tpu.inference.engine import Engine

    model = _tiny_gptoss()
    cfg, params = from_hf(model)
    cfg = cfg.replace(dtype="float32")
    prompt = np.array([[5, 9, 2, 31, 77, 12]], np.int64)
    with torch.no_grad():
        ref = model.generate(
            torch.from_numpy(prompt), max_new_tokens=12, do_sample=False,
        ).numpy()[:, prompt.shape[1]:]
    out = Engine(cfg, params, temperature=0.0, max_len=64).generate(
        jnp.asarray(prompt, jnp.int32), max_new_tokens=12
    )
    np.testing.assert_array_equal(np.asarray(out.tokens), ref)


def test_gptoss_export_roundtrip():
    """ours -> GPT-OSS state_dict -> torch model -> logits parity (the
    fused gate_up re-interleave must invert the import split; sinks and
    every bias must land under their HF names)."""
    from shellac_tpu.models.convert import to_state_dict

    model = _tiny_gptoss()
    cfg, params = from_hf(model)
    sd = {k: torch.from_numpy(v) for k, v in to_state_dict(cfg, params).items()}
    model2 = _tiny_gptoss()
    model2.load_state_dict(sd)
    tokens = torch.randint(0, cfg.vocab_size, (1, 10))
    with torch.no_grad():
        np.testing.assert_allclose(
            model2(tokens).logits.numpy(), model(tokens).logits.numpy(),
            atol=1e-5,
        )
