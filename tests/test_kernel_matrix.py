"""Randomized config-matrix parity sweep for the Pallas kernels.

The targeted tests in test_ops/test_decode_attention pin specific
shapes; this sweep drives a seeded random matrix of (seq, heads, GQA
group, window, packing, causality) combinations through the
interpret-mode kernels against the reference, so mask/edge interactions
the hand-picked cases miss still get coverage. Deterministic: the
matrix is generated from a fixed seed.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu.ops.attention import attention_ref
from shellac_tpu.ops.decode_attention import _decode_ref, decode_attention
from shellac_tpu.ops.flash_attention import flash_attention


def _flash_cases(n=8):
    rng = np.random.default_rng(1234)
    cases = []
    for i in range(n):
        s = int(rng.choice([64, 96, 128, 160]))
        hkv = int(rng.choice([1, 2, 4]))
        g = int(rng.choice([1, 2, 4]))
        d = int(rng.choice([64, 128]))
        causal = bool(rng.random() < 0.8)
        window = None
        if causal and rng.random() < 0.5:
            window = int(rng.integers(1, s + 16))
        packed = bool(rng.random() < 0.5)
        cases.append((i, s, hkv, g, d, causal, window, packed))
    return cases


@pytest.mark.parametrize(
    "i,s,hkv,g,d,causal,window,packed", _flash_cases(),
    ids=lambda v: str(v),
)
def test_flash_matrix(i, s, hkv, g, d, causal, window, packed):
    if not causal and window is not None:
        pytest.skip("undefined combo")
    rng = np.random.default_rng(100 + i)
    h = hkv * g
    q = jnp.asarray(rng.normal(size=(2, s, h, d)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(2, s, hkv, d)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(2, s, hkv, d)).astype(np.float32))
    seg = None
    if packed:
        # 1-3 random documents per row with random boundaries.
        seg_np = np.zeros((2, s), np.int32)
        for b in range(2):
            cuts = np.sort(rng.choice(np.arange(1, s), size=rng.integers(0, 3),
                                      replace=False))
            for j, c in enumerate(cuts):
                seg_np[b, c:] = j + 1
        seg = jnp.asarray(seg_np)

    got = flash_attention(
        q, k, v, causal=causal, window=window, segments=seg,
        block_q=32, block_k=32, interpret=True,
    )
    want = attention_ref(
        q, k, v, causal=causal, window=window, q_segments=seg,
        kv_segments=seg,
    )
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=3e-4, atol=3e-4
    )

    # Gradients on a weighted loss (non-uniform cotangent).
    def loss(fn):
        def f(q, k, v):
            out = fn(q, k, v)
            return (out * jnp.arange(s)[None, :, None, None]).sum()
        return f

    gf = jax.grad(
        loss(lambda q, k, v: flash_attention(
            q, k, v, causal=causal, window=window, segments=seg,
            block_q=32, block_k=32, interpret=True,
        )), argnums=(0, 1, 2),
    )(q, k, v)
    gr = jax.grad(
        loss(lambda q, k, v: attention_ref(
            q, k, v, causal=causal, window=window, q_segments=seg,
            kv_segments=seg,
        )), argnums=(0, 1, 2),
    )(q, k, v)
    for name, a, b in zip("dq dk dv".split(), gf, gr):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=2e-3,
            err_msg=name,
        )


def _decode_cases(n=8):
    rng = np.random.default_rng(4321)
    cases = []
    for i in range(n):
        L = int(rng.choice([64, 128, 256]))
        hkv = int(rng.choice([1, 2, 4]))
        g = int(rng.choice([1, 2, 4]))
        d = int(rng.choice([64, 128]))
        s = int(rng.choice([1, 2, 5]))
        window = int(rng.integers(1, L)) if rng.random() < 0.5 else None
        cases.append((i, L, hkv, g, d, s, window))
    return cases


@pytest.mark.parametrize(
    "i,L,hkv,g,d,s,window", _decode_cases(), ids=lambda v: str(v),
)
def test_decode_matrix(i, L, hkv, g, d, s, window):
    rng = np.random.default_rng(200 + i)
    h = hkv * g
    b = 3
    q = jnp.asarray(rng.normal(size=(b, s, h, d)).astype(np.float32))
    ck = jnp.asarray(rng.normal(size=(b, hkv, L, d)).astype(np.float32))
    cv = jnp.asarray(rng.normal(size=(b, hkv, L, d)).astype(np.float32))
    index = jnp.asarray(
        rng.integers(0, L - s + 1, size=b).astype(np.int32)
    )
    got = decode_attention(
        q, ck, cv, index, window=window, impl="flash", block_k=32,
        interpret=True,
    )
    want = _decode_ref(q, ck, cv, index, window, d ** -0.5)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=3e-4, atol=3e-4
    )
