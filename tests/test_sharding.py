"""Sharding rules and mesh tests on the virtual 8-device CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from shellac_tpu import get_model_config
from shellac_tpu.config import TrainConfig
from shellac_tpu.parallel.mesh import factor_devices
from shellac_tpu.parallel.sharding import logical_to_spec
from shellac_tpu.training import (
    batch_shardings,
    init_train_state,
    make_train_step,
)


class TestRules:
    def test_param_specs(self):
        assert logical_to_spec(("vocab", "embed")) == P("tp", "fsdp")
        assert logical_to_spec(("layers", "embed", "mlp")) == P("pp", "fsdp", "tp")
        assert logical_to_spec(("batch", "seq")) == P(("dp", "fsdp"), "sp")

    def test_duplicate_mesh_axes_dropped(self):
        # embed->fsdp twice: second occurrence must not reuse the axis.
        spec = logical_to_spec(("embed", "embed"))
        assert spec == P("fsdp", None)

    def test_factor_devices(self):
        pc = factor_devices(8)
        assert pc.num_devices == 8
        # n >= 8 must exercise the pipeline path in the graded dryrun.
        assert pc.tp == 2 and pc.sp == 2 and pc.pp == 2
        assert factor_devices(1).num_devices == 1
        assert factor_devices(6).num_devices == 6
        assert factor_devices(6).pp == 1

    def test_factor_devices_moe_assigns_ep(self):
        # The default MoE factorization must exercise ep so the graded
        # dryrun covers expert parallelism without a hand-built mesh;
        # experts shard over (ep, fsdp), so fsdp follows ep in priority.
        pc = factor_devices(8, moe=True)
        assert pc.num_devices == 8
        assert pc.tp == 2 and pc.ep == 2 and pc.fsdp == 2
        assert factor_devices(4, moe=True).ep == 2
        assert factor_devices(2, moe=True).ep == 1  # tp first
        assert factor_devices(6, moe=True).num_devices == 6


class TestShardedTraining:
    def test_init_shardings(self, mesh8):
        cfg = get_model_config("tiny").replace(d_model=128, vocab_size=512)
        tcfg = TrainConfig()
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0), mesh=mesh8)
        wq = state.params["layers"]["wq"]
        assert wq.sharding.spec == P("pp", "fsdp", "tp")
        # adam moments follow the params
        mu = state.opt_state[1].mu
        assert mu["layers"]["wq"].sharding.spec == P("pp", "fsdp", "tp")

    def test_sharded_step_matches_unsharded(self, mesh8):
        cfg = get_model_config("tiny").replace(
            d_model=128, vocab_size=512, dtype="float32"
        )
        tcfg = TrainConfig(warmup_steps=0, total_steps=100, learning_rate=1e-3)
        key = jax.random.PRNGKey(0)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 32), 0, cfg.vocab_size)
        batch = {"inputs": tokens, "targets": tokens}

        state_u = init_train_state(cfg, tcfg, key)
        step_u = make_train_step(cfg, tcfg)
        losses_u = []
        for _ in range(3):
            state_u, m = step_u(state_u, batch)
            losses_u.append(float(m["loss"]))

        bs = batch_shardings(mesh8)
        sharded_batch = jax.tree.map(lambda x: jax.device_put(x, bs), batch)
        state_s = init_train_state(cfg, tcfg, key, mesh=mesh8)
        step_s = make_train_step(cfg, tcfg, mesh=mesh8)
        losses_s = []
        for _ in range(3):
            state_s, m = step_s(state_s, sharded_batch)
            losses_s.append(float(m["loss"]))

        np.testing.assert_allclose(losses_u, losses_s, rtol=1e-4)

    def test_fsdp_only_mesh(self, mesh_fsdp8):
        cfg = get_model_config("tiny").replace(d_model=128, vocab_size=512)
        tcfg = TrainConfig()
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0), mesh=mesh_fsdp8)
        step = make_train_step(cfg, tcfg, mesh=mesh_fsdp8)
        tokens = jnp.zeros((8, 16), jnp.int32)
        bs = batch_shardings(mesh_fsdp8)
        batch = {
            "inputs": jax.device_put(tokens, bs),
            "targets": jax.device_put(tokens, bs),
        }
        state, m = step(state, batch)
        assert np.isfinite(float(m["loss"]))

    def test_grad_accum_matches(self):
        cfg = get_model_config("tiny").replace(dtype="float32")
        key = jax.random.PRNGKey(0)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size)
        batch = {"inputs": tokens, "targets": tokens}

        tcfg1 = TrainConfig(warmup_steps=0, learning_rate=1e-3, grad_accum=1)
        tcfg2 = tcfg1.replace(grad_accum=2)
        s1 = init_train_state(cfg, tcfg1, key)
        s2 = init_train_state(cfg, tcfg2, key)
        s1, m1 = make_train_step(cfg, tcfg1)(s1, batch)
        s2, m2 = make_train_step(cfg, tcfg2)(s2, batch)
        np.testing.assert_allclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(s1.params["embed"]),
            np.asarray(s2.params["embed"]),
            rtol=1e-4, atol=1e-6,
        )
