"""Distributed request tracing, the flight recorder, and on-demand
profiling (docs/observability.md §Tracing).

  - trace-id plumbing: W3C-shaped ids, the x-shellac-trace /
    x-request-id header contract, adoption vs minting;
  - LIVE two-replica propagation: a request that retries after a
    replica refuses carries ONE id verifiable in all four places —
    the tier's attempt log, the replica's span (histogram exemplar),
    the replica's /debug/request/<id> timeline, and the x-request-id
    response header;
  - flight-recorder correctness under overlap_decode=True:
    dispatch/settle ordering, no stale-slot settle events after a
    cancel;
  - exemplar-to-timeline resolution, redaction defaults, --no-debug;
  - POST /debug/profile smoke on a live engine (CPU jax.profiler).

Runs in its own CI job (tier-1's wall-clock window never reaches
late-alphabet files — the test_tools.py precedent).
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.batching import BatchingEngine
from shellac_tpu.inference.openai_api import stream_error_payload
from shellac_tpu.inference.server import InferenceServer, make_http_server
from shellac_tpu.inference.tier import TierRouter, make_tier_http_server
from shellac_tpu.models import transformer
from shellac_tpu.obs import (
    FlightRecorder,
    Registry,
    ServeMetrics,
    adopt_trace,
    format_trace_header,
    new_trace_id,
    parse_trace_header,
)
from shellac_tpu.training.tokenizer import ByteTokenizer


def _tiny():
    return get_model_config("tiny").replace(dtype="float32")


@pytest.fixture(scope="module")
def tiny_model():
    cfg = _tiny()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _mk_server(cfg, params, **kw):
    kw.setdefault("registry", Registry())
    srv = InferenceServer(cfg, params, tokenizer=ByteTokenizer(),
                          n_slots=2, max_len=64, temperature=0.0, **kw)
    httpd = make_http_server(srv)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    return srv, httpd, f"http://127.0.0.1:{httpd.server_address[1]}"


def _post(base, path, payload, headers=None, timeout=120):
    req = urllib.request.Request(
        base + path, data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json", **(headers or {})},
    )
    resp = urllib.request.urlopen(req, timeout=timeout)
    return resp, json.loads(resp.read())


def _get(base, path, timeout=30):
    with urllib.request.urlopen(base + path, timeout=timeout) as r:
        return json.loads(r.read())


# ---- units: ids, headers, recorder, exemplars -----------------------


class TestTraceIds:
    def test_mint_shape_and_uniqueness(self):
        ids = {new_trace_id() for _ in range(64)}
        assert len(ids) == 64
        for tid in ids:
            ver, trace, span, flags = tid.split("-")
            assert (ver, flags) == ("00", "01")
            assert len(trace) == 32 and len(span) == 16
            int(trace, 16), int(span, 16)  # hex or ValueError

    def test_header_roundtrip_with_attempt(self):
        tid = new_trace_id()
        assert parse_trace_header(format_trace_header(tid, 3)) == (tid, 3)
        assert parse_trace_header(tid) == (tid, 0)

    def test_malformed_header_mints_instead_of_rejecting(self):
        for bad in (None, "", "not-a-trace", "00-zzzz-yy-01",
                    "abc;attempt=2"):
            tid, _ = adopt_trace(bad)
            assert parse_trace_header(tid)[0] == tid
        # A good id with a garbage attempt suffix keeps the id.
        good = new_trace_id()
        assert adopt_trace(good + ";attempt=x")[0] == good


class TestFlightRecorder:
    def test_ring_bounds_and_dropped_counter(self):
        reg = Registry()
        rec = FlightRecorder(capacity=4, registry=reg)
        for i in range(10):
            rec.record(f"t{i}", "admit", rid=i)
        st = rec.stats()
        assert st["events"] == 4 and st["dropped"] == 6
        assert reg.value("shellac_flight_recorder_dropped_total") == 6
        # The oldest events were forgotten, the newest retained.
        assert rec.events_for("t0") == []
        assert rec.events_for("t9")[0]["rid"] == 9

    def test_timeline_filter_and_tail_order(self):
        rec = FlightRecorder(capacity=64)
        rec.record("a", "admit")
        rec.record("b", "admit")
        rec.record("a", "finish")
        rec.record(None, "eject", replica="r1")  # system-scoped
        evs = rec.events_for("a")
        assert [e["event"] for e in evs] == ["admit", "finish"]
        assert evs[0]["seq"] < evs[1]["seq"]
        assert [e["event"] for e in rec.tail(2)] == ["finish", "eject"]
        assert rec.events_for(None) == []

    def test_disabled_recorder_is_noop(self):
        rec = FlightRecorder(enabled=False)
        rec.record("a", "admit")
        assert rec.stats()["events"] == 0

    def test_uppercase_lookup_finds_lowercased_timeline(self):
        # Header adoption lowercases ids; a client querying with the
        # uppercase hex it originally sent must still find them.
        rec = FlightRecorder()
        tid = new_trace_id()
        rec.record(tid, "admit")
        assert rec.events_for(tid.upper())[0]["event"] == "admit"


class TestExemplars:
    def test_histogram_retains_last_trace_per_bucket(self):
        reg = Registry()
        h = reg.histogram("x_seconds", buckets=[0.1, 1.0])
        h.observe(0.05, exemplar="t-fast")
        h.observe(0.5, exemplar="t-mid")
        h.observe(50.0, exemplar="t-slow")  # overflow bucket
        h.observe(0.06, exemplar="t-fast2")  # replaces t-fast
        ex = h.bucket_exemplars()
        assert ex == {"0.1": "t-fast2", "1": "t-mid", "+Inf": "t-slow"}

    def test_no_exemplars_is_empty_and_plain_observe_unaffected(self):
        reg = Registry()
        h = reg.histogram("y_seconds", buckets=[1.0])
        h.observe(0.5)
        assert h.bucket_exemplars() == {}
        assert h.count == 1


class TestStreamErrorPayload:
    def test_carries_trace_id(self):
        out = stream_error_payload(TimeoutError("slow"), trace_id="00-x")
        assert out["error"]["trace_id"] == "00-x"
        assert out["error"]["type"] == "timeout_error"
        # Without an id the record keeps its old shape.
        assert "trace_id" not in stream_error_payload(ValueError("b"))["error"]


# ---- flight recorder vs the overlapped decode pipeline --------------


class TestRecorderUnderOverlap:
    def _traced_engine(self, tiny_model):
        cfg, params = tiny_model
        reg = Registry()
        rec = FlightRecorder(registry=reg)
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, overlap_decode=True,
                             registry=reg)
        sm = ServeMetrics(reg)
        return eng, sm, rec

    def test_dispatch_settle_ordering(self, tiny_model):
        eng, sm, rec = self._traced_engine(tiny_model)
        tid = new_trace_id()
        eng.submit(0, [1, 2, 3], 6,
                   trace=sm.trace(trace_id=tid, recorder=rec))
        for _ in range(64):
            if eng.step():
                break
        while eng._windows:  # drain the in-flight window
            eng.step()
        evs = rec.events_for(tid)
        kinds = [e["event"] for e in evs]
        assert kinds[:4] == ["queue", "prefill", "first-token",
                             "window-dispatch"]
        dispatches = [e for e in evs if e["event"] == "window-dispatch"]
        settles = [e for e in evs if e["event"] == "window-settle"]
        assert dispatches and settles
        # Two-deep pipeline: settles never outnumber dispatches, and
        # each settle follows its window's dispatch (seq order).
        assert len(settles) <= len(dispatches) <= len(settles) + 2
        for d, s in zip(dispatches, settles):
            assert d["seq"] < s["seq"]
            assert d["slot"] == s["slot"]
        assert any(d["depth"] >= 1 for d in dispatches)

    def test_no_stale_slot_events_after_cancel(self, tiny_model):
        eng, sm, rec = self._traced_engine(tiny_model)
        tid_a, tid_b = new_trace_id(), new_trace_id()
        eng.submit("a", [1, 2], 32,
                   trace=sm.trace(trace_id=tid_a, recorder=rec))
        eng.submit("b", [3, 4], 32,
                   trace=sm.trace(trace_id=tid_b, recorder=rec))
        eng.step()  # prefill both + dispatch a window (in flight)
        assert eng._windows, "overlap pipeline should be in flight"
        eng.cancel("a")
        cancel_seq = rec.events_for(tid_a)[-1]["seq"]
        assert rec.events_for(tid_a)[-1]["event"] == "cancelled"
        finished = []
        for _ in range(64):
            if not eng.pending:
                break
            finished.extend(rid for rid, _ in eng.step())
        evs_a = rec.events_for(tid_a)
        # The in-flight window's results for the cancelled slot were
        # discarded: the timeline ends at the cancellation — no settle
        # (or any other) event after it.
        assert evs_a[-1]["event"] == "cancelled"
        assert all(e["seq"] <= cancel_seq for e in evs_a)
        # The surviving request ran to completion with a clean tail
        # (finish is the SERVER's span settlement; at engine level the
        # timeline ends with its last settled window).
        assert finished == ["b"]
        kinds_b = [e["event"] for e in rec.events_for(tid_b)]
        assert "window-settle" in kinds_b
        assert "cancelled" not in kinds_b


# ---- live single-server surfaces ------------------------------------


@pytest.fixture(scope="module")
def traced_srv(tiny_model, tmp_path_factory):
    cfg, params = tiny_model
    prof = tmp_path_factory.mktemp("prof")
    srv, httpd, base = _mk_server(cfg, params, profile_dir=str(prof))
    yield srv, base
    httpd.shutdown()
    srv.close()


class TestServerTracing:
    def test_adopts_header_and_echoes_request_id(self, traced_srv):
        srv, base = traced_srv
        tid = new_trace_id()
        resp, out = _post(base, "/generate",
                          {"tokens": [3, 7], "max_new": 4},
                          headers={"x-shellac-trace":
                                   format_trace_header(tid, 2)})
        assert resp.headers.get("x-request-id") == tid
        assert out["trace_id"] == tid
        admit = [e for e in srv.debug_request(tid)["events"]
                 if e["event"] == "admit"][0]
        assert admit["attempt"] == 2

    def test_exemplar_resolves_to_timeline(self, traced_srv):
        srv, base = traced_srv
        resp, out = _post(base, "/generate",
                          {"tokens": [5, 9], "max_new": 4})
        tid = resp.headers.get("x-request-id")
        dbg = _get(base, "/debug/requests")
        # The id is retained as an exemplar on the latency histograms…
        assert tid in dbg["exemplars"]["ttft"].values()
        assert tid in dbg["exemplars"]["e2e"].values()
        # …and resolves to the full flight-recorder timeline.
        tl = _get(base, f"/debug/request/{tid}")
        kinds = [e["event"] for e in tl["events"]]
        for want in ("admit", "queue", "prefill", "first-token",
                     "window-dispatch", "window-settle", "finish"):
            assert want in kinds, kinds
        assert dbg["recorder"]["events"] > 0
        assert "overlap_window_depth" in dbg
        assert dbg["slots"]["backend"] == "dense"
        assert len(dbg["slots"]["slot_tokens"]) == 2

    def test_unknown_trace_is_404(self, traced_srv):
        _, base = traced_srv
        with pytest.raises(urllib.error.HTTPError) as ei:
            _get(base, f"/debug/request/{new_trace_id()}")
        assert ei.value.code == 404

    def test_stream_records_carry_trace_id(self, traced_srv):
        _, base = traced_srv
        req = urllib.request.Request(
            base + "/generate",
            data=json.dumps({"tokens": [1, 2], "max_new": 4,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            tid = r.headers.get("x-request-id")
            lines = [json.loads(ln) for ln in r if ln.strip()]
        assert tid and all(ln["trace_id"] == tid for ln in lines)
        assert lines[-1]["done"] is True

    def test_sse_chunks_carry_trace_id(self, traced_srv):
        _, base = traced_srv
        req = urllib.request.Request(
            base + "/v1/chat/completions",
            data=json.dumps({
                "messages": [{"role": "user", "content": "hi"}],
                "max_tokens": 4, "stream": True,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=120) as r:
            tid = r.headers.get("x-request-id")
            chunks = [json.loads(ln[len(b"data: "):])
                      for ln in r
                      if ln.startswith(b"data: ")
                      and b"[DONE]" not in ln]
        assert tid and chunks
        assert all(c["trace_id"] == tid for c in chunks)

    def test_redaction_by_default(self, traced_srv):
        srv, base = traced_srv
        resp, _ = _post(base, "/generate",
                        {"text": "secret prompt", "max_new": 3})
        tid = resp.headers.get("x-request-id")
        tl = _get(base, f"/debug/request/{tid}")
        blob = json.dumps(tl) + json.dumps(_get(base, "/debug/requests"))
        assert "secret prompt" not in blob
        assert not any("prompt_text" in e for e in tl["events"])

    def test_profile_smoke_and_single_capture_guard(self, traced_srv):
        srv, base = traced_srv

        def post_profile(seconds):
            req = urllib.request.Request(
                base + f"/debug/profile?seconds={seconds}", data=b"")
            return urllib.request.urlopen(req, timeout=60)

        # Concurrent second capture is refused with 409 while the
        # first window is open.
        results = {}

        def first():
            with post_profile(1.0) as r:
                results["first"] = json.loads(r.read())

        t = threading.Thread(target=first)
        t.start()
        # Deterministic overlap: wait until the first capture actually
        # holds the profiler lock (a plain sleep races under CPU
        # contention in CI).
        deadline = time.monotonic() + 15
        while not srv._profile_lock.locked():
            assert time.monotonic() < deadline, "capture never started"
            time.sleep(0.01)
        with pytest.raises(urllib.error.HTTPError) as ei:
            post_profile(0.2)
        assert ei.value.code == 409
        t.join(timeout=30)
        # The capture produced a non-empty trace directory.
        out = results["first"]
        assert out["files"] > 0
        import os
        assert os.path.isdir(out["trace_dir"])
        # The lock released: a fresh capture succeeds.
        with post_profile(0.1) as r:
            assert json.loads(r.read())["files"] > 0


class TestRedactionOptIn:
    def test_include_text_flag_exposes_prompt(self, tiny_model):
        cfg, params = tiny_model
        srv, httpd, base = _mk_server(cfg, params,
                                      debug_include_text=True)
        try:
            resp, _ = _post(base, "/generate",
                            {"text": "visible prompt", "max_new": 3})
            tid = resp.headers.get("x-request-id")
            tl = _get(base, f"/debug/request/{tid}")
            admit = [e for e in tl["events"]
                     if e["event"] == "admit"][0]
            assert "visible prompt" in admit["prompt_text"]
        finally:
            httpd.shutdown()
            srv.close()


class TestNoDebugFlag:
    def test_debug_endpoints_404_and_recording_stops(self, tiny_model):
        cfg, params = tiny_model
        srv, httpd, base = _mk_server(cfg, params, debug=False)
        try:
            for path in ("/debug/requests",
                         f"/debug/request/{new_trace_id()}"):
                with pytest.raises(urllib.error.HTTPError) as ei:
                    _get(base, path)
                assert ei.value.code == 404
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(base, "/debug/profile?seconds=0.1", {})
            assert ei.value.code == 404
            assert srv.recorder.stats()["recorded"] == 0
            # Non-debug surfaces still answer.
            assert _get(base, "/health")["ok"] is True
        finally:
            httpd.shutdown()
            srv.close()


# ---- live two-replica propagation (the acceptance path) -------------


@pytest.fixture(scope="module")
def live_tier(tiny_model):
    cfg, params = tiny_model
    replicas = [_mk_server(cfg, params) for _ in range(2)]
    # A huge health interval: membership changes only via the explicit
    # poll_once() calls below, so the drained replica stays routable
    # and the request must discover the refusal — and retry — itself.
    router = TierRouter(
        [base for _, _, base in replicas],
        registry=Registry(), health_interval=60.0,
        backoff_base=0.01, backoff_cap=0.05, default_timeout=60.0,
    )
    router.poll_once()
    assert all(r.state == "healthy" for r in router.replicas)
    httpd_t = make_tier_http_server(router)
    threading.Thread(target=httpd_t.serve_forever, daemon=True).start()
    tbase = f"http://127.0.0.1:{httpd_t.server_address[1]}"
    yield router, tbase, replicas
    httpd_t.shutdown()
    router.close()
    for srv, httpd, _ in replicas:
        httpd.shutdown()
        srv.close()


class TestLiveTierRetryPropagation:
    def test_one_trace_id_in_all_four_places(self, live_tier):
        router, tbase, replicas = live_tier
        payload = {"tokens": [5, 6, 7], "max_new": 4, "session": "s-1"}
        # Find the session's affinity target, then drain it so the
        # next attempt is refused with a 503 and retried elsewhere.
        status, _, _ = router.forward_json("/generate", dict(payload))
        assert status == 200
        target = next(s for s, _, _ in
                      [r for r in replicas]
                      if s.engine.stats["requests_completed"])
        other = next(s for s, _, _ in replicas if s is not target)
        target.drain()
        try:
            tid = new_trace_id()
            resp, out = _post(tbase, "/generate", payload,
                              headers={"x-shellac-trace": tid})
            # (1) the x-request-id response header
            assert resp.headers.get("x-request-id") == tid
            assert out["trace_id"] == tid
            # (2) the tier's attempt log: two attempts, one retry,
            # a settled finish — all under the SAME id.
            kinds = [e["event"] for e in router.recorder.events_for(tid)]
            assert kinds.count("tier-attempt") >= 2, kinds
            assert "retry" in kinds and "tier-finish" in kinds, kinds
            # (3) the serving replica's flight-recorder timeline,
            # carrying the tier's attempt number on its admit event.
            tl = other.debug_request(tid)
            ekinds = [e["event"] for e in tl["events"]]
            assert "admit" in ekinds and "finish" in ekinds, ekinds
            admit = [e for e in tl["events"] if e["event"] == "admit"][0]
            assert admit["attempt"] == 1
            # The drained replica never admitted it.
            assert target.debug_request(tid) is None
            # (4) the replica's RequestTrace span: the id survives as
            # the exemplar on its latency histograms.
            reg = other._registry
            assert tid in (reg.get("shellac_ttft_seconds")
                           .bucket_exemplars().values())
            # …and the tier's own e2e histogram exemplar agrees.
            assert tid in (router._registry
                           .get("shellac_tier_e2e_seconds")
                           .bucket_exemplars().values())
            # The tier's debug surface serves the same timeline.
            ttl = _get(tbase, f"/debug/request/{tid}")
            assert [e["event"] for e in ttl["events"]] == kinds
        finally:
            target.resume_admission()
            router.poll_once()

    def test_tier_debug_requests_surface(self, live_tier):
        router, tbase, _ = live_tier
        dbg = _get(tbase, "/debug/requests")
        assert dbg["recorder"]["events"] > 0
        assert len(dbg["replicas"]) == 2
        assert any(e["event"] == "tier-finish"
                   for e in dbg["recent_events"])

    def test_tier_no_debug_404(self, live_tier):
        _, _, replicas = live_tier
        router = TierRouter([replicas[0][2]], registry=Registry(),
                            health_interval=60.0, debug=False)
        try:
            assert router.debug_requests is not None  # method exists
            httpd = make_tier_http_server(router)
            threading.Thread(target=httpd.serve_forever,
                             daemon=True).start()
            base = f"http://127.0.0.1:{httpd.server_address[1]}"
            with pytest.raises(urllib.error.HTTPError) as ei:
                _get(base, "/debug/requests")
            assert ei.value.code == 404
            httpd.shutdown()
        finally:
            router.close()
