"""Sharded (multi-device) inference engine tests on the virtual CPU mesh."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import ParallelConfig, get_model_config, make_mesh
from shellac_tpu.inference.engine import Engine, shard_params
from shellac_tpu.models import transformer
from shellac_tpu.ops.quant import QTensor, quantize_params


def _tiny(**kw):
    return get_model_config("tiny").replace(dtype="float32", **kw)


@pytest.fixture(scope="module")
def mesh_tp():
    return make_mesh(ParallelConfig(dp=2, tp=4))


class TestShardedEngine:
    def test_matches_unsharded_greedy(self, mesh_tp):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(1), (4, 8), 0,
                                    cfg.vocab_size)

        ref = Engine(cfg, params, temperature=0.0).generate(
            prompt, max_new_tokens=16
        )
        sharded = shard_params(cfg, params, mesh_tp)
        out = Engine(cfg, sharded, temperature=0.0, mesh=mesh_tp).generate(
            prompt, max_new_tokens=16
        )
        np.testing.assert_array_equal(
            np.asarray(out.tokens), np.asarray(ref.tokens)
        )

    def test_param_placement(self, mesh_tp):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        sharded = shard_params(cfg, params, mesh_tp)
        # wq: ("layers","embed","heads") -> heads axis split over tp=4.
        spec = sharded["layers"]["wq"].sharding.spec
        assert spec[2] == "tp"

    def test_quantized_sharded_generate(self, mesh_tp):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        qparams = quantize_params(cfg, params)
        sharded = shard_params(cfg, qparams, mesh_tp)
        assert isinstance(sharded["layers"]["wq"], QTensor)
        out = Engine(cfg, sharded, temperature=0.0, mesh=mesh_tp).generate(
            jnp.ones((2, 4), jnp.int32), max_new_tokens=8
        )
        assert out.tokens.shape == (2, 8)
        assert np.isfinite(np.asarray(out.logprobs)).all()

    def test_quantized_interleaved_sharded_generate(self):
        """Grouped (moe_every > 1) quantized trees shard and decode."""
        cfg = get_model_config("tiny-moe-interleaved").replace(dtype="float32")
        # fsdp=4 divides num_experts=4 (the MoE mesh convention).
        mesh = make_mesh(ParallelConfig(fsdp=4, tp=2))
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        qparams = quantize_params(cfg, params)
        sharded = shard_params(cfg, qparams, mesh)
        assert isinstance(sharded["layers"]["dense"]["wq"], QTensor)
        assert isinstance(sharded["layers"]["moe"]["w_gate"], QTensor)
        # Batch divides the dp*fsdp axes (KV cache batch dim shards there).
        out = Engine(cfg, sharded, temperature=0.0, mesh=mesh).generate(
            jnp.ones((4, 4), jnp.int32), max_new_tokens=8
        )
        assert out.tokens.shape == (4, 8)
        assert np.isfinite(np.asarray(out.logprobs)).all()

    def test_sharded_batching_engine_bit_matches(self, mesh_tp):
        """tp-sharded continuous batching == unsharded engine, with slot
        churn (more requests than slots)."""
        from shellac_tpu.inference.batching import BatchingEngine

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(3)
        prompts = [rng.integers(1, cfg.vocab_size, size=n).tolist()
                   for n in (3, 7, 5, 9, 4, 6)]

        ref_eng = BatchingEngine(cfg, params, n_slots=2, max_len=64)
        want = ref_eng.run([(i, p, 8) for i, p in enumerate(prompts)])

        sharded = shard_params(cfg, params, mesh_tp)
        eng = BatchingEngine(cfg, sharded, n_slots=2, max_len=64,
                             mesh=mesh_tp)
        got = eng.run([(i, p, 8) for i, p in enumerate(prompts)])
        assert got == want

    def test_sharded_paged_engine_bit_matches(self, mesh_tp):
        """tp-sharded paged serving (with prefix cache) == unsharded."""
        from shellac_tpu.inference.batching import (
            BatchingEngine,
            PagedBatchingEngine,
        )

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(4)
        common = rng.integers(1, cfg.vocab_size, size=20).tolist()
        prompts = [common + rng.integers(1, cfg.vocab_size, size=4).tolist()
                   for _ in range(4)]

        want = BatchingEngine(cfg, params, n_slots=2, max_len=64).run(
            [(i, p, 6) for i, p in enumerate(prompts)]
        )
        sharded = shard_params(cfg, params, mesh_tp)
        eng = PagedBatchingEngine(
            cfg, sharded, n_slots=2, max_len=64, prefix_cache=True,
            mesh=mesh_tp,
        )
        got = eng.run([(i, p, 6) for i, p in enumerate(prompts)])
        assert got == want
        assert eng.stats["prefix_hit_tokens"] > 0

    def test_sharded_speculative_engine_bit_matches(self, mesh_tp):
        """tp-sharded speculative serving (self-draft) == unsharded
        plain engine, greedy — speculation AND sharding both invisible."""
        from shellac_tpu.inference.batching import BatchingEngine
        from shellac_tpu.inference.spec_batching import (
            SpeculativeBatchingEngine,
        )

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(9)
        prompts = [rng.integers(1, cfg.vocab_size, size=n).tolist()
                   for n in (3, 6, 4)]
        want = BatchingEngine(cfg, params, n_slots=2, max_len=64).run(
            [(i, p, 8) for i, p in enumerate(prompts)]
        )
        sharded = shard_params(cfg, params, mesh_tp)
        eng = SpeculativeBatchingEngine(
            cfg, sharded, cfg, sharded, gamma=3,
            n_slots=2, max_len=64, mesh=mesh_tp,
        )
        got = eng.run([(i, p, 8) for i, p in enumerate(prompts)])
        assert got == want
        # Self-draft greedy accepts every proposal.
        assert eng.stats["spec_accepted"] == eng.stats["spec_proposed"]

    def test_speculative_draft_heads_must_divide_tp(self, mesh_tp):
        """A too-small draft fails with a clear message, not a
        device_put PartitionSpec error."""
        from shellac_tpu.inference.spec_batching import (
            SpeculativeBatchingEngine,
        )

        cfg = _tiny()
        draft = cfg.replace(n_heads=2, n_kv_heads=1, d_model=64)
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="draft model heads"):
            SpeculativeBatchingEngine(
                cfg, params, draft,
                transformer.init_params(draft, jax.random.PRNGKey(1)),
                mesh=mesh_tp,
            )

    def test_ragged_prompts_sharded(self, mesh_tp):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        prompt = jax.random.randint(jax.random.PRNGKey(2), (2, 8), 0,
                                    cfg.vocab_size)
        plen = jnp.array([3, 8], jnp.int32)
        ref = Engine(cfg, params, temperature=0.0).generate(
            prompt, plen, max_new_tokens=8
        )
        sharded = shard_params(cfg, params, mesh_tp)
        out = Engine(cfg, sharded, temperature=0.0, mesh=mesh_tp).generate(
            prompt, plen, max_new_tokens=8
        )
        np.testing.assert_array_equal(
            np.asarray(out.tokens), np.asarray(ref.tokens)
        )
