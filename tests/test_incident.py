"""Incident black box conformance (ISSUE 14).

Unit level (fast, no engines): the incident manager's rate limiting /
retention / atomic bundle write / per-section fault isolation, the
durable event spool's rotation / size cap / redaction / torn-line
recovery, and `trace-report` + `--diff` against the two COMMITTED
fixture captures (including the fixture-regeneration self-test that
keeps them from drifting).

Live level (slow-marked — this file is mid-alphabet and must not eat
the tier-1 wall-clock window; the `incident` CI job runs everything
unfiltered): the full trigger matrix — manual POST /debug/incident,
supervisor scheduler-death and wedge→rebuild, restart-budget
exhaustion, tier severed-stream and exhausted-attempts — plus THE
acceptance scenarios: an SLO page auto-producing a bundle whose
manifest names the violating request's trace id with an embedded
timeline matching /debug/request/<id>, and a SIGKILL'd replica whose
mid-stream request's full timeline is recovered from the on-disk
spool.
"""

import gzip
import importlib.util
import json
import os
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from shellac_tpu.obs import (
    EventSpool,
    FlightRecorder,
    IncidentManager,
    Registry,
    read_spool,
    spool_events_for,
    spool_path,
    tracereport,
)
from shellac_tpu.obs.incident import _SlidingWindow
from shellac_tpu.obs.top import run_top

FIXTURES = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "fixtures")
BASE_TRACE = os.path.join(FIXTURES, "decode_base.trace.json.gz")
REGRESSED_TRACE = os.path.join(FIXTURES,
                               "decode_regressed.trace.json.gz")


def wait_until(cond, timeout=60.0, interval=0.05, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------
# Incident manager units
# ---------------------------------------------------------------------


class TestIncidentManager:
    def test_sliding_window(self):
        w = _SlidingWindow(2, 10.0)
        assert w.allow(now=0.0) and w.allow(now=1.0)
        assert not w.allow(now=2.0)          # third inside the window
        assert w.allow(now=11.5)             # first aged out

    def test_bundle_write_list_load(self, tmp_path):
        reg = Registry()
        rec = FlightRecorder(registry=reg)
        mgr = IncidentManager(
            str(tmp_path), registry=reg, recorder=rec,
            sections={"metrics": reg.snapshot,
                      "extra": lambda: {"k": 1}},
        )
        bid = mgr.trigger("manual", trace_id="t-1",
                          detail={"note": "x"})
        assert bid and bid.startswith("inc-")
        lst = mgr.list()
        assert [b["id"] for b in lst] == [bid]
        assert lst[0]["trigger"] == "manual"
        full = mgr.load(bid)
        assert full["manifest"]["trace_id"] == "t-1"
        assert full["manifest"]["sections"] == ["extra", "metrics"]
        assert full["extra"] == {"k": 1}
        # The trigger itself landed in the flight recorder, and the
        # counter/histogram series exist.
        evs = [e for e in rec.tail() if e["event"] == "incident"]
        assert evs and evs[-1]["bundle"] == bid
        assert reg.value("shellac_incidents_total",
                         trigger="manual") == 1
        assert mgr.last["id"] == bid

    def test_broken_section_is_isolated(self, tmp_path):
        mgr = IncidentManager(str(tmp_path), sections={
            "good": lambda: [1, 2],
            "bad": lambda: (_ for _ in ()).throw(RuntimeError("boom")),
        })
        full = mgr.load(mgr.trigger("manual"))
        assert full["good"] == [1, 2]
        assert "boom" in full["bad"]["error"]

    def test_rate_limit_drops_and_counts(self, tmp_path):
        reg = Registry()
        mgr = IncidentManager(str(tmp_path), registry=reg,
                              rate=2, rate_window=3600.0)
        assert mgr.trigger("stream-severed")
        assert mgr.trigger("stream-severed")
        assert mgr.trigger("stream-severed") is None
        assert len(mgr.list()) == 2
        assert reg.value("shellac_incidents_dropped_total",
                         trigger="stream-severed") == 1

    def test_retention_evicts_oldest(self, tmp_path):
        mgr = IncidentManager(str(tmp_path), rate=100,
                              rate_window=3600.0, retention=2)
        ids = [mgr.trigger("manual") for _ in range(4)]
        kept = [b["id"] for b in mgr.list()]
        assert kept == ids[-2:]
        assert mgr.load(ids[0]) is None

    def test_tmp_debris_swept_and_no_traversal(self, tmp_path):
        os.makedirs(tmp_path / ".tmp-inc-dead")
        mgr = IncidentManager(str(tmp_path))
        mgr.trigger("manual")
        assert not (tmp_path / ".tmp-inc-dead").exists()
        # Bundle ids never resolve path structure.
        assert mgr.load("../etc") is None
        assert mgr.load("inc-x/../../etc") is None

    def test_retention_spares_concurrent_live_write(self, tmp_path):
        # A tmp dir registered as an IN-FLIGHT write (a concurrent
        # trigger on another thread) must survive the sweep; only
        # orphaned crash debris is swept.
        mgr = IncidentManager(str(tmp_path))
        live = tmp_path / ".tmp-inc-live"
        os.makedirs(live)
        mgr._active_tmp.add(str(live))
        os.makedirs(tmp_path / ".tmp-inc-orphan")
        mgr.trigger("manual")
        assert live.exists()
        assert not (tmp_path / ".tmp-inc-orphan").exists()

    def test_write_failure_counted_not_rate_limited(self, tmp_path):
        reg = Registry()
        mgr = IncidentManager(str(tmp_path), registry=reg, rate=1,
                              rate_window=3600.0)
        # Point the manager at a FILE: every bundle write now fails.
        blocker = tmp_path / "blocker"
        blocker.write_text("x")
        good = mgr.incident_dir
        mgr.incident_dir = str(blocker)
        assert mgr.trigger("manual") is None
        assert mgr.write_errors == 1
        assert reg.value("shellac_incident_write_errors_total",
                         trigger="manual") == 1
        # NOT a rate-limit drop: that counter stays unset.
        assert reg.value("shellac_incidents_dropped_total",
                         trigger="manual") in (None, 0)
        # The failed write REFUNDED its limiter slot (rate=1): once
        # the disk is healthy again the very next trigger succeeds —
        # a full disk must not also burn the rate budget.
        mgr.incident_dir = good
        assert mgr.trigger("manual") is not None

    def test_capture_arm_writes_into_bundle(self, tmp_path):
        done = threading.Event()

        def capture(seconds):
            return {"trace_dir": str(tmp_path / "cap"),
                    "seconds": seconds}

        def analyze(trace_dir):
            done.set()
            return {"device_time_us": 7.0, "dir": trace_dir}

        mgr = IncidentManager(str(tmp_path / "inc"),
                              capture_fn=capture, capture_seconds=0.25,
                              analyze_fn=analyze)
        bid = mgr.trigger("wedge-rebuild")
        full = mgr.load(bid)
        # The fake capture settles instantly, so the background
        # thread may already have flipped armed -> done.
        assert full["manifest"]["capture"]["state"] in ("armed",
                                                        "done")
        wait_until(done.is_set, timeout=10, msg="capture analysis")
        wait_until(
            lambda: "trace_report" in (mgr.load(bid) or {}),
            timeout=10, msg="trace_report lands in bundle")
        full = mgr.load(bid)
        assert full["capture"]["state"] == "done"
        assert full["trace_report"]["device_time_us"] == 7.0
        # The MANIFEST reflects the settled capture too (the incident
        # list summarizes manifests only — "armed" forever would hide
        # a capture that silently died).
        wait_until(lambda: (mgr.load(bid)["manifest"]["capture"]
                            ["state"]) == "done",
                   timeout=10, msg="manifest capture state settles")


# ---------------------------------------------------------------------
# Durable event spool
# ---------------------------------------------------------------------


class TestEventSpool:
    def test_rotation_keeps_footprint_bounded(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        sp = EventSpool(path, max_bytes=4096)
        for i in range(200):
            sp.append({"seq": i, "event": "admit", "pad": "x" * 40})
        assert sp.rotations >= 1
        on_disk = sum(os.path.getsize(p)
                      for p in (path, path + ".1")
                      if os.path.exists(p))
        assert on_disk <= 4096
        evs = read_spool(path)
        # Newest events survive, oldest rotated away, order intact.
        assert evs[-1]["seq"] == 199
        assert [e["seq"] for e in evs] == sorted(e["seq"] for e in evs)

    def test_redaction_on_disk_by_default(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        EventSpool(path).append(
            {"seq": 1, "event": "admit", "prompt_text": "SECRET",
             "output_text": "SECRET", "text": "SECRET", "rid": 7})
        raw = open(path).read()
        assert "SECRET" not in raw
        assert read_spool(path)[0]["rid"] == 7
        # Opt-in keeps text (the --debug-include-text contract).
        path2 = str(tmp_path / "t.jsonl")
        EventSpool(path2, include_text=True).append(
            {"seq": 1, "event": "admit", "prompt_text": "SECRET"})
        assert "SECRET" in open(path2).read()

    def test_torn_last_line_is_skipped(self, tmp_path):
        path = str(tmp_path / "events.jsonl")
        sp = EventSpool(path)
        sp.append({"seq": 1, "event": "admit", "trace": "t-1"})
        sp.append({"seq": 2, "event": "finish", "trace": "t-1"})
        with open(path, "a") as f:
            f.write('{"seq": 3, "event": "adm')  # the kill landed here
        evs = read_spool(path)
        assert [e["seq"] for e in evs] == [1, 2]
        assert [e["event"] for e in spool_events_for(path, "t-1")] == \
            ["admit", "finish"]

    def test_footprint_cap_is_bytes_not_chars(self, tmp_path):
        # Multibyte UTF-8 under include_text must count in BYTES:
        # a char-counted cap would let the footprint run ~3x over.
        path = str(tmp_path / "events.jsonl")
        sp = EventSpool(path, max_bytes=8192, include_text=True)
        for i in range(300):
            sp.append({"seq": i, "event": "admit",
                       "prompt_text": "盔" * 20})
        on_disk = sum(os.path.getsize(p)
                      for p in (path, path + ".1")
                      if os.path.exists(p))
        assert on_disk <= 8192, on_disk

    def test_out_of_order_appends_resort_by_seq(self, tmp_path):
        # The recorder assigns seq under the ring lock but appends to
        # the spool outside it: two racing writers can land in the
        # file out of order, and readers must restore seq order.
        path = str(tmp_path / "events.jsonl")
        sp = EventSpool(path)
        sp.append({"seq": 2, "event": "first-token", "trace": "t"})
        sp.append({"seq": 1, "event": "admit", "trace": "t"})
        assert [e["event"] for e in read_spool(path)] == \
            ["admit", "first-token"]
        assert [e["seq"] for e in spool_events_for(path, "t")] == [1, 2]

    def test_oversized_event_truncated_to_skeleton(self, tmp_path):
        # One record bigger than a whole file's budget could never be
        # bounded by rotation: the payload is dropped honestly, the
        # skeleton (seq/trace/event + truncated marker) survives.
        path = str(tmp_path / "events.jsonl")
        sp = EventSpool(path, max_bytes=4096, include_text=True)
        sp.append({"seq": 1, "event": "admit", "trace": "t",
                   "prompt_text": "x" * 10000})
        on_disk = os.path.getsize(path)
        assert on_disk <= 4096
        evs = read_spool(path)
        assert evs[0]["truncated"] and evs[0]["event"] == "admit"
        assert "prompt_text" not in evs[0]

    def test_restart_reuses_spool_without_seq_interleave(self,
                                                         tmp_path):
        # A respawned replica reuses --spool-dir: its seq restarts at
        # 1, and the reader must order the runs by file appearance,
        # never merge-sort the two seq sequences together.
        path = str(tmp_path / "events.jsonl")
        run1 = EventSpool(path)
        for i in range(1, 4):
            run1.append({"seq": i, "event": f"old-{i}", "trace": "t"})
        run1.close()
        run2 = EventSpool(path)  # the respawn
        for i in range(1, 3):
            run2.append({"seq": i, "event": f"new-{i}", "trace": "t"})
        evs = read_spool(path)
        assert [e["event"] for e in evs] == \
            ["old-1", "old-2", "old-3", "new-1", "new-2"]
        assert all("_run" not in e for e in evs)

    def test_recorder_spills_and_directory_resolution(self, tmp_path):
        sp = EventSpool(spool_path(str(tmp_path)))
        rec = FlightRecorder(capacity=2, spool=sp)
        tid = "00-" + "a" * 32 + "-" + "b" * 16 + "-01"
        for ev in ("admit", "prefill", "first-token", "finish"):
            rec.record(tid, ev)
        # The ring forgot the start; the spool did not.
        assert len(rec.events_for(tid)) == 2
        assert [e["event"] for e in spool_events_for(str(tmp_path),
                                                     tid)] == \
            ["admit", "prefill", "first-token", "finish"]
        # Case-normalization fallback mirrors the ring's.
        assert spool_events_for(str(tmp_path), tid.upper())


# ---------------------------------------------------------------------
# trace-report on the committed fixtures
# ---------------------------------------------------------------------


class TestTraceReport:
    def test_fixtures_are_regenerable(self, tmp_path):
        """The committed captures must be exactly what the generator
        writes — fixture drift would silently change what the diff
        tests prove."""
        spec = importlib.util.spec_from_file_location(
            "make_trace_fixtures",
            os.path.join(FIXTURES, "make_trace_fixtures.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        mod.HERE = str(tmp_path)
        mod.main()
        for name in ("decode_base.trace.json.gz",
                     "decode_regressed.trace.json.gz"):
            fresh = (tmp_path / name).read_bytes()
            committed = open(os.path.join(FIXTURES, name), "rb").read()
            assert fresh == committed, f"{name} drifted from generator"

    def test_analyze_base_capture(self):
        rep = tracereport.analyze(BASE_TRACE)
        assert rep["device_time_us"] == pytest.approx(8200.0)
        assert rep["distinct_ops"] == 4
        # Phase alignment: decode/prefill modules land on their
        # phases, the module-less copy stays unattributed, host-only
        # phases are structurally zero device time.
        ph = rep["phases"]
        assert ph["decode_sync"]["device_us"] == pytest.approx(6400.0)
        assert ph["prefill_dispatch"]["device_us"] == \
            pytest.approx(1600.0)
        assert ph["admission"]["device_us"] == 0.0
        assert rep["unattributed"]["device_us"] == pytest.approx(200.0)
        # Fusion counting uses RAW names: two distinct fusions even
        # though both normalize to one op row.
        assert rep["fusion"]["distinct"] == 2
        assert rep["fusion"]["total_us"] == pytest.approx(5200.0)
        assert rep["top_ops"][0]["name"] == "fusion"
        assert "jit__decode_impl" in rep["modules"]

    def test_self_diff_is_clean(self):
        rep = tracereport.analyze(BASE_TRACE)
        out = tracereport.diff(rep, rep)
        assert out["ok"] and out["regressions"] == []

    def test_diff_flags_injected_regression(self):
        out = tracereport.diff(tracereport.analyze(BASE_TRACE),
                               tracereport.analyze(REGRESSED_TRACE))
        assert not out["ok"]
        kinds = {r["kind"] for r in out["regressions"]}
        assert {"op_regression", "new_op", "device_time_regression",
                "fusion_breakup"} <= kinds
        dot = next(r for r in out["regressions"]
                   if r["kind"] == "op_regression"
                   and r["name"] == "dot")
        assert dot["ratio"] == pytest.approx(4 / 3, rel=1e-3)
        # Reversed direction: the regressed capture as baseline must
        # NOT flag (things got faster, ops disappeared).
        back = tracereport.diff(tracereport.analyze(REGRESSED_TRACE),
                                tracereport.analyze(BASE_TRACE))
        assert all(r["kind"] != "op_regression"
                   or r["name"] != "dot"
                   for r in back["regressions"])

    def test_cli_exit_codes(self):
        from shellac_tpu.cli import main

        assert main(["trace-report", BASE_TRACE]) == 0
        assert main(["trace-report", "--diff", BASE_TRACE,
                     BASE_TRACE]) == 0
        assert main(["trace-report", "--diff", BASE_TRACE,
                     REGRESSED_TRACE]) == 2

    def test_cli_truncated_capture_fails_cleanly(self, tmp_path):
        # A crash mid-capture leaves a TORN gzip — the CLI must exit
        # with a message, not a raw EOFError traceback.
        from shellac_tpu.cli import main

        torn = tmp_path / "torn.trace.json.gz"
        torn.write_bytes(open(BASE_TRACE, "rb").read()[:120])
        with pytest.raises(SystemExit, match="trace-report:"):
            main(["trace-report", str(torn)])

    def test_directory_resolution_and_errors(self, tmp_path):
        # A capture DIRECTORY (the /debug/profile trace_dir shape)
        # resolves to its newest trace file.
        d = tmp_path / "plugins" / "profile" / "run1"
        os.makedirs(d)
        import shutil

        shutil.copy(BASE_TRACE, d / "host.trace.json.gz")
        rep = tracereport.analyze(str(tmp_path))
        assert rep["distinct_ops"] == 4
        with pytest.raises(FileNotFoundError):
            tracereport.analyze(str(tmp_path / "nope"))
        bad = tmp_path / "bad.trace.json.gz"
        bad.write_bytes(gzip.compress(b'{"no": "events"}'))
        with pytest.raises(ValueError):
            tracereport.analyze(str(bad))

    def test_phase_classifier(self):
        assert tracereport.classify_phase("jit__prefill_impl",
                                          "dot") == "prefill_dispatch"
        assert tracereport.classify_phase("jit__decode_impl",
                                          "dot") == "decode_sync"
        assert tracereport.classify_phase(None,
                                          "jit_chunk_step") == \
            "prefill_dispatch"
        assert tracereport.classify_phase(None, "copy") is None


# ---------------------------------------------------------------------
# Bench ledger satellite
# ---------------------------------------------------------------------


class TestBenchLedger:
    def _mod(self):
        spec = importlib.util.spec_from_file_location(
            "bench_ledger",
            os.path.join(os.path.dirname(FIXTURES), "..", "scripts",
                         "bench_ledger.py"))
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_committed_ledger_is_current(self):
        mod = self._mod()
        assert mod.main(["--check"]) == 0

    def test_schema_drift_fails_loudly(self):
        mod = self._mod()
        with pytest.raises(mod.SchemaDrift, match="neither"):
            mod._round_rows("BENCH_rXX.json",
                            {"surprise": "shape"})
        with pytest.raises(mod.SchemaDrift, match="share"):
            mod._round_rows("BENCH_rXX.json", {
                "churn_tokens_s": 1.0,
                "step_phases": {"overlap": {"admission": {}}},
            })

    def test_round_shapes_normalize(self):
        mod = self._mod()
        train = mod._round_rows("r", {"metric": "m", "value": 1.5,
                                      "unit": "s",
                                      "detail": {"loss": 2.0}})
        assert train[0]["variant"] == "train"
        assert train[0]["loss"] == 2.0
        assert mod._round_rows("r", None) == []


# ---------------------------------------------------------------------
# Live server: manual trigger, supervisor triggers, spool
# ---------------------------------------------------------------------


def _post(url, payload=b"{}", timeout=120):
    req = urllib.request.Request(
        url, data=payload if isinstance(payload, bytes)
        else json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"})
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            return r.status, json.loads(r.read()), dict(r.headers)
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read()), dict(e.headers)


def _get(url, timeout=30):
    try:
        with urllib.request.urlopen(url, timeout=timeout) as r:
            return r.status, json.loads(r.read())
    except urllib.error.HTTPError as e:
        return e.code, json.loads(e.read())


@pytest.fixture(scope="module")
def tiny_model():
    import jax

    from shellac_tpu import get_model_config
    from shellac_tpu.models import transformer

    cfg = get_model_config("tiny").replace(dtype="float32")
    return cfg, transformer.init_params(cfg, jax.random.PRNGKey(0))


@pytest.mark.slow
class TestServerIncidents:
    """Engine-building suites are slow-marked: this file is
    mid-alphabet and must not eat the tier-1 window (the disagg
    precedent); the `incident` CI job runs them unfiltered."""

    def test_manual_trigger_endpoints_and_spool(self, tiny_model,
                                                tmp_path):
        from shellac_tpu.inference.server import (
            InferenceServer,
            make_http_server,
        )

        cfg, params = tiny_model
        idir, sdir = str(tmp_path / "inc"), str(tmp_path / "spool")
        pdir = str(tmp_path / "prof")
        srv = InferenceServer(cfg, params, registry=Registry(),
                              n_slots=2, max_len=64, temperature=0.0,
                              incident_dir=idir, spool_dir=sdir,
                              profile_dir=pdir, incident_rate=2)
        httpd = make_http_server(srv)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            s, r, _ = _post(url + "/generate",
                            {"tokens": [1, 2, 3], "max_new": 4,
                             "timeout": 120})
            assert s == 200
            tid = r["trace_id"]
            # Manual trigger: bundle exists, sections present, the
            # trace id in the manifest is the caller's.
            s, inc, _ = _post(url + "/debug/incident",
                              {"note": "drill"})
            assert s == 200, inc
            s, full = _get(url + "/debug/incident/" + inc["incident"])
            assert s == 200
            assert full["manifest"]["trigger"] == "manual"
            assert full["manifest"]["detail"]["note"] == "drill"
            for section in ("flight_recorder", "metrics", "requests",
                            "step_phases", "config", "latency"):
                assert section in full, section
            assert full["config"]["engine"]["n_slots"] == 2
            assert full["step_phases"]["decode_sync"]["count"] > 0
            # The completed request's events are in the bundle's
            # recorder dump.
            assert any(e.get("trace") == tid
                       for e in full["flight_recorder"])
            s, lst = _get(url + "/debug/incidents")
            assert s == 200 and lst["last"]["id"] == inc["incident"]
            # Rate limit: rate=2 -> third manual trigger answers 429
            # with Retry-After.
            s2, _, _ = _post(url + "/debug/incident")
            s3, r3, h3 = _post(url + "/debug/incident")
            assert (s2, s3) == (200, 429)
            assert int(h3["Retry-After"]) >= 1
            # /debug/profile: capture id + ?report=1 inline analysis.
            s, prof, _ = _post(url
                               + "/debug/profile?seconds=0.3&report=1")
            assert s == 200
            assert prof["capture_id"] == os.path.basename(
                prof["trace_dir"])
            assert "report" in prof
            # trace-report accepts the returned path verbatim.
            rep = tracereport.analyze(prof["trace_dir"])
            assert "device_time_us" in rep
            # The spool holds the request's full timeline (redacted),
            # and the CLI recovery path renders it.
            evs = spool_events_for(sdir, tid)
            names = [e["event"] for e in evs]
            assert {"admit", "prefill", "first-token",
                    "finish"} <= set(names)
            assert all("prompt_text" not in e for e in evs)
            import io

            buf = io.StringIO()
            assert run_top(None, trace=tid, spool=sdir, out=buf) == 0
            assert "first-token" in buf.getvalue()
        finally:
            httpd.shutdown()
            srv.close()

    def test_unconfigured_endpoints_answer_400(self, tiny_model):
        from shellac_tpu.inference.server import (
            InferenceServer,
            make_http_server,
        )

        cfg, params = tiny_model
        srv = InferenceServer(cfg, params, registry=Registry(),
                              n_slots=2, max_len=64, temperature=0.0)
        httpd = make_http_server(srv)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            s, body = _get(url + "/debug/incidents")
            assert s == 400 and "--incident-dir" in body["error"]
            s, body, _ = _post(url + "/debug/incident")
            assert s == 400
        finally:
            httpd.shutdown()
            srv.close()


@pytest.mark.slow
class TestSupervisorIncidentTriggers:
    def _dying_factory(self, tiny_model, registry):
        from shellac_tpu.inference.batching import BatchingEngine

        cfg, params = tiny_model

        class _DyingEngine(BatchingEngine):
            def step(self):
                if self.pending:
                    raise RuntimeError("injected scheduler death")
                return super().step()

        def factory():
            return _DyingEngine(cfg, params, n_slots=2, max_len=64,
                                temperature=0.0, registry=registry)

        return factory

    def test_scheduler_death_then_budget_exhaustion(self, tiny_model,
                                                    tmp_path):
        from shellac_tpu.inference.server import InferenceServer

        cfg, params = tiny_model
        reg = Registry()
        factory = self._dying_factory(tiny_model, reg)
        srv = InferenceServer(cfg, params, engine=factory(),
                              registry=reg, restart_budget=1,
                              engine_factory=factory,
                              incident_dir=str(tmp_path))
        try:
            # First death: recovered (budget 1) -> scheduler-death
            # bundle. Second death: budget exhausted -> fatal +
            # restart-budget-exhausted bundle.
            with pytest.raises(RuntimeError):
                srv.generate([1, 2, 3], max_new=2, timeout=60)
            wait_until(lambda: srv.status in ("ok", "failed"),
                       msg="supervisor settles")
            with pytest.raises(RuntimeError):
                srv.generate([1, 2, 3], max_new=2, timeout=60)
            wait_until(lambda: srv._fatal is not None, msg="fatal")
            # The pending fails (and _fatal lands) BEFORE the bundle
            # write on the scheduler thread; wait for the evidence.
            wait_until(lambda: "restart-budget-exhausted" in
                       [b["trigger"] for b in srv.incidents.list()],
                       timeout=15, msg="exhaustion bundle")
            triggers = [b["trigger"] for b in srv.incidents.list()]
            assert triggers.count("scheduler-death") == 1, triggers
            exhausted = next(
                srv.incidents.load(b["id"])
                for b in srv.incidents.list()
                if b["trigger"] == "restart-budget-exhausted")
            assert "restart budget exhausted" in \
                exhausted["manifest"]["detail"]["error"]
            assert reg.value("shellac_incidents_total",
                             trigger="scheduler-death") == 1
        finally:
            srv.close()

    def test_wedge_rebuild_writes_bundle(self, tiny_model, tmp_path):
        from shellac_tpu.inference.batching import BatchingEngine
        from shellac_tpu.inference.server import InferenceServer

        cfg, params = tiny_model
        reg = Registry()

        class _WedgingEngine(BatchingEngine):
            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                self.release = threading.Event()

            def step(self):
                if self.pending:
                    self.release.wait(3600)
                    return []
                return super().step()

        eng = _WedgingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, registry=reg)

        def factory():
            return BatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  temperature=0.0, registry=reg)

        srv = InferenceServer(cfg, params, engine=eng,
                              registry=reg, step_timeout=1.5,
                              restart_budget=1, engine_factory=factory,
                              incident_dir=str(tmp_path))
        old_thread = srv._thread
        try:
            with pytest.raises(RuntimeError, match="step_timeout"):
                srv.generate([1, 2, 3], max_new=2, timeout=60)
            wait_until(lambda: srv.status == "ok",
                       msg="rebuild completes")
            triggers = [b["trigger"] for b in srv.incidents.list()]
            assert "wedge-rebuild" in triggers, triggers
            bundle = next(srv.incidents.load(b["id"])
                          for b in srv.incidents.list()
                          if b["trigger"] == "wedge-rebuild")
            assert "step_timeout" in \
                bundle["manifest"]["detail"]["error"]
            # Recovered engine serves again.
            out = srv.generate([1, 2, 3], max_new=2, timeout=120)
            assert len(out) == 2
        finally:
            eng.release.set()
            srv.close()
            old_thread.join(timeout=120)
            assert not old_thread.is_alive(), "wedged thread leaked"

    def test_wedge_with_inplace_factory_writes_fatal_bundle(
            self, tiny_model, tmp_path):
        """The terminal in-place-resync-on-a-wedge arm ('restart the
        pod') must still leave evidence behind — the pod restart is
        exactly when the in-memory recorder dies."""
        from shellac_tpu.inference.batching import BatchingEngine
        from shellac_tpu.inference.server import InferenceServer

        cfg, params = tiny_model

        class _WedgingEngine(BatchingEngine):
            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                self.release = threading.Event()

            def step(self):
                if self.pending:
                    self.release.wait(3600)
                    return []
                return super().step()

        eng = _WedgingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, registry=Registry())
        # A bound method OF the engine = the in-place factory shape
        # (MultihostEngine.resync in production).
        srv = InferenceServer(cfg, params, engine=eng,
                              registry=Registry(), step_timeout=1.5,
                              restart_budget=3,
                              engine_factory=eng.abort_all,
                              incident_dir=str(tmp_path))
        old_thread = srv._thread
        try:
            with pytest.raises(RuntimeError, match="step_timeout"):
                srv.generate([1, 2, 3], max_new=2, timeout=60)
            wait_until(lambda: srv._fatal is not None, msg="fatal")
            assert "in-place resync" in srv._fatal
            wait_until(lambda: any(
                b["trigger"] == "wedge-fatal"
                for b in srv.incidents.list()),
                timeout=15, msg="wedge-fatal bundle")
            full = next(srv.incidents.load(b["id"])
                        for b in srv.incidents.list()
                        if b["trigger"] == "wedge-fatal")
            assert "restart the pod" in \
                full["manifest"]["detail"]["error"]
        finally:
            eng.release.set()
            srv.close()
            old_thread.join(timeout=120)
            assert not old_thread.is_alive(), "wedged thread leaked"


# ---------------------------------------------------------------------
# Tier triggers with stub replicas (no engines)
# ---------------------------------------------------------------------


class _StubReplica:
    """Minimal HTTP replica: healthy /health, configurable /generate
    behavior ("sever" = stream one delta then FIN without a
    terminator; "fault" = plain 500)."""

    def __init__(self, mode):
        stub = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path == "/health":
                    body = json.dumps({"status": "ok", "ok": True,
                                       "pending": 0,
                                       "role": "monolith"}).encode()
                    self.send_response(200)
                elif self.path == "/metrics":
                    body = b""
                    self.send_response(200)
                else:
                    body = b"{}"
                    self.send_response(404)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                if stub.mode == "fault":
                    body = json.dumps({"error": "injected"}).encode()
                    self.send_response(500)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                    return
                # "sever": a 200 ndjson stream that dies after one
                # delta — no done record, no error record.
                self.send_response(200)
                self.send_header("Content-Type",
                                 "application/x-ndjson")
                self.end_headers()
                self.wfile.write(b'{"tokens": [5]}\n')
                self.wfile.flush()

        self.mode = mode
        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()


class TestTierIncidentTriggers:
    def _router(self, urls, tmp_path, **kw):
        from shellac_tpu.inference.tier import TierRouter

        return TierRouter(urls, registry=Registry(),
                          health_interval=0.1, backoff_base=0.01,
                          incident_dir=str(tmp_path), **kw)

    def test_severed_stream_triggers_bundle(self, tmp_path):
        from shellac_tpu.inference.tier import make_tier_http_server

        stub = _StubReplica("sever")
        router = self._router([stub.url], tmp_path)
        httpd = make_tier_http_server(router)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            wait_until(lambda: router.replicas[0].routable,
                       msg="stub healthy")
            req = urllib.request.Request(
                base + "/generate",
                data=json.dumps({"tokens": [1], "max_new": 4,
                                 "stream": True,
                                 "timeout": 30}).encode(),
                headers={"Content-Type": "application/json"})
            with urllib.request.urlopen(req, timeout=30) as r:
                tid = r.headers["x-request-id"]
                body = r.read().decode()
            # The loud in-band error reached the client...
            assert "upstream replica lost mid-stream" in body
            # ... and the black box fired with the same trace id.
            wait_until(lambda: len(router.incidents.list()) >= 1,
                       timeout=15, msg="severed bundle")
            b = router.incidents.list()[-1]
            assert b["trigger"] == "stream-severed"
            assert b["trace_id"] == tid
            full = router.incidents.load(b["id"])
            assert full["manifest"]["detail"]["replica"] == stub.url
            assert stub.url in full["fleet"]
        finally:
            httpd.shutdown()
            router.close()
            stub.close()

    def test_exhausted_attempts_trigger_bundle(self, tmp_path):
        stub = _StubReplica("fault")
        router = self._router([stub.url], tmp_path, max_attempts=2)
        try:
            wait_until(lambda: router.replicas[0].routable,
                       msg="stub healthy")
            status, body, _ = router.forward_json(
                "/generate", {"tokens": [1], "max_new": 2,
                              "timeout": 20})
            assert status == 502
            # Automatic tier triggers fire on a background thread so
            # the client's 502 is not delayed by the evidence fetch.
            wait_until(lambda: router.incidents.list(), timeout=15,
                       msg="exhaustion bundle")
            lst = router.incidents.list()
            assert [b["trigger"] for b in lst] == \
                ["attempts-exhausted"]
            full = router.incidents.load(lst[0]["id"])
            assert full["manifest"]["detail"]["status"] == 502
            # The bundle's recorder dump holds the attempt log for
            # the failed request's trace id.
            tid = lst[0]["trace_id"]
            assert any(e.get("trace") == tid
                       and e.get("event") == "tier-attempt"
                       for e in full["flight_recorder"])
        finally:
            router.close()
            stub.close()


# ---------------------------------------------------------------------
# Acceptance: SLO page -> bundle; SIGKILL -> spool recovery
# ---------------------------------------------------------------------


@pytest.mark.slow
class TestAcceptance:
    def test_slo_page_auto_produces_bundle_with_exemplar(
            self, tiny_model, tmp_path):
        """Under induced latency an SLO page must auto-produce a
        bundle whose manifest carries the violating request's trace
        id and whose embedded timeline matches
        /debug/request/<id>."""
        from shellac_tpu.inference.autotune import SimulatedHostLatency
        from shellac_tpu.inference.server import (
            InferenceServer,
            make_http_server,
        )
        from shellac_tpu.inference.tier import TierRouter

        cfg, params = tiny_model
        srv = InferenceServer(cfg, params, registry=Registry(),
                              n_slots=2, max_len=64, temperature=0.0)
        httpd = make_http_server(srv)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        url = f"http://127.0.0.1:{httpd.server_address[1]}"
        # Warm the compile cache so the induced latency, not the
        # compile, dominates the paged requests.
        _post(url + "/generate", {"tokens": [1, 2, 3], "max_new": 2,
                                  "timeout": 300})
        shim = SimulatedHostLatency(srv.engine, device_s=0.4)
        router = TierRouter([url], registry=Registry(),
                            health_interval=0.1,
                            slos=["e2e<250ms@99"],
                            incident_dir=str(tmp_path))
        try:
            wait_until(lambda: router.replicas[0].routable,
                       msg="replica healthy")
            for i in range(4):
                status, _, _ = router.forward_json(
                    "/generate", {"tokens": [2 + i, 3], "max_new": 2,
                                  "timeout": 120})
                assert status == 200
            wait_until(
                lambda: router._slo.state("e2e<250ms@99") == "page",
                timeout=30, msg="burn-rate page")
            wait_until(lambda: any(
                b["trigger"] == "slo-page"
                for b in router.incidents.list()),
                timeout=15, msg="slo-page bundle")
            b = next(x for x in router.incidents.list()
                     if x["trigger"] == "slo-page")
            tid = b["trace_id"]
            assert tid, "page bundle carries no violating trace id"
            full = router.incidents.load(b["id"])
            assert full["manifest"]["detail"]["slo"] == "e2e<250ms@99"
            # Embedded timeline == the live /debug/request/<id>
            # timeline at bundle time (bundle events are a seq-prefix
            # of the live ones).
            bundled = [e for e in full["flight_recorder"]
                       if e.get("trace") == tid]
            assert bundled, "bundle holds no timeline for the exemplar"
            live = router.debug_request(tid)
            assert live is not None
            live_by_seq = {e["seq"]: e["event"]
                           for e in live["events"]}
            for e in bundled:
                assert live_by_seq.get(e["seq"]) == e["event"]
            # SLO section recorded the page.
            row = next(s for s in full["slo"]["slos"]
                       if s["slo"] == "e2e<250ms@99")
            assert row["state"] == "page"
        finally:
            shim.uninstall()
            router.close()
            httpd.shutdown()
            srv.close()

    def test_sigkill_recovers_timeline_from_spool(self, tmp_path):
        """SIGKILL a replica mid-stream; recover that request's full
        timeline from the on-disk spool."""
        from shellac_tpu.inference.chaos import ReplicaProc

        sdir = str(tmp_path / "spool")
        rep = ReplicaProc(extra_args=["--spool-dir", sdir])
        tid = None
        try:
            req = urllib.request.Request(
                rep.url + "/generate",
                data=json.dumps({"tokens": [1, 2, 3], "max_new": 64,
                                 "stream": True,
                                 "timeout": 120}).encode(),
                headers={"Content-Type": "application/json"})
            resp = urllib.request.urlopen(req, timeout=120)
            tid = resp.headers["x-request-id"]
            first = json.loads(resp.readline())
            assert first["tokens"], first
            # Mid-stream, no goodbye.
            rep.kill()
            try:
                resp.read()
            except Exception:  # noqa: BLE001 — the RST is the point
                pass
        finally:
            rep.kill()
        evs = spool_events_for(sdir, tid)
        names = [e["event"] for e in evs]
        # The whole pre-kill lifecycle survived to disk...
        for expected in ("admit", "queue", "prefill", "first-token",
                         "window-dispatch"):
            assert expected in names, (expected, names)
        # ... and never finished (the process died mid-stream).
        assert "finish" not in names
        # `top --trace <id> --spool <dir>` renders the dead replica's
        # timeline.
        import io

        buf = io.StringIO()
        assert run_top(None, trace=tid, spool=sdir, out=buf) == 0
        assert "first-token" in buf.getvalue()
        # Without the spool there is nothing to read — the recovery
        # genuinely came from disk.
        buf2 = io.StringIO()
        assert run_top(None, trace=tid,
                       spool=str(tmp_path / "empty"), out=buf2) == 1
