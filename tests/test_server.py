"""HTTP inference server tests (stdlib client, ephemeral port)."""

import json
import threading
import urllib.request

import jax
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.engine import Engine
from shellac_tpu.inference.server import InferenceServer, make_http_server
from shellac_tpu.models import transformer
from shellac_tpu.training.tokenizer import ByteTokenizer


def _tiny():
    return get_model_config("tiny").replace(dtype="float32")


@pytest.fixture(scope="module")
def http_srv():
    cfg = _tiny()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    srv = InferenceServer(
        cfg, params, tokenizer=ByteTokenizer(),
        n_slots=2, max_len=64, temperature=0.0,
    )
    httpd = make_http_server(srv)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield base, cfg, params
    httpd.shutdown()
    srv.close()


def _post(base, payload, timeout=120):
    req = urllib.request.Request(
        f"{base}/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


class TestHTTPServer:
    def test_health(self, http_srv):
        base, _, _ = http_srv
        with urllib.request.urlopen(f"{base}/health", timeout=30) as r:
            out = json.loads(r.read())
        assert out["ok"] is True

    def test_generate_matches_engine(self, http_srv):
        base, cfg, params = http_srv
        prompt = [3, 7, 11]
        out = _post(base, {"tokens": prompt, "max_new": 6})
        ref = Engine(cfg, params, temperature=0.0).generate(
            np.asarray([prompt], np.int32), max_new_tokens=6
        )
        assert out["tokens"] == np.asarray(ref.tokens)[0].tolist()

    def test_text_roundtrip(self, http_srv):
        base, _, _ = http_srv
        out = _post(base, {"text": "hi", "max_new": 4})
        assert len(out["tokens"]) == 4
        assert isinstance(out["text"], str)

    def test_concurrent_requests(self, http_srv):
        base, cfg, params = http_srv
        prompts = [[1, 2], [3, 4, 5], [6], [7, 8, 9, 10]]
        results = [None] * len(prompts)

        def hit(i):
            results[i] = _post(base, {"tokens": prompts[i], "max_new": 5})

        threads = [threading.Thread(target=hit, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        eng = Engine(cfg, params, temperature=0.0)
        for i, p in enumerate(prompts):
            ref = eng.generate(np.asarray([p], np.int32), max_new_tokens=5)
            assert results[i]["tokens"] == np.asarray(ref.tokens)[0].tolist()

    def test_bad_request(self, http_srv):
        base, _, _ = http_srv
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base, {"max_new": 4})
        assert ei.value.code == 400
