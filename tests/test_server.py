"""HTTP inference server tests (stdlib client, ephemeral port)."""

import json
import threading
import urllib.request

import jax
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.engine import Engine
from shellac_tpu.inference.server import InferenceServer, make_http_server
from shellac_tpu.models import transformer
from shellac_tpu.training.tokenizer import ByteTokenizer


def _tiny():
    return get_model_config("tiny").replace(dtype="float32")


@pytest.fixture(scope="module")
def http_srv():
    cfg = _tiny()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    srv = InferenceServer(
        cfg, params, tokenizer=ByteTokenizer(),
        n_slots=2, max_len=64, temperature=0.0,
    )
    httpd = make_http_server(srv)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield base, cfg, params
    httpd.shutdown()
    srv.close()


def _post(base, payload, timeout=120):
    req = urllib.request.Request(
        f"{base}/generate", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


class TestHTTPServer:
    def test_health(self, http_srv):
        base, _, _ = http_srv
        with urllib.request.urlopen(f"{base}/health", timeout=30) as r:
            out = json.loads(r.read())
        assert out["ok"] is True

    def test_generate_matches_engine(self, http_srv):
        base, cfg, params = http_srv
        prompt = [3, 7, 11]
        out = _post(base, {"tokens": prompt, "max_new": 6})
        ref = Engine(cfg, params, temperature=0.0).generate(
            np.asarray([prompt], np.int32), max_new_tokens=6
        )
        assert out["tokens"] == np.asarray(ref.tokens)[0].tolist()

    def test_text_roundtrip(self, http_srv):
        base, _, _ = http_srv
        out = _post(base, {"text": "hi", "max_new": 4})
        assert len(out["tokens"]) == 4
        assert isinstance(out["text"], str)

    def test_concurrent_requests(self, http_srv):
        base, cfg, params = http_srv
        prompts = [[1, 2], [3, 4, 5], [6], [7, 8, 9, 10]]
        results = [None] * len(prompts)

        def hit(i):
            results[i] = _post(base, {"tokens": prompts[i], "max_new": 5})

        threads = [threading.Thread(target=hit, args=(i,))
                   for i in range(len(prompts))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=120)
        eng = Engine(cfg, params, temperature=0.0)
        for i, p in enumerate(prompts):
            ref = eng.generate(np.asarray([p], np.int32), max_new_tokens=5)
            assert results[i]["tokens"] == np.asarray(ref.tokens)[0].tolist()

    def test_bad_request(self, http_srv):
        base, _, _ = http_srv
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base, {"max_new": 4})
        assert ei.value.code == 400

    def test_logit_bias_and_min_tokens_payload(self, http_srv):
        base, _, _ = http_srv
        out = _post(base, {"tokens": [1, 2], "max_new": 3,
                           "logit_bias": {"7": 1e9}})
        assert out["tokens"] == [7, 7, 7]
        # min_tokens without a server eos_id is a 400, not a crash.
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base, {"tokens": [1], "max_new": 4, "min_tokens": 2})
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base, {"tokens": [1], "max_new": 2, "logit_bias": [1, 2]})
        assert ei.value.code == 400

    def test_per_request_sampling(self, http_srv):
        """Payload sampling overrides: explicit greedy matches the
        default-greedy server; bad values are a 400."""
        base, _, _ = http_srv
        prompt = [3, 7, 11]
        want = _post(base, {"tokens": prompt, "max_new": 6})
        got = _post(base, {"tokens": prompt, "max_new": 6,
                           "temperature": 0.0})
        assert got["tokens"] == want["tokens"]
        hot = _post(base, {"tokens": prompt, "max_new": 6,
                           "temperature": 1.3, "top_k": 8, "top_p": 0.9})
        assert len(hot["tokens"]) == 6
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base, {"tokens": prompt, "max_new": 4, "top_p": 0.0})
        assert ei.value.code == 400
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base, {"tokens": prompt, "max_new": 4,
                         "temperature": "warm"})
        assert ei.value.code == 400
        # Fractional top_k (a swapped top_p, typically) is a 400, not
        # a silent truncation.
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(base, {"tokens": prompt, "max_new": 4, "top_k": 0.9})
        assert ei.value.code == 400


class TestStreaming:
    def test_stream_matches_blocking(self, http_srv):
        """Concatenated deltas + final record equal the blocking path."""
        _, cfg, params = http_srv
        srv = InferenceServer(cfg, params, n_slots=2, max_len=64,
                              temperature=0.0)
        try:
            prompt = [3, 7, 11]
            want = srv.generate(prompt, max_new=8)
            got, final = [], None
            n_deltas = 0
            for kind, val in srv.generate_stream(prompt, max_new=8,
                                                 timeout=120):
                if kind == "delta":
                    got.extend(val)
                    n_deltas += 1
                else:
                    final = val
            assert final == want
            # Deltas cover the full output except possibly the chunk
            # flushed at completion.
            assert got == final[:len(got)]
            assert n_deltas >= 2  # tokens actually arrived incrementally
        finally:
            srv.close()

    def test_stream_stop_holdback(self, http_srv):
        """Stop-truncated tokens are never streamed: every delta token
        is part of the final (truncated) output."""
        _, cfg, params = http_srv
        srv = InferenceServer(cfg, params, n_slots=2, max_len=64,
                              temperature=0.0)
        try:
            prompt = [5, 6]
            ref = srv.generate(prompt, max_new=12)
            stop = [ref[3:5]]  # force a mid-stream stop match
            want = srv.generate(prompt, max_new=12, stop=stop)
            assert want == ref[:3]
            got, final = [], None
            for kind, val in srv.generate_stream(prompt, max_new=12,
                                                 stop=stop, timeout=120):
                if kind == "delta":
                    got.extend(val)
                else:
                    final = val
            assert final == want
            assert got == final[:len(got)]
        finally:
            srv.close()

    def test_http_stream_endpoint(self, http_srv):
        base, _, _ = http_srv
        blocking = _post(base, {"tokens": [2, 4, 6], "max_new": 6})
        req = urllib.request.Request(
            f"{base}/generate",
            data=json.dumps({"tokens": [2, 4, 6], "max_new": 6,
                             "stream": True}).encode(),
            headers={"Content-Type": "application/json"},
        )
        lines = []
        with urllib.request.urlopen(req, timeout=120) as r:
            assert r.headers["Content-Type"] == "application/x-ndjson"
            for raw in r:
                lines.append(json.loads(raw))
        assert lines[-1]["done"] is True
        assert lines[-1]["tokens"] == blocking["tokens"]
        assert "text" in lines[-1]
        streamed = [t for ln in lines[:-1] for t in ln["tokens"]]
        assert streamed == blocking["tokens"][:len(streamed)]

    def test_client_disconnect_mid_stream(self, http_srv):
        """Closing the connection mid-stream must not wedge or crash
        the server; the next request still works."""
        import socket
        from urllib.parse import urlparse

        base, _, _ = http_srv
        u = urlparse(base)
        body = json.dumps({"tokens": [1, 2], "max_new": 16,
                           "stream": True}).encode()
        s = socket.create_connection((u.hostname, u.port), timeout=30)
        s.sendall(
            b"POST /generate HTTP/1.1\r\nHost: x\r\n"
            b"Content-Type: application/json\r\n"
            b"Content-Length: " + str(len(body)).encode() + b"\r\n\r\n" + body
        )
        s.recv(1)  # wait for the response to start, then hang up
        s.close()
        out = _post(base, {"tokens": [9, 9], "max_new": 4})
        assert len(out["tokens"]) == 4

    def test_http_stream_bad_request_is_400(self, http_srv):
        base, _, _ = http_srv
        req = urllib.request.Request(
            f"{base}/generate",
            data=json.dumps({"stream": True, "max_new": 4}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=30)
        assert ei.value.code == 400


def test_stats_endpoint():
    import threading
    import urllib.request

    from shellac_tpu import get_model_config
    from shellac_tpu.inference.server import InferenceServer, make_http_server
    from shellac_tpu.models import transformer

    # A fresh, fixture-free server: the exact counter assertions below
    # need an engine no other test has driven.
    cfg = get_model_config("tiny").replace(dtype="float32")
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    srv = InferenceServer(cfg, params, n_slots=2, max_len=64)
    httpd = make_http_server(srv, "127.0.0.1", 0)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    try:
        port = httpd.server_address[1]
        out = srv.generate([1, 2, 3], max_new=4)
        assert len(out) == 4
        with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/stats", timeout=10
        ) as r:
            stats = json.loads(r.read())
        assert stats["requests_completed"] == 1
        assert stats["tokens_generated"] == 4
        assert stats["prefills"] == 1
        assert stats["engine_steps"] >= 1
        assert stats["n_slots"] == 2
    finally:
        httpd.shutdown()
        srv.close()
