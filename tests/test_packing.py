"""Sequence packing: block-diagonal attention + per-segment positions.

The load-bearing property: a packed document's logits must EXACTLY
equal the same document's logits computed alone (same weights). Any
cross-document leakage or position offset breaks the equality.
"""

import jax
import jax.numpy as jnp
import numpy as np

from shellac_tpu import get_model_config
from shellac_tpu.config import TrainConfig
from shellac_tpu.models import transformer
from shellac_tpu.models.transformer import segment_positions
from shellac_tpu.training import init_train_state, make_train_step
from shellac_tpu.training.data import batch_rows, pack_documents


def _tiny(**kw):
    return get_model_config("tiny").replace(dtype="float32", **kw)


class TestSegmentPositions:
    def test_restarts(self):
        seg = jnp.asarray([[1, 1, 1, 2, 2, 3, 0, 0]])
        pos = np.asarray(segment_positions(seg))
        assert pos.tolist() == [[0, 1, 2, 0, 1, 0, 0, 1]]


class TestPackedForward:
    def test_packed_equals_isolated(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        rng = np.random.default_rng(0)
        d1 = rng.integers(1, cfg.vocab_size, 6)
        d2 = rng.integers(1, cfg.vocab_size, 9)

        packed = np.concatenate([d1, d2])[None].astype(np.int32)
        segs = np.concatenate([np.full(6, 1), np.full(9, 2)])[None].astype(
            np.int32
        )
        out = np.asarray(
            transformer.forward(
                cfg, params, jnp.asarray(packed),
                segment_ids=jnp.asarray(segs),
            )
        )
        alone1 = np.asarray(
            transformer.forward(cfg, params, jnp.asarray(d1[None], jnp.int32))
        )
        alone2 = np.asarray(
            transformer.forward(cfg, params, jnp.asarray(d2[None], jnp.int32))
        )
        np.testing.assert_allclose(out[0, :6], alone1[0], atol=1e-5)
        np.testing.assert_allclose(out[0, 6:], alone2[0], atol=1e-5)

    def test_packed_segments_on_sp_mesh_match_dense(self, mesh8):
        """Packed rows forwarded under sp (ring path) == unsharded forward."""
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        toks = jax.random.randint(
            jax.random.PRNGKey(1), (4, 16), 0, cfg.vocab_size
        )
        segs = jnp.asarray(
            np.repeat(np.array([[1, 1, 2, 2]] * 4), 4, axis=1), jnp.int32
        )
        dense = transformer.forward(cfg, params, toks, segment_ids=segs)
        sharded = jax.jit(
            lambda p, t, s: transformer.forward(
                cfg, p, t, segment_ids=s, mesh=mesh8
            )
        )(params, toks, segs)
        np.testing.assert_allclose(
            np.asarray(dense), np.asarray(sharded), rtol=2e-4, atol=2e-4
        )


class TestPackDocuments:
    def test_pack_and_mask(self):
        docs = [np.arange(1, 5), np.arange(10, 13), np.arange(20, 30)]
        rows = list(pack_documents(docs, seq_len=8))
        assert len(rows) == 2
        r0 = rows[0]
        # Row 0 holds docs 1 (4 toks) + 2 (3 toks), padded to 9.
        assert r0["inputs"].shape == (8,)
        assert r0["segment_ids"].tolist() == [1, 1, 1, 1, 2, 2, 2, 0]
        # Targets crossing a doc boundary or into padding are masked.
        assert r0["mask"].tolist() == [1, 1, 1, 0, 1, 1, 0, 0]

    def test_truncates_long_doc(self):
        rows = list(pack_documents([np.arange(100)], seq_len=8))
        assert len(rows) == 1
        assert rows[0]["inputs"].tolist() == list(range(8))

    def test_batch_rows(self):
        docs = [np.arange(10)] * 5
        batches = list(
            batch_rows(pack_documents(docs, seq_len=9), batch_size=2)
        )
        assert len(batches) == 2  # 5 rows -> 2 full batches, tail dropped
        assert batches[0]["inputs"].shape == (2, 9)

    def test_train_step_on_packed(self):
        cfg = _tiny()
        tcfg = TrainConfig(warmup_steps=1, total_steps=10)
        rng = np.random.default_rng(0)
        docs = [rng.integers(1, cfg.vocab_size, rng.integers(5, 20))
                for _ in range(16)]
        batch = next(
            batch_rows(pack_documents(docs, seq_len=32), batch_size=4)
        )
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
        step = make_train_step(cfg, tcfg)
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))
        # Token count respects the packing mask.
        assert float(metrics["tokens"]) == float(batch["mask"].sum())
