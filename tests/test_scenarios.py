"""Workload model + scenario gate: determinism, registry validation,
SLO evaluation semantics, ledger schema, and the open-loop load
generator against a stub NDJSON server.

Everything here is tier-1: no model, no jax beyond conftest, no
subprocesses. The committed SCENARIO_LEDGER.json is checked against
the statically-recomputable projection, so editing a scenario's
workload without regenerating the ledger fails HERE, not just in the
CI gate job.
"""

import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from shellac_tpu.inference.chaos import LoadGenerator
from shellac_tpu.inference.scenarios import (
    DEFAULT_LEDGER,
    GATE_SLIS,
    LEDGER_SCHEMA,
    SCENARIOS,
    Scenario,
    SchemaDrift,
    check_ledger,
    check_row,
    compare_to_ledger,
    evaluate_slos,
    expected_static_rows,
    load_ledger,
    select_scenarios,
    stable_row,
    write_ledger,
)
from shellac_tpu.inference.spec_batching import EXCLUSIONS
from shellac_tpu.inference.workload import (
    Burst,
    Diurnal,
    RequestSpec,
    WorkloadConfig,
    WorkloadModel,
)
from shellac_tpu.obs import parse_slo_specs

# ---------------------------------------------------------------------
# Workload model: determinism


def small_config(**kw):
    base = dict(
        seed=7, duration_s=20.0, base_rate=4.0,
        tenants=("a", "b", "c", "d"),
        prompt_buckets=((4, 16, 0.7), (16, 64, 0.3)),
        tail_p=0.0, max_new=(2, 6), diurnal=None, vocab=100,
    )
    base.update(kw)
    return WorkloadConfig(**base)


class TestWorkloadDeterminism:
    def test_same_config_same_schedule(self):
        cfg = small_config()
        a = WorkloadModel(cfg)
        b = WorkloadModel(WorkloadConfig(**{**cfg.__dict__}))
        assert [s.row() for s in a.schedule()] \
            == [s.row() for s in b.schedule()]
        assert a.fingerprint() == b.fingerprint()

    def test_fingerprint_is_sha256_hex(self):
        fp = WorkloadModel(small_config()).fingerprint()
        assert len(fp) == 64
        int(fp, 16)

    def test_seed_changes_fingerprint(self):
        a = WorkloadModel(small_config(seed=1)).fingerprint()
        b = WorkloadModel(small_config(seed=2)).fingerprint()
        assert a != b

    def test_rate_change_changes_fingerprint(self):
        a = WorkloadModel(small_config()).fingerprint()
        b = WorkloadModel(small_config(base_rate=5.0)).fingerprint()
        assert a != b

    def test_schedule_sorted_and_bounded(self):
        cfg = small_config(bursts=(Burst(5.0, 3.0, 4.0),),
                           diurnal=Diurnal(0.5, 10.0))
        sched = WorkloadModel(cfg).schedule()
        arrivals = [s.arrival_s for s in sched]
        assert arrivals == sorted(arrivals)
        assert all(0.0 <= t < cfg.duration_s for t in arrivals)
        assert len(sched) > 10

    def test_schedule_cached(self):
        m = WorkloadModel(small_config())
        assert m.schedule() is m.schedule()

    def test_payload_schedule_mirrors_schedule(self):
        m = WorkloadModel(small_config())
        pairs = m.payload_schedule(timeout=9.0)
        assert len(pairs) == len(m.schedule())
        for (t, p), s in zip(pairs, m.schedule()):
            assert t == s.arrival_s
            assert p["tokens"] == list(s.tokens)
            assert p["timeout"] == 9.0


class TestRateCurve:
    def test_burst_multiplies_rate(self):
        m = WorkloadModel(small_config(bursts=(Burst(5.0, 2.0, 3.0),)))
        assert m.rate_at(6.0) == pytest.approx(12.0)
        assert m.rate_at(4.9) == pytest.approx(4.0)
        assert m.rate_at(7.0) == pytest.approx(4.0)  # end-exclusive

    def test_diurnal_triangle_bounds(self):
        d = Diurnal(amplitude=0.5, period_s=10.0)
        assert d.factor(0.0) == pytest.approx(0.5)   # trough
        assert d.factor(5.0) == pytest.approx(1.5)   # peak
        for t in range(0, 30):
            assert 0.5 <= d.factor(t * 0.37) <= 1.5

    def test_peak_rate_is_envelope(self):
        cfg = small_config(bursts=(Burst(2.0, 2.0, 3.0),
                                   Burst(3.0, 2.0, 2.0)),
                           diurnal=Diurnal(0.4, 8.0))
        m = WorkloadModel(cfg)
        peak = m.peak_rate()
        for i in range(200):
            assert m.rate_at(i * cfg.duration_s / 200.0) <= peak + 1e-9

    def test_scaled_preserves_shape(self):
        cfg = small_config(bursts=(Burst(5.0, 3.0, 4.0),),
                           diurnal=Diurnal(0.5, 10.0))
        s = cfg.scaled(0.5)
        assert s.duration_s == pytest.approx(10.0)
        assert s.bursts[0].start_s == pytest.approx(2.5)
        assert s.bursts[0].duration_s == pytest.approx(1.5)
        assert s.bursts[0].multiplier == pytest.approx(4.0)
        assert s.diurnal.period_s == pytest.approx(5.0)
        assert s.diurnal.amplitude == pytest.approx(0.5)


class TestDraws:
    def test_zipf_head_dominates(self):
        cfg = small_config(duration_s=200.0, zipf_s=1.4)
        counts = WorkloadModel(cfg).tenant_counts()
        assert counts["a"] > counts["d"]
        assert counts["a"] == max(counts.values())

    def test_kind_invariants(self):
        cfg = small_config(
            duration_s=120.0,
            mix={"chat": 0.2, "stream": 0.2, "stream_cancel": 0.2,
                 "tool": 0.2, "prefill_heavy": 0.1,
                 "shared_prefix": 0.1},
            shared_prefix_len=12,
        )
        m = WorkloadModel(cfg)
        kinds = m.kind_counts()
        assert set(kinds) == set(cfg.mix)
        prefix = None
        for s in m.schedule():
            assert s.stream == (s.kind in ("stream", "stream_cancel"))
            if s.kind == "stream_cancel":
                assert 1 <= s.cancel_after <= 3
            else:
                assert s.cancel_after is None
            if s.kind == "tool":
                assert s.constraint_regex == cfg.tool_regex
            else:
                assert s.constraint_regex is None
            if s.kind == "prefill_heavy":
                assert s.max_new <= cfg.prefill_heavy_max_new
            if s.kind == "shared_prefix":
                head = s.tokens[:cfg.shared_prefix_len]
                if prefix is None:
                    prefix = head
                assert head == prefix
                assert len(s.tokens) > cfg.shared_prefix_len

    def test_long_tail(self):
        cfg = small_config(duration_s=60.0, tail_p=1.0, tail_len=512)
        for s in WorkloadModel(cfg).schedule():
            if s.kind != "shared_prefix":
                assert len(s.tokens) == 512

    def test_payload_reserved_keys(self):
        spec = RequestSpec(
            arrival_s=1.0, tenant="acme", kind="stream_cancel",
            tokens=(1, 2, 3), max_new=4, stream=True, cancel_after=2,
        )
        p = spec.payload(timeout=5.0)
        assert p["tenant"] == "acme"
        assert p["kind"] == "stream_cancel"
        assert p["cancel_after_deltas"] == 2
        assert p["stream"] is True
        assert p["timeout"] == 5.0


class TestConfigValidation:
    @pytest.mark.parametrize("kw", [
        dict(duration_s=0.0),
        dict(base_rate=-1.0),
        dict(tenants=()),
        dict(zipf_s=-0.1),
        dict(mix={}),
        dict(mix={"nope": 1.0}),
        dict(mix={"chat": -1.0}),
        dict(mix={"chat": 0.0}),
        dict(prompt_buckets=()),
        dict(prompt_buckets=((0, 4, 1.0),)),
        dict(prompt_buckets=((8, 4, 1.0),)),
        dict(prompt_buckets=((4, 8, 0.0),)),
        dict(tail_p=1.5),
        dict(tail_len=0),
        dict(max_new=(0, 4)),
        dict(max_new=(6, 4)),
        dict(cancel_after_deltas=(0, 2)),
        dict(shared_prefix_len=0),
        dict(vocab=1),
        dict(prefill_heavy_max_new=0),
        dict(bursts=(Burst(-1.0, 2.0, 2.0),)),
        dict(bursts=(Burst(1.0, 0.0, 2.0),)),
        dict(bursts=(Burst(1.0, 2.0, 0.0),)),
        dict(diurnal=Diurnal(1.5, 10.0)),
        dict(diurnal=Diurnal(0.5, 0.0)),
    ])
    def test_bad_config_raises(self, kw):
        with pytest.raises(ValueError):
            WorkloadModel(small_config(**kw))

    def test_bad_scale_factor(self):
        with pytest.raises(ValueError):
            small_config().scaled(0.0)


# ---------------------------------------------------------------------
# Scenario registry


class TestScenarioRegistry:
    def test_catalog_validates(self):
        assert len(SCENARIOS) >= 10
        for s in SCENARIOS.values():
            s.validate()

    def test_gate_subset_selection(self):
        gate = select_scenarios(None, include_all=False)
        everything = select_scenarios(None, include_all=True)
        assert {s.name for s in everything} == set(SCENARIOS)
        assert all(s.gate for s in gate)
        assert len(gate) < len(everything)

    def test_unknown_scenario_name_dies(self):
        with pytest.raises(SystemExit):
            select_scenarios(["no_such_scenario"], include_all=False)

    def _scn(self, **kw):
        base = dict(
            name="t", description="d", workload=small_config(),
            slos=("availability@80",),
        )
        base.update(kw)
        return Scenario(**base)

    def test_no_slos_refused(self):
        with pytest.raises(ValueError, match="asserts no SLOs"):
            self._scn(slos=()).validate()

    def test_unparseable_slo_loud(self):
        with pytest.raises(ValueError):
            self._scn(slos=("not an slo",)).validate()

    def test_non_client_sli_refused(self):
        # tpot/queue_wait parse fine in obs/slo.py but the gate cannot
        # measure them client-side — refusing them is the loud path.
        assert parse_slo_specs(("tpot_p95<10ms@99",))
        with pytest.raises(ValueError, match="not client-measurable"):
            self._scn(slos=("tpot_p95<10ms@99",)).validate()

    @pytest.mark.parametrize("kw,msg", [
        (dict(engine="warp"), "unknown engine"),
        (dict(profile="gpu"), "unknown profile"),
        (dict(chaos="earthquake"), "unknown chaos"),
        (dict(requires=("time_travel",)), "unknown required"),
        (dict(name="no spaces!"), "bad scenario name"),
    ])
    def test_bad_fields_refused(self, kw, msg):
        with pytest.raises(ValueError, match=msg):
            self._scn(**kw).validate()


class TestSkipReasons:
    def test_spec_engine_static_skip_is_named(self):
        skips = {s.name: s.skip_reason() for s in SCENARIOS.values()}
        spec = {n: r for n, r in skips.items()
                if SCENARIOS[n].engine == "spec"}
        assert spec, "the catalog must keep spec scenarios visible"
        for name, reason in spec.items():
            assert reason is not None, f"{name} silently passes"
            assert reason.startswith("excluded: ")
            assert reason.split(": ", 1)[1] in EXCLUSIONS

    def test_dense_scenarios_do_not_skip_statically(self):
        for s in SCENARIOS.values():
            if s.engine == "dense":
                assert s.skip_reason() is None

    def test_live_speculative_target_skips(self):
        s = next(s for s in SCENARIOS.values() if s.engine == "spec")
        stats = {"engine": {"class": "SpeculativeBatchingEngine"}}
        reason = s.skip_reason(stats)
        assert reason and reason.startswith("excluded: ")

    def test_live_disabled_overlap_flag_skips(self):
        s = Scenario(name="t", description="d",
                     workload=small_config(),
                     slos=("availability@80",),
                     requires=("overlap_decode",))
        assert s.skip_reason() is None
        on = {"engine": {"class": "Engine", "overlap_decode": True}}
        off = {"engine": {"class": "Engine", "overlap_decode": False}}
        assert s.skip_reason(on) is None
        assert s.skip_reason(off) == "disabled: overlap_decode"


# ---------------------------------------------------------------------
# SLO evaluation semantics


def _row(outcome="ok", latency=1.0, ttft=None, stream=False,
         trace="t-1"):
    return {"outcome": outcome, "latency_s": latency, "ttft_s": ttft,
            "stream": stream, "trace_id": trace}


class TestEvaluateSlos:
    def test_availability_counts_cancel_good(self):
        specs = parse_slo_specs(("availability@50",))
        rows = [_row("ok"), _row("cancelled"), _row("http_500",
                                                    trace="t-bad")]
        [e] = evaluate_slos(specs, rows)
        assert (e["good"], e["total"]) == (2, 3)
        assert e["ok"] is True
        assert e["violating_trace"] is None

    def test_availability_excludes_client_saturated(self):
        specs = parse_slo_specs(("availability@99",))
        rows = [_row("ok"), _row("client_saturated", trace=None)]
        [e] = evaluate_slos(specs, rows)
        assert e["total"] == 1
        assert e["ok"] is True

    def test_violating_trace_is_first_violator(self):
        specs = parse_slo_specs(("availability@99",))
        rows = [_row("ok"), _row("connect_error", trace="t-first"),
                _row("http_503", trace="t-second")]
        [e] = evaluate_slos(specs, rows)
        assert e["ok"] is False
        assert e["violating_trace"] == "t-first"

    def test_zero_events_fails_loudly(self):
        specs = parse_slo_specs(("ttft_p95<100ms@90",))
        [e] = evaluate_slos(specs, [_row("ok", stream=False)])
        assert e["total"] == 0
        assert e["good_fraction"] is None
        assert e["ok"] is False

    def test_ttft_only_measured_on_streams(self):
        specs = parse_slo_specs(("ttft_p95<1s@90",))
        rows = [_row("ok", stream=True, ttft=0.5, trace="fast"),
                _row("ok", stream=True, ttft=2.0, trace="slow"),
                _row("ok", stream=False, ttft=None)]
        [e] = evaluate_slos(specs, rows)
        assert e["total"] == 2
        assert e["good"] == 1
        assert e["ok"] is False
        assert e["violating_trace"] == "slow"

    def test_e2e_only_measured_on_ok(self):
        specs = parse_slo_specs(("e2e<2s@90",))
        rows = [_row("ok", latency=1.0),
                _row("http_500", latency=30.0)]
        [e] = evaluate_slos(specs, rows)
        assert e["total"] == 1
        assert e["ok"] is True


# ---------------------------------------------------------------------
# Ledger schema + the committed baseline


def _good_row(**kw):
    base = {
        "schema": LEDGER_SCHEMA, "scenario": "s",
        "description": "d", "verdict": "pass", "skip_reason": None,
        "engine": "dense", "chaos": None, "requires": [],
        "slos": ["availability@80"], "seed": 1,
        "workload_fingerprint": "0" * 64, "gate": True,
    }
    base.update(kw)
    return base


class TestLedgerSchema:
    def test_good_row_passes(self):
        check_row(_good_row())

    @pytest.mark.parametrize("mutate", [
        lambda r: r.pop("verdict"),
        lambda r: r.update(schema=99),
        lambda r: r.update(verdict="maybe"),
        lambda r: r.update(verdict="skip"),           # no reason
        lambda r: r.update(skip_reason="x"),          # not a skip
        lambda r: r.update(slos=[]),
        lambda r: r.update(slos=["no-objective"]),
        lambda r: r.update(workload_fingerprint="abc"),
    ])
    def test_bad_rows_drift(self, mutate):
        row = _good_row()
        mutate(row)
        with pytest.raises(SchemaDrift):
            check_row(row)

    def test_committed_fail_refused_but_live_allowed(self):
        row = _good_row(verdict="fail")
        with pytest.raises(SchemaDrift, match="not a baseline"):
            check_row(row)
        check_row(row, committed=False)

    def test_duplicate_scenarios_drift(self):
        doc = {"schema": LEDGER_SCHEMA,
               "scenarios": [_good_row(), _good_row()]}
        with pytest.raises(SchemaDrift, match="duplicate"):
            check_ledger(doc)

    def test_write_then_load_roundtrip(self, tmp_path):
        path = str(tmp_path / "ledger.json")
        write_ledger(path, [_good_row(scenario="b"),
                            _good_row(scenario="a")])
        doc = load_ledger(path)
        check_ledger(doc)
        names = [r["scenario"] for r in doc["scenarios"]]
        assert names == ["a", "b"]  # sorted, stable diffs

    def test_stable_row_drops_run_noise(self):
        row = _good_row()
        row["counts"] = {"ok": 10}
        row["slos"] = [{"slo": "availability@80", "good": 9,
                        "total": 10, "good_fraction": 0.9,
                        "objective": 0.8, "ok": True,
                        "violating_trace": None}]
        s = stable_row(row)
        assert "counts" not in s
        assert s["slos"] == ["availability@80"]

    def test_unreadable_ledger_is_drift(self, tmp_path):
        with pytest.raises(SchemaDrift):
            load_ledger(str(tmp_path / "missing.json"))
        bad = tmp_path / "bad.json"
        bad.write_text("{nope")
        with pytest.raises(SchemaDrift):
            load_ledger(str(bad))


class TestCommittedLedger:
    """The repo's own SCENARIO_LEDGER.json must stay fresh: schema
    clean and matching the statically-recomputable projection of the
    current catalog (fingerprints included). This is `--check` as a
    tier-1 test."""

    def test_committed_ledger_fresh(self):
        import shellac_tpu
        import os
        root = os.path.dirname(os.path.dirname(
            os.path.abspath(shellac_tpu.__file__)))
        path = os.path.join(root, DEFAULT_LEDGER)
        doc = load_ledger(path)
        check_ledger(doc)
        gate = [s for s in SCENARIOS.values() if s.gate]
        diff = compare_to_ledger(expected_static_rows(gate), doc,
                                 verdict_known=False)
        assert diff == [], (
            "SCENARIO_LEDGER.json is stale — regenerate with "
            "`python -m shellac_tpu scenarios --update-ledger`"
        )

    def test_expected_static_rows_know_skips(self):
        rows = expected_static_rows(list(SCENARIOS.values()))
        by_name = {r["scenario"]: r for r in rows}
        for s in SCENARIOS.values():
            r = by_name[s.name]
            if s.engine == "spec":
                assert r["verdict"] == "skip"
                assert r["skip_reason"].startswith("excluded: ")
            else:
                assert r["verdict"] is None  # needs a run

    def test_compare_detects_fingerprint_drift(self):
        gate = [s for s in SCENARIOS.values() if s.gate]
        rows = expected_static_rows(gate)
        doc = {"schema": LEDGER_SCHEMA,
               "scenarios": [dict(r) for r in rows]}
        tampered = [dict(r) for r in rows]
        tampered[0]["workload_fingerprint"] = "f" * 64
        diff = compare_to_ledger(tampered, doc, verdict_known=False)
        assert len(diff) == 1
        assert "workload_fingerprint" in diff[0]


# ---------------------------------------------------------------------
# Open-loop LoadGenerator against a stub NDJSON server


class _StubServer:
    """Tiny /generate stub: x-request-id on every response, NDJSON
    when `stream` is set, optional per-request latency via a
    `_sleep_s` payload key (client-side reserved keys are already
    stripped by the generator, so this one rides the wire)."""

    def __init__(self):
        outer = self
        self.seen = []
        self.lock = threading.Lock()

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                body = json.loads(self.rfile.read(n) or b"{}")
                with outer.lock:
                    outer.seen.append(
                        (body, {k.lower(): v for k, v
                                in self.headers.items()}))
                    rid = f"stub-{len(outer.seen)}"
                time.sleep(float(body.get("_sleep_s", 0.0)))
                self.send_response(200)
                self.send_header("x-request-id", rid)
                ctype = ("application/x-ndjson"
                         if body.get("stream") else "application/json")
                self.send_header("Content-Type", ctype)
                self.end_headers()
                if not body.get("stream"):
                    self.wfile.write(json.dumps(
                        {"tokens": [1, 2], "trace_id": rid}).encode())
                    return
                try:
                    for i in range(body.get("max_new", 4)):
                        self.wfile.write(json.dumps(
                            {"tokens": [i], "trace_id": rid}
                        ).encode() + b"\n")
                        self.wfile.flush()
                        time.sleep(0.02)
                    self.wfile.write(json.dumps(
                        {"done": True, "trace_id": rid}).encode()
                        + b"\n")
                except BrokenPipeError:
                    pass  # client cancelled mid-stream

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()


@pytest.fixture()
def stub():
    s = _StubServer()
    yield s
    s.close()


class TestLoadGeneratorOpenLoop:
    def test_plays_schedule_and_captures(self, stub):
        sched = [(0.0, {"tokens": [1], "max_new": 2,
                        "tenant": "acme", "kind": "chat"}),
                 (0.05, {"tokens": [2], "max_new": 2,
                         "tenant": "globex", "kind": "chat"})]
        gen = LoadGenerator(stub.url, schedule=sched, timeout=5,
                            capture=True)
        counts = gen.run()
        assert counts == {"ok": 2}
        assert len(gen.results) == 2
        for row in gen.results:
            assert row["trace_id"].startswith("stub-")
            assert row["outcome"] == "ok"
            assert row["latency_s"] is not None
        # Reserved keys never hit the wire; tenant rides the header.
        for body, headers in stub.seen:
            assert "tenant" not in body and "kind" not in body
            assert headers.get("x-shellac-tenant") in ("acme",
                                                       "globex")

    def test_streaming_ttft_and_done(self, stub):
        gen = LoadGenerator(stub.url, schedule=[
            (0.0, {"tokens": [1], "max_new": 3, "stream": True}),
        ], timeout=5, capture=True)
        assert gen.run() == {"ok": 1}
        [row] = gen.results
        assert row["ttft_s"] is not None
        assert row["ttft_s"] <= row["latency_s"]

    def test_mid_flight_cancellation(self, stub):
        gen = LoadGenerator(stub.url, schedule=[
            (0.0, {"tokens": [1], "max_new": 50, "stream": True,
                   "cancel_after_deltas": 2}),
        ], timeout=5, capture=True)
        assert gen.run() == {"cancelled": 1}
        [row] = gen.results
        assert row["outcome"] == "cancelled"
        assert row["ttft_s"] is not None

    def test_client_saturated_is_loud(self, stub):
        sched = [(0.0, {"tokens": [1], "_sleep_s": 0.8}),
                 (0.05, {"tokens": [2], "kind": "chat"}),
                 (0.1, {"tokens": [3], "kind": "chat"})]
        gen = LoadGenerator(stub.url, schedule=sched, timeout=5,
                            max_in_flight=1, capture=True)
        counts = gen.run()
        assert counts.get("client_saturated", 0) >= 1
        assert counts.get("ok", 0) >= 1
        saturated = [r for r in gen.results
                     if r["outcome"] == "client_saturated"]
        assert saturated
        assert saturated[0]["trace_id"] is None

    def test_connect_error_outcome(self):
        gen = LoadGenerator("http://127.0.0.1:9", schedule=[
            (0.0, {"tokens": [1]})], timeout=2, capture=True)
        counts = gen.run()
        assert counts == {"connect_error": 1}

    def test_seeded_rate_mode_reproducible(self):
        a = LoadGenerator("http://127.0.0.1:9", rate=5.0,
                          duration=10.0, seed=3,
                          payloads=[{"tokens": [1]}, {"tokens": [2]}])
        b = LoadGenerator("http://127.0.0.1:9", rate=5.0,
                          duration=10.0, seed=3,
                          payloads=[{"tokens": [1]}, {"tokens": [2]}])
        assert a.schedule == b.schedule
        assert len(a.schedule) > 10
        assert all(t < 10.0 for t, _ in a.schedule)

    def test_rate_needs_duration(self):
        with pytest.raises(ValueError):
            LoadGenerator("http://x", rate=5.0)

    def test_run_refuses_closed_loop(self):
        gen = LoadGenerator("http://127.0.0.1:9")
        with pytest.raises(RuntimeError):
            gen.run()


class TestGateSlis:
    def test_gate_slis_are_client_measurable_only(self):
        assert set(GATE_SLIS) == {"ttft", "e2e", "availability"}
        for s in SCENARIOS.values():
            for spec in parse_slo_specs(s.slos):
                assert spec.sli in GATE_SLIS
