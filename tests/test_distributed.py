"""Distributed bootstrap helpers (single-process behaviors only)."""

import pytest

from shellac_tpu import ParallelConfig
from shellac_tpu.parallel.distributed import env_config, global_mesh, initialize


class TestEnvConfig:
    def test_empty_env(self, monkeypatch):
        for var in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                    "JAX_NUM_PROCESSES", "NUM_PROCESSES", "WORLD_SIZE",
                    "JAX_PROCESS_ID", "PROCESS_ID", "RANK"):
            monkeypatch.delenv(var, raising=False)
        assert env_config() is None
        assert initialize() is False

    def test_full_env(self, monkeypatch):
        monkeypatch.setenv("COORDINATOR_ADDRESS", "10.0.0.1:1234")
        monkeypatch.setenv("WORLD_SIZE", "4")
        monkeypatch.setenv("RANK", "2")
        cfg = env_config()
        assert cfg == {
            "coordinator_address": "10.0.0.1:1234",
            "num_processes": 4,
            "process_id": 2,
        }

    def test_jax_prefixed_wins(self, monkeypatch):
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "a:1")
        monkeypatch.setenv("COORDINATOR_ADDRESS", "b:2")
        monkeypatch.setenv("WORLD_SIZE", "2")
        monkeypatch.setenv("RANK", "0")
        assert env_config()["coordinator_address"] == "a:1"

    def test_partial_env_raises(self, monkeypatch):
        for var in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                    "JAX_NUM_PROCESSES", "NUM_PROCESSES", "WORLD_SIZE",
                    "JAX_PROCESS_ID", "PROCESS_ID", "RANK"):
            monkeypatch.delenv(var, raising=False)
        monkeypatch.setenv("WORLD_SIZE", "4")
        with pytest.raises(ValueError, match="partial distributed"):
            env_config()

    def test_single_process_noop(self, monkeypatch):
        monkeypatch.setenv("COORDINATOR_ADDRESS", "x:1")
        monkeypatch.setenv("WORLD_SIZE", "1")
        monkeypatch.setenv("RANK", "0")
        assert initialize() is False  # nothing to rendezvous


class TestGlobalMesh:
    def test_device_count_mismatch(self):
        with pytest.raises(ValueError, match="wants 16 devices"):
            global_mesh(ParallelConfig(dp=16))

    def test_builds_over_all_devices(self):
        mesh = global_mesh(ParallelConfig(fsdp=8))
        assert mesh.devices.size == 8
