"""Distributed bootstrap helpers (single-process behaviors only)."""

import pytest

from shellac_tpu import ParallelConfig
from shellac_tpu.parallel.distributed import env_config, global_mesh, initialize


class TestEnvConfig:
    def test_empty_env(self, monkeypatch):
        for var in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                    "JAX_NUM_PROCESSES", "NUM_PROCESSES", "WORLD_SIZE",
                    "JAX_PROCESS_ID", "PROCESS_ID", "RANK"):
            monkeypatch.delenv(var, raising=False)
        assert env_config() is None
        assert initialize() is False

    def test_full_env(self, monkeypatch):
        monkeypatch.setenv("COORDINATOR_ADDRESS", "10.0.0.1:1234")
        monkeypatch.setenv("WORLD_SIZE", "4")
        monkeypatch.setenv("RANK", "2")
        cfg = env_config()
        assert cfg == {
            "coordinator_address": "10.0.0.1:1234",
            "num_processes": 4,
            "process_id": 2,
        }

    def test_jax_prefixed_wins(self, monkeypatch):
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "a:1")
        monkeypatch.setenv("COORDINATOR_ADDRESS", "b:2")
        monkeypatch.setenv("WORLD_SIZE", "2")
        monkeypatch.setenv("RANK", "0")
        assert env_config()["coordinator_address"] == "a:1"

    def test_partial_env_raises(self, monkeypatch):
        for var in ("JAX_COORDINATOR_ADDRESS", "COORDINATOR_ADDRESS",
                    "JAX_NUM_PROCESSES", "NUM_PROCESSES", "WORLD_SIZE",
                    "JAX_PROCESS_ID", "PROCESS_ID", "RANK"):
            monkeypatch.delenv(var, raising=False)
        monkeypatch.setenv("WORLD_SIZE", "4")
        with pytest.raises(ValueError, match="partial distributed"):
            env_config()

    def test_single_process_noop(self, monkeypatch):
        monkeypatch.setenv("COORDINATOR_ADDRESS", "x:1")
        monkeypatch.setenv("WORLD_SIZE", "1")
        monkeypatch.setenv("RANK", "0")
        assert initialize() is False  # nothing to rendezvous


class TestGlobalMesh:
    def test_device_count_mismatch(self):
        with pytest.raises(ValueError, match="wants 16 devices"):
            global_mesh(ParallelConfig(dp=16))

    def test_builds_over_all_devices(self):
        mesh = global_mesh(ParallelConfig(fsdp=8))
        assert mesh.devices.size == 8


_WORKER = """
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
from shellac_tpu.config import ParallelConfig
from shellac_tpu.parallel.distributed import initialize, global_mesh
assert initialize(), "initialize() did not join the cluster"
import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P
assert jax.process_count() == 2, jax.process_count()
assert len(jax.devices()) == 4, jax.devices()
mesh = global_mesh(ParallelConfig(dp=4))
sh = NamedSharding(mesh, P(("dp",)))
data = np.arange(4, dtype=np.float32)
arr = jax.make_array_from_callback((4,), sh, lambda idx: data[idx])
total = jax.jit(lambda x: x.sum(), out_shardings=NamedSharding(mesh, P()))(arr)
assert float(total) == 6.0, float(total)
print("WORKER_OK", jax.process_index(), flush=True)
"""


from conftest import needs_multiprocess_cpu as _needs_multiprocess_cpu


@_needs_multiprocess_cpu
class TestTwoProcessRendezvous:
    """Actual 2-process jax.distributed bring-up over the CPU backend.

    Each worker forces the CPU platform with 2 virtual devices, joins
    through our env-driven initialize(), builds the *global* 4-device
    mesh, and jit-reduces a dp-sharded array — a real cross-process
    collective (Gloo), not env parsing.
    """

    def test_rendezvous_and_allreduce(self, tmp_path):
        import socket
        import subprocess
        import sys

        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
        script = tmp_path / "worker.py"
        script.write_text(_WORKER)
        env_base = {
            **__import__("os").environ,
            "PYTHONPATH": str(__import__("pathlib").Path(__file__).parents[1]),
            "JAX_COORDINATOR_ADDRESS": f"127.0.0.1:{port}",
            "JAX_NUM_PROCESSES": "2",
        }
        procs = [
            subprocess.Popen(
                [sys.executable, str(script)],
                env={**env_base, "JAX_PROCESS_ID": str(r)},
                stdout=subprocess.PIPE,
                stderr=subprocess.STDOUT,
                text=True,
            )
            for r in range(2)
        ]
        outs = []
        try:
            for p in procs:
                out, _ = p.communicate(timeout=180)
                outs.append(out)
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
        for r, (p, out) in enumerate(zip(procs, outs)):
            assert p.returncode == 0, f"rank {r} failed:\n{out}"
            assert f"WORKER_OK {r}" in out, out
