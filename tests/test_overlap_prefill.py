"""Overlapped prefill dispatch: the in-flight prefill pipeline must be
invisible to every request's math.

Core contracts under test:
  - overlap_prefill on/off produce TOKEN-IDENTICAL outputs (and
    identical logprob / top-K / prompt-logprob sidecars) across the
    matrix dense/paged/paged-int8 x chunked/unchunked x greedy/seeded,
    with stop sequences, min_tokens, logit_bias in the mix — the
    acceptance criterion of the prefill-overlap PR;
  - a constrained request's DFA state-0 advance happens at SETTLE (the
    first token is a host value only then) and constrained outputs are
    identical on/off;
  - disaggregated prefill_only freezes at settle and the frozen slot
    exports/imports byte-identically to a non-overlapped engine;
  - cancellation / abort with a prefill in flight never leaks a stale
    first token into a successor request;
  - prefill_chunk auto-tuning picks by measurement (scripted-clock
    unit tests), restores engine state, and "auto" construction is
    inert until tuned;
  - the simulated host-latency harness's prefill clock shows the
    overlap win the perf gate's mixed prefill-heavy rows assert in CI.

NOTE tier-1 timing: this file sorts late enough that the 870s window
never reaches it locally; CI runs it explicitly in the perf-gate job
(same treatment as test_overlap_decode.py).
"""

import time

import jax
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference import disagg
from shellac_tpu.inference.autotune import (
    SimulatedHostLatency,
    autotune_prefill_chunk,
    maybe_autotune_prefill_chunk,
)
from shellac_tpu.inference.batching import (
    BatchingEngine,
    PagedBatchingEngine,
)


def _tiny(**kw):
    return get_model_config("tiny").replace(dtype="float32", **kw)


@pytest.fixture(scope="module")
def setup():
    cfg = _tiny()
    from shellac_tpu.models import transformer

    return cfg, transformer.init_params(cfg, jax.random.PRNGKey(0))


def _drain(eng):
    out = {}
    while eng.pending:
        for rid, toks in eng.step():
            out[rid] = list(toks)
    return out


def _build(cfg, params, *, backend="dense", overlap_prefill=False,
           **kw):
    if backend.startswith("paged"):
        kw.setdefault("block_size", 16 if backend == "paged" else 64)
        kw.setdefault("pool_tokens", 2048)
        return PagedBatchingEngine(
            cfg, params, cache_backend=backend,
            overlap_prefill=overlap_prefill, **kw,
        )
    return BatchingEngine(cfg, params, cache_backend=backend,
                          overlap_prefill=overlap_prefill, **kw)


def _drain_after_submit(eng, req, **kw):
    eng.submit(*req, **kw)
    return _drain(eng)


class TestOverlapPrefillParity:
    """The on/off token-identity matrix. Each run mixes greedy,
    seeded-sampled, stop-sequence, min_tokens + logit_bias, and
    prompt_logprobs requests in ONE workload, on engines built with
    logprobs + top_logprobs — so every sidecar the settle carries is
    compared, not just the tokens."""

    @pytest.mark.parametrize("chunked", [False, True],
                             ids=["whole", "chunked"])
    @pytest.mark.parametrize("backend", ["dense", "paged", "paged-int8"])
    def test_mixed_workload_token_identical(self, setup, backend,
                                            chunked):
        cfg, params = setup
        rng = np.random.default_rng(0)
        # Probe (strict engine) for an EOS id and a stop sequence that
        # actually occur in greedy output.
        probe = _build(cfg, params, n_slots=1, max_len=96)
        full = probe.run([("p", rng.integers(0, cfg.vocab_size, 6),
                           12)])["p"]
        eos = int(full[len(full) // 2])
        stop = [int(full[3]), int(full[4])]
        prompts = [rng.integers(0, cfg.vocab_size, 4 + 3 * i)
                   for i in range(6)]
        got = []
        for overlap in (False, True):
            kw = dict(n_slots=3, max_len=96, decode_ticks=2,
                      eos_id=eos, logprobs=True, top_logprobs=2,
                      overlap_decode=True)
            if chunked:
                kw.update(prefill_chunk=6, max_prefills_per_step=1)
            eng = _build(cfg, params, backend=backend,
                         overlap_prefill=overlap, **kw)
            eng.submit("greedy", prompts[0], 8)
            eng.submit("seeded", prompts[1], 8, temperature=1.3,
                       top_k=None, seed=1234)
            eng.submit("stopped", prompts[2], 10, stop=[stop])
            eng.submit("banned", prompts[3], 10, min_tokens=5,
                       logit_bias={int(full[1]): -2.0})
            eng.submit("scored", prompts[4], 6, prompt_logprobs=True)
            eng.submit("short", prompts[5], 1)
            out = _drain(eng)
            got.append((
                out,
                {r: eng.finished_logprobs.pop(r) for r in out},
                eng.finished_top_logprobs.pop("greedy"),
                eng.finished_prompt_logprobs.pop("scored"),
            ))
            assert len(out) == 6
        assert got[0] == got[1]
        # The scored prompt's per-token list covers the whole prompt.
        assert len(got[0][3]) == prompts[4].size

    def test_constraint_first_token_advances_at_settle(self, setup):
        """A constrained request's DFA state-0 advance needs the
        SETTLED first token: before the settle the slot's device state
        is still state 0, after it the state matches the host DFA walk
        of the first emitted token — and outputs are identical
        on/off."""
        from shellac_tpu.inference.constraints import compile_token_dfa
        from shellac_tpu.training.tokenizer import ByteTokenizer

        cfg, params = setup
        eos = cfg.vocab_size - 2
        dfa = compile_token_dfa("(cat|dog)", ByteTokenizer(),
                                cfg.vocab_size, eos_id=eos)
        outs = []
        for overlap in (False, True):
            eng = _build(cfg, params, n_slots=2, max_len=64,
                         eos_id=eos, decode_ticks=2,
                         overlap_prefill=overlap, overlap_decode=True)
            eng.submit("c", np.array([1, 2, 3], np.int32), 8,
                       constraint=dfa)
            if overlap:
                eng.step()  # dispatch only: flight in the pipeline
                assert eng._pflights, "prefill never went in flight"
                slot = eng._pflights[0].slot
                # Pre-settle: the device DFA state is still state 0.
                assert int(np.asarray(eng._cstate)[slot]) == 0
                # Settle exactly (white-box: the next step() would
                # also dispatch a window and advance the state past
                # the first token before returning).
                eng._settle_prefills()
                req = next(r for r in eng._slots if r is not None)
                assert req.out, "settle deposited no first token"
                want = max(int(dfa.trans[0, req.out[0]]), 0)
                assert int(np.asarray(eng._cstate)[slot]) == want
            outs.append(_drain(eng))
        assert outs[0] == outs[1]
        text = bytes(outs[0]["c"][:3]).decode()
        assert text in ("cat", "dog")

    def test_ttft_recorded_at_settle(self, setup):
        """The span's first-token mark fires at the settle boundary,
        not at dispatch (the settle-point TTFT definition)."""
        from shellac_tpu.obs import Registry, ServeMetrics

        cfg, params = setup
        reg = Registry()
        sm = ServeMetrics(reg)
        eng = _build(cfg, params, n_slots=1, max_len=64,
                     overlap_prefill=True, registry=reg)
        tr = sm.trace()
        eng.submit("t", np.arange(5, dtype=np.int32), 4, trace=tr)
        eng.step()  # dispatch
        h = reg.get("shellac_ttft_seconds")
        assert h is None or h.count == 0
        eng.step()  # settle
        h = reg.get("shellac_ttft_seconds")
        assert h is not None and h.count == 1
        _drain(eng)


class TestOverlapPrefillLifecycle:
    def test_cancel_mid_prefill_flight(self, setup):
        """A request cancelled while its prefill is in flight must not
        leak its first token into the slot's next tenant."""
        cfg, params = setup
        rng = np.random.default_rng(7)
        prompt = rng.integers(0, cfg.vocab_size, 6)
        eng = _build(cfg, params, n_slots=1, max_len=64,
                     overlap_prefill=True, decode_ticks=2)
        eng.submit("c1", prompt, 10)
        eng.step()  # prefill dispatched, not settled
        assert eng._pflights
        assert eng.cancel("c1")
        got = _drain_after_submit(eng, ("c2", prompt[:4], 5))
        want = _build(cfg, params, n_slots=1, max_len=64,
                      decode_ticks=2).run([("c2", prompt[:4], 5)])
        assert got == {k: list(v) for k, v in want.items()}

    def test_abort_all_mid_prefill_flight(self, setup):
        """abort_all with prefills in flight drains them (synced and
        discarded) and the next tenant produces exactly the
        strict-ordering output."""
        cfg, params = setup
        rng = np.random.default_rng(8)
        prompt = rng.integers(0, cfg.vocab_size, 8)
        eng = _build(cfg, params, backend="paged", n_slots=2,
                     max_len=64, overlap_prefill=True, decode_ticks=2)
        free0 = len(eng._free)
        eng.submit("a", prompt, 8)
        eng.submit("b", prompt[:3], 6)
        eng.step()
        assert eng._pflights, "no prefill in flight"
        dropped = eng.abort_all()
        assert sorted(dropped) == ["a", "b"]
        assert not eng._pflights
        assert len(eng._free) == free0  # pool restored
        got = _drain_after_submit(eng, ("fresh", prompt[:5], 4))
        want = _build(cfg, params, backend="paged", n_slots=2,
                      max_len=64, decode_ticks=2).run(
            [("fresh", prompt[:5], 4)])
        assert got == {k: list(v) for k, v in want.items()}

    def test_completed_at_prefill_settles_next_boundary(self, setup):
        """max_new=1 requests complete at settle; the freed slot is
        reused and every output matches strict ordering."""
        cfg, params = setup
        rng = np.random.default_rng(9)
        reqs = [(i, rng.integers(0, cfg.vocab_size, 3 + i), 1)
                for i in range(5)]
        outs = []
        for overlap in (False, True):
            eng = _build(cfg, params, n_slots=2, max_len=64,
                         overlap_prefill=overlap)
            for r in reqs:
                eng.submit(*r)
            outs.append(_drain(eng))
        assert outs[0] == outs[1]
        assert all(len(v) == 1 for v in outs[0].values())

    def test_prefill_only_freezes_at_settle_then_exports(self, setup):
        """Disagg composition: under overlap the freeze appears only
        at the settle boundary, and the exported slot continues
        byte-identically on the importing engine."""
        cfg, params = setup
        prompt = np.arange(1, 9, dtype=np.int32)
        ctrl = _build(cfg, params, backend="paged", n_slots=2,
                      max_len=96)
        expected = ctrl.run([("c", prompt, 6)])["c"]

        a = _build(cfg, params, backend="paged", n_slots=2, max_len=96,
                   overlap_prefill=True)
        a.submit("m", prompt, 6, prefill_only=True)
        a.step()  # dispatch only
        assert not a.frozen_prefills, "froze before the settle"
        while not a.frozen_prefills:
            a.step()
        slot = a.frozen_prefills["m"]
        blob = disagg.MigrationBlob.deserialize(
            disagg.export_slot(a, slot, a._slots[slot]).serialize()
        )
        assert a.release_frozen("m") is not None

        b = _build(cfg, params, backend="paged", n_slots=2, max_len=96,
                   overlap_prefill=True)
        disagg.import_blob(b, blob, rid="m")
        assert _drain(b)["m"] == list(expected)

    def test_prefix_registration_moves_to_settle(self, setup):
        """on_prefill_complete (prefix-cache registration) fires at
        settle: a cancelled in-flight prefill never registers its
        blocks, and a settled one does."""
        cfg, params = setup
        prompt = np.arange(32, dtype=np.int32)
        eng = _build(cfg, params, backend="paged", n_slots=2,
                     max_len=96, overlap_prefill=True,
                     prefix_cache=True)
        eng.submit("x", prompt, 4)
        eng.step()  # dispatch
        assert len(eng._hash_to_block) == 0, "registered pre-settle"
        eng.step()  # settle
        assert len(eng._hash_to_block) > 0
        eng.cancel("x")
        n_reg = len(eng._hash_to_block)
        eng.submit("y", prompt[:16], 4)
        eng.step()
        assert len(eng._hash_to_block) == n_reg  # in flight: no change
        _drain(eng)


class TestPrefillChunkAutotune:
    def test_auto_is_inert_until_tuned(self, setup):
        cfg, params = setup
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             prefill_chunk="auto")
        assert eng.prefill_chunk is None
        assert eng.prefill_chunk_requested == "auto"
        assert eng.prefill_chunk_source == "auto"
        assert eng.stats["prefill_chunk"] == 0

    def test_bad_prefill_chunk_string_rejected(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="auto"):
            BatchingEngine(cfg, params, prefill_chunk="fast")

    def test_scripted_clock_selects_winner(self, setup):
        """Selection is measurement-driven: a scripted clock that
        makes chunk=16 fastest must elect 16 regardless of real wall
        time."""
        cfg, params = setup
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=128,
                             prefill_chunk="auto", seed=3)
        elapsed = {None: 5.0, 8: 3.0, 16: 0.5, 48: 4.0}
        clock = {"t": 0.0, "nticks": 0}

        def timer():
            # Three calls per candidate (t0, t_first, t1): advance the
            # scripted elapsed on the LAST call of each triple.
            clock["nticks"] += 1
            if clock["nticks"] % 3 == 0:
                clock["t"] += elapsed[eng.prefill_chunk]
            return clock["t"]

        res = autotune_prefill_chunk(
            eng, candidates=(None, 8, 16, 48), timer=timer,
        )
        assert res.best == 16
        assert eng.prefill_chunk == 16
        assert eng.prefill_chunk_source == "auto-tuned"
        assert eng.stats["prefill_chunk"] == 16
        assert set(res.measurements) == {None, 8, 16, 48}

    def test_tune_restores_key_and_leaves_engine_idle(self, setup):
        cfg, params = setup
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=128,
                             prefill_chunk="auto", seed=7)
        key0 = np.asarray(eng._key).copy()
        stats0 = dict(eng.stats)
        autotune_prefill_chunk(eng, candidates=(None, 16))
        assert eng.pending == 0
        assert (np.asarray(eng._key) == key0).all()
        for k in ("requests_completed", "tokens_generated", "prefills"):
            assert eng.stats[k] == stats0[k]

    def test_tuned_engine_still_matches_reference(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(11)
        prompt = rng.integers(0, cfg.vocab_size, 40)
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=128,
                             prefill_chunk="auto", seed=3,
                             overlap_prefill=True)
        autotune_prefill_chunk(eng, candidates=(None, 16))
        got = _drain_after_submit(eng, ("r", prompt, 8))
        ref = BatchingEngine(cfg, params, n_slots=2, max_len=128,
                             prefill_chunk=eng.prefill_chunk, seed=3)
        assert got == {"r": list(ref.run([("r", prompt, 8)])["r"])}

    def test_maybe_skips_fixed_and_spec(self, setup):
        cfg, params = setup
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=128,
                             prefill_chunk=8)
        assert maybe_autotune_prefill_chunk(eng) is None
        assert eng.prefill_chunk == 8

    def test_rolling_refuses_chunk_growth(self, setup):
        cfg, params = setup
        wcfg = _tiny(attn_window=32)
        from shellac_tpu.models import transformer

        wparams = transformer.init_params(wcfg, jax.random.PRNGKey(0))
        eng = BatchingEngine(wcfg, wparams, n_slots=2, max_len=64,
                             cache_backend="rolling", prefill_chunk=4)
        with pytest.raises(ValueError, match="chunk slack"):
            eng.set_prefill_chunk(16)
        eng.set_prefill_chunk(2)  # shrinking inside the slack is fine
        assert eng.prefill_chunk == 2


class TestSimulatedPrefillLatency:
    def test_overlap_hides_injected_prefill_latency(self, setup):
        """The gate's mixed-row claim at smoke scale: with an injected
        per-prefill round trip, the in-flight pipeline beats inline
        settles. Thresholds are lenient (the gate's calibrated run
        asserts the real 1.3x floor)."""
        cfg, params = setup
        rng = np.random.default_rng(12)

        def run(overlap):
            eng = _build(cfg, params, n_slots=2, max_len=96,
                         overlap_prefill=overlap, overlap_decode=True,
                         decode_ticks=2, max_prefills_per_step=1)
            eng.run([("w", rng.integers(0, cfg.vocab_size, 8), 2)])
            shim = SimulatedHostLatency(eng, device_s=0.03,
                                        prefill_s=0.05)
            for i in range(6):
                eng.submit(i, rng.integers(0, cfg.vocab_size, 8), 4)
            t0 = time.perf_counter()
            done = {}
            while eng.pending:
                for rid, out in eng.step():
                    done[rid] = out
                time.sleep(0.02)
            dt = time.perf_counter() - t0
            shim.uninstall()
            assert len(done) == 6
            return dt

        serial, overlapped = run(False), run(True)
        assert serial / overlapped > 1.1, (serial, overlapped)

    def test_shim_outputs_identical(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(13)
        prompt = rng.integers(0, cfg.vocab_size, 6)
        eng = _build(cfg, params, n_slots=1, max_len=64,
                     overlap_prefill=True, decode_ticks=2)
        shim = SimulatedHostLatency(eng, device_s=0.01, prefill_s=0.02)
        got = _drain_after_submit(eng, ("x", prompt, 6))
        shim.uninstall()
        ref = _build(cfg, params, n_slots=1, max_len=64, decode_ticks=2)
        assert got == {"x": list(ref.run([("x", prompt, 6)])["x"])}


class TestStatsSurface:
    def test_engine_stats_expose_prefill_config(self, setup):
        cfg, params = setup
        eng = _build(cfg, params, n_slots=1, max_len=64,
                     overlap_prefill=True, prefill_chunk=8)
        assert eng.stats["overlap_prefill"] == 1
        assert eng.stats["prefill_chunk"] == 8
        eng2 = _build(cfg, params, n_slots=1, max_len=64)
        assert eng2.stats["overlap_prefill"] == 0
        assert eng2.stats["prefill_chunk"] == 0

    def test_prefill_settle_phase_observed(self, setup):
        """The step-phase partition carries the new prefill_settle
        phase, and under overlap the settle cost lands there instead
        of in prefill_dispatch."""
        from shellac_tpu.obs import STEP_PHASES, Registry

        assert "prefill_settle" in STEP_PHASES
        cfg, params = setup
        reg = Registry()
        eng = _build(cfg, params, n_slots=2, max_len=64,
                     overlap_prefill=True, registry=reg)
        _drain_after_submit(eng, ("h", np.arange(5, dtype=np.int32), 4))
        h = reg.get("shellac_step_phase_seconds",
                    phase="prefill_settle")
        assert h is not None and h.count > 0 and h.sum > 0

    def test_server_stats_expose_prefill_knobs(self, setup):
        from shellac_tpu.inference.server import InferenceServer

        cfg, params = setup
        srv = InferenceServer(cfg, params, n_slots=2, max_len=64,
                              overlap_prefill=True, prefill_chunk=8,
                              metrics=False)
        try:
            eng = srv.engine
            assert eng.overlap_prefill
            assert eng.prefill_chunk == 8
            assert eng.prefill_chunk_source == "fixed"
        finally:
            srv.close()
