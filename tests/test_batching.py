"""Continuous batching engine tests.

Core invariant: scheduling must be invisible to the math — each
request's greedy output equals the single-request Engine's, no matter
how requests share slots.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.batching import BatchingEngine
from shellac_tpu.inference.engine import Engine
from shellac_tpu.models import transformer


def _tiny(**kw):
    return get_model_config("tiny").replace(dtype="float32", **kw)


@pytest.fixture(scope="module")
def setup():
    cfg = _tiny()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    return cfg, params


def _ref_generate(cfg, params, tokens, max_new):
    eng = Engine(cfg, params, temperature=0.0)
    out = eng.generate(
        jnp.asarray(np.asarray(tokens, np.int32)[None]),
        max_new_tokens=max_new,
    )
    return np.asarray(out.tokens)[0].tolist()


class TestContinuousBatching:
    def test_matches_engine_ragged(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(0)
        reqs = [
            ("a", rng.integers(0, cfg.vocab_size, 5), 7),
            ("b", rng.integers(0, cfg.vocab_size, 12), 3),
            ("c", rng.integers(0, cfg.vocab_size, 3), 10),
        ]
        srv = BatchingEngine(cfg, params, n_slots=2, max_len=64)
        results = srv.run(reqs)
        assert set(results) == {"a", "b", "c"}
        for rid, toks, max_new in reqs:
            assert results[rid] == _ref_generate(cfg, params, toks, max_new), rid

    def test_more_requests_than_slots(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(1)
        reqs = [(i, rng.integers(0, cfg.vocab_size, 4 + i % 3), 4)
                for i in range(7)]
        srv = BatchingEngine(cfg, params, n_slots=2, max_len=64)
        results = srv.run(reqs)
        assert len(results) == 7
        for rid, toks, max_new in reqs:
            assert results[rid] == _ref_generate(cfg, params, toks, max_new)

    def test_per_request_sampling_isolated(self, setup):
        """A greedy request sharing the batch with high-temperature
        requests is unaffected by them (per-slot sampling vectors)."""
        cfg, params = setup
        rng = np.random.default_rng(3)
        greedy_prompt = rng.integers(0, cfg.vocab_size, 6)
        want = _ref_generate(cfg, params, greedy_prompt, 8)
        srv = BatchingEngine(cfg, params, n_slots=3, max_len=64,
                             temperature=1.5)
        srv.submit("hot1", rng.integers(0, cfg.vocab_size, 4), 8)
        srv.submit("greedy", greedy_prompt, 8, temperature=0.0)
        srv.submit("hot2", rng.integers(0, cfg.vocab_size, 5), 8,
                   temperature=1.2, top_k=8)
        results = {}
        while srv.pending:
            results.update(srv.step())
        assert results["greedy"] == want
        assert len(results["hot1"]) == 8 and len(results["hot2"]) == 8

    def test_sampling_params_reset_on_slot_reuse(self, setup):
        """A slot freed by a sampled request must not leak its settings
        into the next (default-greedy) request."""
        cfg, params = setup
        rng = np.random.default_rng(4)
        p1 = rng.integers(0, cfg.vocab_size, 4)
        p2 = rng.integers(0, cfg.vocab_size, 7)
        srv = BatchingEngine(cfg, params, n_slots=1, max_len=64)
        srv.submit("hot", p1, 4, temperature=2.0)
        srv.submit("greedy", p2, 6)  # engine default: greedy
        results = {}
        while srv.pending:
            results.update(srv.step())
        assert results["greedy"] == _ref_generate(cfg, params, p2, 6)

    def test_bad_sampling_params_rejected(self, setup):
        cfg, params = setup
        srv = BatchingEngine(cfg, params, n_slots=1, max_len=64)
        with pytest.raises(ValueError, match="top_p"):
            srv.submit("x", np.array([1], np.int32), 2, top_p=0.0)
        with pytest.raises(ValueError, match="temperature"):
            srv.submit("x", np.array([1], np.int32), 2, temperature=-1.0)
        with pytest.raises(ValueError, match="min_p"):
            srv.submit("x", np.array([1], np.int32), 2, min_p=1.0)
        with pytest.raises(ValueError, match="top_k"):
            srv.submit("x", np.array([1], np.int32), 2, top_k=0)

    def test_min_tokens_suppresses_eos(self, setup):
        """EOS is banned from sampling until min_tokens are emitted;
        without the ban the same request stops early."""
        cfg, params = setup
        prompt = np.array([1, 2, 3], np.int32)
        full = _ref_generate(cfg, params, prompt, 12)
        eos = full[3]  # greedy emits this as token 4
        srv = BatchingEngine(cfg, params, n_slots=1, max_len=64, eos_id=eos)
        assert srv.run([("early", prompt, 12)])["early"] == full[:4]
        srv.submit("late", prompt, 12, min_tokens=8)
        results = {}
        while srv.pending:
            results.update(srv.step())
        out = results["late"]
        # The first 8 tokens can never be EOS; generation may still end
        # later (budget or a genuine post-ban EOS).
        assert len(out) >= 8
        assert all(t != eos for t in out[:8])

    def test_min_tokens_needs_eos_id(self, setup):
        cfg, params = setup
        srv = BatchingEngine(cfg, params, n_slots=1, max_len=64)
        with pytest.raises(ValueError, match="eos_id"):
            srv.submit("x", np.array([1], np.int32), 4, min_tokens=2)

    def test_logit_bias_forces_and_bans_tokens(self, setup):
        """A huge positive bias forces a token; a huge negative bias on
        the greedy choice bans it."""
        cfg, params = setup
        prompt = np.array([4, 5, 6], np.int32)
        base = _ref_generate(cfg, params, prompt, 4)
        srv = BatchingEngine(cfg, params, n_slots=1, max_len=64)
        srv.submit("forced", prompt, 4, logit_bias={42: 1e9})
        srv.submit("banned", prompt, 1, logit_bias={base[0]: -1e9})
        results = {}
        while srv.pending:
            results.update(srv.step())
        assert results["forced"] == [42, 42, 42, 42]
        assert results["banned"][0] != base[0]

    def test_logit_bias_out_of_vocab_rejected(self, setup):
        cfg, params = setup
        srv = BatchingEngine(cfg, params, n_slots=1, max_len=64)
        with pytest.raises(ValueError, match="vocab"):
            srv.submit("x", np.array([1], np.int32), 2,
                       logit_bias={cfg.vocab_size: 1.0})

    def test_cancel_frees_slot_and_queue(self, setup):
        """cancel() drops in-flight work (slot reusable at once) and
        queued work; surviving requests stay exact."""
        cfg, params = setup
        rng = np.random.default_rng(9)
        p1 = rng.integers(0, cfg.vocab_size, 4)
        p2 = rng.integers(0, cfg.vocab_size, 6)
        p3 = rng.integers(0, cfg.vocab_size, 5)
        srv = BatchingEngine(cfg, params, n_slots=1, max_len=64)
        srv.submit("doomed", p1, 30)
        srv.submit("queued_doomed", p3, 30)
        srv.submit("keeper", p2, 6)
        srv.step()  # "doomed" occupies the only slot
        assert srv.cancel("doomed") is True
        assert srv.cancel("queued_doomed") is True
        assert srv.cancel("nope") is False
        results = {}
        while srv.pending:
            results.update(srv.step())
        assert list(results) == ["keeper"]
        assert results["keeper"] == _ref_generate(cfg, params, p2, 6)

    def test_eos_frees_slot_early(self, setup):
        cfg, params = setup
        prompt = np.array([1, 2, 3], np.int32)
        full = _ref_generate(cfg, params, prompt, 12)
        # Use the 4th greedy token as "EOS": generation must stop there.
        eos = full[3]
        srv = BatchingEngine(cfg, params, n_slots=1, max_len=64, eos_id=eos)
        results = srv.run([("x", prompt, 12)])
        assert results["x"] == full[:4]

    def test_incremental_submit(self, setup):
        """Requests arriving mid-flight join free slots."""
        cfg, params = setup
        srv = BatchingEngine(cfg, params, n_slots=2, max_len=64)
        srv.submit("first", np.array([5, 6], np.int32), 6)
        done = {}
        for _ in range(3):
            for rid, out in srv.step():
                done[rid] = out
        srv.submit("late", np.array([9], np.int32), 4)
        while srv.pending:
            for rid, out in srv.step():
                done[rid] = out
        assert done["first"] == _ref_generate(cfg, params, [5, 6], 6)
        assert done["late"] == _ref_generate(cfg, params, [9], 4)

    def test_validation(self, setup):
        cfg, params = setup
        srv = BatchingEngine(cfg, params, n_slots=1, max_len=32)
        with pytest.raises(ValueError, match="empty prompt"):
            srv.submit("e", np.zeros((0,), np.int32), 4)
        with pytest.raises(ValueError, match="exceeds max_len"):
            srv.submit("big", np.ones((20,), np.int32), 20)


class TestMultiTickDecode:
    """decode_ticks > 1: K decode steps per host sync must be invisible
    to the math — greedy per-request output identical to the
    single-request engine, including EOS/budget finishing mid-window."""

    @pytest.mark.parametrize("ticks", [2, 4, 7])
    def test_matches_engine_through_churn(self, setup, ticks):
        cfg, params = setup
        rng = np.random.default_rng(5)
        reqs = [
            ("a", rng.integers(0, cfg.vocab_size, 5), 7),
            ("b", rng.integers(0, cfg.vocab_size, 12), 3),
            ("c", rng.integers(0, cfg.vocab_size, 3), 10),
            ("d", rng.integers(0, cfg.vocab_size, 9), 1),
        ]
        srv = BatchingEngine(
            cfg, params, n_slots=2, max_len=64, decode_ticks=ticks
        )
        results = srv.run(reqs)
        for rid, toks, max_new in reqs:
            assert results[rid] == _ref_generate(cfg, params, toks, max_new), rid

    def test_eos_mid_window_discards_overshoot(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(6)
        toks = rng.integers(0, cfg.vocab_size, 6)
        want = _ref_generate(cfg, params, toks, 12)
        eos = want[2]  # force an EOS two tokens in
        srv = BatchingEngine(
            cfg, params, n_slots=1, max_len=64, eos_id=eos, decode_ticks=5
        )
        got = srv.run([("x", toks, 12)])["x"]
        ref = BatchingEngine(
            cfg, params, n_slots=1, max_len=64, eos_id=eos
        ).run([("x", toks, 12)])["x"]
        assert got == ref
        assert got[-1] == eos or len(got) == 12

    def test_paged_multi_tick(self, setup):
        from shellac_tpu.inference.batching import PagedBatchingEngine

        cfg, params = setup
        rng = np.random.default_rng(7)
        reqs = [(i, rng.integers(0, cfg.vocab_size, 20), 6) for i in range(5)]
        srv = PagedBatchingEngine(
            cfg, params, n_slots=2, max_len=64, block_size=8,
            pool_tokens=96, decode_ticks=3,
        )
        results = srv.run(reqs)
        assert len(results) == 5
        for rid, toks, max_new in reqs:
            assert results[rid] == _ref_generate(cfg, params, toks, max_new), rid

    def test_bad_decode_ticks_rejected(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="decode_ticks"):
            BatchingEngine(cfg, params, decode_ticks=0)


class TestStopSequences:
    def test_stop_truncates_and_frees(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(8)
        toks = rng.integers(0, cfg.vocab_size, 6)
        full = _ref_generate(cfg, params, toks, 12)
        # Use the 3rd-4th generated tokens as a 2-token stop sequence.
        stop = [full[2], full[3]]
        srv = BatchingEngine(cfg, params, n_slots=1, max_len=64)
        srv.submit("x", toks, 12, stop=[stop])
        out = srv.run()["x"]
        assert out == full[:2]
        # The slot must be free for the next request.
        srv.submit("y", toks, 3)
        assert srv.run()["y"] == full[:3]

    def test_stop_with_multi_tick(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(9)
        toks = rng.integers(0, cfg.vocab_size, 5)
        full = _ref_generate(cfg, params, toks, 10)
        stop = [full[4]]
        # The stop token may also occur before index 4 (the sampled
        # sequence is backend/version dependent); generation ends at its
        # FIRST occurrence, wherever that is.
        expect = full[: full.index(full[4])]
        for ticks in (1, 4):
            srv = BatchingEngine(
                cfg, params, n_slots=2, max_len=64, decode_ticks=ticks
            )
            srv.submit("x", toks, 10, stop=[stop])
            assert srv.run()["x"] == expect, ticks

    def test_no_match_runs_to_budget(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(10)
        toks = rng.integers(0, cfg.vocab_size, 5)
        full = _ref_generate(cfg, params, toks, 6)
        srv = BatchingEngine(cfg, params, n_slots=1, max_len=64)
        srv.submit("x", toks, 6, stop=[[cfg.vocab_size - 1] * 3])
        assert srv.run()["x"] == full

    def test_empty_stop_rejected(self, setup):
        cfg, params = setup
        srv = BatchingEngine(cfg, params, n_slots=1, max_len=64)
        with pytest.raises(ValueError, match="empty stop"):
            srv.submit("x", [1, 2], 4, stop=[[]])


def test_prefill_finish_conditions_checked_for_refilled_slots(setup):
    """A request admitted after another finishes at prefill must get its
    own prefill-phase finish check (stop hit by the prefill token,
    max_new=1) before any decode window runs."""
    cfg, params = setup
    rng = np.random.default_rng(11)
    toks = rng.integers(0, cfg.vocab_size, 5)
    first = _ref_generate(cfg, params, toks, 1)  # the prefill token
    srv = BatchingEngine(cfg, params, n_slots=1, max_len=64, decode_ticks=4)
    # A finishes at prefill (max_new=1), freeing the slot; B's stop
    # sequence is exactly its prefill token.
    srv.submit("a", toks, 1)
    srv.submit("b", toks, 8, stop=[[first[0]]])
    srv.submit("c", toks, 1)
    results = srv.run()
    assert results["a"] == first
    assert results["b"] == []  # stop matched at prefill, truncated
    assert results["c"] == first


class TestPrefillBudget:
    def test_results_unchanged_with_budget(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(12)
        reqs = [
            (i, rng.integers(0, cfg.vocab_size, int(rng.integers(3, 15))),
             int(rng.integers(2, 8)))
            for i in range(6)
        ]
        want = {
            rid: _ref_generate(cfg, params, toks, mx)
            for rid, toks, mx in reqs
        }
        srv = BatchingEngine(
            cfg, params, n_slots=4, max_len=64, max_prefills_per_step=1
        )
        results = srv.run(reqs)
        assert results == want

    def test_at_most_budget_prefills_per_step(self, setup):
        cfg, params = setup
        rng = np.random.default_rng(13)
        srv = BatchingEngine(
            cfg, params, n_slots=4, max_len=64, max_prefills_per_step=2
        )
        for i in range(4):
            srv.submit(i, rng.integers(0, cfg.vocab_size, 5), 6)
        before = srv.stats["prefills"]
        srv.step()
        assert srv.stats["prefills"] - before == 2
        srv.step()
        assert srv.stats["prefills"] == 4

    def test_bad_budget_rejected(self, setup):
        cfg, params = setup
        with pytest.raises(ValueError, match="max_prefills"):
            BatchingEngine(cfg, params, max_prefills_per_step=0)
