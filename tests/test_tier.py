"""Serving-tier router (`inference/tier.py`) against scripted stub
replicas — no engines, no JAX: these tests pin the ROUTER's contract
(docs/serving_tier.md) at the HTTP boundary.

  - membership: health polling, circuit-breaker ejection on repeated
    failures, half-open probe readmission, drain observation, replica
    respawn through the factory;
  - requests: retryable failures (connect, 503, 429, retryable
    in-band stream errors) land on a DIFFERENT replica within the
    deadline; non-retryable outcomes (400, mid-stream loss after
    bytes flowed) fail loudly;
  - routing: affinity keys stick to one replica, spill to the
    least-loaded when the target runs hot, and fall back when the
    target is ejected.

The heavyweight twin — real engines, real SIGKILL — is
tests/test_tier_chaos.py (isolated fault-injection CI job).
"""

import json
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from shellac_tpu.inference.chaos import ChaosProxy
from shellac_tpu.inference.server import retry_after
from shellac_tpu.inference.tier import (
    TierRouter,
    histogram_quantile,
    make_tier_http_server,
    parse_prometheus,
)
from shellac_tpu.obs import Registry
from shellac_tpu.utils.failure import CircuitBreaker


def wait_until(cond, timeout=10.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


class StubReplica:
    """Scriptable replica: the InferenceServer HTTP surface (health,
    metrics, generate incl. streaming, drain) driven by writable
    attributes instead of an engine."""

    def __init__(self, tag, *, pending=0, queue_depth=0, kv_util=0.0,
                 prefix_blocks=0, role="monolith"):
        self.tag = tag
        self.mode = "ok"        # ok | recovering | draining | err503 |
        #                         err429 | err400 | err500
        self.role = role
        self.pending = pending
        self.queue_depth = queue_depth
        self.kv_util = kv_util
        self.prefix_blocks = prefix_blocks
        self.stream_first_error = None   # dict -> sole (retryable?) line
        self.stream_cut_after = None     # int deltas, then abrupt close
        self.requests = 0                # POSTs that reached generate
        # Disaggregated-protocol scripting: prefill_only POSTs ack a
        # migration (counted in `prefills`); adopt POSTs answer per
        # adopt_mode (ok | err500 | err503).
        self.prefills = 0
        self.adopt_mode = "ok"
        self.lock = threading.Lock()
        stub = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, obj, hdrs=None):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                for k, v in (hdrs or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    if stub.mode == "draining":
                        self._send(503, {"status": "draining",
                                         "ok": False,
                                         "pending": stub.pending})
                    elif stub.mode == "recovering":
                        self._send(503, {"status": "recovering",
                                         "ok": False})
                    else:
                        self._send(200, {"status": "ok", "ok": True,
                                         "role": stub.role,
                                         "pending": stub.pending})
                elif self.path == "/metrics":
                    txt = (
                        f"shellac_pending_requests {stub.pending}\n"
                        f"shellac_engine_queue_depth {stub.queue_depth}\n"
                        f"shellac_kv_utilization {stub.kv_util}\n"
                        f"shellac_prefix_cache_blocks "
                        f"{stub.prefix_blocks}\n"
                    )
                    b = txt.encode()
                    self.send_response(200)
                    self.send_header("Content-Type", "text/plain")
                    self.send_header("Content-Length", str(len(b)))
                    self.end_headers()
                    self.wfile.write(b)
                else:
                    self._send(404, {"error": "not found"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                payload = json.loads(self.rfile.read(n) or b"{}")
                if self.path == "/drain":
                    stub.mode = ("ok" if payload.get("resume")
                                 else "draining")
                    self._send(200, {"status": stub.mode,
                                     "pending": stub.pending,
                                     "draining": stub.mode == "draining"})
                    return
                with stub.lock:
                    stub.requests += 1
                if stub.mode in ("err503", "recovering", "draining"):
                    msg = ("server draining: not admitting"
                           if stub.mode == "draining"
                           else "server recovering from an engine fault")
                    self._send(503, {"error": msg},
                               {"Retry-After": "1"})
                    return
                if stub.mode == "err429":
                    self._send(429, {"error": "server overloaded"},
                               {"Retry-After": "1"})
                    return
                if stub.mode == "err400":
                    self._send(400, {"error": "bad stop sequences"})
                    return
                if stub.mode == "err500":
                    self._send(500, {"error": "scheduler died"})
                    return
                if payload.get("prefill_only"):
                    with stub.lock:
                        stub.prefills += 1
                        mid = f"mig-{stub.tag}-{stub.prefills}"
                    self._send(200, {"migrated": True,
                                     "migration_id": mid,
                                     "replica": stub.tag})
                    return
                if payload.get("adopt") is not None:
                    if stub.adopt_mode == "err500":
                        self._send(500, {"error": "scheduler died"})
                        return
                    if stub.adopt_mode == "err503":
                        self._send(503, {"error": "unknown migration "
                                                  "id; re-run"},
                                   {"Retry-After": "1"})
                        return
                    self._send(200, {"tokens": [7],
                                     "replica": stub.tag,
                                     "adopted": payload["adopt"]})
                    return
                if payload.get("stream"):
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "application/x-ndjson")
                    self.end_headers()
                    if stub.stream_first_error is not None:
                        self.wfile.write(
                            (json.dumps(
                                {"error": stub.stream_first_error}
                            ) + "\n").encode()
                        )
                        return
                    deltas = [[1], [2], [3]]
                    for i, d in enumerate(deltas):
                        if (stub.stream_cut_after is not None
                                and i >= stub.stream_cut_after):
                            # Abrupt close mid-stream: no done record.
                            self.wfile.flush()
                            self.connection.close()
                            return
                        self.wfile.write(
                            (json.dumps({"tokens": d}) + "\n").encode()
                        )
                        self.wfile.flush()
                    self.wfile.write((json.dumps(
                        {"done": True, "tokens": [1, 2, 3],
                         "replica": stub.tag}
                    ) + "\n").encode())
                    return
                self._send(200, {"tokens": [7], "replica": stub.tag})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()


def _mk_router(stubs, **kw):
    kw.setdefault("registry", Registry())
    kw.setdefault("health_interval", 0.05)
    kw.setdefault("backoff_base", 0.01)
    kw.setdefault("backoff_cap", 0.05)
    kw.setdefault("default_timeout", 10.0)
    r = TierRouter([s.url for s in stubs], **kw)
    wait_until(lambda: all(x.state != "unknown" for x in r.replicas),
               msg="initial health sweep")
    return r


def _replica_of(body: bytes) -> str:
    return json.loads(body)["replica"]


class TestCircuitBreaker:
    def test_trips_at_max_failures_in_window(self):
        b = CircuitBreaker(3, window=10.0, cooldown=1.0)
        assert not b.record_failure(now=0.0)
        assert not b.record_failure(now=1.0)
        assert b.record_failure(now=2.0)
        assert b.state == "open"

    def test_window_expiry_forgives(self):
        b = CircuitBreaker(3, window=10.0, cooldown=1.0)
        b.record_failure(now=0.0)
        b.record_failure(now=1.0)
        # The first two age out: this third failure is alone in its
        # window and must NOT trip.
        assert not b.record_failure(now=20.0)
        assert b.state == "closed"

    def test_closed_state_success_does_not_clear_window(self):
        # A replica can answer /health 200 while its DATA path fails:
        # routine successes must not erase the failures accumulating
        # in the window, or such a replica could never be ejected.
        b = CircuitBreaker(2, window=100.0, cooldown=1.0)
        b.record_failure(now=0.0)
        b.record_success()
        assert b.record_failure(now=1.0)  # second failure trips
        assert b.state == "open"

    def test_probe_success_clears_failure_window(self):
        b = CircuitBreaker(2, window=100.0, cooldown=1.0)
        b.record_failure(now=0.0)
        b.record_failure(now=1.0)
        assert b.allow_probe(now=3.0)
        b.record_success()  # readmitted: starts fresh
        assert not b.record_failure(now=4.0)

    def test_half_open_probe_and_readmit(self):
        b = CircuitBreaker(1, window=10.0, cooldown=2.0)
        assert b.record_failure(now=0.0)
        assert not b.allow_probe(now=1.0)       # cooling down
        assert b.allow_probe(now=3.0)
        assert b.state == "half_open"
        assert not b.allow_probe(now=3.1)       # one probe at a time
        b.record_success()
        assert b.state == "closed"

    def test_failed_probe_reopens_with_fresh_cooldown(self):
        b = CircuitBreaker(1, window=10.0, cooldown=2.0)
        b.record_failure(now=0.0)
        assert b.allow_probe(now=2.5)
        assert b.record_failure(now=2.6)        # probe failed
        assert b.state == "open"
        assert not b.allow_probe(now=3.0)       # cooldown restarted
        assert b.allow_probe(now=5.0)

    def test_validation(self):
        with pytest.raises(ValueError):
            CircuitBreaker(0)
        with pytest.raises(ValueError):
            CircuitBreaker(1, window=0)
        with pytest.raises(ValueError):
            CircuitBreaker(1, cooldown=0)


class TestRetryAfterJitter:
    def test_within_bounds_and_actually_jitters(self):
        vals = {retry_after(1.0, 4.0) for _ in range(64)}
        assert all(1.0 <= v <= 4.0 for v in vals)
        # 64 draws collapsing to one value would mean the jitter is
        # gone and clients re-synchronize on a recovering replica.
        assert len(vals) > 8


class TestPrometheusScrape:
    def test_parse_and_quantile(self):
        text = (
            "# HELP shellac_ttft_seconds t\n"
            "# TYPE shellac_ttft_seconds histogram\n"
            'shellac_ttft_seconds_bucket{le="0.1"} 50\n'
            'shellac_ttft_seconds_bucket{le="1"} 99\n'
            'shellac_ttft_seconds_bucket{le="+Inf"} 100\n'
            "shellac_ttft_seconds_sum 12.5\n"
            "shellac_ttft_seconds_count 100\n"
            "shellac_kv_utilization 0.75\n"
        )
        p = parse_prometheus(text)
        assert p["shellac_kv_utilization"] == 0.75
        buckets = p["shellac_ttft_seconds!buckets"]
        p50 = histogram_quantile(buckets, 0.50)
        assert p50 is not None and p50 <= 0.1
        p999 = histogram_quantile(buckets, 0.999)
        assert p999 == 1.0  # overflow bucket reports last finite edge

    def test_empty_histogram_is_none(self):
        assert histogram_quantile([], 0.99) is None
        assert histogram_quantile([(0.1, 0.0), (float("inf"), 0.0)],
                                  0.99) is None


class TestRoutingPolicy:
    def test_least_loaded_wins_without_affinity(self):
        idle, busy = StubReplica("idle"), StubReplica("busy", pending=50)
        r = _mk_router([idle, busy])
        try:
            wait_until(
                lambda: any((x.load.get("score") or 0) > 10
                            for x in r.replicas),
                msg="load scrape")
            # No prompt fields at all -> no affinity key -> pure
            # least-loaded. (The stub ignores the missing tokens.)
            hits = {
                _replica_of(r.forward_json("/generate",
                                           {"max_new": 2})[1])
                for _ in range(6)
            }
            assert hits == {"idle"}
        finally:
            r.close()
            idle.close()
            busy.close()

    def test_affinity_sticks_across_requests(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _mk_router([a, b])
        try:
            payload = {"tokens": [5, 6, 7, 8], "max_new": 2}
            first = _replica_of(r.forward_json("/generate", payload)[1])
            for _ in range(8):
                assert _replica_of(
                    r.forward_json("/generate", payload)[1]
                ) == first
            # A different prompt prefix is free to land elsewhere, and
            # across many keys both replicas must see traffic.
            seen = {
                _replica_of(r.forward_json(
                    "/generate",
                    {"tokens": [i * 3 + 1, i * 7 + 2], "max_new": 2},
                )[1])
                for i in range(16)
            }
            assert seen == {"a", "b"}
        finally:
            r.close()
            a.close()
            b.close()

    def test_session_key_overrides_prompt(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _mk_router([a, b])
        try:
            hits = {
                _replica_of(r.forward_json(
                    "/generate",
                    {"tokens": [i, i + 1], "max_new": 2,
                     "session": "user-42"},
                )[1])
                for i in range(8)
            }
            assert len(hits) == 1  # one session -> one replica
        finally:
            r.close()
            a.close()
            b.close()

    def test_affinity_spills_when_target_overloaded(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _mk_router([a, b], affinity_tolerance=4.0)
        try:
            payload = {"tokens": [5, 6, 7, 8], "max_new": 2,
                       "session": "sticky"}
            target_tag = _replica_of(
                r.forward_json("/generate", payload)[1])
            target = a if target_tag == "a" else b
            other_tag = "b" if target_tag == "a" else "a"
            # Pile load far past the tolerance onto the affinity
            # target; the router must spill to the least-loaded.
            target.pending = 100
            wait_until(
                lambda: any((x.load.get("score") or 0) > 50
                            for x in r.replicas),
                msg="load scrape sees the hot spot")
            assert _replica_of(
                r.forward_json("/generate", payload)[1]) == other_tag
            # Load drains -> affinity resumes.
            target.pending = 0
            wait_until(
                lambda: all((x.load.get("score") or 0) < 1
                            for x in r.replicas),
                msg="load scrape sees the drain")
            assert _replica_of(
                r.forward_json("/generate", payload)[1]) == target_tag
        finally:
            r.close()
            a.close()
            b.close()

    def test_affinity_falls_back_when_target_ejected(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _mk_router([a, b])
        try:
            payload = {"tokens": [9, 9, 9], "max_new": 2,
                       "session": "s1"}
            target_tag = _replica_of(
                r.forward_json("/generate", payload)[1])
            target = a if target_tag == "a" else b
            other_tag = "b" if target_tag == "a" else "a"
            target.mode = "recovering"
            wait_until(lambda: [x for x in r.replicas
                                if x.url == target.url][0].state
                       == "ejected", msg="ejection")
            assert _replica_of(
                r.forward_json("/generate", payload)[1]) == other_tag
        finally:
            r.close()
            a.close()
            b.close()


class TestFailureAwareRetry:
    def test_retry_on_503_lands_on_other_replica_within_deadline(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _mk_router([a, b])
        try:
            payload = {"tokens": [4, 4, 4], "max_new": 2, "timeout": 8}
            target_tag = _replica_of(
                r.forward_json("/generate", payload)[1])
            target = a if target_tag == "a" else b
            other_tag = "b" if target_tag == "a" else "a"
            target.mode = "err503"
            t0 = time.monotonic()
            status, body, _ = r.forward_json("/generate", payload)
            assert status == 200
            assert _replica_of(body) == other_tag
            assert time.monotonic() - t0 < 8.0
            reg = r._registry
            assert reg.value("shellac_tier_retries_total",
                             replica=target.url,
                             kind="status_503") >= 1
            assert reg.value("shellac_tier_requests_total",
                             outcome="ok") >= 2
        finally:
            r.close()
            a.close()
            b.close()

    def test_connect_error_retried(self):
        a = StubReplica("a")
        # A port with nothing listening: connect errors immediately.
        sock = socket.socket()
        sock.bind(("127.0.0.1", 0))
        dead_url = f"http://127.0.0.1:{sock.getsockname()[1]}"
        sock.close()
        r = TierRouter([dead_url, a.url], registry=Registry(),
                       health_interval=0.05, backoff_base=0.01,
                       default_timeout=10.0)
        try:
            wait_until(lambda: any(x.state == "healthy"
                                   for x in r.replicas),
                       msg="stub healthy")
            ok = 0
            for i in range(6):
                status, body, _ = r.forward_json(
                    "/generate", {"tokens": [i], "max_new": 2})
                assert status == 200, body
                assert _replica_of(body) == "a"
                ok += 1
            assert ok == 6
        finally:
            r.close()
            a.close()

    def test_429_retried_without_charging_breaker(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _mk_router([a, b])
        try:
            payload = {"tokens": [2, 2], "max_new": 2}
            target_tag = _replica_of(
                r.forward_json("/generate", payload)[1])
            target = a if target_tag == "a" else b
            target.mode = "err429"
            status, body, _ = r.forward_json("/generate", payload)
            assert status == 200
            rep = [x for x in r.replicas if x.url == target.url][0]
            assert rep.breaker.state == "closed"
        finally:
            r.close()
            a.close()
            b.close()

    def test_replica_500_retried_elsewhere(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _mk_router([a, b])
        try:
            payload = {"tokens": [3, 1], "max_new": 2}
            target_tag = _replica_of(
                r.forward_json("/generate", payload)[1])
            target = a if target_tag == "a" else b
            other_tag = "b" if target_tag == "a" else "a"
            target.mode = "err500"
            status, body, _ = r.forward_json("/generate", payload)
            assert status == 200
            assert _replica_of(body) == other_tag
        finally:
            r.close()
            a.close()
            b.close()

    def test_400_is_permanent_and_relayed(self):
        a, b = StubReplica("a"), StubReplica("b")
        for s in (a, b):
            s.mode = "err400"
        r = _mk_router([a, b])
        try:
            status, body, _ = r.forward_json(
                "/generate", {"tokens": [1], "max_new": 2})
            assert status == 400
            assert b"bad stop sequences" in body
            # Exactly one attempt: a 400 must never fan out.
            assert a.requests + b.requests == 1
        finally:
            r.close()
            a.close()
            b.close()

    def test_attempts_exhausted_with_budget_left_is_502(self):
        # Fast failures with most of the deadline remaining are an
        # upstream availability problem (502 "failed"), not client-
        # deadline pressure — a 504 here would read an outage as
        # latency on every dashboard.
        a = StubReplica("a")
        a.mode = "err503"
        r = _mk_router([a], max_attempts=3, default_timeout=30.0)
        try:
            status, body, _ = r.forward_json(
                "/generate", {"tokens": [1], "max_new": 2,
                              "timeout": 20})
            assert status == 502
            assert b"attempts" in body
            assert r._registry.value("shellac_tier_requests_total",
                                     outcome="failed") == 1
        finally:
            r.close()
            a.close()

    def test_deadline_exhaustion_is_504(self):
        a = StubReplica("a")
        a.mode = "err503"
        # Backoffs large relative to the deadline: the clock, not the
        # attempt budget, runs out.
        r = _mk_router([a], max_attempts=50, backoff_base=0.2,
                       backoff_cap=0.4, default_timeout=1.0)
        try:
            status, body, _ = r.forward_json(
                "/generate", {"tokens": [1], "max_new": 2,
                              "timeout": 0.8})
            assert status == 504
            assert b"deadline" in body
            assert r._registry.value("shellac_tier_requests_total",
                                     outcome="deadline") == 1
        finally:
            r.close()
            a.close()

    def test_no_routable_replica_is_503(self):
        a = StubReplica("a")
        a.mode = "recovering"
        r = _mk_router([a], default_timeout=1.0)
        try:
            wait_until(lambda: not r.replicas[0].routable,
                       msg="ejection")
            status, body, _ = r.forward_json(
                "/generate", {"tokens": [1], "max_new": 2,
                              "timeout": 0.5})
            assert status == 503
            assert b"no routable replica" in body
        finally:
            r.close()
            a.close()


class TestDisaggRetryContract:
    """The KV-migration retry contract (docs/serving_tier.md
    §Disaggregated serving): a decode-replica failure strictly before
    the first client byte classifies RETRYABLE and re-runs the FULL
    prefill->migrate path on a fresh pair; with no pair left, the
    request serves monolithically — the client never sees the leg."""

    def test_decode_failure_reruns_full_path_on_fresh_pair(self):
        pre = StubReplica("P", role="prefill")
        d1 = StubReplica("D1", role="decode")
        d2 = StubReplica("D2", role="decode", pending=5)  # d1 first
        d1.adopt_mode = "err500"  # decode dies before any client byte
        reg = Registry()
        r = _mk_router([pre, d1, d2], registry=reg,
                       disagg_min_prompt=1)
        try:
            status, body, _ = r.forward_json(
                "/generate", {"tokens": [1] * 8, "max_new": 2})
            assert status == 200
            out = json.loads(body)
            # Served by the SECOND pair's decode replica.
            assert out["replica"] == "D2" and "adopted" in out
            # The full path re-ran: the prefill replica served TWO
            # prefill_only legs, one per pair.
            assert pre.prefills == 2
            assert reg.value("shellac_migrations_total",
                             outcome="ok") == 1
            assert (reg.value("shellac_tier_retries_total",
                              replica=d1.url, kind="status_500")
                    or 0) >= 1
        finally:
            r.close()
            for s in (pre, d1, d2):
                s.close()

    def test_streamed_decode_failure_reruns_full_path(self):
        pre = StubReplica("P", role="prefill")
        d1 = StubReplica("D1", role="decode")
        d2 = StubReplica("D2", role="decode", pending=5)
        d1.adopt_mode = "err503"
        reg = Registry()
        r = _mk_router([pre, d1, d2], registry=reg,
                       disagg_min_prompt=1)
        try:
            opened, err = r.open_stream(
                "/generate",
                {"tokens": [1] * 8, "max_new": 2, "stream": True})
            assert err is None
            resp, first, ct, rep_url, _ = opened
            assert rep_url == d2.url
            assert json.loads(first)["adopted"]  # D2's adopt answered
            resp.close()
            assert pre.prefills == 2
        finally:
            r.close()
            for s in (pre, d1, d2):
                s.close()

    def test_no_pair_left_falls_back_monolithic(self):
        pre = StubReplica("P", role="prefill")
        d1 = StubReplica("D1", role="decode")
        mono = StubReplica("M")
        d1.adopt_mode = "err500"
        reg = Registry()
        r = _mk_router([pre, d1, mono], registry=reg,
                       disagg_min_prompt=1, disagg_attempts=2)
        try:
            status, body, _ = r.forward_json(
                "/generate", {"tokens": [1] * 8, "max_new": 2})
            assert status == 200
            # Monolithic fallback answered (a plain generate, not an
            # adoption), and the fallback was counted with its reason.
            assert "adopted" not in json.loads(body)
            assert reg.value("shellac_migrations_total",
                             outcome="fallback_failed") == 1
        finally:
            r.close()
            for s in (pre, d1, mono):
                s.close()

    def test_monolithic_fleet_keeps_disagg_inert(self):
        stubs = [StubReplica(t) for t in ("a", "b")]
        reg = Registry()
        r = _mk_router(stubs, registry=reg)
        try:
            status, _, _ = r.forward_json(
                "/generate", {"tokens": [1] * 64, "max_new": 2})
            assert status == 200
            # No role-labeled replica anywhere: no migration series.
            assert reg.value("shellac_migrations_total",
                             outcome="ok") is None
            for reason in ("no_pair", "cost", "feature", "failed"):
                assert reg.value("shellac_migrations_total",
                                 outcome=f"fallback_{reason}") is None
        finally:
            r.close()
            for s in stubs:
                s.close()


class TestMembership:
    def test_breaker_ejects_flapping_replica_then_readmits(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _mk_router([a, b], breaker_cooldown=0.3)
        try:
            a.mode = "recovering"
            wait_until(lambda: [x for x in r.replicas
                                if x.url == a.url][0].state == "ejected",
                       msg="ejection")
            reg = r._registry
            assert reg.value("shellac_tier_ejections_total",
                             replica=a.url) >= 1
            # While ejected, all traffic lands on b.
            for i in range(4):
                status, body, _ = r.forward_json(
                    "/generate", {"tokens": [i], "max_new": 2})
                assert _replica_of(body) == "b"
            # Recovery: the half-open probe readmits it.
            a.mode = "ok"
            wait_until(lambda: [x for x in r.replicas
                                if x.url == a.url][0].state == "healthy",
                       msg="readmission")
            assert reg.value("shellac_tier_readmissions_total",
                             replica=a.url) >= 1
        finally:
            r.close()
            a.close()
            b.close()

    def test_drain_observed_and_traffic_bled_off(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _mk_router([a, b])
        try:
            a.mode = "draining"
            wait_until(lambda: [x for x in r.replicas
                                if x.url == a.url][0].state
                       == "draining", msg="drain observed")
            # Draining is deliberate: the breaker must stay closed.
            rep = [x for x in r.replicas if x.url == a.url][0]
            assert rep.breaker.state == "closed"
            assert r._registry.value(
                "shellac_tier_drains_observed_total", replica=a.url) == 1
            for i in range(4):
                _, body, _ = r.forward_json(
                    "/generate", {"tokens": [i], "max_new": 2})
                assert _replica_of(body) == "b"
            # Resume: traffic may come back.
            a.mode = "ok"
            wait_until(lambda: rep.state == "healthy", msg="resume")
        finally:
            r.close()
            a.close()
            b.close()

    def test_respawn_replaces_dead_replica(self):
        a, b, c = StubReplica("a"), StubReplica("b"), StubReplica("c")

        def factory(dead_url):
            assert dead_url == a.url
            return c.url

        r = _mk_router([a, b], replica_factory=factory,
                       respawn_after=0.2, breaker_cooldown=30.0)
        try:
            a.mode = "recovering"
            wait_until(lambda: any(x.url == c.url for x in r.replicas),
                       msg="respawn")
            urls = {x.url for x in r.replicas}
            assert urls == {b.url, c.url}
            assert r._registry.value("shellac_tier_respawns_total") == 1
            wait_until(lambda: [x for x in r.replicas
                                if x.url == c.url][0].state == "healthy",
                       msg="replacement healthy")
        finally:
            r.close()
            a.close()
            b.close()
            c.close()


class TestStreaming:
    def _stream_lines(self, base, payload, timeout=10):
        req = urllib.request.Request(
            base + "/generate",
            data=json.dumps({**payload, "stream": True}).encode(),
            headers={"Content-Type": "application/json"},
        )
        resp = urllib.request.urlopen(req, timeout=timeout)
        return resp, [json.loads(l) for l in resp if l.strip()]

    def test_retryable_first_event_error_retried_elsewhere(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _mk_router([a, b])
        httpd = make_tier_http_server(r)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            payload = {"tokens": [8, 8], "max_new": 3}
            _, lines = self._stream_lines(base, payload)
            target = a if lines[-1]["replica"] == "a" else b
            other_tag = "b" if target is a else "a"
            # The affinity target now sheds every stream before the
            # first token (the server's retryable in-band record).
            target.stream_first_error = {
                "message": "request shed: deadline expired",
                "type": "overloaded_error", "retryable": True,
            }
            _, lines = self._stream_lines(base, payload)
            assert lines[-1]["done"] is True
            assert lines[-1]["replica"] == other_tag
            assert r._registry.value(
                "shellac_tier_retries_total", replica=target.url,
                kind="stream_pre_byte") >= 1
        finally:
            httpd.shutdown()
            r.close()
            a.close()
            b.close()

    def test_mid_stream_cut_after_bytes_fails_loudly(self):
        a = StubReplica("a")
        a.stream_cut_after = 2  # two deltas, then the wire dies
        r = _mk_router([a])
        httpd = make_tier_http_server(r)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            resp, lines = self._stream_lines(
                base, {"tokens": [1, 2], "max_new": 3})
            # Deltas arrived, then a LOUD in-band non-retryable error —
            # never a silent truncation that looks like completion.
            assert any("tokens" in l for l in lines)
            assert not any(l.get("done") for l in lines)
            err = [l for l in lines if "error" in l]
            assert err, lines
            assert err[-1]["error"]["retryable"] is False
        finally:
            httpd.shutdown()
            r.close()
            a.close()


class TestTierHTTPSurface:
    def test_health_stats_metrics_and_routing(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _mk_router([a, b])
        httpd = make_tier_http_server(r)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            with urllib.request.urlopen(base + "/health",
                                        timeout=10) as resp:
                h = json.loads(resp.read())
            assert h["ok"] and h["replicas_healthy"] == 2

            req = urllib.request.Request(
                base + "/generate",
                data=json.dumps({"tokens": [1], "max_new": 2}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                out = json.loads(resp.read())
            assert out["replica"] in ("a", "b")

            with urllib.request.urlopen(base + "/stats",
                                        timeout=10) as resp:
                stats = json.loads(resp.read())
            assert stats["routed"] >= 1
            assert stats["replicas_total"] == 2

            with urllib.request.urlopen(base + "/metrics",
                                        timeout=10) as resp:
                text = resp.read().decode()
            assert "shellac_tier_routed_total" in text
            assert "shellac_tier_replicas_healthy 2" in text
        finally:
            httpd.shutdown()
            r.close()
            a.close()
            b.close()

    def test_admin_drain_forwards_and_bleeds(self):
        a, b = StubReplica("a"), StubReplica("b")
        r = _mk_router([a, b])
        httpd = make_tier_http_server(r)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            req = urllib.request.Request(
                base + "/admin/drain",
                data=json.dumps({"replica": a.url}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                out = json.loads(resp.read())
            assert out["state"] == "draining"
            assert a.mode == "draining"      # the replica got the POST
            for i in range(4):
                _, body, _ = r.forward_json(
                    "/generate", {"tokens": [i], "max_new": 2})
                assert _replica_of(body) == "b"
            # Resume through the same admin surface.
            req = urllib.request.Request(
                base + "/admin/drain",
                data=json.dumps({"replica": a.url,
                                 "resume": True}).encode(),
                headers={"Content-Type": "application/json"},
            )
            with urllib.request.urlopen(req, timeout=10) as resp:
                out = json.loads(resp.read())
            assert a.mode == "ok"
        finally:
            httpd.shutdown()
            r.close()
            a.close()
            b.close()

    def test_unroutable_tier_health_is_503_with_retry_after(self):
        a = StubReplica("a")
        a.mode = "recovering"
        r = _mk_router([a])
        httpd = make_tier_http_server(r)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            wait_until(lambda: not r.replicas[0].routable,
                       msg="ejection")
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(base + "/health", timeout=10)
            assert e.value.code == 503
            assert e.value.headers.get("Retry-After") is not None
        finally:
            httpd.shutdown()
            r.close()
            a.close()


class TestChaosProxyWire:
    """The chaos injectors themselves, against a stub — so the tier
    chaos suite can trust its instruments."""

    def test_refuse_and_unavailable_and_passthrough(self):
        a = StubReplica("a")
        proxy = ChaosProxy("127.0.0.1", a.url.rsplit(":", 1)[1])
        r = _mk_router([StubProxyHandle(proxy)], default_timeout=5.0,
                       breaker_cooldown=0.3)
        try:
            status, body, _ = r.forward_json(
                "/generate", {"tokens": [1], "max_new": 2})
            assert status == 200 and _replica_of(body) == "a"
            proxy.unavailable()
            status, body, _ = r.forward_json(
                "/generate", {"tokens": [1], "max_new": 2,
                              "timeout": 1.0})
            # 502 (attempts exhausted fast) or 503 (the poller ejected
            # the only replica before the first attempt landed).
            assert status in (502, 503), status
            proxy.pass_through()
            # The poller ejected the replica while it 503'd; wait for
            # the half-open probe to readmit it.
            wait_until(lambda: r.replicas[0].routable, msg="readmit")
            status, body, _ = r.forward_json(
                "/generate", {"tokens": [1], "max_new": 2})
            assert status == 200
            proxy.refuse()
            status, body, _ = r.forward_json(
                "/generate", {"tokens": [1], "max_new": 2,
                              "timeout": 1.0})
            assert status in (502, 503), status
        finally:
            r.close()
            proxy.close()
            a.close()


class StubProxyHandle:
    """Adapter so _mk_router can take a ChaosProxy where it expects an
    object with .url."""

    def __init__(self, proxy):
        self.url = proxy.url
