"""Multi-tenant QoS conformance (ISSUE 18).

Three layers, mirroring the implementation:

  - pure-unit: token-bucket math, tenant-config validation (every
    malformed shape dies a ValueError, never a guessed quota),
    admission controller leases, the weighted-fair queue's deque
    contract + DRR share math, and the autoscaler policy engine
    driven tick-by-tick on a fake clock;
  - tier-edge (stub replicas, no jax): the 429 + Retry-After throttle
    answer, tenant-header forwarding on routed attempts, and the
    autoscaler actuating a real router's membership (scale-out
    through the factory, idle scale-down through drain);
  - engine-level (tiny real engine, slow-marked like the disagg
    precedent, run unfiltered in the qos CI job): per-tenant server
    admission over HTTP, and the preempt -> park -> resume
    acceptance — the preempted request's tokens are IDENTICAL to an
    unpreempted run, dense and paged, greedy and seeded, with the
    victim chosen by measured resident bytes.
"""

import json
import threading
import time
import types
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import pytest

from shellac_tpu.inference.autoscale import Autoscaler, AutoscalePolicy
from shellac_tpu.inference.qos import (
    ANONYMOUS,
    TENANT_HEADER,
    AdmissionController,
    TenantPolicy,
    TokenBucket,
    WeightedFairQueue,
)
from shellac_tpu.obs import Registry


def wait_until(cond, timeout=30.0, interval=0.02, msg="condition"):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return
        time.sleep(interval)
    raise AssertionError(f"timed out waiting for {msg}")


# ---------------------------------------------------------------------
# Token bucket
# ---------------------------------------------------------------------


class TestTokenBucket:
    def test_burst_then_refill(self):
        b = TokenBucket(rate=10.0, burst=20.0, now=0.0)
        ok, wait = b.try_take(20.0, now=0.0)
        assert ok and wait == 0.0
        ok, wait = b.try_take(10.0, now=0.0)
        assert not ok
        assert wait == pytest.approx(1.0)  # 10 tokens at 10/s
        ok, _ = b.try_take(10.0, now=1.0)
        assert ok

    def test_never_exceeds_burst(self):
        b = TokenBucket(rate=100.0, burst=5.0, now=0.0)
        assert b.try_take(5.0, now=1000.0)[0]
        ok, _ = b.try_take(5.0, now=1000.0)
        assert not ok

    def test_cost_above_burst_hint_is_finite(self):
        # A request bigger than the bucket can EVER hold still gets a
        # finite retry hint (time to refill the full burst).
        b = TokenBucket(rate=10.0, burst=10.0, now=0.0)
        b.try_take(10.0, now=0.0)
        ok, wait = b.try_take(50.0, now=0.0)
        assert not ok
        assert wait == pytest.approx(1.0)


# ---------------------------------------------------------------------
# Tenant policy parsing — admission never guesses at a quota
# ---------------------------------------------------------------------


class TestTenantPolicy:
    def test_parse_full_config(self):
        pol = TenantPolicy.parse(json.dumps({
            "alice": {"rate": 100, "burst": 500, "max_concurrency": 4,
                      "priority": "interactive", "weight": 9},
            "default": {"rate": 10, "priority": "batch"},
        }))
        a = pol.spec("alice")
        assert a.rate == 100.0 and a.burst == 500.0
        assert a.max_concurrency == 4
        assert a.qos_class == 0 and a.qos_weight == 9.0

    def test_tenants_wrapper_accepted(self):
        pol = TenantPolicy.parse({"tenants": {"bob": {"rate": 5}}})
        assert pol.spec("bob").rate == 5.0

    def test_unknown_tenant_inherits_default_with_own_name(self):
        pol = TenantPolicy.parse({"default": {"rate": 7,
                                              "priority": "batch"}})
        s = pol.spec("stranger")
        assert s.name == "stranger"  # own bucket, default's limits
        assert s.rate == 7.0 and s.priority == "batch"

    def test_rate_without_burst_gets_one_second_depth(self):
        pol = TenantPolicy.parse({"t": {"rate": 30}})
        assert pol.spec("t").burst == 30.0

    @pytest.mark.parametrize("raw", [
        "not json {",
        "[1, 2]",
        {"t": 5},
        {"t": {"tokens_per_s": 5}},          # unknown key
        {"t": {"rate": 0}},
        {"t": {"rate": -3}},
        {"t": {"burst": 100}},               # burst without rate
        {"t": {"rate": 5, "burst": -1}},
        {"t": {"max_concurrency": 0}},
        {"t": {"priority": "platinum"}},
        {"t": {"rate": 5, "weight": 0}},
        {"": {"rate": 5}},
    ])
    def test_malformed_config_raises(self, raw):
        with pytest.raises(ValueError):
            TenantPolicy.parse(raw)


# ---------------------------------------------------------------------
# Admission controller
# ---------------------------------------------------------------------


class TestAdmissionController:
    def test_concurrency_quota_and_release(self):
        ctl = AdmissionController(TenantPolicy.parse(
            {"t": {"max_concurrency": 2}}))
        assert ctl.admit("t", 1)[0]
        assert ctl.admit("t", 1)[0]
        ok, why, wait = ctl.admit("t", 1)
        assert not ok and why == "concurrency" and wait > 0
        ctl.release("t")
        assert ctl.admit("t", 1)[0]

    def test_rate_throttle_reason_and_hint(self):
        ctl = AdmissionController(TenantPolicy.parse(
            {"t": {"rate": 10, "burst": 10}}))
        assert ctl.admit("t", 10, now=0.0)[0]
        ok, why, wait = ctl.admit("t", 10, now=0.0)
        assert not ok and why == "rate"
        assert wait == pytest.approx(1.0)

    def test_tenants_do_not_share_buckets(self):
        ctl = AdmissionController(TenantPolicy.parse(
            {"default": {"rate": 10, "burst": 10}}))
        assert ctl.admit("a", 10, now=0.0)[0]
        # b has its OWN bucket under the default limits: a's flood
        # never consumes b's budget.
        assert ctl.admit("b", 10, now=0.0)[0]
        assert not ctl.admit("a", 1, now=0.0)[0]

    def test_anonymous_maps_to_default(self):
        ctl = AdmissionController(TenantPolicy.parse(
            {"default": {"max_concurrency": 1}}))
        assert ctl.admit(None, 1)[0]
        ok, why, _ = ctl.admit(None, 1)
        assert not ok and why == "concurrency"
        assert ANONYMOUS in ctl.snapshot()

    def test_snapshot_shape(self):
        ctl = AdmissionController(TenantPolicy.parse(
            {"t": {"rate": 1, "burst": 5, "priority": "interactive"}}))
        ctl.admit("t", 5, now=0.0)
        ctl.admit("t", 5, now=0.0)
        snap = ctl.snapshot()["t"]
        assert snap["inflight"] == 1
        assert snap["admitted"] == 1 and snap["throttled"] == 1
        assert snap["priority"] == "interactive"


# ---------------------------------------------------------------------
# Weighted-fair queue
# ---------------------------------------------------------------------


def _req(rid, n=4, max_new=4, cls=1, weight=4.0):
    return types.SimpleNamespace(rid=rid, tokens=[0] * n,
                                 max_new=max_new, qos_class=cls,
                                 qos_weight=weight)


class TestWeightedFairQueue:
    def test_single_class_is_fifo(self):
        q = WeightedFairQueue()
        items = [_req(i) for i in range(8)]
        for it in items:
            q.append(it)
        assert [q.popleft().rid for _ in range(8)] == list(range(8))
        assert len(q) == 0 and not q

    def test_appendleft_putback_pops_first(self):
        q = WeightedFairQueue()
        q.append(_req("a", cls=0))       # better class waiting...
        back = _req("b", cls=2)
        q.appendleft(back)               # ...but the put-back wins:
        assert q.popleft() is back       # the engine's retry-first rule
        assert q.popleft().rid == "a"

    def test_pop_removes_most_recently_appended(self):
        q = WeightedFairQueue()
        q.append(_req("a", cls=0))
        q.append(_req("b", cls=2))
        assert q.pop().rid == "b"        # the importer's contract
        assert q.pop().rid == "a"
        with pytest.raises(IndexError):
            q.pop()

    def test_remove_and_iter(self):
        q = WeightedFairQueue()
        a, b, c = _req("a", cls=0), _req("b", cls=1), _req("c", cls=2)
        for it in (a, b, c):
            q.append(it)
        q.remove(b)
        assert [it.rid for it in q] == ["a", "c"]
        with pytest.raises(ValueError):
            q.remove(b)

    def test_drr_share_tracks_weights(self):
        # Equal-cost items, weight 8 vs 1, small quantum so several
        # rotations happen: the interactive lane's serve share must
        # track the 8:1 weight ratio, not starve batch entirely.
        q = WeightedFairQueue(quantum=8.0)
        for i in range(100):
            q.append(_req(f"i{i}", n=4, max_new=4, cls=0, weight=8.0))
            q.append(_req(f"b{i}", n=4, max_new=4, cls=2, weight=1.0))
        first = [q.popleft().rid[0] for _ in range(90)]
        i_served = first.count("i")
        b_served = first.count("b")
        assert b_served > 0              # no starvation
        assert 5.0 <= i_served / b_served <= 12.0

    def test_best_waiting_and_depths(self):
        q = WeightedFairQueue()
        assert q.best_waiting() is None
        q.append(_req("b", cls=2))
        q.append(_req("a", cls=0))
        cls, head = q.best_waiting()
        assert cls == 0 and head.rid == "a"
        assert q.depths() == {0: 1, 2: 1}
        q.clear()
        assert q.depths() == {} and q.best_waiting() is None

    def test_emptied_lane_forfeits_deficit(self):
        # Standard DRR: an idle class must not bank credit. Drain a
        # lane, refill it, and check service still interleaves (a
        # banked deficit would let it monopolize).
        q = WeightedFairQueue(quantum=8.0)
        for i in range(4):
            q.append(_req(f"x{i}", cls=0, weight=8.0))
        while q:
            q.popleft()
        assert q._deficit == {}


# ---------------------------------------------------------------------
# Autoscaler policy engine (fake clock, fake actuators)
# ---------------------------------------------------------------------


class _Harness:
    def __init__(self, policy=None, routable=2, total=2, load=0.0):
        self.clock = 0.0
        self.routable, self.total, self.load = routable, total, load
        self.out_calls = 0
        self.down_calls = 0
        self.out_result = "http://new"
        self.down_result = "http://victim"
        self.events = []
        self.scaler = Autoscaler(
            policy or AutoscalePolicy(min_replicas=1, max_replicas=4,
                                      cooldown_s=10.0,
                                      idle_after_s=30.0),
            scale_out=self._out, scale_down=self._down,
            observe=lambda: (self.routable, self.total, self.load),
            on_action=lambda a, u, **d: self.events.append((a, u, d)),
            now=lambda: self.clock,
        )

    def _out(self):
        self.out_calls += 1
        return self.out_result

    def _down(self):
        self.down_calls += 1
        return self.down_result


class TestAutoscalePolicy:
    def test_envelope_validation(self):
        with pytest.raises(ValueError):
            AutoscalePolicy(min_replicas=0)
        with pytest.raises(ValueError):
            AutoscalePolicy(min_replicas=3, max_replicas=2)
        with pytest.raises(ValueError):
            AutoscalePolicy(high_load=0.1, idle_load=0.5)

    def test_page_scales_out_after_boot_cooldown(self):
        h = _Harness()
        h.scaler.on_slo_transition("ttft", "ok", "page")
        h.clock = 5.0
        assert h.scaler.tick() is None          # still in boot cooldown
        h.clock = 11.0
        assert h.scaler.tick() == "scale_out"
        assert h.out_calls == 1
        assert h.events[-1][0] == "scale_out"
        assert "slo-page:ttft" in h.events[-1][2]["reason"]

    def test_recovery_disarms_pending_page(self):
        h = _Harness()
        h.scaler.on_slo_transition("ttft", "ok", "page")
        h.scaler.on_slo_transition("ttft", "page", "ok")
        h.clock = 60.0
        assert h.scaler.tick() is None
        assert h.out_calls == 0

    def test_at_max_refuses_and_consumes_page(self):
        h = _Harness(total=4)
        h.scaler.on_slo_transition("ttft", "ok", "page")
        h.clock = 11.0
        assert h.scaler.tick() is None
        assert h.out_calls == 0
        assert h.events[-1][0] == "refused_at_max"
        h.clock = 12.0
        assert h.scaler.tick() is None          # consumed, no re-log
        assert h.events[-1][0] == "refused_at_max"
        assert len(h.events) == 1

    def test_load_needs_consecutive_hot_ticks(self):
        h = _Harness(routable=1, load=100.0)     # per-replica 100 > 16
        h.clock = 11.0
        assert h.scaler.tick() is None           # hot tick 1
        h.clock = 12.0
        assert h.scaler.tick() is None           # hot tick 2
        h.clock = 13.0
        assert h.scaler.tick() == "scale_out"    # hysteresis = 3
        # One cold tick resets the streak.
        h2 = _Harness(routable=1, load=100.0)
        h2.clock = 11.0
        h2.scaler.tick()
        h2.load = 0.0
        h2.clock = 12.0
        h2.scaler.tick()
        h2.load = 100.0
        h2.clock = 13.0
        h2.clock = 14.0
        assert h2.scaler.tick() is None

    def test_sustained_idle_drains_above_floor(self):
        h = _Harness(routable=2, total=2, load=0.0)
        h.clock = 11.0
        assert h.scaler.tick() is None           # idle clock starts
        h.clock = 40.0
        assert h.scaler.tick() is None           # 29s < idle_after 30
        h.clock = 42.0
        assert h.scaler.tick() == "scale_down"
        assert h.down_calls == 1

    def test_idle_never_drains_below_floor(self):
        h = _Harness(routable=1, total=1, load=0.0)
        h.clock = 11.0
        h.scaler.tick()
        h.clock = 100.0
        assert h.scaler.tick() is None
        assert h.down_calls == 0

    def test_cooldown_spans_actions_and_failures(self):
        h = _Harness()
        h.out_result = None                      # broken factory
        h.scaler.on_slo_transition("ttft", "ok", "page")
        h.clock = 11.0
        assert h.scaler.tick() is None
        assert h.events[-1][0] == "scale_out_failed"
        h.clock = 12.0
        assert h.scaler.tick() is None           # cooling down the retry
        assert h.out_calls == 1
        h.out_result = "http://new"
        h.clock = 22.0
        assert h.scaler.tick() == "scale_out"    # retried after cooldown
        assert h.scaler.status()["failures"] == 1

    def test_status_shape(self):
        h = _Harness()
        st = h.scaler.status()
        assert st["min_replicas"] == 1 and st["max_replicas"] == 4
        assert st["cooldown_remaining_s"] == pytest.approx(10.0)
        assert st["last_action"] is None and st["actions"] == 0


# ---------------------------------------------------------------------
# Tier edge: stub replicas, real router, no jax
# ---------------------------------------------------------------------


class _Stub:
    """Minimal scriptable replica: healthy /health, empty /metrics,
    a /generate that records request headers, a /drain that flips
    draining state."""

    def __init__(self):
        self.mode = "ok"
        self.seen_headers = []
        self.lock = threading.Lock()
        stub = self

        class H(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def _send(self, code, obj):
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                if self.path == "/health":
                    if stub.mode == "draining":
                        self._send(503, {"status": "draining",
                                         "ok": False, "pending": 0})
                    else:
                        self._send(200, {"status": "ok", "ok": True,
                                         "pending": 0,
                                         "role": "monolith"})
                elif self.path == "/metrics":
                    self.send_response(200)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                else:
                    self._send(404, {"error": "nope"})

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                if self.path == "/drain":
                    payload = {}
                    stub.mode = "draining"
                    self._send(200, {"status": "draining",
                                     "draining": True, "pending": 0})
                    return
                with stub.lock:
                    stub.seen_headers.append(dict(self.headers))
                self._send(200, {"tokens": [1], "text": "x"})

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()
        self.url = f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def close(self):
        self.httpd.shutdown()


def _router_over(urls, **kw):
    from shellac_tpu.inference.tier import TierRouter

    kw.setdefault("registry", Registry())
    kw.setdefault("health_interval", 0.1)
    kw.setdefault("backoff_base", 0.02)
    r = TierRouter(list(urls), **kw)
    wait_until(lambda: all(x.state == "healthy" for x in r.replicas),
               timeout=15, msg="replicas healthy")
    return r


class TestTierEdgeAdmission:
    def test_tenant_header_forwarded_and_throttled(self):
        from shellac_tpu.inference.tier import make_tier_http_server

        stub = _Stub()
        router = _router_over([stub.url], tenant_config={
            "miser": {"rate": 1, "burst": 40},
        })
        httpd = make_tier_http_server(router)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        base = f"http://127.0.0.1:{httpd.server_address[1]}"
        try:
            body = json.dumps({"tokens": [1, 2, 3],
                               "max_new": 16}).encode()

            def post(tenant):
                req = urllib.request.Request(
                    base + "/generate", data=body,
                    headers={"Content-Type": "application/json",
                             TENANT_HEADER: tenant},
                )
                with urllib.request.urlopen(req, timeout=30) as r:
                    return r.status

            # cost = 3 prompt + 16 decode = 19; burst 40 admits two.
            assert post("miser") == 200
            assert post("miser") == 200
            with pytest.raises(urllib.error.HTTPError) as ei:
                post("miser")
            assert ei.value.code == 429
            assert int(ei.value.headers["Retry-After"]) >= 1
            err = json.loads(ei.value.read())
            assert err["reason"] == "rate"
            # The admitted attempts carried the tenant header to the
            # replica (the trace-header twin).
            with stub.lock:
                tenants = [h.get(TENANT_HEADER)
                           or h.get(TENANT_HEADER.title())
                           for h in stub.seen_headers]
            assert tenants.count("miser") == 2
            # Throttle counted per tenant on the tier's exposition.
            text = router.metrics_text()
            assert "shellac_tenant_throttles_total" in text
            assert 'tenant="miser"' in text
            # /stats carries the per-tenant snapshot.
            snap = router.stats()["tenants"]
            assert snap["miser"]["admitted"] == 2
            assert snap["miser"]["throttled"] == 1
            assert router.stats()["autoscale"] is None  # flag off
        finally:
            httpd.shutdown()
            router.close()
            stub.close()

    def test_anonymous_traffic_untouched_without_config(self):
        stub = _Stub()
        router = _router_over([stub.url])
        try:
            status, body, _ = router.forward_json(
                "/generate", {"tokens": [1], "max_new": 2})
            assert status == 200
            assert router.stats()["tenants"] is None
        finally:
            router.close()
            stub.close()


class TestTierAutoscaleActuation:
    def test_page_scale_out_then_idle_drain(self):
        spawned = []

        def factory(template_url):
            s = _Stub()
            spawned.append(s)
            return s.url

        stub = _Stub()
        reg = Registry()
        router = _router_over(
            [stub.url],
            registry=reg,
            replica_factory=factory,
            autoscale=AutoscalePolicy(
                min_replicas=1, max_replicas=2, cooldown_s=0.3,
                idle_after_s=0.5, idle_load=0.5,
            ),
        )
        try:
            time.sleep(0.35)                     # boot cooldown
            router._autoscaler.on_slo_transition("ttft", "ok", "page")
            wait_until(lambda: len(router.replicas) == 2, timeout=10,
                       msg="scale-out appended a replica")
            assert len(spawned) == 1
            assert reg.value("shellac_autoscale_actions_total",
                             action="scale_out") == 1
            st = router.stats()["autoscale"]
            assert st["last_action"] == "scale_out"
            # The decision is on the fleet timeline.
            events = [e for e in router.recorder.tail(64)
                      if e.get("event") == "autoscale"]
            assert any(e.get("action") == "scale_out" for e in events)

            # Now sustained idle (stub load is zero): the autoscaler
            # drains the least-loaded replica — but never below min.
            wait_until(
                lambda: reg.value("shellac_autoscale_actions_total",
                                  action="scale_down") == 1,
                timeout=15, msg="idle scale-down",
            )
            wait_until(
                lambda: any(r.state == "draining"
                            for r in router.replicas),
                timeout=10, msg="victim draining",
            )
            # Floor holds: one routable replica remains and no second
            # drain fires.
            time.sleep(1.0)
            assert reg.value("shellac_autoscale_actions_total",
                             action="scale_down") == 1
        finally:
            router.close()
            stub.close()
            for s in spawned:
                s.close()

    def test_no_autoscale_constructs_nothing(self):
        stub = _Stub()
        router = _router_over([stub.url])
        try:
            assert router._autoscaler is None
            assert router.stats()["autoscale"] is None
        finally:
            router.close()
            stub.close()


# ---------------------------------------------------------------------
# Engine-level: per-tenant server admission + preempt/park/resume
# (slow-marked; run unfiltered in the qos CI job)
# ---------------------------------------------------------------------


TENANTS = {
    "free": {"rate": 1, "burst": 40},
    "batch-t": {"priority": "batch"},
    "inter-t": {"priority": "interactive"},
}


def _tiny():
    from shellac_tpu import get_model_config

    return get_model_config("tiny").replace(dtype="float32")


def _mk_server(tmp_path=None, **kw):
    import jax

    from shellac_tpu.inference.cache import engine_class
    from shellac_tpu.inference.server import (
        InferenceServer,
        make_http_server,
    )
    from shellac_tpu.models import transformer

    cfg = _tiny()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    reg = Registry()
    backend = kw.pop("cache_backend", "dense")
    eng = engine_class(backend)(
        cfg, params, n_slots=kw.pop("n_slots", 1),
        max_len=kw.pop("max_len", 64),
        temperature=kw.pop("temperature", 0.0),
        cache_backend=backend,
    )
    srv = InferenceServer(cfg, params, registry=reg, engine=eng, **kw)
    httpd = make_http_server(srv)
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    return srv, httpd, base, cfg, params, reg


def _post(base, payload, tenant=None, timeout=300):
    headers = {"Content-Type": "application/json"}
    if tenant:
        headers[TENANT_HEADER] = tenant
    req = urllib.request.Request(
        f"{base}/generate", data=json.dumps(payload).encode(),
        headers=headers,
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


@pytest.mark.slow
class TestServerTenantAdmission:
    def test_throttle_429_metrics_and_stats(self):
        srv, httpd, base, _, _, reg = _mk_server(tenant_config=TENANTS)
        try:
            # cost = 3 prompt + 16 decode = 19; burst 40, rate 1/s.
            _post(base, {"tokens": [1, 2, 3], "max_new": 16},
                  tenant="free")
            _post(base, {"tokens": [1, 2, 3], "max_new": 16},
                  tenant="free")
            with pytest.raises(urllib.error.HTTPError) as ei:
                _post(base, {"tokens": [1, 2, 3], "max_new": 16},
                      tenant="free")
            assert ei.value.code == 429
            assert int(ei.value.headers["Retry-After"]) >= 1
            # Anonymous traffic rides free: no quota configured for it.
            _post(base, {"tokens": [9], "max_new": 2})
            assert reg.value("shellac_tenant_throttles_total",
                             tenant="free", reason="rate") == 1
            assert reg.value("shellac_admission_rejects_total",
                             reason="throttled", tenant="free") == 1
            # Both admitted requests charged prompt + budget = 19 each.
            assert reg.value("shellac_tenant_tokens_admitted_total",
                             tenant="free") == 38
            # /stats carries the QoS block.
            with urllib.request.urlopen(f"{base}/stats",
                                        timeout=30) as r:
                stats = json.loads(r.read())
            qos = stats["qos"]
            assert qos["tenants"]["free"]["throttled"] == 1
            assert "queue_depths" in qos
        finally:
            httpd.shutdown()
            srv.close()

    def test_malformed_config_fails_construction(self):
        import jax

        from shellac_tpu.inference.server import InferenceServer
        from shellac_tpu.models import transformer

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError):
            InferenceServer(cfg, params, registry=Registry(),
                            tenant_config={"t": {"rate": -1}})
        with pytest.raises(ValueError):
            InferenceServer(cfg, params, registry=Registry(),
                            preempt_after=0.0)

    def test_debug_requests_show_tenant(self):
        srv, httpd, base, _, _, _ = _mk_server(tenant_config=TENANTS)
        try:
            seen = {}

            def long_req():
                _post(base, {"tokens": [2, 3], "max_new": 30},
                      tenant="batch-t")

            t = threading.Thread(target=long_req, daemon=True)
            t.start()

            def has_tenant_row():
                with urllib.request.urlopen(f"{base}/debug/requests",
                                            timeout=30) as r:
                    rows = json.loads(r.read()).get("in_flight", [])
                for row in rows:
                    if row.get("tenant") == "batch-t":
                        seen.update(row)
                        return True
                return False

            wait_until(has_tenant_row, timeout=120,
                       msg="tenant on a debug row")
            assert seen["state"] in ("queued", "prefilling",
                                     "decoding", "parked")
            t.join(timeout=300)
        finally:
            httpd.shutdown()
            srv.close()


@pytest.mark.slow
class TestPreemptParkResume:
    """The acceptance: preemption is invisible to the victim's client
    except latency — its token stream is IDENTICAL to an unpreempted
    run."""

    @pytest.mark.parametrize("backend", ["dense", "paged"])
    def test_token_identity_greedy(self, backend, tmp_path):
        import jax
        import numpy as np

        from shellac_tpu.inference.engine import Engine

        srv, httpd, base, cfg, params, _ = _mk_server(
            tenant_config=TENANTS, preempt_after=0.05, max_len=128,
            cache_backend=backend, park_dir=str(tmp_path),
        )
        try:
            # Warm the compile caches so the chaos clock below starts
            # on a hot engine.
            _post(base, {"tokens": [1, 2, 3], "max_new": 2})

            prompt = [5, 6, 7]
            ref = Engine(cfg, params, temperature=0.0,
                         max_len=128).generate(
                np.asarray([prompt], np.int32), max_new_tokens=100)
            want = np.asarray(ref.tokens)[0].tolist()

            out = {}

            def victim():
                out["got"] = _post(
                    base, {"tokens": prompt, "max_new": 100},
                    tenant="batch-t")["tokens"]

            t = threading.Thread(target=victim, daemon=True)
            t.start()
            eng = srv._g.engine
            wait_until(lambda: len(eng.preemptable()) == 1,
                       timeout=120, msg="victim decoding")
            # The interactive request finds no free slot; past
            # preempt_after the batch victim is frozen, parked, and
            # later resumed — mid-window, token-exact.
            quick = _post(base, {"tokens": [9, 9], "max_new": 2},
                          tenant="inter-t")
            assert len(quick["tokens"]) == 2
            t.join(timeout=300)
            assert not t.is_alive()
            assert out["got"] == want
            assert eng.stats["preemptions"] >= 1
            # The park-spool safety copy landed (fire-and-forget,
            # allow it a moment).
            from shellac_tpu.inference.fabric import KVParkStore

            def parked():
                return any(e["park_id"].startswith("preempt-")
                           for e in KVParkStore(str(tmp_path)).list())

            wait_until(parked, timeout=30, msg="park safety copy")
            # The flight recorder tells the story end to end.
            kinds = [e.get("event") for e in srv.recorder.tail(srv.recorder.capacity)]
            assert "preempt" in kinds
            assert "preempt-park" in kinds
            assert "preempt-resume" in kinds
        finally:
            httpd.shutdown()
            srv.close()

    def test_token_identity_seeded(self):
        srv, httpd, base, cfg, params, _ = _mk_server(
            tenant_config=TENANTS, preempt_after=0.05, max_len=128)
        try:
            _post(base, {"tokens": [1, 2, 3], "max_new": 2})
            prompt = [4, 5, 6]
            samp = {"temperature": 1.0, "seed": 11}
            # Reference: the same server, uncontended (no waiter, so
            # nothing preempts) — seeded sampling is deterministic.
            want = _post(base, {"tokens": prompt, "max_new": 100,
                                **samp}, tenant="batch-t")["tokens"]

            out = {}

            def victim():
                out["got"] = _post(
                    base, {"tokens": prompt, "max_new": 100, **samp},
                    tenant="batch-t")["tokens"]

            t = threading.Thread(target=victim, daemon=True)
            t.start()
            eng = srv._g.engine
            wait_until(lambda: len(eng.preemptable()) == 1,
                       timeout=120, msg="victim decoding")
            _post(base, {"tokens": [8], "max_new": 2},
                  tenant="inter-t")
            t.join(timeout=300)
            assert out["got"] == want
            assert eng.stats["preemptions"] >= 1
        finally:
            httpd.shutdown()
            srv.close()

    def test_victim_is_cheapest_resident(self):
        # Two batch decodes, asymmetric prompt lengths: the rule says
        # preempt the FEWEST parked bytes — the short-prompt slot.
        srv, httpd, base, _, _, _ = _mk_server(
            tenant_config=TENANTS, preempt_after=0.05, n_slots=2,
            max_len=128)
        try:
            _post(base, {"tokens": [1, 2, 3], "max_new": 2})
            done = []

            def run(tokens, n):
                done.append(_post(base, {"tokens": tokens,
                                         "max_new": n},
                                  tenant="batch-t"))

            big = threading.Thread(
                target=run, args=([11] * 12, 100), daemon=True)
            small = threading.Thread(
                target=run, args=([7, 8], 100), daemon=True)
            big.start()
            small.start()
            eng = srv._g.engine
            wait_until(lambda: len(eng.preemptable()) == 2,
                       timeout=120, msg="both victims decoding")
            _post(base, {"tokens": [3], "max_new": 2},
                  tenant="inter-t")
            big.join(timeout=300)
            small.join(timeout=300)
            assert len(done) == 2
            parks = [e for e in srv.recorder.tail(srv.recorder.capacity)
                     if e.get("event") == "preempt-park"]
            assert parks
            # Fewest resident tokens won the victim election.
            assert min(p["resident_tokens"] for p in parks) \
                == parks[0]["resident_tokens"]
        finally:
            httpd.shutdown()
            srv.close()
