"""OpenAI-compatible API surface (/v1/completions, /v1/chat/completions,
/v1/models) over the native continuous-batching server.

The invariants: greedy completions must be BIT-identical to the native
/generate path (the OpenAI layer is a translator, not a second engine),
streaming SSE must re-assemble to the non-streaming text, and unsupported
knobs with non-neutral values must 400 — never silently change sampling.
"""

import json
import threading
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.engine import Engine
from shellac_tpu.inference.server import InferenceServer, make_http_server
from shellac_tpu.models import transformer
from shellac_tpu.training.tokenizer import ByteTokenizer


def _tiny():
    return get_model_config("tiny").replace(dtype="float32")


@pytest.fixture(scope="module")
def oai_srv():
    cfg = _tiny()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    srv = InferenceServer(
        cfg, params, tokenizer=ByteTokenizer(), model_name="tiny",
        n_slots=2, max_len=64, temperature=0.0, logprobs=True,
    )
    httpd = make_http_server(srv)
    t = threading.Thread(target=httpd.serve_forever, daemon=True)
    t.start()
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    yield base, cfg, params
    httpd.shutdown()
    srv.close()


def _post(base, path, payload, timeout=120):
    req = urllib.request.Request(
        f"{base}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return json.loads(r.read())


def _sse(base, path, payload, timeout=120):
    req = urllib.request.Request(
        f"{base}{path}", data=json.dumps(payload).encode(),
        headers={"Content-Type": "application/json"},
    )
    chunks = []
    with urllib.request.urlopen(req, timeout=timeout) as r:
        assert r.headers["Content-Type"] == "text/event-stream"
        for raw in r:
            line = raw.decode().strip()
            if not line.startswith("data: "):
                continue
            data = line[len("data: "):]
            if data == "[DONE]":
                return chunks, True
            chunks.append(json.loads(data))
    return chunks, False


class TestModels:
    def test_list_models(self, oai_srv):
        base, _, _ = oai_srv
        with urllib.request.urlopen(f"{base}/v1/models", timeout=30) as r:
            out = json.loads(r.read())
        assert out["object"] == "list"
        assert out["data"][0]["id"] == "tiny"


class TestCompletions:
    def test_greedy_matches_engine(self, oai_srv):
        base, cfg, params = oai_srv
        prompt = "hello"
        out = _post(base, "/v1/completions", {
            "model": "tiny", "prompt": prompt, "max_tokens": 6,
            "temperature": 0,
        })
        assert out["object"] == "text_completion"
        tok = ByteTokenizer()
        ids = tok.encode(prompt)
        ref = Engine(cfg, params, temperature=0.0).generate(
            np.asarray([ids], np.int32), max_new_tokens=6
        ).tokens[0]
        assert out["choices"][0]["text"] == tok.decode(np.asarray(ref))
        assert out["choices"][0]["finish_reason"] == "length"
        assert out["usage"]["prompt_tokens"] == len(ids)
        assert out["usage"]["completion_tokens"] == 6
        assert out["usage"]["total_tokens"] == len(ids) + 6

    def test_token_prompt_and_logprobs(self, oai_srv):
        base, _, _ = oai_srv
        out = _post(base, "/v1/completions", {
            "prompt": [3, 7, 11], "max_tokens": 4, "temperature": 0,
            "logprobs": 1,
        })
        lp = out["choices"][0]["logprobs"]
        assert len(lp["token_logprobs"]) == 4
        assert all(v <= 0.0 for v in lp["token_logprobs"])

    def test_streaming_reassembles(self, oai_srv):
        base, _, _ = oai_srv
        plain = _post(base, "/v1/completions", {
            "prompt": "ab", "max_tokens": 6, "temperature": 0,
        })
        chunks, done = _sse(base, "/v1/completions", {
            "prompt": "ab", "max_tokens": 6, "temperature": 0,
            "stream": True,
        })
        assert done
        text = "".join(c["choices"][0]["text"] for c in chunks)
        assert text == plain["choices"][0]["text"]
        assert chunks[-1]["choices"][0]["finish_reason"] == "length"

    def test_n_sampling(self, oai_srv):
        base, _, _ = oai_srv
        out = _post(base, "/v1/completions", {
            "prompt": "xy", "max_tokens": 4, "temperature": 1.1, "n": 2,
        })
        assert len(out["choices"]) == 2
        assert [c["index"] for c in out["choices"]] == [0, 1]
        assert out["usage"]["completion_tokens"] == 8

    def test_stop_gives_stop_reason(self, oai_srv):
        base, cfg, params = oai_srv
        # Force a KNOWN first token through the public logit_bias knob
        # (+100 dwarfs any random-init logit under greedy argmax), then
        # stop on exactly that token. Predicting the first token with a
        # reference Engine instead couples this test to backend
        # numerics: the batching engine's greedy argmax can drift from
        # the plain engine's on ties, and the stop-reason CONTRACT —
        # a matched stop yields finish_reason "stop" and truncates the
        # match — holds regardless of which token the backend favors.
        forced = 33  # "!" in the byte tokenizer
        stop_txt = ByteTokenizer().decode([forced])
        out = _post(base, "/v1/completions", {
            "prompt": "ab", "max_tokens": 8, "temperature": 0,
            "logit_bias": {str(forced): 100.0},
            "stop": [stop_txt],
        })
        assert out["choices"][0]["finish_reason"] == "stop"
        assert out["choices"][0]["text"] == ""

    def test_nonneutral_unsupported_rejected(self, oai_srv):
        base, _, _ = oai_srv
        with pytest.raises(urllib.error.HTTPError) as e:
            _post(base, "/v1/completions", {
                "prompt": "a", "suffix": "tail",
            })
        assert e.value.code == 400
        body = json.loads(e.value.read())
        assert body["error"]["type"] == "invalid_request_error"
        # neutral value passes; penalties and echo are SUPPORTED knobs
        out = _post(base, "/v1/completions", {
            "prompt": "a", "max_tokens": 2, "suffix": "",
            "echo": True, "presence_penalty": 0.5, "temperature": 0,
        })
        assert out["choices"][0]["text"].startswith("a")


class TestChat:
    def test_chat_completion(self, oai_srv):
        base, cfg, params = oai_srv
        msgs = [
            {"role": "system", "content": "be brief"},
            {"role": "user", "content": "hi"},
        ]
        out = _post(base, "/v1/chat/completions", {
            "messages": msgs, "max_tokens": 5, "temperature": 0,
        })
        assert out["object"] == "chat.completion"
        choice = out["choices"][0]
        assert choice["message"]["role"] == "assistant"
        # must equal the engine run on the rendered fallback template
        from shellac_tpu.inference.openai_api import render_chat

        tok = ByteTokenizer()
        ids = tok.encode(render_chat(msgs, tok))
        ref = Engine(cfg, params, temperature=0.0).generate(
            np.asarray([ids], np.int32), max_new_tokens=5
        ).tokens[0]
        assert choice["message"]["content"] == tok.decode(np.asarray(ref))

    def test_chat_streaming(self, oai_srv):
        base, _, _ = oai_srv
        msgs = [{"role": "user", "content": "go"}]
        plain = _post(base, "/v1/chat/completions", {
            "messages": msgs, "max_tokens": 5, "temperature": 0,
        })
        chunks, done = _sse(base, "/v1/chat/completions", {
            "messages": msgs, "max_tokens": 5, "temperature": 0,
            "stream": True,
        })
        assert done
        assert chunks[0]["choices"][0]["delta"].get("role") == "assistant"
        text = "".join(
            c["choices"][0]["delta"].get("content", "") for c in chunks
        )
        assert text == plain["choices"][0]["message"]["content"]
        assert chunks[-1]["choices"][0]["finish_reason"] == "length"

    def test_bad_messages_rejected(self, oai_srv):
        base, _, _ = oai_srv
        for payload in (
            {"messages": []},
            {"messages": [{"role": "alien", "content": "x"}]},
            {"messages": [{"content": "x"}]},
        ):
            with pytest.raises(urllib.error.HTTPError) as e:
                _post(base, "/v1/chat/completions", payload)
            assert e.value.code == 400


class TestStreamFixes:
    def test_streaming_logprobs_on_finish_chunk(self, oai_srv):
        base, _, _ = oai_srv
        chunks, done = _sse(base, "/v1/completions", {
            "prompt": "ab", "max_tokens": 4, "temperature": 0,
            "logprobs": 1, "stream": True,
        })
        assert done
        lp = chunks[-1]["choices"][0].get("logprobs")
        assert lp is not None and len(lp["token_logprobs"]) == 4

    def test_abandoned_stream_frees_the_slot(self, oai_srv):
        """Closing the SSE response mid-generation must cancel the
        engine request (not leave the slot generating unread tokens)."""
        import time

        base, _, _ = oai_srv

        def cancelled_count():
            with urllib.request.urlopen(f"{base}/stats", timeout=30) as s:
                return json.loads(s.read())["requests_cancelled"]

        before = cancelled_count()
        req = urllib.request.Request(
            f"{base}/v1/completions",
            data=json.dumps({
                "prompt": "ab", "max_tokens": 56, "temperature": 0,
                "stream": True,
            }).encode(),
            headers={"Content-Type": "application/json"},
        )
        r = urllib.request.urlopen(req, timeout=60)
        r.readline()  # first chunk arrived; generation is in flight
        r.close()  # hang up
        # The handler thread notices the hangup on its next write and
        # posts the cancel marker; the scheduler drains it.
        deadline = time.time() + 30
        while time.time() < deadline and cancelled_count() == before:
            time.sleep(0.2)
        assert cancelled_count() == before + 1
