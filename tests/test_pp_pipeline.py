"""Token-level pipelined pp serving (inference/pp_pipeline.py).

The contract: with pp_pipeline=True on a pp mesh, slot groups stagger
across pipeline stages so >= 2 groups' ticks are in flight on distinct
stages at the same microtick (the schedule test pins this), while every
request's greedy output stays BIT-IDENTICAL to the unsharded,
unpipelined engine (the parity tests pin that) — the stages stop
idling and the math doesn't move.
"""

import jax
import numpy as np
import pytest

from shellac_tpu import ParallelConfig, get_model_config, make_mesh
from shellac_tpu.inference.batching import BatchingEngine
from shellac_tpu.inference.engine import shard_params
from shellac_tpu.inference.pp_pipeline import pp_schedule


def _cfg():
    return get_model_config("tiny").replace(dtype="float32")


@pytest.fixture(scope="module")
def setup():
    cfg = _cfg()
    from shellac_tpu.models import transformer

    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    mesh = make_mesh(ParallelConfig(pp=2, tp=2, dp=2))
    return cfg, params, shard_params(cfg, params, mesh), mesh


def _reqs(cfg, lens=(3, 7, 5, 9, 4, 6), max_new=8):
    rng = np.random.default_rng(7)
    return [(i, rng.integers(1, cfg.vocab_size, size=s).tolist(), max_new)
            for i, s in enumerate(lens)]


class TestSchedule:
    def test_stages_overlap_on_distinct_groups(self):
        # The heart of the feature: at steady state, every microtick
        # has ALL stages live, each on a different group — two or more
        # slots' ticks genuinely in flight across stages at once.
        for pp, ticks in ((2, 1), (2, 4), (4, 2)):
            sched = pp_schedule(pp, ticks)
            assert len(sched) == pp * ticks + pp - 1
            steady = [s for s in sched if len(s["stages"]) == pp]
            assert steady, f"no fully-live microtick for pp={pp}"
            for s in steady:
                groups = list(s["stages"].values())
                assert len(set(groups)) == pp, s

    def test_every_group_exits_ticks_times(self):
        for pp, ticks in ((2, 3), (4, 2)):
            sched = pp_schedule(pp, ticks)
            exits = [s["exit"] for s in sched if s["exit"] is not None]
            assert len(exits) == pp * ticks
            for g in range(pp):
                assert exits.count(g) == ticks
            # Round-robin: group g's k-th token exits at microtick
            # pp-1 + k*pp + g — the reshape in _decode_impl_pp relies
            # on exactly this order.
            want = [(m % pp) for m in range(pp * ticks)]
            assert exits == want


class TestPipelinedParity:
    def test_greedy_bit_exact_with_churn(self, setup):
        # 6 requests through 4 slots (two groups of two): slot churn,
        # ragged prompts, multi-tick windows.
        cfg, params, sharded, mesh = setup
        reqs = _reqs(cfg)
        want = BatchingEngine(cfg, params, n_slots=4, max_len=64,
                              temperature=0.0, decode_ticks=3).run(reqs)
        got = BatchingEngine(cfg, sharded, n_slots=4, max_len=64,
                             temperature=0.0, decode_ticks=3,
                             mesh=mesh, pp_pipeline=True).run(reqs)
        assert got == want

    def test_greedy_bit_exact_single_tick(self, setup):
        cfg, params, sharded, mesh = setup
        reqs = _reqs(cfg, lens=(5, 2), max_new=6)
        want = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                              temperature=0.0).run(reqs)
        got = BatchingEngine(cfg, sharded, n_slots=2, max_len=64,
                             temperature=0.0, mesh=mesh,
                             pp_pipeline=True).run(reqs)
        assert got == want

    def test_logprobs_match_unpipelined(self, setup):
        cfg, params, sharded, mesh = setup
        reqs = _reqs(cfg, lens=(4, 6), max_new=5)
        ref = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, logprobs=True)
        out_ref = ref.run(reqs)
        eng = BatchingEngine(cfg, sharded, n_slots=2, max_len=64,
                             temperature=0.0, logprobs=True,
                             mesh=mesh, pp_pipeline=True)
        out = eng.run(reqs)
        assert out == out_ref
        for rid in (0, 1):
            np.testing.assert_allclose(
                eng.finished_logprobs[rid], ref.finished_logprobs[rid],
                atol=1e-5,
            )

    def test_seeded_sampling_deterministic(self, setup):
        # Seeded rows draw from fold_in(seed, gen_idx) — position in
        # their OWN stream — so the pipelined engine reproduces the
        # unpipelined engine's seeded tokens exactly.
        cfg, params, sharded, mesh = setup

        def run(engine):
            for i, toks, n in _reqs(cfg, lens=(4, 6), max_new=6):
                engine.submit(i, toks, n, temperature=1.3, seed=123 + i)
            out = {}
            while engine.pending:
                for rid, toks in engine.step():
                    out[rid] = toks
            return out

        want = run(BatchingEngine(cfg, params, n_slots=2, max_len=64))
        got = run(BatchingEngine(cfg, sharded, n_slots=2, max_len=64,
                                 mesh=mesh, pp_pipeline=True))
        assert got == want

    def test_min_tokens_and_logit_bias(self, setup):
        cfg, params, sharded, mesh = setup

        def run(engine):
            engine.submit(0, [3, 5, 7], 6, min_tokens=4,
                          logit_bias={9: 30.0})
            engine.submit(1, [2, 4], 6)
            out = {}
            while engine.pending:
                for rid, toks in engine.step():
                    out[rid] = toks
            return out

        want = run(BatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  temperature=0.0, eos_id=9))
        got = run(BatchingEngine(cfg, sharded, n_slots=2, max_len=64,
                                 temperature=0.0, eos_id=9, mesh=mesh,
                                 pp_pipeline=True))
        assert got == want


class TestPipelinedParityExtras:
    def test_penalties_match_unpipelined(self, setup):
        # presence/frequency penalties update counts on device at the
        # group exit — same math as the unpipelined scan's full-batch
        # scatter (shared via _row_decode_step).
        cfg, params, sharded, mesh = setup

        def run(engine):
            engine.submit(0, [3, 5, 7], 8, presence_penalty=1.2,
                          frequency_penalty=0.7)
            engine.submit(1, [2, 4, 6, 8], 8, presence_penalty=0.5)
            out = {}
            while engine.pending:
                for rid, toks in engine.step():
                    out[rid] = toks
            return out

        want = run(BatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  temperature=0.0))
        got = run(BatchingEngine(cfg, sharded, n_slots=2, max_len=64,
                                 temperature=0.0, mesh=mesh,
                                 pp_pipeline=True))
        assert got == want

    def test_constrained_decoding_matches_unpipelined(self, setup):
        # DFA-masked decoding: the constraint row gather and state
        # advance ride the pipelined exit like any other per-row state.
        from shellac_tpu.inference.constraints import compile_token_dfa
        from shellac_tpu.models import transformer
        from shellac_tpu.training.tokenizer import ByteTokenizer

        _, _, _, mesh = setup
        # Needs the byte tokenizer's vocab (EOS=257 must be a real
        # row); build a local model instead of the module fixture's.
        # Padded to 260 so the tp=2-sharded embed divides evenly.
        cfg = _cfg().replace(
            vocab_size=ByteTokenizer.vocab_size + 1
        )
        params = transformer.init_params(cfg, jax.random.PRNGKey(1))
        sharded = shard_params(cfg, params, mesh)
        eos = ByteTokenizer.EOS
        dfa = compile_token_dfa("[0-9]{1,6}", ByteTokenizer(),
                                cfg.vocab_size, eos_id=eos)

        def run(engine):
            engine.submit(0, [3, 5], 8, constraint=dfa)
            engine.submit(1, [2, 4, 6], 8)
            out = {}
            while engine.pending:
                for rid, toks in engine.step():
                    out[rid] = toks
            return out

        kw = dict(n_slots=2, max_len=64, temperature=0.0, eos_id=eos)
        want = run(BatchingEngine(cfg, params, **kw))
        got = run(BatchingEngine(cfg, sharded, mesh=mesh,
                                 pp_pipeline=True, **kw))
        assert got == want
        digits = bytes(int(t) for t in want[0] if t != eos)
        assert digits.decode().isdigit()


class TestInt8Pipelined:
    def test_int8_greedy_bit_exact(self, setup):
        """int8 KV composes with the pipelined decode: the scale
        stacks stage-split with the value stacks, so quantize-at-write
        is per-row identical to the unpipelined int8 engine."""
        cfg, params, sharded, mesh = setup
        reqs = _reqs(cfg, lens=(5, 9, 3, 7), max_new=7)
        want = BatchingEngine(cfg, params, n_slots=4, max_len=64,
                              temperature=0.0, kv_quant="int8",
                              decode_ticks=2).run(reqs)
        got = BatchingEngine(cfg, sharded, n_slots=4, max_len=64,
                             temperature=0.0, kv_quant="int8",
                             decode_ticks=2, mesh=mesh,
                             pp_pipeline=True).run(reqs)
        assert got == want


class TestPatternedPipelined:
    @pytest.mark.parametrize("preset,layers", [
        ("tiny-gemma2", None),    # (window, full) pattern + softcaps
        ("tiny-gemma3", 12),      # 5:1 pattern + DUAL rope
        ("tiny-gptoss", None),    # pattern + attention sinks
    ])
    def test_patterned_greedy_bit_exact(self, setup, preset, layers):
        """Patterned stacks (dense cache) compose: each stage's layer
        chunk holds whole pattern periods, kinds unroll inside the
        stage scan exactly as forward_with_cache's pattern_scan, and
        window layers take the local rope when the model has one."""
        from shellac_tpu.models import transformer as tr

        _, _, _, mesh = setup
        cfg = get_model_config(preset).replace(dtype="float32")
        if layers is not None:
            cfg = cfg.replace(n_layers=layers)
        params = tr.init_params(cfg, jax.random.PRNGKey(3))
        sharded = shard_params(cfg, params, mesh)
        reqs = _reqs(cfg, lens=(5, 9), max_new=7)
        want = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                              temperature=0.0, decode_ticks=2).run(reqs)
        got = BatchingEngine(cfg, sharded, n_slots=2, max_len=64,
                             temperature=0.0, decode_ticks=2,
                             mesh=mesh, pp_pipeline=True).run(reqs)
        assert got == want

    def test_patterned_int8_bit_exact(self, setup):
        """Patterned stack x int8 cache x pipelined decode: the quant
        field tuple threads through the shared period walk."""
        from shellac_tpu.models import transformer as tr

        _, _, _, mesh = setup
        cfg = get_model_config("tiny-gemma2").replace(dtype="float32")
        params = tr.init_params(cfg, jax.random.PRNGKey(4))
        sharded = shard_params(cfg, params, mesh)
        reqs = _reqs(cfg, lens=(4, 8), max_new=6)
        want = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                              temperature=0.0, kv_quant="int8",
                              decode_ticks=2).run(reqs)
        got = BatchingEngine(cfg, sharded, n_slots=2, max_len=64,
                             temperature=0.0, kv_quant="int8",
                             decode_ticks=2, mesh=mesh,
                             pp_pipeline=True).run(reqs)
        assert got == want

    def test_pattern_period_must_divide_stage_chunk(self, setup):
        from shellac_tpu.models import transformer as tr

        _, _, _, mesh = setup
        cfg = get_model_config("tiny-gemma3").replace(dtype="float32")
        # 6 layers / pp=2 -> 3 per stage, not a whole 6-layer period.
        params = tr.init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="whole pattern periods"):
            BatchingEngine(cfg, params, n_slots=2, mesh=mesh,
                           pp_pipeline=True)

    def test_patterned_rolling_rejected(self, setup):
        from shellac_tpu.models import transformer as tr

        _, _, _, mesh = setup
        cfg = get_model_config("tiny-gemma2").replace(dtype="float32")
        params = tr.init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="PatternedKVCache"):
            BatchingEngine(cfg, params, n_slots=2, mesh=mesh,
                           pp_pipeline=True, rolling_window=True)


class TestGuards:
    def test_requires_pp_mesh(self, setup):
        cfg, params, _, _ = setup
        flat = make_mesh(ParallelConfig(tp=2, dp=4))
        with pytest.raises(ValueError, match="pp >= 2"):
            BatchingEngine(cfg, params, n_slots=4, mesh=flat,
                           pp_pipeline=True)
        with pytest.raises(ValueError, match="pp >= 2"):
            BatchingEngine(cfg, params, n_slots=4, pp_pipeline=True)

    def test_requires_divisible_slots(self, setup):
        cfg, _, sharded, mesh = setup
        with pytest.raises(ValueError, match="divisible by pp"):
            BatchingEngine(cfg, sharded, n_slots=3, mesh=mesh,
                           pp_pipeline=True)

    def test_rolling_ring_bit_exact_through_wrap(self, setup):
        """Rolling ring caches compose: the pipelined drain's
        one-ahead stale writes alias only positions already outside
        every window (ring >= window + slack), so greedy output stays
        bit-exact through ring wrap."""
        from shellac_tpu.models import transformer as tr

        _, _, _, mesh = setup
        wcfg = _cfg().replace(attn_window=12)
        params = tr.init_params(wcfg, jax.random.PRNGKey(2))
        sharded = shard_params(wcfg, params, mesh)
        # Long enough generations that positions wrap the ring.
        reqs = _reqs(wcfg, lens=(5, 9, 3, 7), max_new=24)
        want = BatchingEngine(wcfg, params, n_slots=4, max_len=64,
                              temperature=0.0, rolling_window=True,
                              decode_ticks=3).run(reqs)
        got = BatchingEngine(wcfg, sharded, n_slots=4, max_len=64,
                             temperature=0.0, rolling_window=True,
                             decode_ticks=3, mesh=mesh,
                             pp_pipeline=True).run(reqs)
        assert got == want

    def test_rejects_paged(self, setup):
        from shellac_tpu.inference.batching import PagedBatchingEngine

        cfg, _, sharded, mesh = setup
        with pytest.raises(ValueError, match="dense-cache"):
            PagedBatchingEngine(cfg, sharded, n_slots=4, block_size=32,
                                mesh=mesh, pp_pipeline=True)

    def test_rejects_speculative(self, setup):
        from shellac_tpu.inference.spec_batching import (
            SpeculativeBatchingEngine,
        )

        cfg, params, sharded, mesh = setup
        with pytest.raises(ValueError, match="pp_pipeline"):
            SpeculativeBatchingEngine(
                cfg, sharded, cfg, params, mesh=mesh, pp_pipeline=True,
            )
