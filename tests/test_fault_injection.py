"""Fault injection on the serving path.

Failure semantics under test (docs/inference.md, failure section):

  - A WEDGED engine step (a follower process dying mid-collective
    leaves the primary stuck in native code — no exception ever
    surfaces) is detected by the server's step watchdog
    (`step_timeout`): every pending request fails loudly with the
    fatal message, new submissions are refused with HTTP 500, and the
    process stays responsive. The stuck thread itself is
    unrecoverable; the contract is LOUD failure, never a silent hang.
  - A client disconnecting mid-stream under the MULTIHOST engine
    cancels the generation on every rank (the cancel rides the
    command broadcast), freeing the slot pod-wide.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import jax
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.batching import BatchingEngine
from shellac_tpu.inference.server import InferenceServer, make_http_server
from shellac_tpu.models import transformer

from conftest import run_two_process


def _tiny():
    return get_model_config("tiny").replace(dtype="float32")


class _WedgingEngine(BatchingEngine):
    """Engine whose step() wedges after `good_steps` steps — the
    observable behavior of a primary whose follower died mid-
    collective. The wedge is an Event wait so the test can RELEASE the
    scheduler thread at teardown: a thread left sleeping inside
    step() for the rest of the pytest process has crashed later XLA
    compiles (both full-suite segfaults pointed here)."""

    def __init__(self, *a, good_steps=0, **kw):
        super().__init__(*a, **kw)
        self._good = good_steps
        self.wedged = threading.Event()
        self.release = threading.Event()

    def step(self):
        if self._good <= 0:
            self.wedged.set()
            self.release.wait(3600)
            return []
        self._good -= 1
        return super().step()


def _teardown(srv, eng, httpd=None):
    """Release the wedged scheduler thread and JOIN it before the test
    returns — no engine thread may outlive its test."""
    if httpd is not None:
        httpd.shutdown()
    eng.release.set()
    srv.close()  # sets the stop flag and joins the scheduler thread
    assert not srv._thread.is_alive(), "scheduler thread leaked"


class TestStepWatchdog:
    def test_wedged_step_fails_pending_loudly(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = _WedgingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, good_steps=0)
        srv = InferenceServer(cfg, params, engine=eng, step_timeout=2.0)
        try:
            t0 = time.monotonic()
            with pytest.raises(RuntimeError, match="step_timeout"):
                srv.generate([1, 2, 3], max_new=4, timeout=60)
            # Detection must come from the watchdog (well under the
            # pessimistic request timeout), and the server must now
            # refuse new work with the same loud error, not hang.
            assert time.monotonic() - t0 < 30
            with pytest.raises(RuntimeError, match="step_timeout"):
                srv.generate([4, 5], max_new=4, timeout=60)
        finally:
            _teardown(srv, eng)

    def test_http_surface_returns_500(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = _WedgingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, good_steps=0)
        srv = InferenceServer(cfg, params, engine=eng, step_timeout=2.0)
        httpd = make_http_server(srv)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            base = f"http://127.0.0.1:{httpd.server_address[1]}"
            req = urllib.request.Request(
                base + "/generate",
                json.dumps({"tokens": [3, 5, 7], "max_new": 4}).encode(),
                {"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=60)
            assert e.value.code == 500
            assert "step_timeout" in e.value.read().decode()
        finally:
            _teardown(srv, eng, httpd)

    def test_healthy_server_unaffected(self):
        """A generous timeout never fires on a healthy engine — the
        watchdog must not produce false positives mid-service."""
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        srv = InferenceServer(cfg, params, n_slots=2, max_len=64,
                              temperature=0.0, step_timeout=120.0)
        out = srv.generate([1, 2, 3], max_new=6, timeout=120)
        assert len(out) >= 1
        srv.close()

    def test_bad_timeout_rejected(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="step_timeout"):
            InferenceServer(cfg, params, n_slots=2, step_timeout=0.0)


_FOLLOWER_DEATH_WORKER = """
import json, os, threading, time, urllib.request, urllib.error
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
import numpy as np
from shellac_tpu import ParallelConfig, get_model_config
from shellac_tpu.inference.batching import BatchingEngine
from shellac_tpu.inference.engine import shard_params
from shellac_tpu.inference.multihost import MultihostEngine
from shellac_tpu.inference.server import InferenceServer, make_http_server
from shellac_tpu.models import transformer
from shellac_tpu.parallel.distributed import global_mesh, initialize

assert initialize()
cfg = get_model_config("tiny").replace(dtype="float32")
params = transformer.init_params(cfg, jax.random.PRNGKey(0))
mesh = global_mesh(ParallelConfig(tp=4))
sharded = shard_params(cfg, params, mesh)
eng = MultihostEngine(
    BatchingEngine(cfg, sharded, n_slots=2, max_len=64, mesh=mesh)
)

if eng.is_primary:
    srv = InferenceServer(cfg, sharded, engine=eng, step_timeout=20.0)
    httpd = make_http_server(srv)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    # One healthy request proves the pod serves before the fault.
    req = urllib.request.Request(
        base + "/generate",
        json.dumps({"tokens": [3, 5, 7], "max_new": 4}).encode(),
        {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        assert len(json.loads(r.read())["tokens"]) >= 1
    # The follower dies now (it exits after its first request). The
    # next request must fail LOUDLY as HTTP 500 — via whichever
    # detection fires first: on this CPU/Gloo transport the dead peer
    # raises promptly in the step ("scheduler died: ... Gloo"), on a
    # real pod a wedged collective never raises and the step watchdog
    # trips ("step_timeout"). Both are the contracted behavior; a
    # hang or a 200 is the bug.
    req2 = urllib.request.Request(
        base + "/generate",
        json.dumps({"tokens": [9, 9], "max_new": 4}).encode(),
        {"Content-Type": "application/json"})
    try:
        urllib.request.urlopen(req2, timeout=120)
        raise AssertionError("request against a dead pod succeeded")
    except urllib.error.HTTPError as e:
        assert e.code == 500, e.code
        body = e.read().decode()
        assert ("step_timeout" in body) or ("scheduler died" in body), body
    print("WORKER_OK", jax.process_index(), flush=True)
    # The scheduler thread is wedged in the dead collective; a normal
    # interpreter exit would join it forever.
    os._exit(0)
else:
    # Serve until the first request completes, then die abruptly
    # mid-pod — the injected fault. The primary's next broadcast
    # wedges with no peer on the other side.
    while eng.step() is not None:
        if eng.stats.get("requests_completed", 0) >= 1:
            os._exit(1)
"""


_DISCONNECT_WORKER = """
import json, socket, threading, time
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
import numpy as np
from shellac_tpu import ParallelConfig, get_model_config
from shellac_tpu.inference.batching import BatchingEngine
from shellac_tpu.inference.engine import shard_params
from shellac_tpu.inference.multihost import MultihostEngine
from shellac_tpu.inference.server import InferenceServer, make_http_server
from shellac_tpu.models import transformer
from shellac_tpu.parallel.distributed import global_mesh, initialize

assert initialize()
cfg = get_model_config("tiny").replace(dtype="float32")
params = transformer.init_params(cfg, jax.random.PRNGKey(0))
mesh = global_mesh(ParallelConfig(tp=4))
sharded = shard_params(cfg, params, mesh)
eng = MultihostEngine(
    BatchingEngine(cfg, sharded, n_slots=2, max_len=64, mesh=mesh)
)

if eng.is_primary:
    srv = InferenceServer(cfg, sharded, engine=eng)
    httpd = make_http_server(srv)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    # Raw-socket streaming request, disconnected after the first chunk:
    # the generator must cancel the generation pod-wide.
    s = socket.create_connection(("127.0.0.1", port), timeout=60)
    body = json.dumps({"tokens": [3, 5, 7], "max_new": 40,
                       "stream": True}).encode()
    s.sendall(b"POST /generate HTTP/1.1\\r\\nHost: x\\r\\n"
              b"Content-Type: application/json\\r\\n"
              + f"Content-Length: {len(body)}\\r\\n\\r\\n".encode() + body)
    s.recv(1)  # first byte of the response = stream started
    s.close()  # abrupt disconnect mid-stream
    deadline = time.time() + 60
    while (srv.engine.stats.get("requests_cancelled", 0) < 1
           and time.time() < deadline):
        time.sleep(0.2)
    assert srv.engine.stats["requests_cancelled"] == 1, srv.engine.stats
    httpd.shutdown()
    srv.close()  # broadcasts shutdown -> rank 1 exits serve_forever
else:
    eng.serve_forever()
    # The cancel rode the command broadcast: this rank's replica
    # dropped the same request.
    assert eng.stats.get("requests_cancelled", 0) == 1, eng.stats
print("WORKER_OK", jax.process_index(), flush=True)
"""


from conftest import needs_multiprocess_cpu as _needs_multiprocess_cpu


@_needs_multiprocess_cpu
class TestMultihostFaults:
    def test_follower_death_detected_loudly(self, tmp_path):
        run_two_process(tmp_path, _FOLLOWER_DEATH_WORKER, timeout=420,
                        ok_ranks=(0,))

    def test_client_disconnect_cancels_pod_wide(self, tmp_path):
        run_two_process(tmp_path, _DISCONNECT_WORKER, timeout=420)
