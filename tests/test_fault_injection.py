"""Fault injection on the serving path.

Failure semantics under test (docs/inference.md, failure section):

  - A WEDGED engine step (a follower process dying mid-collective
    leaves the primary stuck in native code — no exception ever
    surfaces) is detected by the server's step watchdog
    (`step_timeout`): every pending request fails loudly with the
    fatal message. Without a restart budget (the default) new
    submissions are refused with HTTP 500 and the process stays
    responsive — loud failure, never a silent hang.
  - With `restart_budget > 0` the SUPERVISOR recovers in-process:
    the wedged thread is abandoned under its old engine generation, a
    fresh engine is rebuilt from the retained params/config, and
    serving resumes; results a stale generation ever produces are
    discarded; the budget (a sliding-window circuit breaker) turns a
    crash-looping engine fatal instead of rebuilding forever.
  - Admission is bounded (`max_pending` -> HTTP 429 + Retry-After),
    expired-deadline requests shed before prefill, and /health is a
    real readiness signal (ok | recovering | failed).
  - A client disconnecting mid-stream under the MULTIHOST engine
    cancels the generation on every rank (the cancel rides the
    command broadcast), freeing the slot pod-wide.
"""

import json
import os
import signal
import subprocess
import sys
import threading
import time
import urllib.error
import urllib.request

import jax
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.config import TrainConfig
from shellac_tpu.inference.batching import BatchingEngine
from shellac_tpu.inference.server import (
    InferenceServer,
    ServerUnavailable,
    make_http_server,
)
from shellac_tpu.models import transformer
from shellac_tpu.obs import Registry, set_default_registry
from shellac_tpu.training import chaos
from shellac_tpu.training.checkpoint import TMP_DIR_MARKER, Checkpointer
from shellac_tpu.training.data import token_batches
from shellac_tpu.training.loop import fit

from conftest import run_two_process


def _tiny():
    return get_model_config("tiny").replace(dtype="float32")


class _WedgingEngine(BatchingEngine):
    """Engine whose step() wedges after `good_steps` steps — the
    observable behavior of a primary whose follower died mid-
    collective. The wedge is an Event wait so the test can RELEASE the
    scheduler thread at teardown: a thread left sleeping inside
    step() for the rest of the pytest process has crashed later XLA
    compiles (both full-suite segfaults pointed here)."""

    def __init__(self, *a, good_steps=0, **kw):
        super().__init__(*a, **kw)
        self._good = good_steps
        self.wedged = threading.Event()
        self.release = threading.Event()
        # Optional forged (rid, tokens) the released step reports as
        # finished — the stale-generation discard test plants a result
        # colliding with a live rid of the REBUILT engine.
        self.fake = None

    def step(self):
        if self._good <= 0:
            self.wedged.set()
            self.release.wait(3600)
            return [self.fake] if self.fake is not None else []
        self._good -= 1
        return super().step()


def _teardown(srv, eng, httpd=None, old_threads=()):
    """Release the wedged scheduler thread and JOIN it before the test
    returns — no engine thread may outlive its test. Recovery tests
    pass the ABANDONED generations' threads via old_threads: close()
    only joins the current generation's."""
    if httpd is not None:
        httpd.shutdown()
    eng.release.set()
    srv.close()  # sets the stop flag and joins the scheduler thread
    assert not srv._thread.is_alive(), "scheduler thread leaked"
    for t in old_threads:
        t.join(timeout=120)
        assert not t.is_alive(), "stale scheduler thread leaked"


class TestStepWatchdog:
    def test_wedged_step_fails_pending_loudly(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = _WedgingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, good_steps=0)
        srv = InferenceServer(cfg, params, engine=eng, step_timeout=2.0)
        try:
            t0 = time.monotonic()
            with pytest.raises(RuntimeError, match="step_timeout"):
                srv.generate([1, 2, 3], max_new=4, timeout=60)
            # Detection must come from the watchdog (well under the
            # pessimistic request timeout), and the server must now
            # refuse new work with the same loud error, not hang.
            assert time.monotonic() - t0 < 30
            with pytest.raises(RuntimeError, match="step_timeout"):
                srv.generate([4, 5], max_new=4, timeout=60)
        finally:
            _teardown(srv, eng)

    def test_http_surface_returns_500(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = _WedgingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, good_steps=0)
        srv = InferenceServer(cfg, params, engine=eng, step_timeout=2.0)
        httpd = make_http_server(srv)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            base = f"http://127.0.0.1:{httpd.server_address[1]}"
            req = urllib.request.Request(
                base + "/generate",
                json.dumps({"tokens": [3, 5, 7], "max_new": 4}).encode(),
                {"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(req, timeout=60)
            assert e.value.code == 500
            assert "step_timeout" in e.value.read().decode()
        finally:
            _teardown(srv, eng, httpd)

    def test_healthy_server_unaffected(self):
        """A generous timeout never fires on a healthy engine — the
        watchdog must not produce false positives mid-service."""
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        srv = InferenceServer(cfg, params, n_slots=2, max_len=64,
                              temperature=0.0, step_timeout=120.0)
        out = srv.generate([1, 2, 3], max_new=6, timeout=120)
        assert len(out) >= 1
        srv.close()

    def test_bad_timeout_rejected(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="step_timeout"):
            InferenceServer(cfg, params, n_slots=2, step_timeout=0.0)


class _GatedEngine(BatchingEngine):
    """Engine whose step() waits for an explicit go-ahead each call —
    a controllable slow engine (never wedged from the watchdog's view
    unless the test wants it: the gate has a deadline)."""

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        self.gate = threading.Event()

    def step(self):
        self.gate.wait(120)
        return super().step()


def _mk(engine_cls=_WedgingEngine, **kw):
    cfg = _tiny()
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    eng = engine_cls(cfg, params, n_slots=2, max_len=64, temperature=0.0,
                     **kw)
    return cfg, params, eng


def _wait_status(srv, want, timeout=60):
    deadline = time.monotonic() + timeout
    while srv.status != want and time.monotonic() < deadline:
        time.sleep(0.05)
    assert srv.status == want, (srv.status, srv._fatal)


class TestSupervisorRecovery:
    def test_wedge_recovers_and_serves_again(self):
        """The acceptance path: wedge -> watchdog fails every in-flight
        request loudly -> supervisor rebuilds a fresh engine under a
        new generation -> a subsequent generate() succeeds, all in one
        server process."""
        cfg, params, eng = _mk(good_steps=0)

        def factory():
            return BatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  temperature=0.0)

        # step_timeout must clear the rebuilt engine's first-step
        # compile, or the watchdog trips on the recovery itself (the
        # documented sizing rule).
        srv = InferenceServer(cfg, params, engine=eng, step_timeout=10.0,
                              restart_budget=2, engine_factory=factory)
        gen0_thread = srv._thread
        try:
            with pytest.raises(RuntimeError, match="step_timeout"):
                srv.generate([1, 2, 3], max_new=4, timeout=120)
            _wait_status(srv, "ok")
            assert srv.restarts == 1
            assert srv._g.gen == 1
            out = srv.generate([4, 5, 6], max_new=4, timeout=120)
            assert len(out) == 4
            h = srv.health()
            assert h["ok"] and h["status"] == "ok" and h["restarts"] == 1
        finally:
            _teardown(srv, eng, old_threads=(gen0_thread,))

    def test_circuit_breaker_exhausts_budget(self):
        """A crash-looping engine (every rebuild wedges again) exhausts
        the restart budget and the server stays fatal: generate raises,
        /health returns 503 with status=failed."""
        cfg, params, eng = _mk(good_steps=0)
        engines = [eng]

        def bad_factory():
            e = _WedgingEngine(cfg, params, n_slots=2, max_len=64,
                               temperature=0.0, good_steps=0)
            engines.append(e)
            return e

        srv = InferenceServer(cfg, params, engine=eng, step_timeout=2.0,
                              restart_budget=1, engine_factory=bad_factory)
        gen0_thread = srv._thread
        httpd = make_http_server(srv)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            with pytest.raises(RuntimeError, match="step_timeout"):
                srv.generate([1, 2, 3], max_new=4, timeout=120)
            # Poke the rebuilt generation so it steps (and wedges);
            # the second wedge must exhaust the budget of 1.
            deadline = time.monotonic() + 120
            while srv.status != "failed" and time.monotonic() < deadline:
                if srv.status == "ok":
                    try:
                        srv._submit([9], 2, None, {}, stream=False)
                    except RuntimeError:
                        pass
                time.sleep(0.1)
            assert srv.status == "failed"
            assert "restart budget exhausted" in srv._fatal
            with pytest.raises(RuntimeError, match="restart budget"):
                srv.generate([7], max_new=2, timeout=10)
            base = f"http://127.0.0.1:{httpd.server_address[1]}"
            with pytest.raises(urllib.error.HTTPError) as e:
                urllib.request.urlopen(base + "/health", timeout=30)
            assert e.value.code == 503
            body = json.loads(e.value.read())
            assert body["status"] == "failed" and not body["ok"]
            assert "step_timeout" in body["error"]
            # /stats stays 200 through the outage but names the fault.
            with urllib.request.urlopen(base + "/stats", timeout=30) as r:
                stats = json.loads(r.read())
            assert "step_timeout" in stats["fatal"]
            assert stats["status"] == "failed"
        finally:
            httpd.shutdown()
            for e in engines:
                e.release.set()
            srv.close()
            assert not srv._thread.is_alive()
            gen0_thread.join(timeout=120)
            assert not gen0_thread.is_alive(), "stale scheduler leaked"

    def test_admission_while_recovering_is_503(self):
        """While the supervisor is mid-rebuild, admission refuses with
        a retryable 503 instead of queueing into a dead generation."""
        cfg, params, eng = _mk(good_steps=0)
        factory_gate = threading.Event()
        built = []

        def slow_factory():
            factory_gate.wait(120)
            e = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                               temperature=0.0)
            built.append(e)
            return e

        # step_timeout must clear the rebuilt engine's first-step
        # compile, or the final post-recovery generate() trips the
        # watchdog again and exhausts the budget.
        srv = InferenceServer(cfg, params, engine=eng, step_timeout=10.0,
                              restart_budget=1, engine_factory=slow_factory)
        gen0_thread = srv._thread
        try:
            with pytest.raises(RuntimeError, match="step_timeout"):
                srv.generate([1, 2, 3], max_new=4, timeout=120)
            _wait_status(srv, "recovering")
            with pytest.raises(ServerUnavailable) as e:
                srv.generate([4], max_new=2, timeout=10)
            assert e.value.http_status == 503
            factory_gate.set()
            _wait_status(srv, "ok")
            out = srv.generate([4, 5], max_new=3, timeout=120)
            assert len(out) == 3
        finally:
            factory_gate.set()
            _teardown(srv, eng, old_threads=(gen0_thread,))

    def test_stale_generation_results_discarded(self):
        """A wedged thread that eventually un-wedges and returns
        results must NOT resolve the new generation's pendings — even
        when the rids collide by construction."""
        from shellac_tpu.inference.server import _Pending

        cfg, params, eng = _mk(good_steps=0)

        def factory():
            return BatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  temperature=0.0)

        srv = InferenceServer(cfg, params, engine=eng, step_timeout=10.0,
                              restart_budget=1, engine_factory=factory)
        old_thread = srv._thread
        try:
            with pytest.raises(RuntimeError, match="step_timeout"):
                srv.generate([1, 2, 3], max_new=4, timeout=120)
            _wait_status(srv, "ok")
            # Plant a live pending on the NEW generation, then have the
            # OLD thread wake up claiming that very rid finished with a
            # forged output. The generation check must discard it.
            rid = 424242
            p = _Pending(rid)
            srv._pending[rid] = p
            eng.fake = (rid, [999, 999])
            eng.release.set()
            old_thread.join(timeout=30)
            assert not old_thread.is_alive(), "stale scheduler leaked"
            assert not p.event.is_set(), \
                "stale-generation result resolved a live request"
            assert srv._pending.pop(rid, None) is p
            # The new generation still serves normally.
            out = srv.generate([5, 6], max_new=3, timeout=120)
            assert len(out) == 3
        finally:
            _teardown(srv, eng)

    def test_scheduler_death_recovers(self):
        """An exception (not a wedge) in the engine step takes the
        scheduler-death path into the same supervisor: loud failure,
        then rebuild — no watchdog needed."""
        cfg, params, _ = _mk(good_steps=0)

        class _DyingEngine(BatchingEngine):
            def step(self):
                raise OSError("transport reset by peer")

        def factory():
            return BatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  temperature=0.0)

        srv = InferenceServer(
            cfg, params,
            engine=_DyingEngine(cfg, params, n_slots=2, max_len=64,
                                temperature=0.0),
            restart_budget=1, engine_factory=factory,
        )
        try:
            with pytest.raises(RuntimeError, match="scheduler died"):
                srv.generate([1, 2, 3], max_new=4, timeout=120)
            _wait_status(srv, "ok")
            out = srv.generate([4, 5], max_new=3, timeout=120)
            assert len(out) == 3
        finally:
            srv.close()
            assert not srv._thread.is_alive()


class TestMultihostResyncThroughSupervisor:
    """engine_factory=MultihostEngine.resync (the cmd_serve wiring),
    on a single-process (degenerate) wrapper."""

    def test_scheduler_death_resync_recovers(self):
        from shellac_tpu.inference.multihost import MultihostEngine

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))

        class _DieOnce(BatchingEngine):
            def __init__(self, *a, **kw):
                super().__init__(*a, **kw)
                self._die = True

            def step(self):
                if self._die:
                    self._die = False
                    raise OSError("transport reset by peer")
                return super().step()

        mh = MultihostEngine(_DieOnce(cfg, params, n_slots=2, max_len=64,
                                      temperature=0.0))
        srv = InferenceServer(cfg, params, engine=mh, restart_budget=1,
                              engine_factory=mh.resync)
        try:
            with pytest.raises(RuntimeError, match="scheduler died"):
                srv.generate([1, 2, 3], max_new=4, timeout=120)
            _wait_status(srv, "ok")
            # Recovery was an epoch resync of the SAME wrapper, not a
            # rebuild: safe here because the dead scheduler thread has
            # left the engine.
            assert mh.epoch == 1
            assert srv.engine is mh
            out = srv.generate([4, 5], max_new=3, timeout=120)
            assert len(out) == 3
        finally:
            srv.close()
            assert not srv._thread.is_alive()

    def test_wedge_with_inplace_resync_goes_fatal(self):
        """A WEDGED step cannot be recovered by an in-place resync —
        the stuck thread still owns the engine, and two threads must
        not race one command broadcast. The supervisor must refuse and
        go fatal instead of attempting it."""
        from shellac_tpu.inference.multihost import MultihostEngine

        cfg, params, eng = _mk(good_steps=0)
        mh = MultihostEngine(eng)
        srv = InferenceServer(cfg, params, engine=mh, step_timeout=2.0,
                              restart_budget=3, engine_factory=mh.resync)
        try:
            with pytest.raises(RuntimeError, match="step_timeout"):
                srv.generate([1, 2, 3], max_new=4, timeout=120)
            _wait_status(srv, "failed")
            assert "in-place resync" in srv._fatal
            assert srv.restarts == 0  # no rebuild was attempted
            assert mh.epoch == 0  # resync never ran against the engine
        finally:
            eng.release.set()
            srv.close()
            assert not srv._thread.is_alive()


class TestAbortAll:
    """BatchingEngine.abort_all — the supervisor-rebuild / multi-host
    epoch-resync cleanup helper. (Exact post-abort output parity vs a
    bare engine is pinned by test_multihost_serving's resync test.)"""

    def test_clears_engine_for_rebuild(self):
        import numpy as np

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = BatchingEngine(cfg, params, n_slots=1, max_len=64)
        eng.submit("in_flight", np.array([1, 2, 3], np.int32), 30)
        eng.submit("queued", np.array([4, 5], np.int32), 30)
        eng.step()  # "in_flight" occupies the only slot
        dropped = eng.abort_all()
        assert sorted(dropped) == ["in_flight", "queued"]
        assert eng.pending == 0
        assert eng.stats["requests_cancelled"] == 2
        results = eng.run([("fresh", np.array([7, 8], np.int32), 4)])
        assert list(results) == ["fresh"] and len(results["fresh"]) == 4

    def test_returns_paged_blocks(self):
        import numpy as np

        from shellac_tpu.inference.batching import PagedBatchingEngine

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = PagedBatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  block_size=16)
        n_free = len(eng._free)
        eng.submit("a", np.array([1, 2, 3], np.int32), 20)
        eng.submit("b", np.array([4, 5], np.int32), 20)
        eng.step()
        assert len(eng._free) < n_free
        eng.abort_all()
        assert len(eng._free) == n_free, "blocks leaked across abort"
        results = eng.run([("fresh", np.array([1, 2, 3], np.int32), 5)])
        assert list(results) == ["fresh"] and len(results["fresh"]) == 5

    def test_abort_all_purges_prefix_cache(self):
        """Paged abort must reset the allocator to its CANONICAL state
        (prefix registries empty, free list in constructor order) —
        the multi-host resync path aborts replicas AFTER they have
        diverged, and surviving per-host prefix registries would make
        a later prompt prefix-hit on one host but miss on another
        (different-shaped programs, wedged collective again)."""
        import numpy as np

        from shellac_tpu.inference.batching import PagedBatchingEngine

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = PagedBatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  block_size=16, prefix_cache=True)
        pristine = list(eng._free)
        prompt = (np.arange(40) % cfg.vocab_size).astype(np.int32)
        eng.run([("a", prompt, 4)])
        assert eng._hash_to_block, "prefix blocks were never registered"
        eng.abort_all()
        assert not eng._hash_to_block and not eng._block_ref
        assert eng._free == pristine, "free list not canonical"
        results = eng.run([("b", prompt, 4)])
        assert len(results["b"]) == 4


class TestOverlapFaults:
    """Overlapped decode dispatch x the failure machinery: a window in
    flight when the supervisor/watchdog/abort path fires must be
    DRAINED (synced and discarded), never attributed to a successor
    request or generation."""

    def test_wedge_recovers_onto_fresh_overlap_engine(self):
        """Wedge -> watchdog -> rebuild, with BOTH generations running
        overlap_decode=True: the rebuilt generation serves correct,
        strict-ordering-identical output."""
        import numpy as np

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = _WedgingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, good_steps=0,
                             overlap_decode=True, decode_ticks=2)

        def factory():
            return BatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  temperature=0.0, overlap_decode=True,
                                  decode_ticks=2)

        srv = InferenceServer(cfg, params, engine=eng, step_timeout=10.0,
                              restart_budget=2, engine_factory=factory)
        gen0_thread = srv._thread
        try:
            with pytest.raises(RuntimeError, match="step_timeout"):
                srv.generate([1, 2, 3], max_new=4, timeout=120)
            _wait_status(srv, "ok")
            out = srv.generate([4, 5, 6], max_new=6, timeout=120)
            ref = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                                 temperature=0.0, decode_ticks=2)
            want = ref.run([("r", np.array([4, 5, 6], np.int32), 6)])["r"]
            assert list(out) == list(want)
        finally:
            _teardown(srv, eng, old_threads=(gen0_thread,))

    def test_abort_all_mid_window_no_stale_leak(self):
        """The resync/rebuild cleanup contract under overlap: windows
        in flight at abort_all are synced-and-discarded, and the next
        tenant of every slot produces exactly the strict-ordering
        output (no stale-generation tokens leak)."""
        import numpy as np

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, overlap_decode=True,
                             decode_ticks=3)
        eng.submit("a", np.array([1, 2, 3], np.int32), 20)
        eng.submit("b", np.array([4, 5], np.int32), 20)
        eng.step()
        eng.step()  # a window is in flight beyond the settled one
        assert eng._windows, "pipeline never engaged"
        dropped = eng.abort_all()
        assert sorted(dropped) == ["a", "b"]
        assert not eng._windows
        results = eng.run([("fresh", np.array([7, 8], np.int32), 6)])
        ref = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, decode_ticks=3)
        want = ref.run([("fresh", np.array([7, 8], np.int32), 6)])
        assert {k: list(v) for k, v in results.items()} == {
            k: list(v) for k, v in want.items()}

    def test_streaming_deltas_under_overlap(self):
        """The server's streaming invariant (out only ever grows;
        holdback protects stop truncation) holds when deltas arrive in
        overlapped window batches."""
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        srv = InferenceServer(cfg, params, n_slots=2, max_len=64,
                              temperature=0.0, overlap_decode=True,
                              decode_ticks=2)
        try:
            deltas, final = [], None
            for kind, val in srv.generate_stream([1, 2, 3], max_new=8,
                                                 timeout=120):
                if kind == "delta":
                    deltas.append(list(val))
                else:
                    final = list(val)
            streamed = [t for d in deltas for t in d]
            assert final is not None and len(final) == 8
            assert streamed == final[:len(streamed)]
        finally:
            srv.close()

    def test_deadline_shed_with_overlap_engine(self):
        """Deadline shedding composes with the overlapped engine: a
        request whose deadline expires while the scheduler is parked in
        a gated step is shed before prefill (same contract as
        TestDeadlineShedding, on the overlap pipeline)."""
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = _GatedEngine(cfg, params, n_slots=2, max_len=64,
                           temperature=0.0, overlap_decode=True,
                           decode_ticks=2)
        srv = InferenceServer(cfg, params, engine=eng)
        try:
            results = []
            t = threading.Thread(target=lambda: results.append(
                srv.generate([1, 2, 3], max_new=4, timeout=120)))
            t.start()
            deadline = time.monotonic() + 60
            while not srv._pending and time.monotonic() < deadline:
                time.sleep(0.01)
            time.sleep(0.2)  # scheduler is now inside the gated step
            with pytest.raises(TimeoutError):
                srv.generate([5, 6], max_new=4, timeout=0.2)
            time.sleep(0.1)
            eng.gate.set()
            t.join(timeout=120)
            assert results and len(results[0]) == 4
            deadline = time.monotonic() + 60
            while srv.shed < 1 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert srv.shed == 1
            assert eng.stats["prefills"] == 1
        finally:
            eng.gate.set()
            srv.close()

    def test_abort_all_mid_prefill_flight_no_stale_leak(self):
        """Overlapped PREFILL x the failure machinery: prefills in
        flight at abort_all (the supervisor rebuild / resync cleanup)
        are synced-and-discarded like in-flight windows, and the next
        tenant of every slot produces exactly the strict-ordering
        output — no stale first token leaks."""
        import numpy as np

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, overlap_prefill=True,
                             overlap_decode=True, decode_ticks=2)
        eng.submit("a", np.array([1, 2, 3], np.int32), 8)
        eng.submit("b", np.array([4, 5], np.int32), 8)
        eng.step()  # prefills dispatched, NOT settled
        assert eng._pflights, "no prefill in flight"
        dropped = eng.abort_all()
        assert sorted(dropped) == ["a", "b"]
        assert not eng._pflights
        results = eng.run([("fresh", np.array([7, 8], np.int32), 6)])
        ref = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, decode_ticks=2)
        want = ref.run([("fresh", np.array([7, 8], np.int32), 6)])
        assert {k: list(v) for k, v in results.items()} == {
            k: list(v) for k, v in want.items()}

    def test_wedge_recovers_onto_fresh_overlap_prefill_engine(self):
        """Wedge -> watchdog -> rebuild with BOTH generations running
        the full overlap pipeline (decode AND prefill): the rebuilt
        generation serves strict-ordering-identical output."""
        import numpy as np

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = _WedgingEngine(cfg, params, n_slots=2, max_len=64,
                             temperature=0.0, good_steps=0,
                             overlap_decode=True, overlap_prefill=True,
                             decode_ticks=2)

        def factory():
            return BatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  temperature=0.0, overlap_decode=True,
                                  overlap_prefill=True, decode_ticks=2)

        srv = InferenceServer(cfg, params, engine=eng, step_timeout=10.0,
                              restart_budget=2, engine_factory=factory)
        gen0_thread = srv._thread
        try:
            with pytest.raises(RuntimeError, match="step_timeout"):
                srv.generate([1, 2, 3], max_new=4, timeout=120)
            _wait_status(srv, "ok")
            out = srv.generate([4, 5, 6], max_new=6, timeout=120)
            ref = BatchingEngine(cfg, params, n_slots=2, max_len=64,
                                 temperature=0.0, decode_ticks=2)
            want = ref.run([("r", np.array([4, 5, 6], np.int32), 6)])["r"]
            assert list(out) == list(want)
        finally:
            _teardown(srv, eng, old_threads=(gen0_thread,))


class TestAdmissionControl:
    def test_over_limit_rejected_429(self):
        cfg, params, eng = _mk(good_steps=0)
        srv = InferenceServer(cfg, params, engine=eng, max_pending=2)
        httpd = make_http_server(srv)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            for _ in range(2):
                srv._submit([1, 2], 4, None, {}, stream=False)
            with pytest.raises(ServerUnavailable) as e:
                srv._submit([1, 2], 4, None, {}, stream=False)
            assert e.value.http_status == 429
            assert "max_pending=2" in str(e.value)
            base = f"http://127.0.0.1:{httpd.server_address[1]}"
            req = urllib.request.Request(
                base + "/generate",
                json.dumps({"tokens": [1, 2], "max_new": 4}).encode(),
                {"Content-Type": "application/json"},
            )
            with pytest.raises(urllib.error.HTTPError) as he:
                urllib.request.urlopen(req, timeout=30)
            assert he.value.code == 429
            assert he.value.headers.get("Retry-After") is not None
            assert "overloaded" in json.loads(he.value.read())["error"]
            # /health keeps answering (the cap gates generate only) and
            # reports the saturation.
            with urllib.request.urlopen(base + "/health", timeout=30) as r:
                h = json.loads(r.read())
            assert h["pending"] == 2 and h["max_pending"] == 2
        finally:
            httpd.shutdown()
            _teardown(srv, eng)

    def test_bad_max_pending_rejected(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="max_pending"):
            InferenceServer(cfg, params, n_slots=2, max_pending=0)

    def test_prebuilt_engine_needs_factory_for_budget(self):
        cfg, params, eng = _mk(good_steps=10)
        try:
            with pytest.raises(ValueError, match="engine_factory"):
                InferenceServer(cfg, params, engine=eng, restart_budget=1)
        finally:
            eng.release.set()


class TestDeadlineShedding:
    def test_expired_deadline_never_reaches_prefill(self):
        """A request whose client timeout expires while the scheduler
        is busy is shed BEFORE prefill: the engine never sees it."""
        cfg, params, _ = _mk(good_steps=0)
        eng = _GatedEngine(cfg, params, n_slots=2, max_len=64,
                           temperature=0.0)
        srv = InferenceServer(cfg, params, engine=eng)
        try:
            results = []
            t = threading.Thread(target=lambda: results.append(
                srv.generate([1, 2, 3], max_new=4, timeout=120)))
            t.start()
            # Wait for A to be prefill-eligible: the scheduler is now
            # blocked inside step() at the gate.
            deadline = time.monotonic() + 60
            while not srv._pending and time.monotonic() < deadline:
                time.sleep(0.01)
            time.sleep(0.2)  # let the scheduler enter the gated step
            with pytest.raises(TimeoutError):
                srv.generate([5, 6], max_new=4, timeout=0.2)
            time.sleep(0.1)
            eng.gate.set()
            t.join(timeout=120)
            assert results and len(results[0]) == 4
            # B was shed at the scheduler: exactly one prefill (A's).
            deadline = time.monotonic() + 60
            while srv.shed < 1 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert srv.shed == 1
            assert eng.stats["prefills"] == 1
        finally:
            eng.gate.set()
            srv.close()
            assert not srv._thread.is_alive()


class TestObservabilityCounters:
    """The obs layer under faults: supervisor restarts and deadline
    sheds must increment their counters (and settle the request spans)
    across an engine rebuild — the /metrics view of PR 2's recovery
    story."""

    def test_restart_counter_increments_across_rebuild(self):
        reg = Registry()
        cfg, params, eng = _mk(good_steps=0, registry=reg)

        def factory():
            return BatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  temperature=0.0, registry=reg)

        srv = InferenceServer(cfg, params, engine=eng, step_timeout=10.0,
                              restart_budget=2, engine_factory=factory,
                              registry=reg)
        gen0_thread = srv._thread
        httpd = make_http_server(srv)
        threading.Thread(target=httpd.serve_forever, daemon=True).start()
        try:
            with pytest.raises(RuntimeError, match="step_timeout"):
                srv.generate([1, 2, 3], max_new=4, timeout=120)
            _wait_status(srv, "ok")
            # The wedged in-flight request settled as a fault span and
            # the supervisor rebuild incremented the restart counter.
            assert reg.value("shellac_supervisor_restarts_total") == 1
            assert reg.value(
                "shellac_requests_total", outcome="fault"
            ) == 1
            out = srv.generate([4, 5, 6], max_new=4, timeout=120)
            assert len(out) == 4
            assert reg.value("shellac_requests_total", outcome="ok") == 1
            # The REBUILT engine deposits into the same registry, and a
            # scrape shows the new generation + the restart.
            base = f"http://127.0.0.1:{httpd.server_address[1]}"
            with urllib.request.urlopen(base + "/metrics",
                                        timeout=30) as r:
                text = r.read().decode()
            assert "shellac_supervisor_restarts_total 1" in text
            assert "shellac_engine_generation 1" in text
            assert 'shellac_ttft_seconds_bucket{le="' in text
        finally:
            _teardown(srv, eng, httpd=httpd, old_threads=(gen0_thread,))

    def test_shed_counter_increments(self):
        """A deadline-shed request settles its span as shed and bumps
        shellac_requests_shed_total (the scenario of
        TestDeadlineShedding, observed through the registry)."""
        reg = Registry()
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        eng = _GatedEngine(cfg, params, n_slots=2, max_len=64,
                           temperature=0.0, registry=reg)
        srv = InferenceServer(cfg, params, engine=eng, registry=reg)
        try:
            results = []
            t = threading.Thread(target=lambda: results.append(
                srv.generate([1, 2, 3], max_new=4, timeout=120)))
            t.start()
            deadline = time.monotonic() + 60
            while not srv._pending and time.monotonic() < deadline:
                time.sleep(0.01)
            time.sleep(0.2)  # let the scheduler enter the gated step
            with pytest.raises(TimeoutError):
                srv.generate([5, 6], max_new=4, timeout=0.2)
            time.sleep(0.1)
            eng.gate.set()
            t.join(timeout=120)
            assert results and len(results[0]) == 4
            deadline = time.monotonic() + 60
            while srv.shed < 1 and time.monotonic() < deadline:
                time.sleep(0.05)
            assert reg.value("shellac_requests_shed_total") == 1
            assert reg.value(
                "shellac_requests_total", outcome="shed"
            ) == 1
            # Only the served request's span reached prefill/TTFT.
            assert reg.value("shellac_ttft_seconds") == 1
        finally:
            eng.gate.set()
            srv.close()
            assert not srv._thread.is_alive()


class TestCloseAndHeartbeat:
    def test_close_fails_pending_loudly(self):
        """close() must fail still-pending requests immediately instead
        of leaving blocked generate() callers waiting out their full
        timeout."""
        cfg, params, _ = _mk(good_steps=0)
        eng = _GatedEngine(cfg, params, n_slots=2, max_len=64,
                           temperature=0.0)
        srv = InferenceServer(cfg, params, engine=eng)
        errors = []

        def hit():
            t0 = time.monotonic()
            try:
                srv.generate([1, 2, 3], max_new=4, timeout=300)
            except RuntimeError as e:
                errors.append((time.monotonic() - t0, str(e)))

        t = threading.Thread(target=hit)
        t.start()
        deadline = time.monotonic() + 60
        while not srv._pending and time.monotonic() < deadline:
            time.sleep(0.01)
        srv.close()
        t.join(timeout=30)
        assert not t.is_alive()
        assert errors, "caller was not failed"
        elapsed, msg = errors[0]
        assert "closed" in msg
        assert elapsed < 60, "caller waited out its timeout"
        # Release the gated step and JOIN the scheduler before the test
        # returns — no engine thread may outlive its test.
        eng.gate.set()
        srv._thread.join(timeout=120)
        assert not srv._thread.is_alive(), "scheduler thread leaked"

    def test_scheduler_beats_heartbeat(self, tmp_path):
        from shellac_tpu.utils.failure import Heartbeat

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        path = str(tmp_path / "serve_hb.json")
        srv = InferenceServer(cfg, params, n_slots=2, max_len=64,
                              temperature=0.0, heartbeat_path=path)
        try:
            deadline = time.monotonic() + 30
            while Heartbeat.is_stale(path, 3600) and \
                    time.monotonic() < deadline:
                time.sleep(0.05)
            assert not Heartbeat.is_stale(path, 3600)
        finally:
            srv.close()

    def test_rebuild_beats_heartbeat_without_watchdog(self, tmp_path):
        """With no step watchdog armed (no step_timeout), the
        supervisor itself must keep the heartbeat fresh through an
        engine rebuild — otherwise an external watchdog restarts the
        pod mid-recovery."""
        from shellac_tpu.utils.failure import heartbeat_age

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        path = str(tmp_path / "rebuild_hb.json")
        factory_gate = threading.Event()

        def slow_factory():
            factory_gate.wait(60)
            return BatchingEngine(cfg, params, n_slots=2, max_len=64,
                                  temperature=0.0)

        class _DyingEngine(BatchingEngine):
            def step(self):
                raise OSError("transport reset by peer")

        srv = InferenceServer(
            cfg, params,
            engine=_DyingEngine(cfg, params, n_slots=2, max_len=64,
                                temperature=0.0),
            restart_budget=1, engine_factory=slow_factory,
            heartbeat_path=path,
        )
        try:
            with pytest.raises(RuntimeError, match="scheduler died"):
                srv.generate([1, 2, 3], max_new=4, timeout=120)
            _wait_status(srv, "recovering")
            time.sleep(2.0)  # deep in the rebuild window
            deadline = time.monotonic() + 15
            age = None
            while time.monotonic() < deadline:
                age = heartbeat_age(path)
                if age is not None and age < 1.5:
                    break
                time.sleep(0.2)
            assert age is not None and age < 1.5, age
        finally:
            factory_gate.set()
            _wait_status(srv, "ok")
            srv.close()
            assert not srv._thread.is_alive()

    def test_watchdog_cobeats_heartbeat_through_wedge(self, tmp_path):
        """With the step watchdog armed, the heartbeat must stay fresh
        WHILE a step is wedged (the scheduler loop can't beat) — an
        external watchdog restarting the pod before the supervisor's
        own detection window elapses would defeat in-process
        recovery."""
        from shellac_tpu.utils.failure import heartbeat_age

        cfg, params, eng = _mk(good_steps=0)
        path = str(tmp_path / "wedge_hb.json")
        srv = InferenceServer(cfg, params, engine=eng, step_timeout=60.0,
                              heartbeat_path=path)
        try:
            srv._submit([1, 2], 4, None, {}, stream=False)
            assert eng.wedged.wait(60), "engine never wedged"
            time.sleep(2.5)  # several watchdog polls with the step stuck
            # The co-beat cadence is <= ~2s (1s poll x 1s throttle);
            # poll for a fresh beat rather than asserting one instant,
            # so a loaded CI runner can't flake the window.
            deadline = time.monotonic() + 15
            age = None
            while time.monotonic() < deadline:
                age = heartbeat_age(path)
                if age is not None and age < 1.5:
                    break
                time.sleep(0.2)
            assert age is not None and age < 1.5, age
        finally:
            _teardown(srv, eng)


_FOLLOWER_DEATH_WORKER = """
import json, os, threading, time, urllib.request, urllib.error
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
import numpy as np
from shellac_tpu import ParallelConfig, get_model_config
from shellac_tpu.inference.batching import BatchingEngine
from shellac_tpu.inference.engine import shard_params
from shellac_tpu.inference.multihost import MultihostEngine
from shellac_tpu.inference.server import InferenceServer, make_http_server
from shellac_tpu.models import transformer
from shellac_tpu.parallel.distributed import global_mesh, initialize

assert initialize()
cfg = get_model_config("tiny").replace(dtype="float32")
params = transformer.init_params(cfg, jax.random.PRNGKey(0))
mesh = global_mesh(ParallelConfig(tp=4))
sharded = shard_params(cfg, params, mesh)
eng = MultihostEngine(
    BatchingEngine(cfg, sharded, n_slots=2, max_len=64, mesh=mesh)
)

if eng.is_primary:
    srv = InferenceServer(cfg, sharded, engine=eng, step_timeout=20.0)
    httpd = make_http_server(srv)
    base = f"http://127.0.0.1:{httpd.server_address[1]}"
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    # One healthy request proves the pod serves before the fault.
    req = urllib.request.Request(
        base + "/generate",
        json.dumps({"tokens": [3, 5, 7], "max_new": 4}).encode(),
        {"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=120) as r:
        assert len(json.loads(r.read())["tokens"]) >= 1
    # The follower dies now (it exits after its first request). The
    # next request must fail LOUDLY as HTTP 500 — via whichever
    # detection fires first: on this CPU/Gloo transport the dead peer
    # raises promptly in the step ("scheduler died: ... Gloo"), on a
    # real pod a wedged collective never raises and the step watchdog
    # trips ("step_timeout"). Both are the contracted behavior; a
    # hang or a 200 is the bug.
    req2 = urllib.request.Request(
        base + "/generate",
        json.dumps({"tokens": [9, 9], "max_new": 4}).encode(),
        {"Content-Type": "application/json"})
    try:
        urllib.request.urlopen(req2, timeout=120)
        raise AssertionError("request against a dead pod succeeded")
    except urllib.error.HTTPError as e:
        assert e.code == 500, e.code
        body = e.read().decode()
        assert ("step_timeout" in body) or ("scheduler died" in body), body
    print("WORKER_OK", jax.process_index(), flush=True)
    # The scheduler thread is wedged in the dead collective; a normal
    # interpreter exit would join it forever.
    os._exit(0)
else:
    # Serve until the first request completes, then die abruptly
    # mid-pod — the injected fault. The primary's next broadcast
    # wedges with no peer on the other side.
    while eng.step() is not None:
        if eng.stats.get("requests_completed", 0) >= 1:
            os._exit(1)
"""


_DISCONNECT_WORKER = """
import json, socket, threading, time
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
import numpy as np
from shellac_tpu import ParallelConfig, get_model_config
from shellac_tpu.inference.batching import BatchingEngine
from shellac_tpu.inference.engine import shard_params
from shellac_tpu.inference.multihost import MultihostEngine
from shellac_tpu.inference.server import InferenceServer, make_http_server
from shellac_tpu.models import transformer
from shellac_tpu.parallel.distributed import global_mesh, initialize

assert initialize()
cfg = get_model_config("tiny").replace(dtype="float32")
params = transformer.init_params(cfg, jax.random.PRNGKey(0))
mesh = global_mesh(ParallelConfig(tp=4))
sharded = shard_params(cfg, params, mesh)
eng = MultihostEngine(
    BatchingEngine(cfg, sharded, n_slots=2, max_len=64, mesh=mesh)
)

if eng.is_primary:
    srv = InferenceServer(cfg, sharded, engine=eng)
    httpd = make_http_server(srv)
    port = httpd.server_address[1]
    threading.Thread(target=httpd.serve_forever, daemon=True).start()
    # Raw-socket streaming request, disconnected after the first chunk:
    # the generator must cancel the generation pod-wide.
    s = socket.create_connection(("127.0.0.1", port), timeout=60)
    body = json.dumps({"tokens": [3, 5, 7], "max_new": 40,
                       "stream": True}).encode()
    s.sendall(b"POST /generate HTTP/1.1\\r\\nHost: x\\r\\n"
              b"Content-Type: application/json\\r\\n"
              + f"Content-Length: {len(body)}\\r\\n\\r\\n".encode() + body)
    s.recv(1)  # first byte of the response = stream started
    s.close()  # abrupt disconnect mid-stream
    deadline = time.time() + 60
    while (srv.engine.stats.get("requests_cancelled", 0) < 1
           and time.time() < deadline):
        time.sleep(0.2)
    assert srv.engine.stats["requests_cancelled"] == 1, srv.engine.stats
    httpd.shutdown()
    srv.close()  # broadcasts shutdown -> rank 1 exits serve_forever
else:
    eng.serve_forever()
    # The cancel rode the command broadcast: this rank's replica
    # dropped the same request.
    assert eng.stats.get("requests_cancelled", 0) == 1, eng.stats
print("WORKER_OK", jax.process_index(), flush=True)
"""


from conftest import needs_multiprocess_cpu as _needs_multiprocess_cpu


@_needs_multiprocess_cpu
class TestMultihostFaults:
    def test_follower_death_detected_loudly(self, tmp_path):
        run_two_process(tmp_path, _FOLLOWER_DEATH_WORKER, timeout=420,
                        ok_ranks=(0,))

    def test_client_disconnect_cancels_pod_wide(self, tmp_path):
        run_two_process(tmp_path, _DISCONNECT_WORKER, timeout=420)


# ---------------------------------------------------------------------------
# Train-loop chaos (docs/training.md, "Failure semantics"): the training
# half of the fault story. A run must survive a NaN batch (rollback to
# the last-good checkpoint, deterministic replay), a corrupt latest
# checkpoint (fallback restore + quarantine), and a kill mid-save
# (startup sweep; resume from the newest intact step) — all without a
# human in the loop, all visible through shellac_train_* counters.
# ---------------------------------------------------------------------------


@pytest.fixture
def fresh_train_registry():
    """Swap the process-global obs registry (the fit loop and the
    checkpointer deposit there) so counter assertions see only this
    test's events."""
    reg = Registry()
    old = set_default_registry(reg)
    yield reg
    set_default_registry(old)


class TestTrainChaos:
    def _factory(self, skip=0):
        return token_batches(
            np.tile(np.arange(32, dtype=np.int32), 50),
            batch_size=2, seq_len=16, num_batches=200, skip=skip,
        )

    def _tcfg(self, steps):
        return TrainConfig(warmup_steps=0, learning_rate=3e-3,
                           total_steps=steps)

    @staticmethod
    def _assert_states_equal(a, b):
        assert int(jax.device_get(a.step)) == int(jax.device_get(b.step))
        jax.tree.map(
            lambda x, y: np.testing.assert_array_equal(
                np.asarray(x), np.asarray(y)
            ),
            (a.params, a.opt_state), (b.params, b.opt_state),
        )

    def test_nan_at_step_k_rolls_back_and_completes_bit_identical(
            self, tmp_path, fresh_train_registry):
        """The acceptance drill: a transient NaN batch at step 5 (last
        checkpoint at 3) rolls the run back and — because the data
        stream is re-derived from the restored step — the final state
        is BIT-identical to an unfaulted run's."""
        cfg = _tiny()
        reg = fresh_train_registry
        baseline = fit(cfg, self._tcfg(8), self._factory(), log_every=1)
        faulted = fit(
            cfg, self._tcfg(8),
            chaos.poison_batches(self._factory(), at_step=5),
            checkpoint_dir=str(tmp_path / "run"), checkpoint_every=3,
            log_every=1, data_factory=self._factory,
        )
        self._assert_states_equal(baseline, faulted)
        assert reg.value("shellac_train_rollbacks_total") == 1
        assert reg.value(
            "shellac_train_anomalies_total",
            kind="nonfinite_loss", action="rollback",
        ) == 1
        assert reg.value("shellac_train_last_good_step") == 8

    def test_corrupt_latest_checkpoint_falls_back_on_resume(
            self, tmp_path, fresh_train_registry):
        """Kill a run at step 6, scramble its newest checkpoint, and
        resume: restore walks back to the newest INTACT step (4), the
        bad one is quarantined (renamed, never re-selected), the data
        stream re-derives from the restored step, and the finished
        state matches an unfaulted straight-through run."""
        cfg = _tiny()
        reg = fresh_train_registry
        ckdir = str(tmp_path / "run")
        baseline = fit(cfg, self._tcfg(8), self._factory(), log_every=1)
        # "Die" at step 6 by exhausting the stream — total_steps stays 8
        # so the LR schedule (cosine to total_steps) matches the
        # baseline's; a shorter total_steps would be a different run.
        died_early = token_batches(
            np.tile(np.arange(32, dtype=np.int32), 50),
            batch_size=2, seq_len=16, num_batches=6,
        )
        fit(cfg, self._tcfg(8), died_early, checkpoint_dir=ckdir,
            checkpoint_every=2, log_every=1, data_factory=self._factory)
        chaos.scramble_step(ckdir, 6)
        # The stale pre-restore skip (6, what the CLI would compute
        # from latest_step) is deliberately wrong; the loop re-derives
        # it from the step actually restored.
        resumed = fit(
            cfg, self._tcfg(8), self._factory(6), checkpoint_dir=ckdir,
            checkpoint_every=2, log_every=1, data_factory=self._factory,
        )
        self._assert_states_equal(baseline, resumed)
        assert os.path.isdir(os.path.join(ckdir, "6.corrupt"))
        assert reg.value("shellac_train_ckpt_quarantined_total") == 1
        assert reg.value("shellac_train_ckpt_fallback_restores_total") == 1
        # The quarantined directory stays on disk for forensics, while
        # the replay re-saved a FRESH step 6 that verifies clean — the
        # run healed its own checkpoint history.
        ck = Checkpointer(ckdir)
        assert ck.verify(6) is None
        assert ck.latest_step() == 8
        ck.close()

    def test_poisoned_corpus_escalates_to_fatal(self, tmp_path,
                                                fresh_train_registry):
        """A fault that REPLAYS (bad shard, not a transient): every
        rebuilt iterator re-poisons step 4, so rollback can never get
        past it — the sentinel's budget (2 recoveries) drains and the
        run dies loudly instead of loop-rolling forever."""
        cfg = _tiny()
        reg = fresh_train_registry

        def poisoned_factory(skip=0):
            return chaos.poison_batches(
                self._factory(skip), at_step=4, start_step=skip,
            )

        with pytest.raises(RuntimeError, match="budget spent"):
            fit(
                cfg, self._tcfg(6), poisoned_factory(),
                checkpoint_dir=str(tmp_path / "run"), checkpoint_every=2,
                log_every=1, data_factory=poisoned_factory,
                max_restores=2,
            )
        assert reg.value("shellac_train_rollbacks_total") == 2
        assert reg.value(
            "shellac_train_anomalies_total",
            kind="nonfinite_loss", action="fatal",
        ) == 1

    def test_sigkill_mid_save_resumes_from_intact_step(self, tmp_path):
        """SIGKILL with an async save in flight: orbax's atomic-rename
        commit means the victim leaves either a committed step or tmp
        debris — never a half-step selectable as latest. The next
        Checkpointer sweeps the debris and restores cleanly."""
        ckdir = str(tmp_path / "run")
        script = f"""
import os, signal
import numpy as np
from shellac_tpu.training.checkpoint import Checkpointer
ck = Checkpointer({ckdir!r})
state = {{"w": np.arange(3_000_000, dtype=np.float32),
          "b": np.ones((64, 64), np.float32)}}
ck.save(1, state, wait=True)
ck.save(2, {{"w": state["w"] + 1, "b": state["b"] + 1}})  # async
os.kill(os.getpid(), signal.SIGKILL)  # dies with the write in flight
"""
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        proc = subprocess.run(
            [sys.executable, "-c", script], env=env, timeout=300,
            capture_output=True, text=True,
        )
        assert proc.returncode == -signal.SIGKILL, proc.stderr

        # Any debris the kill left behind reads as ABANDONED once it
        # crosses the sweep's TTL (young tmp dirs are left alone — they
        # could be a concurrent process's live save); backdate it so
        # this construction sweeps it.
        for name in os.listdir(ckdir):
            if TMP_DIR_MARKER in name:
                old = time.time() - 2 * 3600
                os.utime(os.path.join(ckdir, name), (old, old))
        ck = Checkpointer(ckdir)
        assert not any(
            TMP_DIR_MARKER in name for name in os.listdir(ckdir)
        )
        latest = ck.latest_step()
        # Step 1 is always intact; step 2 only if the async write
        # committed before the kill. Either way the selected latest
        # verifies and restores to the values saved FOR THAT step.
        assert latest in (1, 2)
        assert ck.verify(latest) is None
        restored = ck.restore(latest)
        np.testing.assert_array_equal(
            np.asarray(restored["w"][:3]),
            np.arange(3, dtype=np.float32) + (latest - 1),
        )
        ck.close()
