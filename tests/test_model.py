"""Model-level tests: shapes, causality, dtype policy, determinism."""

import jax
import jax.numpy as jnp
import numpy as np

from shellac_tpu import get_model_config
from shellac_tpu.models import transformer


def _tiny(**kw):
    return get_model_config("tiny").replace(dtype="float32", **kw)


class TestTransformer:
    def test_shapes_and_dtype(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.zeros((2, 16), jnp.int32)
        logits = transformer.forward(cfg, params, tokens)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    def test_causality(self):
        """Changing a future token must not change past logits."""
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        key = jax.random.PRNGKey(1)
        tokens = jax.random.randint(key, (1, 16), 0, cfg.vocab_size)
        logits1 = transformer.forward(cfg, params, tokens)
        tokens2 = tokens.at[0, 10].set((tokens[0, 10] + 1) % cfg.vocab_size)
        logits2 = transformer.forward(cfg, params, tokens2)
        np.testing.assert_allclose(
            np.asarray(logits1[0, :10]), np.asarray(logits2[0, :10]), atol=1e-5
        )
        assert not np.allclose(np.asarray(logits1[0, 10:]), np.asarray(logits2[0, 10:]))

    def test_deterministic(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.ones((1, 8), jnp.int32)
        l1 = transformer.forward(cfg, params, tokens)
        l2 = transformer.forward(cfg, params, tokens)
        np.testing.assert_array_equal(np.asarray(l1), np.asarray(l2))

    def test_gqa_config(self):
        cfg = get_model_config("tiny-gqa").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        assert params["layers"]["wk"].shape == (
            cfg.n_layers, cfg.d_model, cfg.kv_heads * cfg.dim_per_head
        )
        tokens = jnp.zeros((1, 8), jnp.int32)
        logits = transformer.forward(cfg, params, tokens)
        assert logits.shape == (1, 8, cfg.vocab_size)

    def test_remat_same_output(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.ones((1, 8), jnp.int32)
        l1 = transformer.forward(cfg, params, tokens)
        l2 = transformer.forward(cfg.replace(remat=True), params, tokens)
        np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-6)

    def test_untied_head(self):
        cfg = _tiny(tie_embeddings=False)
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        assert "lm_head" in params
        tokens = jnp.zeros((1, 8), jnp.int32)
        assert transformer.forward(cfg, params, tokens).shape == (1, 8, cfg.vocab_size)

    def test_logical_axes_match_params(self):
        cfg = _tiny(tie_embeddings=False)
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        axes = transformer.logical_axes(cfg)
        flat_p = jax.tree_util.tree_flatten_with_path(params)[0]
        flat_a = jax.tree_util.tree_flatten_with_path(
            axes, is_leaf=lambda x: isinstance(x, tuple)
        )[0]
        paths_p = {tuple(str(k) for k in path): leaf.ndim for path, leaf in flat_p}
        paths_a = {tuple(str(k) for k in path): len(leaf) for path, leaf in flat_a}
        assert paths_p == paths_a
