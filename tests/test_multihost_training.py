"""Multi-host training: 2 real processes, global batch assembly, parity.

The workers bring up jax.distributed on the CPU backend (2 processes x
2 devices), build a global fsdp=4 mesh, assemble global batches from
per-process local slices (distribute_batches), and train tiny for a few
steps. The test process independently trains the same model
single-process on the CONCATENATED batches (process 0's rows then
process 1's) and checks the multi-host losses match it — the global
batch semantics, not just "it ran".
"""

import re

import jax
import numpy as np

from shellac_tpu import ParallelConfig, get_model_config, make_mesh
from shellac_tpu.config import TrainConfig
from shellac_tpu.training import init_train_state, make_train_step

STEPS = 4
LOCAL_BATCH = 2
SEQ = 32


def _local_batches(proc: int, vocab: int):
    """Process `proc`'s deterministic local stream."""
    yield from _local_batches_n(proc, vocab, STEPS)


def _local_batches_n(proc: int, vocab: int, n: int):
    rng = np.random.default_rng(100 + proc)
    for _ in range(n):
        w = rng.integers(0, vocab, size=(LOCAL_BATCH, SEQ + 1), dtype=np.int32)
        yield {"inputs": w[:, :-1], "targets": w[:, 1:]}


_WORKER = """
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
import numpy as np
from shellac_tpu import ParallelConfig, get_model_config
from shellac_tpu.config import TrainConfig
from shellac_tpu.parallel.distributed import global_mesh, initialize
from shellac_tpu.training import init_train_state, make_train_step
from shellac_tpu.training.data import distribute_batches

assert initialize()
proc = jax.process_index()

STEPS, LOCAL_BATCH, SEQ = {steps}, {local_batch}, {seq}
cfg = get_model_config("tiny").replace(dtype="float32")
tcfg = TrainConfig(learning_rate=1e-3, warmup_steps=2, total_steps=STEPS)
mesh = global_mesh(ParallelConfig(fsdp=4))


def local_batches():
    rng = np.random.default_rng(100 + proc)
    for _ in range(STEPS):
        w = rng.integers(0, cfg.vocab_size, size=(LOCAL_BATCH, SEQ + 1),
                         dtype=np.int32)
        yield {{"inputs": w[:, :-1], "targets": w[:, 1:]}}


state = init_train_state(cfg, tcfg, jax.random.PRNGKey(tcfg.seed), mesh=mesh)
step = make_train_step(cfg, tcfg, mesh=mesh)
loss = None
for batch in distribute_batches(local_batches(), mesh):
    state, m = step(state, batch)
    loss = float(jax.device_get(m["loss"]))
print("FINAL_LOSS", proc, loss, flush=True)
print("WORKER_OK", proc, flush=True)
"""


_FIT_WORKER = """
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
import numpy as np
from shellac_tpu import ParallelConfig, get_model_config
from shellac_tpu.config import TrainConfig
from shellac_tpu.parallel.distributed import global_mesh, initialize
from shellac_tpu.training.loop import fit

assert initialize()
proc = jax.process_index()
cfg = get_model_config("tiny").replace(dtype="float32")
mesh = global_mesh(ParallelConfig(fsdp=4))


def local_batches(n):
    rng = np.random.default_rng(100 + proc)
    for _ in range(n):
        w = rng.integers(0, cfg.vocab_size, size=(2, 33), dtype=np.int32)
        yield {{"inputs": w[:, :-1], "targets": w[:, 1:]}}


# First run: 4 steps, checkpoint every 2 (collective orbax saves).
tcfg = TrainConfig(learning_rate=1e-3, warmup_steps=2, total_steps=4)
state = fit(cfg, tcfg, local_batches(8), mesh=mesh,
            checkpoint_dir={ckpt!r}, checkpoint_every=2,
            log_path=({log!r} if proc == 0 else None))
assert int(jax.device_get(state.step)) == 4

# Resume: total_steps=6 restores step 4 and trains 2 more.
tcfg = TrainConfig(learning_rate=1e-3, warmup_steps=2, total_steps=6)
state = fit(cfg, tcfg, local_batches(8), mesh=mesh,
            checkpoint_dir={ckpt!r}, checkpoint_every=2)
assert int(jax.device_get(state.step)) == 6, int(jax.device_get(state.step))
print("WORKER_OK", proc, flush=True)
"""


_ELASTIC_WORKER = """
import jax
jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 2)
import numpy as np
from shellac_tpu import ParallelConfig, get_model_config
from shellac_tpu.config import TrainConfig
from shellac_tpu.parallel.distributed import global_mesh, initialize
from shellac_tpu.training.loop import fit

assert initialize()
proc = jax.process_index()
cfg = get_model_config("tiny").replace(dtype="float32")
mesh = global_mesh(ParallelConfig(fsdp=4))


def local_batches():
    rng = np.random.default_rng(100 + proc)
    for _ in range({steps}):
        w = rng.integers(0, cfg.vocab_size, size=({local_batch}, {seq} + 1),
                         dtype=np.int32)
        yield {{"inputs": w[:, :-1], "targets": w[:, 1:]}}


# total_steps=6 — the SAME schedule as the continuation runs (the LR
# at each step depends on total_steps, so a shorter horizon here would
# checkpoint a genuinely different trajectory). The 4-batch stream
# stops the loop at step 4 via StopIteration; fit force-saves there.
tcfg = TrainConfig(learning_rate=1e-3, warmup_steps=2, total_steps=6)
state = fit(cfg, tcfg, local_batches(), mesh=mesh,
            checkpoint_dir={ckpt!r}, checkpoint_every=100)
assert int(jax.device_get(state.step)) == 4
print("WORKER_OK", proc, flush=True)
"""


from conftest import run_two_process as _run_pair


from conftest import needs_multiprocess_cpu as _needs_multiprocess_cpu


@_needs_multiprocess_cpu
class TestMultihostTraining:
    def test_fit_checkpoint_resume(self, tmp_path):
        """fit() across 2 processes: collective orbax saves, proc-0-only
        metrics file, and a resumed run continuing from the restore."""
        ckpt = tmp_path / "ckpt"
        log = tmp_path / "metrics.jsonl"
        _run_pair(tmp_path, _FIT_WORKER.format(
            ckpt=str(ckpt), log=str(log)
        ))
        assert log.exists() and log.read_text().strip()

    def test_two_process_training_matches_single(self, tmp_path):
        outs = _run_pair(tmp_path, _WORKER.format(
            steps=STEPS, local_batch=LOCAL_BATCH, seq=SEQ
        ))
        losses = []
        for r, out in enumerate(outs):
            m = re.search(rf"FINAL_LOSS {r} ([0-9.]+)", out)
            assert m, out
            losses.append(float(m.group(1)))
        # Both processes observed the same replicated loss.
        assert losses[0] == losses[1], losses

        # Single-process reference over the concatenated global batches.
        cfg = get_model_config("tiny").replace(dtype="float32")
        tcfg = TrainConfig(learning_rate=1e-3, warmup_steps=2,
                           total_steps=STEPS)
        mesh = make_mesh(ParallelConfig(fsdp=4), devices=jax.devices()[:4])
        state = init_train_state(
            cfg, tcfg, jax.random.PRNGKey(tcfg.seed), mesh=mesh
        )
        step = make_train_step(cfg, tcfg, mesh=mesh)
        streams = [_local_batches(p, cfg.vocab_size) for p in range(2)]
        ref_loss = None
        for b0, b1 in zip(*streams):
            batch = {k: np.concatenate([b0[k], b1[k]]) for k in b0}
            state, m = step(state, batch)
            ref_loss = float(jax.device_get(m["loss"]))
        assert abs(losses[0] - ref_loss) < 1e-4, (losses[0], ref_loss)

    def test_elastic_rescale_resume(self, tmp_path):
        """Elastic recovery: a checkpoint written by a 2-process fsdp=4
        job restores onto a SINGLE-process fsdp=2 mesh (different
        process count AND topology — orbax reshards onto the target
        shardings) and continues with losses EQUAL to an uninterrupted
        single-process run over the same global batch stream. This is
        the down-scale-after-losing-a-host story, loss-exact."""
        ckpt = tmp_path / "ckpt"
        steps_total = 6
        # The worker's stream carries only the first 4 batches: fit
        # stops on StopIteration at step 4 and force-saves there.
        _run_pair(tmp_path, _ELASTIC_WORKER.format(
            steps=4, local_batch=LOCAL_BATCH, seq=SEQ,
            ckpt=str(ckpt),
        ))

        cfg = get_model_config("tiny").replace(dtype="float32")
        streams = [list(_local_batches_n(p, cfg.vocab_size, steps_total))
                   for p in range(2)]
        global_batches = [
            {k: np.concatenate([b0[k], b1[k]]) for k in b0}
            for b0, b1 in zip(*streams)
        ]

        # Uninterrupted single-process run over all 6 batches — the
        # trajectory anchor (loose: phase A ran fsdp=4 across 2 procs,
        # so cross-mesh reduction-order float noise is already in the
        # handoff state).
        tcfg = TrainConfig(learning_rate=1e-3, warmup_steps=2,
                           total_steps=steps_total)
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(tcfg.seed))
        step = make_train_step(cfg, tcfg)
        full = []
        for batch in global_batches:
            state, m = step(state, batch)
            full.append(float(jax.device_get(m["loss"])))

        # Two continuations from the SAME checkpoint: unsharded, and
        # re-scaled onto an fsdp=2 mesh. They start from bit-identical
        # state, so they must agree tightly — THE elastic-resume
        # equivalence (restore-onto-new-topology changes nothing).
        import json as _json

        from shellac_tpu.training.loop import fit

        def continue_from_ckpt(mesh, tag):
            # Private copy: fit writes a final save, which would bleed
            # a later step into the next continuation's restore.
            import shutil

            my_ckpt = tmp_path / f"ckpt_{tag}"
            shutil.copytree(ckpt, my_ckpt)
            log = tmp_path / f"resumed_{tag}.jsonl"
            final = fit(cfg, tcfg, iter(global_batches[4:]), mesh=mesh,
                        checkpoint_dir=str(my_ckpt), checkpoint_every=100,
                        log_path=str(log), log_every=1)
            assert int(jax.device_get(final.step)) == steps_total
            rows = [_json.loads(x) for x in log.read_text().splitlines()]
            return {r["step"]: r["loss"] for r in rows if "loss" in r}

        mesh2 = make_mesh(ParallelConfig(fsdp=2),
                          devices=jax.devices()[:2])
        flat = continue_from_ckpt(None, "flat")
        rescaled = continue_from_ckpt(mesh2, "fsdp2")
        for s in (5, 6):
            assert abs(rescaled[s] - flat[s]) < 2e-4, (s, rescaled, flat)
            # Loose anchor against the uninterrupted trajectory.
            assert abs(rescaled[s] - full[s - 1]) < 5e-3, (
                s, rescaled, full
            )
