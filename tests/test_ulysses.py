"""Ulysses (all-to-all sequence parallelism) vs the dense reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import ParallelConfig, get_model_config, make_mesh
from shellac_tpu.models import transformer
from shellac_tpu.ops.attention import attention_ref
from shellac_tpu.parallel.ulysses import ulysses_attention, ulysses_supported


@pytest.fixture(scope="module")
def mesh_sp4():
    return make_mesh(ParallelConfig(sp=4, tp=2))


class TestUlyssesAttention:
    def test_causal_matches_ref(self, mesh_sp4):
        rng = np.random.default_rng(0)
        q = jnp.asarray(rng.normal(size=(2, 64, 8, 32)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(2, 64, 8, 32)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, 64, 8, 32)).astype(np.float32))
        got = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh_sp4))(q, k, v)
        want = attention_ref(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)

    def test_window_matches_ref(self, mesh_sp4):
        """Sliding windows work (the thing ring attention cannot do)."""
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(1, 64, 8, 16)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 64, 8, 16)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 64, 8, 16)).astype(np.float32))
        got = jax.jit(
            lambda q, k, v: ulysses_attention(q, k, v, mesh_sp4, window=16)
        )(q, k, v)
        want = attention_ref(q, k, v, causal=True, window=16)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)

    def test_gqa_broadcast_path(self, mesh_sp4):
        """kv heads not divisible by sp: broadcast fallback stays correct."""
        rng = np.random.default_rng(2)
        q = jnp.asarray(rng.normal(size=(2, 32, 8, 16)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(2, 32, 2, 16)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, 32, 2, 16)).astype(np.float32))
        got = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh_sp4))(q, k, v)
        want = attention_ref(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)

    def test_gqa_lcm_repeat_path(self, mesh_sp4):
        """hkv repeats only to lcm(hkv_loc, sp), not full broadcast: h=16
        hkv=4 on tp=2/sp=4 gives hkv_loc=2 -> 4 repeated heads vs 8."""
        rng = np.random.default_rng(7)
        q = jnp.asarray(rng.normal(size=(2, 32, 16, 16)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(2, 32, 4, 16)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, 32, 4, 16)).astype(np.float32))
        got = jax.jit(lambda q, k, v: ulysses_attention(q, k, v, mesh_sp4))(q, k, v)
        want = attention_ref(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5)

    def test_explicit_ulysses_unsupported_heads_raises(self, mesh_sp4):
        """Explicit attn_impl='ulysses' with indivisible heads -> clear error."""
        cfg = get_model_config("tiny").replace(
            d_model=64, n_heads=4, vocab_size=512, dtype="float32"
        )
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.zeros((2, 32), jnp.int32)
        with pytest.raises(ValueError, match="divisible by sp"):
            transformer.forward(
                cfg, params, tokens, mesh=mesh_sp4, attn_impl="ulysses"
            )

    def test_grads_match_ref(self, mesh_sp4):
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.normal(size=(1, 32, 8, 16)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(1, 32, 8, 16)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(1, 32, 8, 16)).astype(np.float32))
        g1 = jax.grad(
            lambda q, k, v: ulysses_attention(q, k, v, mesh_sp4).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        g2 = jax.grad(
            lambda q, k, v: attention_ref(q, k, v, causal=True).sum(),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(g1, g2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-5)

    def test_supported_predicate(self, mesh_sp4):
        assert ulysses_supported(8, 8, mesh_sp4)  # 8/tp2 = 4, % sp4 == 0
        assert not ulysses_supported(4, 4, mesh_sp4)  # 4/tp2 = 2, % sp4 != 0
        assert not ulysses_supported(6, 6, mesh_sp4)  # 6 % tp2 == 0, 3 % 4 != 0

    def test_model_forward_ulysses_matches_dense(self, mesh_sp4):
        cfg = get_model_config("tiny").replace(
            d_model=64, n_heads=8, vocab_size=512, dtype="float32"
        )
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)
        dense = transformer.forward(cfg, params, tokens)
        sharded = jax.jit(
            lambda p, t: transformer.forward(cfg, p, t, mesh=mesh_sp4, attn_impl="ulysses")
        )(params, tokens)
        np.testing.assert_allclose(
            np.asarray(dense), np.asarray(sharded), rtol=1e-4, atol=1e-4
        )

    def test_model_auto_uses_ulysses_for_window(self, mesh_sp4, monkeypatch):
        """auto + window + sp routes to ulysses (not dense) and stays correct."""
        import shellac_tpu.parallel.ulysses as ulysses_mod

        calls = []
        real = ulysses_mod.ulysses_attention

        def spy(*args, **kw):
            calls.append(1)
            return real(*args, **kw)

        monkeypatch.setattr(ulysses_mod, "ulysses_attention", spy)
        cfg = get_model_config("tiny").replace(
            d_model=64, n_heads=8, vocab_size=512, attn_window=8, dtype="float32"
        )
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 32), 0, cfg.vocab_size)
        dense = transformer.forward(cfg, params, tokens)
        sharded = jax.jit(
            lambda p, t: transformer.forward(cfg, p, t, mesh=mesh_sp4)
        )(params, tokens)
        assert calls, "auto+window+sp did not route through ulysses_attention"
        np.testing.assert_allclose(
            np.asarray(dense), np.asarray(sharded), rtol=1e-4, atol=1e-4
        )

    def test_ulysses_without_sp_raises(self):
        cfg = get_model_config("tiny").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        tokens = jnp.zeros((1, 16), jnp.int32)
        with pytest.raises(ValueError, match="requires a mesh with sp"):
            transformer.forward(cfg, params, tokens, attn_impl="ulysses")


class TestUlyssesSegments:
    def test_packed_segments_match_ref(self, mesh_sp4):
        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.normal(size=(2, 64, 8, 32)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(2, 64, 8, 32)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, 64, 8, 32)).astype(np.float32))
        segs = jnp.asarray(
            np.repeat(np.array([[1, 1, 2, 3]] * 2), 16, axis=1), jnp.int32
        )
        got = jax.jit(
            lambda q, k, v, s: ulysses_attention(
                q, k, v, mesh_sp4, segments=s
            )
        )(q, k, v, segs)
        want = attention_ref(
            q, k, v, causal=True, q_segments=segs, kv_segments=segs
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )

    def test_bidirectional_matches_ref(self, mesh_sp4):
        rng = np.random.default_rng(4)
        q = jnp.asarray(rng.normal(size=(2, 32, 8, 16)).astype(np.float32))
        k = jnp.asarray(rng.normal(size=(2, 32, 8, 16)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, 32, 8, 16)).astype(np.float32))
        got = jax.jit(
            lambda q, k, v: ulysses_attention(q, k, v, mesh_sp4, causal=False)
        )(q, k, v)
        want = attention_ref(q, k, v, causal=False)
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=1e-5, atol=1e-5
        )
