"""Int8 weight-only quantization tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.engine import Engine
from shellac_tpu.models import transformer
from shellac_tpu.ops.quant import (
    QTensor,
    dequantize,
    quantize,
    quantize_logical_axes,
    quantize_params,
)


def _tiny(**kw):
    return get_model_config("tiny").replace(dtype="float32", **kw)


class TestQuantize:
    def test_roundtrip_error_bounded(self, rng):
        w = jnp.asarray(rng.normal(size=(4, 64, 128)).astype(np.float32))
        qt = quantize(w)
        assert qt.q.dtype == jnp.int8
        assert qt.scale.shape == (4, 1, 128)
        back = dequantize(qt)
        # Per-channel symmetric int8: error <= scale/2 per element.
        err = np.abs(np.asarray(back - w))
        bound = np.asarray(qt.scale) / 2 + 1e-8
        assert (err <= np.broadcast_to(bound, err.shape)).all()

    def test_zero_channel_safe(self):
        w = jnp.zeros((2, 8, 4))
        qt = quantize(w)
        np.testing.assert_array_equal(np.asarray(dequantize(qt)), 0.0)

    def test_scan_compatible(self):
        """QTensor flows through lax.scan like a plain array stack."""
        w = jnp.asarray(np.random.default_rng(0).normal(size=(3, 8, 8)),
                        jnp.float32)
        qt = quantize(w)

        def body(c, layer):
            return c @ dequantize(layer), None

        out, _ = jax.lax.scan(body, jnp.eye(8), qt)
        assert out.shape == (8, 8)

    def test_unknown_target_raises(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="unknown quantization targets"):
            quantize_params(cfg, params, targets=("nope",))


class TestQuantizedForward:
    def test_logits_close_to_fp(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        qparams = quantize_params(cfg, params)
        assert isinstance(qparams["layers"]["wq"], QTensor)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        l_fp = transformer.forward(cfg, params, tokens)
        l_q = transformer.forward(cfg, qparams, tokens)
        # Int8 noise is small relative to the logit scale.
        scale = float(jnp.std(l_fp)) + 1e-6
        rel = float(jnp.max(jnp.abs(l_q - l_fp))) / scale
        assert rel < 0.15, f"relative logit error {rel}"

    def test_moe_forward_runs(self):
        cfg = get_model_config("tiny-moe").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        qparams = quantize_params(cfg, params)
        tokens = jnp.zeros((1, 16), jnp.int32)
        logits = transformer.forward(cfg, qparams, tokens)
        assert logits.shape == (1, 16, cfg.vocab_size)
        assert np.isfinite(np.asarray(logits)).all()

    def test_engine_generate(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        qparams = quantize_params(cfg, params)
        eng = Engine(cfg, qparams, temperature=0.0)
        prompt = jnp.ones((1, 4), jnp.int32)
        out = eng.generate(prompt, max_new_tokens=8)
        assert out.tokens.shape == (1, 8)
        assert np.isfinite(np.asarray(out.logprobs)).all()

    def test_quantized_axes_match_params(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        qparams = quantize_params(cfg, params)
        qaxes = quantize_logical_axes(transformer.logical_axes(cfg))
        flat_p = jax.tree_util.tree_flatten_with_path(qparams)[0]
        flat_a = jax.tree_util.tree_flatten_with_path(
            qaxes, is_leaf=lambda x: isinstance(x, tuple)
        )[0]
        paths_p = {tuple(str(k) for k in p): leaf.ndim for p, leaf in flat_p}
        paths_a = {tuple(str(k) for k in p): len(leaf) for p, leaf in flat_a}
        assert paths_p == paths_a

    def test_sharded_quantized_forward(self, mesh_fsdp8):
        from shellac_tpu.parallel.sharding import shard_pytree

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        qparams = quantize_params(cfg, params)
        qaxes = quantize_logical_axes(transformer.logical_axes(cfg))
        sharded = shard_pytree(qparams, mesh_fsdp8, qaxes)
        tokens = jnp.zeros((8, 16), jnp.int32)
        logits = jax.jit(
            lambda p, t: transformer.forward(cfg, p, t, mesh=mesh_fsdp8)
        )(sharded, tokens)
        assert logits.shape == (8, 16, cfg.vocab_size)
