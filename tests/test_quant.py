"""Int8 weight-only quantization tests."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.engine import Engine
from shellac_tpu.models import transformer
from shellac_tpu.ops.quant import (
    QTensor,
    dequantize,
    quantize,
    quantize_logical_axes,
    quantize_params,
)


def _tiny(**kw):
    return get_model_config("tiny").replace(dtype="float32", **kw)


class TestQuantize:
    def test_roundtrip_error_bounded(self, rng):
        w = jnp.asarray(rng.normal(size=(4, 64, 128)).astype(np.float32))
        qt = quantize(w)
        assert qt.q.dtype == jnp.int8
        assert qt.scale.shape == (4, 1, 128)
        back = dequantize(qt)
        # Per-channel symmetric int8: error <= scale/2 per element.
        err = np.abs(np.asarray(back - w))
        bound = np.asarray(qt.scale) / 2 + 1e-8
        assert (err <= np.broadcast_to(bound, err.shape)).all()

    def test_zero_channel_safe(self):
        w = jnp.zeros((2, 8, 4))
        qt = quantize(w)
        np.testing.assert_array_equal(np.asarray(dequantize(qt)), 0.0)

    def test_scan_compatible(self):
        """QTensor flows through lax.scan like a plain array stack."""
        w = jnp.asarray(np.random.default_rng(0).normal(size=(3, 8, 8)),
                        jnp.float32)
        qt = quantize(w)

        def body(c, layer):
            return c @ dequantize(layer), None

        out, _ = jax.lax.scan(body, jnp.eye(8), qt)
        assert out.shape == (8, 8)

    def test_unknown_target_raises(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        with pytest.raises(ValueError, match="unknown quantization targets"):
            quantize_params(cfg, params, targets=("nope",))


class TestQuantizedForward:
    def test_logits_close_to_fp(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        qparams = quantize_params(cfg, params)
        assert isinstance(qparams["layers"]["wq"], QTensor)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        l_fp = transformer.forward(cfg, params, tokens)
        l_q = transformer.forward(cfg, qparams, tokens)
        # Int8 noise is small relative to the logit scale.
        scale = float(jnp.std(l_fp)) + 1e-6
        rel = float(jnp.max(jnp.abs(l_q - l_fp))) / scale
        assert rel < 0.15, f"relative logit error {rel}"

    def test_moe_forward_runs(self):
        cfg = get_model_config("tiny-moe").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        qparams = quantize_params(cfg, params)
        tokens = jnp.zeros((1, 16), jnp.int32)
        logits = transformer.forward(cfg, qparams, tokens)
        assert logits.shape == (1, 16, cfg.vocab_size)
        assert np.isfinite(np.asarray(logits)).all()

    def test_engine_generate(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        qparams = quantize_params(cfg, params)
        eng = Engine(cfg, qparams, temperature=0.0)
        prompt = jnp.ones((1, 4), jnp.int32)
        out = eng.generate(prompt, max_new_tokens=8)
        assert out.tokens.shape == (1, 8)
        assert np.isfinite(np.asarray(out.logprobs)).all()

    def test_quantized_axes_match_params(self):
        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        qparams = quantize_params(cfg, params)
        qaxes = quantize_logical_axes(transformer.logical_axes(cfg))
        flat_p = jax.tree_util.tree_flatten_with_path(qparams)[0]
        flat_a = jax.tree_util.tree_flatten_with_path(
            qaxes, is_leaf=lambda x: isinstance(x, tuple)
        )[0]
        paths_p = {tuple(str(k) for k in p): leaf.ndim for p, leaf in flat_p}
        paths_a = {tuple(str(k) for k in p): len(leaf) for p, leaf in flat_a}
        assert paths_p == paths_a

    def test_interleaved_moe_forward(self):
        """moe_every > 1: both the dense and moe sub-stacks quantize."""
        cfg = get_model_config("tiny-moe-interleaved").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        qparams = quantize_params(cfg, params)
        assert isinstance(qparams["layers"]["dense"]["wq"], QTensor)
        assert isinstance(qparams["layers"]["moe"]["w_gate"], QTensor)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 16), 0,
                                    cfg.vocab_size)
        l_fp = transformer.forward(cfg, params, tokens)
        l_q = transformer.forward(cfg, qparams, tokens)
        scale = float(jnp.std(l_fp)) + 1e-6
        rel = float(jnp.max(jnp.abs(l_q - l_fp))) / scale
        assert rel < 0.15, f"relative logit error {rel}"

    def test_interleaved_axes_match_params(self):
        cfg = get_model_config("tiny-moe-interleaved").replace(dtype="float32")
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        qparams = quantize_params(cfg, params)
        qaxes = quantize_logical_axes(transformer.logical_axes(cfg))
        flat_p = jax.tree_util.tree_flatten_with_path(qparams)[0]
        flat_a = jax.tree_util.tree_flatten_with_path(
            qaxes, is_leaf=lambda x: isinstance(x, tuple)
        )[0]
        paths_p = {tuple(str(k) for k in p): leaf.ndim for p, leaf in flat_p}
        paths_a = {tuple(str(k) for k in p): len(leaf) for p, leaf in flat_a}
        assert paths_p == paths_a

    def test_sharded_quantized_forward(self, mesh_fsdp8):
        from shellac_tpu.parallel.sharding import shard_pytree

        cfg = _tiny()
        params = transformer.init_params(cfg, jax.random.PRNGKey(0))
        qparams = quantize_params(cfg, params)
        qaxes = quantize_logical_axes(transformer.logical_axes(cfg))
        sharded = shard_pytree(qparams, mesh_fsdp8, qaxes)
        tokens = jnp.zeros((8, 16), jnp.int32)
        logits = jax.jit(
            lambda p, t: transformer.forward(cfg, p, t, mesh=mesh_fsdp8)
        )(sharded, tokens)
        assert logits.shape == (8, 16, cfg.vocab_size)


class TestQuantizedTraining:
    """TrainConfig(quant='int8'): int8 forward dots, fp32 master params."""

    def test_int8_dot_close_to_exact(self, rng):
        from shellac_tpu.ops.qtrain import int8_dot

        x = jnp.asarray(rng.normal(size=(4, 12, 64)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(64, 96)), jnp.float32)
        got = int8_dot(x, w)
        want = x @ w
        # Per-row/per-channel int8: ~1% relative error at these sizes.
        err = jnp.linalg.norm(got - want) / jnp.linalg.norm(want)
        assert float(err) < 0.02, float(err)

    def test_int8_dot_grads_are_straight_through(self, rng):
        from shellac_tpu.ops.qtrain import int8_dot

        x = jnp.asarray(rng.normal(size=(3, 16)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(16, 8)), jnp.float32)
        g1 = jax.grad(lambda x, w: (int8_dot(x, w) ** 2).sum(), (0, 1))(x, w)
        g2 = jax.grad(lambda x, w: ((x @ w) ** 2).sum(), (0, 1))(x, w)
        # Backward is the exact bf16 path; difference comes only from the
        # fwd output entering the squared loss.
        for a, b in zip(g1, g2):
            err = jnp.linalg.norm(a - b) / jnp.linalg.norm(b)
            assert float(err) < 0.05, float(err)

    def test_loss_parity_vs_bf16(self):
        """Short tiny-model run: int8 loss curves track bf16 closely."""
        from shellac_tpu import get_model_config
        from shellac_tpu.config import TrainConfig
        from shellac_tpu.training import init_train_state, make_train_step

        cfg = get_model_config("tiny")
        key = jax.random.PRNGKey(0)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (4, 64), 0, cfg.vocab_size
        )
        batch = {"inputs": tokens, "targets": tokens}
        losses = {}
        for quant in (None, "int8", "int8_bwd"):
            tcfg = TrainConfig(
                learning_rate=1e-3, warmup_steps=2, total_steps=30,
                quant=quant,
            )
            state = init_train_state(cfg, tcfg, key)
            step = make_train_step(cfg, tcfg)
            for _ in range(25):
                state, m = step(state, batch)
            losses[quant] = float(m["loss"])
        assert losses["int8"] == pytest.approx(losses[None], rel=0.05), losses
        # Quantized backward adds gradient rounding noise on top; the
        # curve still has to land in the same neighbourhood.
        assert losses["int8_bwd"] == pytest.approx(
            losses[None], rel=0.10
        ), losses

    def test_int8_full_grads_close_to_exact(self, rng):
        """int8_dot_full: both backward matmuls quantized, small error."""
        from shellac_tpu.ops.qtrain import int8_dot_full

        x = jnp.asarray(rng.normal(size=(4, 12, 64)), jnp.float32)
        w = jnp.asarray(rng.normal(size=(64, 96)), jnp.float32)
        got = int8_dot_full(x, w)
        want = x @ w
        err = jnp.linalg.norm(got - want) / jnp.linalg.norm(want)
        assert float(err) < 0.02, float(err)

        def loss(f):
            return lambda x, w: (f(x, w) ** 2).sum()

        g1 = jax.grad(loss(int8_dot_full), (0, 1))(x, w)
        g2 = jax.grad(loss(jnp.matmul), (0, 1))(x, w)
        for a, b in zip(g1, g2):
            e = jnp.linalg.norm(a - b) / jnp.linalg.norm(b)
            assert float(e) < 0.06, float(e)

    def test_params_stay_fp32(self):
        from shellac_tpu import get_model_config
        from shellac_tpu.config import TrainConfig
        from shellac_tpu.training import init_train_state, make_train_step

        cfg = get_model_config("tiny")
        tcfg = TrainConfig(quant="int8", warmup_steps=1, total_steps=5)
        state = init_train_state(cfg, tcfg, jax.random.PRNGKey(0))
        step = make_train_step(cfg, tcfg)
        state, _ = step(state, {"inputs": jnp.zeros((2, 16), jnp.int32),
                                "targets": jnp.zeros((2, 16), jnp.int32)})
        assert all(
            p.dtype == jnp.float32 for p in jax.tree.leaves(state.params)
        )

    def test_bad_quant_name_raises(self):
        from shellac_tpu import get_model_config

        with pytest.raises(ValueError, match="quant_training"):
            get_model_config("tiny").replace(quant_training="fp4").validate()

    def test_quant_train_on_mesh(self, mesh_fsdp8):
        """int8 training composes with GSPMD sharding (fsdp mesh)."""
        from shellac_tpu import get_model_config
        from shellac_tpu.config import TrainConfig
        from shellac_tpu.training import (
            batch_shardings,
            init_train_state,
            make_train_step,
        )

        cfg = get_model_config("tiny")
        tcfg = TrainConfig(quant="int8", warmup_steps=1, total_steps=5)
        state = init_train_state(
            cfg, tcfg, jax.random.PRNGKey(0), mesh=mesh_fsdp8
        )
        step = make_train_step(cfg, tcfg, mesh=mesh_fsdp8)
        tokens = jax.random.randint(
            jax.random.PRNGKey(1), (8, 32), 0, cfg.vocab_size
        )
        bs = batch_shardings(mesh_fsdp8)
        batch = {
            "inputs": jax.device_put(tokens, bs),
            "targets": jax.device_put(tokens, bs),
        }
        state, m = step(state, batch)
        assert np.isfinite(float(m["loss"]))
