"""Speculative decoding tests.

The load-bearing check: greedy (temperature 0) speculative output must
EXACTLY equal greedy target-only decoding, regardless of the draft model
— speculative decoding changes the schedule, never the distribution.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from shellac_tpu import get_model_config
from shellac_tpu.inference.engine import Engine
from shellac_tpu.inference.speculative import SpeculativeEngine
from shellac_tpu.models import transformer


def _tiny(**kw):
    return get_model_config("tiny").replace(dtype="float32", **kw)


@pytest.fixture(scope="module")
def models():
    cfg = _tiny()
    draft_cfg = cfg.replace(n_layers=1, d_model=32, n_heads=2)
    params = transformer.init_params(cfg, jax.random.PRNGKey(0))
    draft_params = transformer.init_params(draft_cfg, jax.random.PRNGKey(1))
    return cfg, params, draft_cfg, draft_params


class TestGreedyExactness:
    def test_matches_target_greedy(self, models):
        cfg, params, draft_cfg, draft_params = models
        prompt = jax.random.randint(jax.random.PRNGKey(2), (3, 8), 0,
                                    cfg.vocab_size)
        ref = Engine(cfg, params, temperature=0.0).generate(
            prompt, max_new_tokens=24
        )
        spec = SpeculativeEngine(
            cfg, params, draft_cfg, draft_params, gamma=3, temperature=0.0
        ).generate(prompt, max_new_tokens=24)
        np.testing.assert_array_equal(
            np.asarray(spec.tokens), np.asarray(ref.tokens)
        )

    def test_matches_target_greedy_ragged(self, models):
        cfg, params, draft_cfg, draft_params = models
        prompt = jax.random.randint(jax.random.PRNGKey(3), (2, 8), 0,
                                    cfg.vocab_size)
        plen = jnp.array([5, 8], jnp.int32)
        ref = Engine(cfg, params, temperature=0.0).generate(
            prompt, plen, max_new_tokens=16
        )
        spec = SpeculativeEngine(
            cfg, params, draft_cfg, draft_params, gamma=4, temperature=0.0
        ).generate(prompt, plen, max_new_tokens=16)
        np.testing.assert_array_equal(
            np.asarray(spec.tokens), np.asarray(ref.tokens)
        )

    def test_self_draft_accepts_everything(self, models):
        """Draft == target, greedy: every proposal must be accepted."""
        cfg, params, _, _ = models
        prompt = jnp.ones((2, 4), jnp.int32)
        spec = SpeculativeEngine(
            cfg, params, cfg, params, gamma=4, temperature=0.0
        ).generate(prompt, max_new_tokens=20)
        assert float(spec.accept_rate) == pytest.approx(1.0)
        # All-accept rounds emit gamma+1 tokens: ceil((20-1)/5) = 4 rounds.
        assert int(spec.rounds) == 4


class TestSampledDistribution:
    def test_first_token_distribution_matches_target(self, models):
        """Rejection sampling must reproduce the target distribution.

        Run many single-token generations in one batch and compare the
        empirical first-token histogram against the target softmax.
        """
        cfg, params, draft_cfg, draft_params = models
        # Random inits are near-uniform (TV(target, draft) ~ 0.07), which
        # would let a buggy engine that samples from the DRAFT pass.
        # Sharpen the target by scaling its (tied) embedding so the two
        # marginals are far apart and the test has discriminating power.
        params = dict(params, embed=params["embed"] * 12.0)
        n = 4096
        prompt = jnp.ones((n, 4), jnp.int32)
        spec = SpeculativeEngine(
            cfg, params, draft_cfg, draft_params, gamma=2, temperature=1.0
        )
        out = spec.generate(prompt, max_new_tokens=2,
                            key=jax.random.PRNGKey(9))
        # Token 0 comes from prefill (plain target sample); token 1 is the
        # first speculative-round token — the one under test. Its exact
        # marginal is sum_t0 P(t0) P(t1|t0), computable for a tiny vocab.
        second = np.asarray(out.tokens)[:, 1]

        v = cfg.vocab_size
        logits0 = transformer.forward(cfg, params, prompt[:1])[0, -1]
        p0 = np.asarray(jax.nn.softmax(logits0))  # (V,)
        ctxs = jnp.concatenate(
            [jnp.broadcast_to(prompt[:1], (v, prompt.shape[1])),
             jnp.arange(v, dtype=jnp.int32)[:, None]], axis=1
        )
        cond = np.asarray(
            jax.nn.softmax(transformer.forward(cfg, params, ctxs)[:, -1])
        )  # (V, V): row t0 -> P(t1 | t0)
        p = p0 @ cond

        counts = np.bincount(second, minlength=v)
        emp = counts / counts.sum()
        tv = 0.5 * np.abs(emp - p).sum()
        # TV distance of an m-sample empirical dist from its own source
        # concentrates near sqrt(V/(2*pi*m)) ~ 0.1 here.
        assert tv < 0.3, f"total variation from target {tv}"

        # Power check: the draft's marginal must be clearly rejected.
        d_cond = np.asarray(jax.nn.softmax(
            transformer.forward(draft_cfg, draft_params, ctxs)[:, -1]
        ))
        d0 = np.asarray(jax.nn.softmax(
            transformer.forward(draft_cfg, draft_params, prompt[:1])[0, -1]
        ))
        p_draft = d0 @ d_cond
        tv_draft = 0.5 * np.abs(emp - p_draft).sum()
        assert tv_draft > 0.4, (
            f"test has no power: TV from draft only {tv_draft}"
        )

    def test_accept_rate_reported(self, models):
        cfg, params, draft_cfg, draft_params = models
        prompt = jnp.ones((4, 4), jnp.int32)
        out = SpeculativeEngine(
            cfg, params, draft_cfg, draft_params, gamma=3, temperature=1.0
        ).generate(prompt, max_new_tokens=12)
        assert 0.0 <= float(out.accept_rate) <= 1.0
        assert int(out.rounds) >= 3  # at most gamma+1 tokens per round


class TestValidation:
    def test_vocab_mismatch(self, models):
        cfg, params, draft_cfg, draft_params = models
        bad = draft_cfg.replace(vocab_size=128)
        with pytest.raises(ValueError, match="vocab mismatch"):
            SpeculativeEngine(cfg, params, bad, draft_params)

    def test_cache_overflow_guard(self, models):
        cfg, params, draft_cfg, draft_params = models
        eng = SpeculativeEngine(cfg, params, draft_cfg, draft_params,
                                gamma=2, max_len=32)
        with pytest.raises(ValueError, match="cache length"):
            eng.generate(jnp.ones((1, 16), jnp.int32), max_new_tokens=20)
